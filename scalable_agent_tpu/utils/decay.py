"""Piecewise-linear schedules.  (reference: utils/decay.py:4-47)"""

from typing import Sequence, Tuple


class LinearDecay:
    """Value interpolated between (step, value) milestones.

    Before the first milestone: first value.  After the last: last value.
    ``staircase`` > 0 quantizes the interpolated value into that many
    discrete steps per segment.
    """

    def __init__(self, milestones: Sequence[Tuple[int, float]],
                 staircase: int = 0):
        if not milestones:
            raise ValueError("need at least one milestone")
        self._milestones = sorted(milestones)
        self._staircase = staircase

    def at(self, step: int) -> float:
        ms = self._milestones
        if step <= ms[0][0]:
            return ms[0][1]
        if step >= ms[-1][0]:
            return ms[-1][1]
        for (x0, y0), (x1, y1) in zip(ms, ms[1:]):
            if x0 <= step <= x1:
                fraction = (step - x0) / (x1 - x0)
                if self._staircase:
                    fraction = (int(fraction * self._staircase)
                                / self._staircase)
                return y0 + fraction * (y1 - y0)
        raise AssertionError("unreachable")
