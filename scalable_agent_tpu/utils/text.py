"""Host-side text hashing for language instructions (numpy-only).

Lives under utils so env-worker subprocesses can import it without
pulling in jax/flax (workers must never initialize the TPU runtime;
envs/worker.py).  Device-side embedding/encoding is
models/instruction.py.
"""

import zlib

import numpy as np

NUM_HASH_BUCKETS = 1000  # reference: experiment.py:131
MAX_INSTRUCTION_LEN = 16


def hash_instruction(
    instruction: str,
    max_len: int = MAX_INSTRUCTION_LEN,
    num_buckets: int = NUM_HASH_BUCKETS,
) -> np.ndarray:
    """Whitespace-split and hash words to 1-based bucket ids.

    Returns int32 [max_len]; 0 is padding.  Bucket ids are 1..num_buckets
    so "no token" is distinguishable from any real token.  Uses crc32 — a
    stable, python-version-independent hash (the reference's in-graph
    fingerprint hash has the same "small risk of collisions" caveat,
    reference: experiment.py:129-132).

    Instructions longer than ``max_len`` words are truncated — a
    deliberate divergence from the reference's unbounded dynamic_rnn:
    TPU/XLA needs static shapes, and DMLab instructions are short ("go to
    the red door"); raise ``max_len`` if a level family needs more.
    """
    ids = np.zeros([max_len], dtype=np.int32)
    for i, word in enumerate(instruction.split()[:max_len]):
        ids[i] = 1 + zlib.crc32(word.encode("utf-8")) % num_buckets
    return ids
