"""Algorithm utilities: running statistics, discounted sums, GAE.

Parity with the reference's vendored Sample-Factory utilities
(reference: algorithms/utils/algo_utils.py:6-159).  Unused by the
IMPALA path (V-trace supersedes GAE there — same as the reference,
where these feed the absent PPO modules), but part of the public
algorithm-utility surface.
"""

from typing import Sequence, Tuple

import numpy as np

EPS = 1e-8


class RunningMeanStd:
    """Streaming mean/variance via the parallel-variance update.

    (reference: algo_utils.py:6-47, the Chan et al. parallel algorithm)
    """

    def __init__(self, shape: Tuple[int, ...] = (), epsilon: float = 1e-4):
        self.mean = np.zeros(shape, np.float64)
        self.var = np.ones(shape, np.float64)
        self.count = float(epsilon)

    def update(self, batch: np.ndarray) -> None:
        batch = np.asarray(batch, np.float64)
        batch_mean = batch.mean(axis=0)
        batch_var = batch.var(axis=0)
        batch_count = batch.shape[0]
        self.update_from_moments(batch_mean, batch_var, batch_count)

    def update_from_moments(self, batch_mean, batch_var,
                            batch_count: float) -> None:
        delta = batch_mean - self.mean
        total = self.count + batch_count
        self.mean = self.mean + delta * batch_count / total
        m_a = self.var * self.count
        m_b = batch_var * batch_count
        m2 = m_a + m_b + delta ** 2 * self.count * batch_count / total
        self.var = m2 / total
        self.count = total

    def normalize(self, x: np.ndarray) -> np.ndarray:
        return (np.asarray(x) - self.mean) / np.sqrt(self.var + EPS)


def discounted_sums(values: Sequence[float], gamma: float) -> np.ndarray:
    """x_t + gamma * X_{t+1} computed right-to-left.

    (reference: algo_utils.py:86-99)
    """
    values = np.asarray(values, np.float64)
    out = np.zeros_like(values)
    acc = 0.0
    for t in range(len(values) - 1, -1, -1):
        acc = values[t] + gamma * acc
        out[t] = acc
    return out


def calculate_gae(rewards: Sequence[float], dones: Sequence[bool],
                  values: Sequence[float], gamma: float,
                  gae_lambda: float) -> Tuple[np.ndarray, np.ndarray]:
    """Generalized Advantage Estimation.

    ``values`` has one more entry than rewards (bootstrap).  Returns
    (advantages, returns) with returns = advantages + values[:-1]
    (reference: algo_utils.py:102-127).
    """
    rewards = np.asarray(rewards, np.float64)
    dones = np.asarray(dones, bool)
    values = np.asarray(values, np.float64)
    if len(values) != len(rewards) + 1:
        raise ValueError(
            f"values needs len(rewards)+1 entries, got {len(values)} "
            f"for {len(rewards)} rewards")
    not_done = 1.0 - dones.astype(np.float64)
    advantages = np.zeros_like(rewards)
    acc = 0.0
    for t in range(len(rewards) - 1, -1, -1):
        delta = (rewards[t] + gamma * values[t + 1] * not_done[t]
                 - values[t])
        acc = delta + gamma * gae_lambda * not_done[t] * acc
        advantages[t] = acc
    return advantages, advantages + values[:-1]


def num_env_steps(infos: Sequence[dict]) -> int:
    """Total simulator frames across a batch of info dicts
    (reference: algo_utils.py:130-136 — frameskip-aware counting)."""
    return sum(int(info.get("num_frames", 1)) for info in infos)