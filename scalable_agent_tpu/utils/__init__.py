from scalable_agent_tpu.utils.misc import AttrDict, log
from scalable_agent_tpu.utils.timing import AvgTime, Timing
from scalable_agent_tpu.utils.decay import LinearDecay
