"""Network helpers for multiplayer simulators.

(reference: utils/network.py:6-15 — the UDP port probe VizDoom
multiplayer games use to pick their host ports)
"""

import socket


def is_udp_port_available(port: int) -> bool:
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as sock:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind(("127.0.0.1", port))
        return True
    except OSError:
        return False


def find_available_udp_port(start_port: int, increment: int = 1000) -> int:
    """First available UDP port in start + k*increment (reference:
    envs/doom/multiplayer/doom_multiagent.py:16-22)."""
    port = start_port
    while port < 65535 and not is_udp_port_available(port):
        port += increment
    return port
