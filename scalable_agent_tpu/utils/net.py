"""Network helpers for multiplayer simulators.

(reference: utils/network.py:6-15 — the UDP port probe VizDoom
multiplayer games use to pick their host ports)
"""

import socket


def is_udp_port_available(port: int) -> bool:
    if not 0 < port < 65536:
        return False
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as sock:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind(("127.0.0.1", port))
        return True
    except OSError:
        return False


def find_available_udp_port(start_port: int, increment: int = 1000) -> int:
    """First available UDP port in start + k*increment (reference:
    envs/doom/multiplayer/doom_multiagent.py:16-22).  Raises instead of
    returning an out-of-range port."""
    port = start_port
    while port < 65536:
        if is_udp_port_available(port):
            return port
        port += increment
    raise RuntimeError(
        f"no available UDP port in {start_port} + k*{increment} "
        f"below 65536")
