"""GIF encoding by piping raw frames through ffmpeg.

(reference: utils/gifs.py:4-29 — same ffmpeg-subprocess approach; fails
with a clear error when ffmpeg isn't installed.)
"""

import shutil
import subprocess
from typing import Sequence

import numpy as np


def encode_gif(frames: Sequence[np.ndarray], fps: int = 30) -> bytes:
    """RGB uint8 [H, W, 3] frames -> animated GIF bytes."""
    frames = [np.asarray(f) for f in frames]
    if not frames:
        raise ValueError("no frames to encode")
    h, w, c = frames[0].shape
    if c != 3:
        raise ValueError(f"need RGB frames, got {c} channels")
    if shutil.which("ffmpeg") is None:
        raise RuntimeError(
            "encode_gif needs the ffmpeg binary on PATH")
    cmd = [
        "ffmpeg", "-y", "-f", "rawvideo", "-vcodec", "rawvideo",
        "-r", f"{fps:.02f}", "-s", f"{w}x{h}", "-pix_fmt", "rgb24",
        "-i", "-", "-filter_complex",
        "[0:v]split[x][z];[z]palettegen[y];[x]paletteuse",
        "-r", f"{fps:.02f}", "-f", "gif", "-",
    ]
    proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)
    data, err = proc.communicate(
        input=b"".join(np.ascontiguousarray(f, np.uint8).tobytes()
                       for f in frames))
    if proc.returncode:
        raise RuntimeError(f"ffmpeg failed: {err.decode()[-500:]}")
    return data