"""Context-manager timers.  (reference: utils/timing.py:8-64)"""

import time
from collections import deque
from typing import Dict


class AvgTime:
    """Moving average over the last ``num_values`` measurements."""

    def __init__(self, num_values: int = 50):
        self.values = deque(maxlen=num_values)

    def add(self, value: float):
        self.values.append(value)

    @property
    def value(self) -> float:
        return sum(self.values) / len(self.values) if self.values else 0.0

    def __str__(self):
        return f"{self.value:.4f}s (avg of {len(self.values)})"


class _TimingContext:
    def __init__(self, timing, key: str, mode: str):
        self._timing = timing
        self._key = key
        self._mode = mode

    def __enter__(self):
        self._start = time.monotonic()
        return self

    def __exit__(self, *exc_info):
        elapsed = time.monotonic() - self._start
        t = self._timing
        if self._mode == "overwrite":
            t[self._key] = elapsed
        elif self._mode == "add":
            t[self._key] = t.get(self._key, 0.0) + elapsed
        else:  # avg
            entry = t.get(self._key)
            if not isinstance(entry, AvgTime):
                entry = AvgTime()
                t[self._key] = entry
            entry.add(elapsed)


class Timing(dict):
    """``with timing.timeit('x'):`` records elapsed seconds under 'x'."""

    def timeit(self, key: str):
        return _TimingContext(self, key, "overwrite")

    def add_time(self, key: str):
        return _TimingContext(self, key, "add")

    def time_avg(self, key: str):
        return _TimingContext(self, key, "avg")

    def summary(self) -> Dict[str, float]:
        """Flat ``{key: seconds}`` snapshot, ``AvgTime`` entries
        unwrapped to their moving average — the machine-readable twin of
        ``__str__`` so timings feed the metrics registry and
        ``MetricsWriter`` without string parsing."""
        return {
            key: value.value if isinstance(value, AvgTime)
            else float(value)
            for key, value in self.items()
        }

    def __str__(self):
        parts = []
        for key, value in self.items():
            if isinstance(value, AvgTime):
                parts.append(f"{key}: {value}")
            else:
                parts.append(f"{key}: {value:.4f}s")
        return ", ".join(parts)
