"""Small shared utilities.

(reference: utils/utils.py — colorlog logger :13-37, AttrDict :42-49; the
logger here is stdlib-only since colorlog isn't a baked dependency)
"""

import logging
import os
import sys


def _make_logger() -> logging.Logger:
    logger = logging.getLogger("scalable_agent_tpu")
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(logging.Formatter(
            "[%(asctime)s][%(process)05d] %(levelname)s %(message)s",
            datefmt="%Y-%m-%d %H:%M:%S"))
        logger.addHandler(handler)
        logger.setLevel(os.environ.get("SA_TPU_LOGLEVEL", "INFO"))
        logger.propagate = False
    return logger


log = _make_logger()


class AttrDict(dict):
    """dict with attribute access.  (reference: utils/utils.py:42-49)"""

    __setattr__ = dict.__setitem__

    def __getattr__(self, key):
        try:
            return self[key]
        except KeyError as exc:
            raise AttributeError(key) from exc


def memory_consumption_mb() -> float:
    """Resident set size of this process in MB.

    (reference: utils/utils.py:139-142)
    """
    try:
        import psutil

        return psutil.Process().memory_info().rss / (1024 * 1024)
    except ImportError:
        return 0.0
