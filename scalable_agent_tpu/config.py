"""One dataclass-based config for the whole framework.

Replaces the reference's two coexisting systems — tf.app.flags
(reference: experiment.py:49-95) and SF argparse with per-env overrides +
cfg.json persistence (reference: algorithms/utils/arguments.py:27-99) —
with a single dataclass: reference hyperparameter names/defaults are kept
verbatim so parity runs transfer unchanged, JSON round-trips to
``<logdir>/config.json``, and env families can override defaults through
``apply_env_overrides``.
"""

import dataclasses
import json
import os
from typing import Optional, Tuple


@dataclasses.dataclass
class Config:
    # -- run control (reference: experiment.py:49-60)
    mode: str = "train"  # train | test
    logdir: str = "/tmp/agent"
    level_name: str = "fake_benchmark"
    seed: int = 1

    # -- training sizes (reference: experiment.py:61-72)
    num_actors: int = 64  # total env count across groups
    batch_size: int = 32
    unroll_length: int = 100
    num_action_repeats: int = 4
    total_environment_frames: float = 1e9

    # -- loss (reference: experiment.py:73-81)
    entropy_cost: float = 0.00025
    baseline_cost: float = 0.5
    discounting: float = 0.99
    reward_clipping: str = "abs_one"  # abs_one | soft_asymmetric | none

    # -- optimizer (reference: experiment.py:89-95)
    learning_rate: float = 0.00048
    rmsprop_decay: float = 0.99
    rmsprop_momentum: float = 0.0
    rmsprop_epsilon: float = 0.1

    # -- env (reference: experiment.py:82-88)
    width: int = 96
    height: int = 72
    benchmark_mode: bool = False
    num_env_workers_per_group: int = 8
    # DMLab-only: psychlab dataset location and renderer backend
    # (reference: experiment.py:77-87 dataset_path/renderer flags;
    # software is the run-anywhere default, hardware needs EGL).
    dataset_path: str = ""
    renderer: str = "software"

    # -- eval (reference: experiment.py:57-58)
    test_num_episodes: int = 10
    test_batch_size: int = 8  # parallel eval envs per level
    test_num_workers: int = 2  # env worker processes per eval fleet
    # Record eval episodes (frames.npy + actions/rewards JSON per
    # episode, one subdir per level/env slot) — the Sample-Factory
    # record_to flag's role (reference: env_wrappers.py:433-497).
    record_to: str = ""  # empty = no recording; test mode only

    # -- TPU-native knobs (no reference equivalent)
    torso_type: str = "shallow"  # shallow | resnet
    # Activation/matmul dtype END-TO-END (torso, LSTM core, heads):
    # params, loss, V-trace, and optimizer reductions stay f32
    # regardless (models/agent.py documents the full policy).
    compute_dtype: str = "bfloat16"
    # LSTM core: auto | xla | pallas — auto picks the fused Pallas
    # unroll (ops/lstm_pallas.py) on a single-device TPU mesh, the
    # nn.scan path elsewhere.  Param trees are identical either way.
    core_impl: str = "auto"
    # Pallas-core matmul operand precision: auto | float32 | bfloat16.
    # "auto" follows the ONE dtype policy: the pallas core's matmuls
    # run at compute_dtype (bf16 operands, f32 accumulation — the
    # parity-proven recipe), while the xla core always trains at the
    # f32 params' precision.  Explicit values decouple the two.
    core_matmul_dtype: str = "auto"
    # Stem-conv grad-W lowering: auto | xla | pallas.  "pallas" swaps
    # ONLY the stem's weight gradient for the im2col MXU kernel
    # (ops/conv_pallas.py) — the named worst kernel in the roofline
    # ledger (conv0_gradw, 0.107 MFU).  "auto" = pallas on TPU, xla
    # elsewhere (the lstm_pallas precedent; off-TPU the kernel would
    # run interpreted).  Param trees are identical either way.
    conv_backend: str = "auto"
    # Fused single-forward loss (runtime/learner.py): one unroll feeds
    # both the behaviour-comparison quantities and the differentiated
    # loss outputs.  False compiles the two-pass reference shape —
    # bench_kernel_war's baseline, not a production setting.
    fused_forward: bool = True
    # Rematerialize the torso in the backward pass: auto | on | off.
    # "auto" = on for TPU runs (keeps the fused single-forward update
    # flat on peak activation memory at B=256), off elsewhere.
    # Numerically identity; trades a torso recompute for memory.
    remat_torso: str = "auto"
    use_instruction: bool = False
    # (the actor-group count is derived: num_actors // batch_size — each
    # group is one learner batch; >= 2 groups overlap env-sim with TPU
    # inference.  See driver.make_env_groups.)
    mesh_data: int = 0  # 0 = all devices
    # Sequence/context parallelism (SURVEY §5.7): batches shard over
    # (data x seq); the V-trace recurrence's time dimension shards over
    # seq (parallel/sequence.py, scan_impl="time_sharded").
    mesh_seq: int = 1
    mesh_model: int = 1
    # Multi-host (DCN) distribution — empty/0/-1 = single process.
    # (role of the reference's ClusterSpec + --job_name/--task flags,
    # experiment.py:497-512)
    distributed_coordinator: str = ""  # e.g. "10.0.0.1:8476"
    distributed_num_processes: int = 0
    distributed_process_id: int = -1
    # Actor inference: "structural" (one jitted step per group),
    # "service" (C++ dynamic batcher co-batches groups into one call —
    # the reference's architecture, dynamic_batching.py + batcher.cc),
    # "accum" (on-device trajectory accumulation: per step only frame
    # bytes go up and actions come down, runtime/accum_actor.py), or
    # "accum_fused" (accum + cross-group lockstep co-dispatch: ONE
    # device call and ONE action fetch serve all groups per step —
    # ~1 link RTT regardless of group count).
    inference_mode: str = "structural"
    # accum_fused only: number of lockstep shards the group fleet
    # splits into (separate threads).  1 = one device call serves ALL
    # groups (minimum RTTs, right co-located); 2 lets one shard's
    # upload + env stepping overlap the other's link round trip —
    # measured 1.6-1.8x e2e on bandwidth-constrained links
    # (BENCH_NOTES r4 sweep; 3 shards regressed).  Default 0 = AUTO:
    # the pool probes the link at startup (RTT + H2D bandwidth) and
    # picks the predicted-best count from the RTT-floor model
    # (runtime/linktune.py) — so co-located chips get 1 and degraded
    # tunnels get 2 without per-deployment tuning.  The pool clamps
    # explicit values to the group count.
    accum_fused_shards: int = 0
    # Host actor runtime: "grouped" (the ActorPool — one thread per env
    # group, lockstep step_send/step_recv, the slowest env gates its
    # group) or "service" (runtime/service.py — continuous-batching:
    # env workers stream observations out individually, one inference
    # thread batches whatever arrived against a device-resident LSTM
    # state slab, per-env trajectory packing; no per-step group
    # barrier).  docs/performance.md, "Continuous-batching actor
    # service".
    actor: str = "grouped"
    # service only: the largest device batch the inference thread forms
    # (rows = envs).  Formed batches pad up a power-of-two bucket
    # ladder so XLA sees ~log2(max) shapes.  0 = auto (all of this
    # process's envs — one full sweep fits one batch).
    service_max_batch: int = 0
    # Training backend: "host" (actor pool + prefetch + learner — the
    # reference's architecture, experiment.py:479-672) or "ingraph"
    # (rollout + update fused into ONE jitted device program for
    # device-expressible levels, runtime/ingraph.py — zero per-step
    # host↔device traffic).
    train_backend: str = "host"
    # ingraph only: fused updates per device dispatch (the multi-update
    # megaloop, runtime/ingraph.py).  K > 1 runs K rollout+update
    # iterations as ONE lax.scan per launch, so a cheap-env run is no
    # longer dispatch-bound — bit-exact with K dispatches of 1 over the
    # same total update count.  Checkpoint/log/preemption decisions
    # land on dispatch boundaries (granularity K updates).
    # Incompatible with replay_ratio > 0 (replayed updates interleave
    # between dispatches).
    updates_per_dispatch: int = 1
    # Trajectory transport (runtime/transport.py): "packed" flattens
    # every trajectory leaf into ONE contiguous staging buffer per batch
    # (dtype-segmented, 128-byte-aligned offsets) so a batch costs a
    # single H2D copy + a jitted on-device unpack; "per_leaf" is the
    # seed path — one device_put per leaf — preserved bit-for-bit.
    # Device-resident trajectories (inference_mode=accum*) bypass the
    # pack either way: they re-shard on device instead of uploading.
    transport: str = "packed"
    # Bounded in-flight dispatch: keep up to this many updates dispatched
    # but unmaterialized; the driver blocks only when the window is full
    # (metrics surface when their update falls out of the window).  The
    # default of 2 overlaps batch k+1's pack/upload with update k while
    # blocking at most one update behind — the seed loop's effective
    # pipelining, now with an explicit bound; 1 forces strict lock-step
    # (a per-update completion wait the seed loop never paid — use it
    # for debugging, not throughput).
    inflight_updates: int = 2
    # vtrace: auto | associative | sequential | pallas | time_sharded —
    # auto picks time_sharded when mesh_seq > 1, the fused Pallas kernel
    # on a single-device TPU mesh, associative else.
    scan_impl: str = "auto"
    # -- off-policy replay (runtime/replay.py, ops/impact.py;
    # docs/performance.md "Replay & the off-policy dial") ----------------
    # Loss surrogate: "vtrace" (the seed objective, bit-for-bit) or
    # "impact" (clipped-target surrogate with a target network riding
    # in TrainState — tolerates far staler data, the objective replay
    # needs).
    loss: str = "vtrace"
    # Replayed updates per fresh batch: every fresh batch's packed
    # upload also lands in the device-resident replay slab, and R
    # uniformly sampled batches ride behind each fresh update — the
    # learner-throughput dial that decouples learner fps from actor
    # fps.  0 disables replay entirely (no slab is ever allocated).
    # Replayed updates do NOT advance env_frames (fresh frames count
    # exactly once) and are tuned against the
    # ledger/staleness_replayed_s split.
    replay_ratio: int = 0
    # Replay slab capacity in whole batches.  Device HBM cost is
    # capacity x packed-batch bytes; contents are intentionally not
    # checkpointed (docs/robustness.md, replay warm-up after restore).
    replay_capacity: int = 64
    # IMPACT target network: hard-copy the online params into the
    # target every this many FRESH updates (in-graph, no extra sync).
    target_update_interval: int = 100
    # IMPACT surrogate ratio clip epsilon (pi_theta/pi_target outside
    # [1-eps, 1+eps] stops contributing gradient).
    impact_clip_epsilon: float = 0.3
    checkpoint_interval_s: float = 600.0  # reference: experiment.py:611-612
    checkpoint_keep: int = 5
    log_interval_s: float = 10.0
    # jax.profiler tracing (SURVEY §5.1): capture device+host traces for
    # profile_num_updates updates starting at profile_start_update.
    profile_dir: str = ""  # empty = disabled
    profile_start_update: int = 10
    profile_num_updates: int = 5
    # Observability (obs/): --trace captures host pipeline spans (actor
    # env-step/inference, batcher queues, learner update, checkpoint,
    # h2d transfers) to <logdir>/trace.json — Chrome trace-event format,
    # loadable in Perfetto.  Unlike --profile_dir's device trace this
    # shows the host-side hand-offs, costs a few us per span, and is
    # bounded: capture stops (with a truncation marker) at the tracer's
    # 2M-event budget (~200 MB) so long runs can't fill the disk.  The
    # metrics registry + Prometheus snapshot (<logdir>/metrics.prom) and
    # the stall attributor are always on; see docs/observability.md.
    # Trace files carry a .p<proc>.<pid> suffix so two runs sharing a
    # logdir (or N processes of one run) can never clobber each other;
    # `python -m scalable_agent_tpu.obs.aggregate <logdir>` merges them.
    trace: bool = False
    # Watchdog (obs/watchdog.py): a pipeline thread (actor, batcher
    # consumer, prefetch, learner) that makes no progress for this many
    # seconds trips the stalled_thread verdict and dumps the flight
    # recorder + all-thread stacks (<logdir>/flightrec.<pid>.json,
    # stacks.<pid>.txt).  0 disables (unit tests construct their own).
    # The default is generous: it must sit above a worst-case production
    # compile or checkpoint, not above a step.
    watchdog_timeout_s: float = 300.0
    # Abort the process (exit 70) after the watchdog dump instead of
    # hanging forever — the right setting under a supervisor that
    # restarts failed workers.
    watchdog_abort: bool = False
    # Serve live Prometheus text over HTTP at this port (0 = disabled):
    # scrapers hit http://host:<port>/metrics instead of polling
    # <logdir>/metrics.prom off disk.  Multi-process runs offset the
    # port by the process index.
    metrics_http_port: int = 0
    # Learning-dynamics plane (docs/observability.md): V-trace/IMPACT
    # clip + ESS diagnostics, policy entropy/KL, value explained-
    # variance, and per-layer-group optimizer telemetry accumulated
    # in-graph (devtel/learn/*, zero added host syncs), read by the
    # health detectors, obs.watch, obs.report, and `python -m
    # scalable_agent_tpu.obs.diagnose <logdir>`.
    learn_telemetry: bool = True
    # -- run-health plane (obs/health.py, docs/observability.md) ---------
    # Online anomaly detection at log-interval cadence: EWMA z-score
    # (level shifts), CUSUM (slow drifts), hard thresholds (invariants)
    # over throughput/loss/grad-norm/staleness/segment-rho/nonfinite/
    # peers.  A trip appends <logdir>/anomalies.jsonl, pins + dumps the
    # flight recorder, and may open a bounded auto-profile window.
    health: bool = True
    # Log intervals before a detector arms (the compile-dominated first
    # intervals must not poison the baseline or trip an alarm).
    health_warmup_intervals: int = 8
    # EWMA smoothing for the detector baselines (mean and variance).
    health_ewma_alpha: float = 0.35
    # z-score a deviation needs to trip (with a material relative
    # deviation); a relative drop/rise past health_rel_threshold trips
    # on its own regardless of the variance estimate.
    health_z_threshold: float = 4.0
    health_rel_threshold: float = 0.6
    # Per-detector re-trip cooldown AND the minimum gap between auto-
    # profile windows: a flapping detector logs one suppressed count
    # per swallowed trip instead of a record per interval.
    health_cooldown_s: float = 120.0
    # Auto-profile window budget for the whole run (0 disables windows;
    # detection, records, and flightrec dumps stay on).
    health_max_windows: int = 2
    # Updates one anomaly-triggered profiling window spans.
    health_window_updates: int = 5
    # Prime detectors from the newest committed BENCH_r*.json so a run
    # that STARTS slower than the last proving round trips immediately:
    # '' = off, 'auto' = the repo's committed rounds, else a directory.
    health_baseline_dir: str = ""
    # -- self-healing (docs/robustness.md) --------------------------------
    # Non-finite guard: a NaN/Inf loss or gradient makes the update a
    # no-op (params/opt_state held, frames still retired) and counts in
    # learner/nonfinite_skips_total.  This many CONSECUTIVE skips
    # triggers a rollback to the last verified checkpoint (or exit 71
    # with --no_rollback).  0 disables the rollback policy; the guard
    # itself is always on.
    nonfinite_tolerance: int = 10
    # Numerics sentinel (runtime/sentinel.py): every K updates, shadow-
    # audit the hot path's gradients and param deltas against the
    # reference path (XLA stem, f32 compute, two-pass loss) and demote
    # down the degradation ladder on breach; also publish a param
    # fingerprint per log interval and compare it across processes at
    # the decision-broadcast cadence.  0 disables the sentinel entirely
    # (the default path stays bit-exact).  In-graph runs require
    # --updates_per_dispatch=1 while the sentinel is armed.
    sentinel_interval: int = 0
    # Max per-leaf L2-relative deviation ||hot - ref|| / (||ref|| + eps)
    # any grad or param-delta leaf may show before an audit breaches.
    # Calibrated against bench_sentinel's clean hot-vs-reference run at
    # production shapes: legitimate bf16-vs-f32 drift measures ~0.38 on
    # the worst (near-cancelled conv-bias) leaf, a 2x-miscomputing
    # kernel reads exactly 1.0, and a param bit-flip dwarfs the
    # reference delta's norm — 0.6 splits the bands with margin both
    # ways.  Watch devtel/sentinel/max_deviation to re-calibrate.
    sentinel_rtol: float = 0.6
    # Exit with code 71 instead of rolling back when the non-finite
    # tolerance is exhausted — the right setting under a supervisor
    # that reschedules the run (rollback-on-restart then happens via
    # the normal resume path).
    no_rollback: bool = False
    # Bounded actor-thread respawn: a failing actor retries with capped
    # exponential backoff this many times before its exception ends the
    # run (actor/restarts_total; per-actor detail in the flight
    # recorder).  0 restores fail-fast.
    actor_max_restarts: int = 3
    # Deterministic fault injection (runtime/faults.py), chaos testing
    # only: 'point@i[:j...]' / 'point@t=30s' / 'point@p=0.01' entries
    # joined by ';', e.g.
    # 'nan_grad@7;actor_raise@3:12;ckpt_torn@t=5s;worker_kill@p=0.01'.
    # Empty = no faults.
    chaos_spec: str = ""
    # Arm the runtime injection channel: the injector tails
    # <logdir>/chaos_inject.jsonl and fires each appended
    # {"point": ..., "t_unix": ...} line once at that point's next
    # evaluation — faults land in an ALREADY-RUNNING fleet (the chaos
    # soak engine, runtime/soak.py, writes the lines).  Propagates to
    # relaunched elastic workers like any other flag.
    chaos_channel: bool = False
    # JAX persistent compilation cache directory ('' = disabled).  MTTR
    # engineering: an elastic relaunch's recovery time is dominated by
    # the fresh process's first compile; with the cache armed, epoch 0
    # populates it and every relaunch (and every restart of the same
    # config) compiles from disk.  Wired through both driver backends;
    # safe to share across fleet processes (the cache is keyed by
    # program fingerprint and written atomically).
    compile_cache_dir: str = ""
    # -- fleet fault domains (runtime/fleet.py, docs/robustness.md) ------
    # Peer heartbeat deadline: in a multi-process run, a peer whose
    # KV-store heartbeat stops advancing for this long (local monotonic
    # clock) is declared lost — forensic dump + exit 72 instead of
    # hanging forever in the next collective.  0 disables detection
    # (single-process runs never arm it).
    peer_timeout_s: float = 60.0
    # Preemption grace: SIGTERM raises a fleet-wide preemption flag
    # instead of dumping and dying; every process drains its in-flight
    # window and takes ONE coordinated final verified checkpoint within
    # this many seconds, then exits 0 for frame-exact resume.  Blowing
    # the window means forensics + exit 72; a second SIGTERM escalates
    # to the legacy immediate dump.  0 restores dump-and-exit(143).
    preemption_grace_s: float = 30.0
    # Deadline on each blocking cross-process point (decision
    # broadcasts, trajectory assembly, checkpoint save/restore
    # collectives): a collective older than this is attributed in the
    # flight recorder and the process exits 72.  0 = auto
    # (max(600, 4x peer_timeout_s)) — it must sit above a worst-case
    # first-update compile or Orbax read, not above a step; the
    # heartbeat deadline above is the FAST detector.
    collective_timeout_s: float = 0.0
    # Bounded retry (capped exponential backoff) around
    # jax.distributed.initialize: process N racing the coordinator's
    # startup retries for this long before failing the run
    # (fleet/init_retries_total counts the attempts).
    coordinator_init_timeout_s: float = 60.0
    # -- elastic fleet membership (runtime/elastic.py) -------------------
    # Supervisor mode: instead of training directly, own
    # distributed_num_processes (or 1) worker processes, watch their
    # exit codes, and convert a fleet-fatal (exit 72) or preemption
    # into a RESHARD event — relaunch the survivors as an (N-1)-process
    # fleet resuming from the newest verified checkpoint — then scale
    # back to N when the lost slot rejoins.  Equivalent CLI:
    # python -m scalable_agent_tpu.runtime.elastic <same flags>.
    elastic: bool = False
    # Membership epoch this worker belongs to (set by the supervisor on
    # every (re)launch; surfaces as the fleet/epoch gauge and in the
    # fleet_epoch.json membership verdict).  Operators never set it.
    fleet_epoch: int = 0
    # Reshard-restart budget: consecutive fleet relaunches (capped
    # exponential backoff between them) before the supervisor gives up
    # and exits with the workers' code.  The counter resets once an
    # epoch survives elastic_stable_s.
    elastic_restart_budget: int = 8
    # Seconds a fleet must run before its epoch counts as stable
    # (resets the restart budget and the backoff).
    elastic_stable_s: float = 300.0
    # Seconds after a slot is LOST (worker SIGKILLed / host gone)
    # before the supervisor may schedule its rejoin; an operator can
    # force an earlier rejoin by touching <logdir>/rejoin.<slot>.
    # The scale-up itself happens at the next checkpoint boundary: the
    # running fleet is drained through the preemption-grace protocol
    # (one coordinated verified checkpoint, exit 0) and relaunched at
    # the larger size.
    elastic_rejoin_delay_s: float = 60.0

    # -------------------------------------------------------------------

    def group_size(self) -> int:
        """Envs per actor group == this host's share of the learner
        batch (minimum slice layout; ``batch_size`` is GLOBAL in
        multi-host runs, matching the reference's one learner batch fed
        by all actors, experiment.py:576)."""
        import jax

        processes = jax.process_count()
        if self.batch_size % processes:
            raise ValueError(
                f"batch_size {self.batch_size} not divisible by "
                f"{processes} processes")
        return self.batch_size // processes

    def frames_per_update(self) -> int:
        """(reference: experiment.py:417-420)"""
        return (self.batch_size * self.unroll_length
                * self.num_action_repeats)

    def save(self, path: Optional[str] = None) -> str:
        """Persist to JSON (the reference's cfg.json,
        algorithms/utils/agent.py:190-193)."""
        path = path or os.path.join(self.logdir, "config.json")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump(dataclasses.asdict(self), f, indent=2, sort_keys=True)
        return path

    @classmethod
    def load(cls, path: str) -> "Config":
        with open(path) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    @classmethod
    def from_argv(cls, argv=None, description=None) -> "Config":
        """Parse a full CLI flag set (one ``--<field>`` per dataclass
        field) into a Config — the ONE parser shared by the driver and
        the elastic supervisor entry points, so their flag surfaces can
        never drift.  ``description`` is what ``--help`` prints above
        the option list (the driver passes its module docstring — the
        curated flag reference)."""
        import argparse

        parser = argparse.ArgumentParser(
            description=description,
            formatter_class=argparse.RawDescriptionHelpFormatter)
        for field in dataclasses.fields(cls):
            arg_type = type(field.default)
            if arg_type is bool:
                parser.add_argument(
                    f"--{field.name}", type=lambda v: v.lower() in
                    ("1", "true", "yes"), default=field.default)
            else:
                parser.add_argument(
                    f"--{field.name}", type=arg_type,
                    default=field.default)
        return cls(**vars(parser.parse_args(argv)))

    def to_argv(self, exclude: Tuple[str, ...] = ()) -> list:
        """The inverse of ``from_argv``: the minimal ``--field=value``
        list reproducing this config (non-default fields only, minus
        ``exclude``) — how the elastic supervisor hands its own config
        to the worker processes it spawns."""
        args = []
        for field in dataclasses.fields(self):
            if field.name in exclude:
                continue
            value = getattr(self, field.name)
            if value == field.default:
                continue
            if isinstance(value, bool):
                value = "true" if value else "false"
            args.append(f"--{field.name}={value}")
        return args

    @classmethod
    def from_checkpoint_dir(cls, logdir: str, **overrides) -> "Config":
        """Load a run's persisted config, applying CLI overrides on top
        (the reference's checkpoint-config precedence,
        arguments.py:69-89)."""
        path = os.path.join(logdir, "config.json")
        config = cls.load(path) if os.path.exists(path) else cls()
        return dataclasses.replace(config, logdir=logdir, **overrides)


# Per-env-family default overrides (the reference's
# env_override_defaults / *_params.py pattern, envs/env_config.py:1-24).
_ENV_OVERRIDES = {
    "doom_": {"width": 128, "height": 72, "num_action_repeats": 4},
    "atari_": {"width": 84, "height": 84, "num_action_repeats": 4},
    "dmlab_": {"width": 96, "height": 72, "num_action_repeats": 4},
    # The full suite: DMLab defaults + instruction observations (the
    # language levels need them; the reference's dmlab30 agent always
    # consumes INSTR, experiment.py:179-189).
    "dmlab30": {"width": 96, "height": 72, "num_action_repeats": 4,
                "use_instruction": True},
}


def apply_env_overrides(config: Config) -> Config:
    for prefix, overrides in _ENV_OVERRIDES.items():
        if config.level_name.startswith(prefix):
            defaults = Config()
            fields = {
                k: v for k, v in overrides.items()
                # CLI-set values win over family defaults.
                if getattr(config, k) == getattr(defaults, k)
            }
            return dataclasses.replace(config, **fields)
    return config
