"""Language-instruction handling, split host/device.

The reference hashes instruction strings to embedding buckets *inside the TF
graph* (``tf.string_to_hash_bucket_fast``, reference: experiment.py:123-146).
Strings cannot exist on a TPU, so the TPU-native design splits the work:

- host side: ``hash_instruction`` turns a string into fixed-length int32
  token ids (0 = padding) before the observation is ever device_put.
- device side: ``InstructionEncoder`` (a Flax module) embeds the ids and runs
  a small LSTM, returning the output at the last non-pad position — the same
  "last output of a length-masked dynamic_rnn" the reference computes
  (reference: experiment.py:142-146).
"""

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

# Host-side hashing lives in utils.text (numpy-only, importable by env
# workers that must never pull in jax); re-exported here for the device
# side of the pipeline.
from scalable_agent_tpu.utils.text import (  # noqa: F401
    MAX_INSTRUCTION_LEN,
    NUM_HASH_BUCKETS,
    hash_instruction,
)

EMBEDDING_SIZE = 20  # reference: experiment.py:135
LSTM_SIZE = 64  # reference: experiment.py:142


class _MaskedLSTMStep(nn.Module):
    """One LSTM step that freezes the carry where mask == 0.

    Freezing past the last real token makes the final carry's hidden state
    equal the output at position length-1 — the reference's
    ``reverse_sequence[:, 0]`` trick (reference: experiment.py:146).
    """

    features: int

    @nn.compact
    def __call__(self, carry, xs):
        x_t, m_t = xs
        new_carry, y = nn.OptimizedLSTMCell(
            self.features, name="cell")(carry, x_t)
        m = m_t[:, None]
        new_carry = jax.tree_util.tree_map(
            lambda new, old: m * new + (1.0 - m) * old, new_carry, carry)
        return new_carry, y


class InstructionEncoder(nn.Module):
    """Embed hashed token ids and LSTM-encode; output at last real token.

    Input: int32 [B, L] (0 = pad).  Output: f32 [B, LSTM_SIZE].
    (reference: experiment.py:123-146)
    """

    num_buckets: int = NUM_HASH_BUCKETS
    embedding_size: int = EMBEDDING_SIZE
    lstm_size: int = LSTM_SIZE

    @nn.compact
    def __call__(self, token_ids):
        batch = token_ids.shape[0]
        mask = (token_ids != 0).astype(jnp.float32)  # [B, L]
        # +1: id 0 is padding; real ids are 1..num_buckets.
        embedding = nn.Embed(self.num_buckets + 1, self.embedding_size,
                             name="embed")(token_ids)  # [B, L, E]

        scan = nn.scan(
            _MaskedLSTMStep,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=0,
            out_axes=0,
        )
        carry = (
            jnp.zeros((batch, self.lstm_size)),
            jnp.zeros((batch, self.lstm_size)),
        )
        # Time-major scan over L.
        carry, _ = scan(self.lstm_size, name="language_lstm")(
            carry,
            (jnp.swapaxes(embedding, 0, 1), jnp.swapaxes(mask, 0, 1)),
        )
        _, h = carry
        return h
