"""Visual torsos for the IMPALA agent.

Two variants, matching the reference's two (one active, one commented-out):

- ``ShallowConvTorso``: the 3-layer conv stack the fork actually runs —
  (32, 8x8, /4), (64, 4x4, /2), (128, 3x3, /2), each ReLU, then
  flatten → Dense(256) → ReLU (reference: experiment.py:178-189).
- ``ResNetTorso``: the deep IMPALA ResNet the fork keeps commented out —
  3 sections of [conv3x3 → maxpool/2 → 2 residual blocks] with channels
  (16, 32, 32) (reference: experiment.py:156-176).

TPU notes: callers flatten [T, B] into one [T*B] batch before the torso so
every conv/matmul hits the MXU with the largest possible batch; compute can
run in bfloat16 (``dtype``) with float32 params.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

# Stem-conv backends every torso accepts (``--conv_backend``).  "xla"
# is the plain nn.Conv lowering; "pallas" swaps ONLY the weight
# gradient for the im2col MXU kernel (ops/conv_pallas.py) — forward
# math is identical, parameter trees are identical, checkpoints are
# interchangeable.  The (negative-result) space-to-depth formulation
# is deliberately NOT in this registry: it stays reachable via
# ``ShallowConvTorso(space_to_depth=True)`` as documentation of the
# measurement (see _SpaceToDepthFirstConv), but it is retired from
# the flag surface — BENCH_NOTES' round-5 conv table is why.
CONV_BACKENDS = ("xla", "pallas")


def _normalize_frame(frame, dtype):
    """uint8 HWC frame -> [0, 1] float.  (reference: experiment.py:153-155)"""
    return jnp.asarray(frame, dtype) / 255.0


def space_to_depth_rearrange(x, kernel):
    """The stem's space-to-depth re-indexing, as one pure function:
    ``(x [N,H,W,C], kernel [8,8,C,F]) -> (x' [N,bh,bw,16C],
    k' [2,2,16C,F])`` such that a VALID 2x2/stride-1 conv of the primed
    pair equals the SAME 8x8/stride-4 conv of the originals.  Shared by
    ``_SpaceToDepthFirstConv`` and bench.py's cross-round conv
    diagnostic so the published timing always measures the shipped
    formulation."""
    n, height, width, c = x.shape
    f = kernel.shape[-1]

    # SAME padding for kernel 8 / stride 4; the padded extent
    # (ceil(d/4) + 1) * 4 is always a multiple of the block size.
    def pads(size):
        total = max(0, (-(-size // 4) - 1) * 4 + 8 - size)
        return total // 2, total - total // 2

    x = jnp.pad(x, ((0, 0), pads(height), pads(width), (0, 0)))
    bh, bw = x.shape[1] // 4, x.shape[2] // 4
    x = x.reshape(n, bh, 4, bw, 4, c).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(n, bh, bw, 16 * c)
    # kernel (kh, kw) -> (block, in-block) pairs, matching the
    # (ph, pw, c) channel order the input rearrangement produced.
    k = kernel.reshape(2, 4, 2, 4, c, f)
    k = k.transpose(0, 2, 1, 3, 4, 5).reshape(2, 2, 16 * c, f)
    return x, k


class _SpaceToDepthFirstConv(nn.Module):
    """The torso's 8x8/stride-4 stem conv, computed as space-to-depth(4)
    + a 2x2/stride-1 conv — the classic TPU reformulation for
    small-channel strided stems.  Measured on v5e at the bench shapes
    (BENCH_NOTES round-5 conv table), it is a NEGATIVE result for THIS
    architecture and stays off by default: the win only exists when the
    conv's input gradient is computed (3.4x there), but the stem's
    input is the uint8 frame — a gradient-free leaf — and with
    weights-only backward the direct form is 2.3x FASTER than s2d
    (XLA's native lowering already runs at the layer's output-lane
    ceiling, and the explicit 1 GB block transpose is pure added HBM
    traffic).  Kept because the measurement matters and because other
    torso stacks (an image-gradient consumer) may want it.

    Parameter tree, shapes, and initializers are IDENTICAL to the
    ``nn.Conv(32, (8, 8), strides=4, padding="SAME")`` it replaces —
    kernel [8, 8, C, F] + bias under the same module name — so
    checkpoints are interchangeable both ways, and the rearrangement is
    a pure re-indexing (numerically equal output up to contraction
    order; tests/test_networks.py)."""

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (8, 8, c, self.features))
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (self.features,))
        x, k = space_to_depth_rearrange(x, kernel)
        x, k, b = (jnp.asarray(t, self.dtype) for t in (x, k, bias))
        out = jax.lax.conv_general_dilated(
            x, k, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return out + b


class PallasStemConv(nn.Module):
    """A SAME-padded strided conv whose weight gradient is the Pallas
    im2col kernel (ops/conv_pallas.py stem_conv).  Forward and input
    gradient are XLA's own — numerically this IS the ``nn.Conv`` it
    replaces; only d/dW's lowering changes.  Parameter tree, shapes,
    and initializers are IDENTICAL to
    ``nn.Conv(features, (k, k), strides=s, padding="SAME")`` — kernel
    [k, k, C, F] + bias under the same module name — so checkpoints
    are interchangeable both ways (the _SpaceToDepthFirstConv
    contract, tests/test_conv_pallas.py pins it).

    Runs the identical kernel under the Pallas interpreter off-TPU, so
    CPU tier-1 exercises the same code path (the lstm_pallas.py
    precedent).  MXU operand precision follows ``dtype``: a bfloat16
    module runs bf16 operands with f32 accumulation; override with
    ``matmul_dtype`` to decouple them."""

    features: int
    kernel_size: int = 8
    stride: int = 4
    dtype: Any = jnp.float32
    matmul_dtype: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        # Lazy like _PallasCore: XLA-only consumers never pay (or
        # depend on) the Pallas TPU imports.
        from scalable_agent_tpu.ops import conv_pallas

        c = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (self.kernel_size, self.kernel_size, c, self.features))
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (self.features,))
        x, k, b = (jnp.asarray(t, self.dtype) for t in (x, kernel, bias))
        matmul_dtype = self.matmul_dtype or (
            "bfloat16" if jnp.dtype(self.dtype) == jnp.dtype(jnp.bfloat16)
            else "float32")
        out = conv_pallas.stem_conv(
            x, k, self.stride, jax.default_backend() != "tpu",
            matmul_dtype)
        return out + b


def _stem_backend(conv_backend):
    if conv_backend not in CONV_BACKENDS:
        raise ValueError(
            f"unknown conv_backend: {conv_backend!r} "
            f"(choices: {CONV_BACKENDS})")
    return conv_backend == "pallas"


class ShallowConvTorso(nn.Module):
    """(32,8,4), (64,4,2), (128,3,2) conv stack + Dense(256).

    Input [N, H, W, C] uint8; output [N, 256] float32.
    (reference: experiment.py:178-189)

    ``conv_backend`` ("xla" | "pallas") picks the stem conv's grad-W
    lowering (see CONV_BACKENDS); ``space_to_depth`` computes the stem
    conv in its space-to-depth form — same parameters, same linear
    map.  Default OFF: measured SLOWER for this torso, whose stem
    input needs no gradient (see _SpaceToDepthFirstConv for the
    measurement story).  Output dtype is ``dtype`` — the caller owns
    any upcast (the agent's heads return f32 logits/baseline).
    """

    dtype: Any = jnp.float32
    space_to_depth: bool = False
    conv_backend: str = "xla"

    @nn.compact
    def __call__(self, frame):
        pallas_stem = _stem_backend(self.conv_backend)
        x = _normalize_frame(frame, self.dtype)
        for i, (num_ch, filter_size, stride) in enumerate(
                [(32, 8, 4), (64, 4, 2), (128, 3, 2)]):
            if i == 0 and pallas_stem:
                x = PallasStemConv(
                    num_ch, filter_size, stride, dtype=self.dtype,
                    name="conv_0")(x)
            elif i == 0 and self.space_to_depth:
                x = _SpaceToDepthFirstConv(
                    num_ch, dtype=self.dtype, name="conv_0")(x)
            else:
                x = nn.Conv(
                    num_ch, (filter_size, filter_size),
                    strides=(stride, stride),
                    padding="SAME", dtype=self.dtype, name=f"conv_{i}")(x)
            x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.dtype, name="fc")(x)
        x = nn.relu(x)
        # The torso stays in its compute dtype end-to-end: under a
        # bfloat16 policy the downstream concat/core/head matmuls are
        # the point of the policy, and the agent upcasts its OUTPUTS
        # (logits/baseline) to f32 for the loss.  asarray is an
        # identity under the f32 default, so the golden-loss anchor
        # (tests/test_replay.py) is untouched.
        return jnp.asarray(x, self.dtype)


class _ResidualBlock(nn.Module):
    num_ch: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        block_input = x
        x = nn.relu(x)
        x = nn.Conv(self.num_ch, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv_0")(x)
        x = nn.relu(x)
        x = nn.Conv(self.num_ch, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv_1")(x)
        return x + block_input


class ResNetTorso(nn.Module):
    """Deep IMPALA ResNet: sections (16, 32, 32) x 2 residual blocks.

    Input [N, H, W, C] uint8; output [N, 256] in ``dtype`` (the agent
    owns the f32 upcast of its outputs — see ShallowConvTorso).
    (reference: experiment.py:156-176, commented-out variant)

    ``conv_backend="pallas"`` routes the stem (``downscale_0`` — like
    the shallow torso's conv_0, its input is the gradient-free frame)
    through the Pallas grad-W kernel; 3x3/stride-1 satisfies the
    kernel's K % S == 0 layout, so both torsos honor the one flag.
    """

    dtype: Any = jnp.float32
    conv_backend: str = "xla"

    @nn.compact
    def __call__(self, frame):
        pallas_stem = _stem_backend(self.conv_backend)
        x = _normalize_frame(frame, self.dtype)
        for i, (num_ch, num_blocks) in enumerate([(16, 2), (32, 2), (32, 2)]):
            if i == 0 and pallas_stem:
                x = PallasStemConv(num_ch, 3, 1, dtype=self.dtype,
                                   name="downscale_0")(x)
            else:
                x = nn.Conv(num_ch, (3, 3), padding="SAME",
                            dtype=self.dtype, name=f"downscale_{i}")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            for j in range(num_blocks):
                x = _ResidualBlock(num_ch, dtype=self.dtype,
                                   name=f"residual_{i}_{j}")(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.dtype, name="fc")(x)
        x = nn.relu(x)
        return jnp.asarray(x, self.dtype)


TORSOS = {
    "shallow": ShallowConvTorso,
    "resnet": ResNetTorso,
}
