"""Visual torsos for the IMPALA agent.

Two variants, matching the reference's two (one active, one commented-out):

- ``ShallowConvTorso``: the 3-layer conv stack the fork actually runs —
  (32, 8x8, /4), (64, 4x4, /2), (128, 3x3, /2), each ReLU, then
  flatten → Dense(256) → ReLU (reference: experiment.py:178-189).
- ``ResNetTorso``: the deep IMPALA ResNet the fork keeps commented out —
  3 sections of [conv3x3 → maxpool/2 → 2 residual blocks] with channels
  (16, 32, 32) (reference: experiment.py:156-176).

TPU notes: callers flatten [T, B] into one [T*B] batch before the torso so
every conv/matmul hits the MXU with the largest possible batch; compute can
run in bfloat16 (``dtype``) with float32 params.
"""

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


def _normalize_frame(frame, dtype):
    """uint8 HWC frame -> [0, 1] float.  (reference: experiment.py:153-155)"""
    return jnp.asarray(frame, dtype) / 255.0


def space_to_depth_rearrange(x, kernel):
    """The stem's space-to-depth re-indexing, as one pure function:
    ``(x [N,H,W,C], kernel [8,8,C,F]) -> (x' [N,bh,bw,16C],
    k' [2,2,16C,F])`` such that a VALID 2x2/stride-1 conv of the primed
    pair equals the SAME 8x8/stride-4 conv of the originals.  Shared by
    ``_SpaceToDepthFirstConv`` and bench.py's cross-round conv
    diagnostic so the published timing always measures the shipped
    formulation."""
    n, height, width, c = x.shape
    f = kernel.shape[-1]

    # SAME padding for kernel 8 / stride 4; the padded extent
    # (ceil(d/4) + 1) * 4 is always a multiple of the block size.
    def pads(size):
        total = max(0, (-(-size // 4) - 1) * 4 + 8 - size)
        return total // 2, total - total // 2

    x = jnp.pad(x, ((0, 0), pads(height), pads(width), (0, 0)))
    bh, bw = x.shape[1] // 4, x.shape[2] // 4
    x = x.reshape(n, bh, 4, bw, 4, c).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(n, bh, bw, 16 * c)
    # kernel (kh, kw) -> (block, in-block) pairs, matching the
    # (ph, pw, c) channel order the input rearrangement produced.
    k = kernel.reshape(2, 4, 2, 4, c, f)
    k = k.transpose(0, 2, 1, 3, 4, 5).reshape(2, 2, 16 * c, f)
    return x, k


class _SpaceToDepthFirstConv(nn.Module):
    """The torso's 8x8/stride-4 stem conv, computed as space-to-depth(4)
    + a 2x2/stride-1 conv — the classic TPU reformulation for
    small-channel strided stems.  Measured on v5e at the bench shapes
    (BENCH_NOTES round-5 conv table), it is a NEGATIVE result for THIS
    architecture and stays off by default: the win only exists when the
    conv's input gradient is computed (3.4x there), but the stem's
    input is the uint8 frame — a gradient-free leaf — and with
    weights-only backward the direct form is 2.3x FASTER than s2d
    (XLA's native lowering already runs at the layer's output-lane
    ceiling, and the explicit 1 GB block transpose is pure added HBM
    traffic).  Kept because the measurement matters and because other
    torso stacks (an image-gradient consumer) may want it.

    Parameter tree, shapes, and initializers are IDENTICAL to the
    ``nn.Conv(32, (8, 8), strides=4, padding="SAME")`` it replaces —
    kernel [8, 8, C, F] + bias under the same module name — so
    checkpoints are interchangeable both ways, and the rearrangement is
    a pure re-indexing (numerically equal output up to contraction
    order; tests/test_networks.py)."""

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (8, 8, c, self.features))
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (self.features,))
        x, k = space_to_depth_rearrange(x, kernel)
        x, k, b = (jnp.asarray(t, self.dtype) for t in (x, k, bias))
        out = jax.lax.conv_general_dilated(
            x, k, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return out + b


class ShallowConvTorso(nn.Module):
    """(32,8,4), (64,4,2), (128,3,2) conv stack + Dense(256).

    Input [N, H, W, C] uint8; output [N, 256] float32.
    (reference: experiment.py:178-189)

    ``space_to_depth`` computes the stem conv in its space-to-depth
    form — same parameters, same linear map.  Default OFF: measured
    SLOWER for this torso, whose stem input needs no gradient (see
    _SpaceToDepthFirstConv for the measurement story).
    """

    dtype: Any = jnp.float32
    space_to_depth: bool = False

    @nn.compact
    def __call__(self, frame):
        x = _normalize_frame(frame, self.dtype)
        for i, (num_ch, filter_size, stride) in enumerate(
                [(32, 8, 4), (64, 4, 2), (128, 3, 2)]):
            if i == 0 and self.space_to_depth:
                x = _SpaceToDepthFirstConv(
                    num_ch, dtype=self.dtype, name="conv_0")(x)
            else:
                x = nn.Conv(
                    num_ch, (filter_size, filter_size),
                    strides=(stride, stride),
                    padding="SAME", dtype=self.dtype, name=f"conv_{i}")(x)
            x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.dtype, name="fc")(x)
        x = nn.relu(x)
        return jnp.asarray(x, jnp.float32)


class _ResidualBlock(nn.Module):
    num_ch: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        block_input = x
        x = nn.relu(x)
        x = nn.Conv(self.num_ch, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv_0")(x)
        x = nn.relu(x)
        x = nn.Conv(self.num_ch, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv_1")(x)
        return x + block_input


class ResNetTorso(nn.Module):
    """Deep IMPALA ResNet: sections (16, 32, 32) x 2 residual blocks.

    Input [N, H, W, C] uint8; output [N, 256] float32.
    (reference: experiment.py:156-176, commented-out variant)
    """

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, frame):
        x = _normalize_frame(frame, self.dtype)
        for i, (num_ch, num_blocks) in enumerate([(16, 2), (32, 2), (32, 2)]):
            x = nn.Conv(num_ch, (3, 3), padding="SAME", dtype=self.dtype,
                        name=f"downscale_{i}")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            for j in range(num_blocks):
                x = _ResidualBlock(num_ch, dtype=self.dtype,
                                   name=f"residual_{i}_{j}")(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.dtype, name="fc")(x)
        x = nn.relu(x)
        return jnp.asarray(x, jnp.float32)


TORSOS = {
    "shallow": ShallowConvTorso,
    "resnet": ResNetTorso,
}
