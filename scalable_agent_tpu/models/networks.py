"""Visual torsos for the IMPALA agent.

Two variants, matching the reference's two (one active, one commented-out):

- ``ShallowConvTorso``: the 3-layer conv stack the fork actually runs —
  (32, 8x8, /4), (64, 4x4, /2), (128, 3x3, /2), each ReLU, then
  flatten → Dense(256) → ReLU (reference: experiment.py:178-189).
- ``ResNetTorso``: the deep IMPALA ResNet the fork keeps commented out —
  3 sections of [conv3x3 → maxpool/2 → 2 residual blocks] with channels
  (16, 32, 32) (reference: experiment.py:156-176).

TPU notes: callers flatten [T, B] into one [T*B] batch before the torso so
every conv/matmul hits the MXU with the largest possible batch; compute can
run in bfloat16 (``dtype``) with float32 params.
"""

from typing import Any

import jax.numpy as jnp
from flax import linen as nn


def _normalize_frame(frame, dtype):
    """uint8 HWC frame -> [0, 1] float.  (reference: experiment.py:153-155)"""
    return jnp.asarray(frame, dtype) / 255.0


class ShallowConvTorso(nn.Module):
    """(32,8,4), (64,4,2), (128,3,2) conv stack + Dense(256).

    Input [N, H, W, C] uint8; output [N, 256] float32.
    (reference: experiment.py:178-189)
    """

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, frame):
        x = _normalize_frame(frame, self.dtype)
        for i, (num_ch, filter_size, stride) in enumerate(
                [(32, 8, 4), (64, 4, 2), (128, 3, 2)]):
            x = nn.Conv(
                num_ch, (filter_size, filter_size), strides=(stride, stride),
                padding="SAME", dtype=self.dtype, name=f"conv_{i}")(x)
            x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.dtype, name="fc")(x)
        x = nn.relu(x)
        return jnp.asarray(x, jnp.float32)


class _ResidualBlock(nn.Module):
    num_ch: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        block_input = x
        x = nn.relu(x)
        x = nn.Conv(self.num_ch, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv_0")(x)
        x = nn.relu(x)
        x = nn.Conv(self.num_ch, (3, 3), padding="SAME", dtype=self.dtype,
                    name="conv_1")(x)
        return x + block_input


class ResNetTorso(nn.Module):
    """Deep IMPALA ResNet: sections (16, 32, 32) x 2 residual blocks.

    Input [N, H, W, C] uint8; output [N, 256] float32.
    (reference: experiment.py:156-176, commented-out variant)
    """

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, frame):
        x = _normalize_frame(frame, self.dtype)
        for i, (num_ch, num_blocks) in enumerate([(16, 2), (32, 2), (32, 2)]):
            x = nn.Conv(num_ch, (3, 3), padding="SAME", dtype=self.dtype,
                        name=f"downscale_{i}")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            for j in range(num_blocks):
                x = _ResidualBlock(num_ch, dtype=self.dtype,
                                   name=f"residual_{i}_{j}")(x)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.dtype, name="fc")(x)
        x = nn.relu(x)
        return jnp.asarray(x, jnp.float32)


TORSOS = {
    "shallow": ShallowConvTorso,
    "resnet": ResNetTorso,
}
