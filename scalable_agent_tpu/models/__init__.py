from scalable_agent_tpu.models.agent import (
    ImpalaAgent,
    actor_step,
    initial_state,
)
from scalable_agent_tpu.models.instruction import hash_instruction
from scalable_agent_tpu.models.networks import CONV_BACKENDS
