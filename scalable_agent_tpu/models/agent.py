"""The IMPALA agent: conv torso + optional language LSTM + LSTM core + heads.

Functional parity with the reference ``Agent`` (reference:
experiment.py:109-237), re-designed for TPU/XLA:

- The reference unrolls the LSTM with a *Python loop over tf.unstack'd
  timesteps* because the per-step ``tf.where(done)`` state reset rules out
  CuDNN (reference: experiment.py:225-237 and its own comment).  Here the
  unroll is a single ``nn.scan``/``lax.scan`` — XLA compiles it to one fused
  on-device loop, and the done-reset is a multiply by ``(1 - done)`` (the
  initial state is zeros, so "reset to initial" == "zero the carry").

- The torso runs on the whole [T*B] flattened batch at once (one big conv
  batch for the MXU) instead of the reference's per-timestep BatchApply.

- Sampling is separated from the forward pass: the model returns logits and
  baseline; ``actor_step`` samples with an explicit PRNG key (the reference
  samples with ``tf.multinomial`` inside ``_head``, experiment.py:205-208 —
  implicit-RNG ops don't exist in JAX).
"""

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from scalable_agent_tpu.models.instruction import InstructionEncoder
from scalable_agent_tpu.models.networks import TORSOS
from scalable_agent_tpu.ops import distributions
from scalable_agent_tpu.types import (
    AgentOutput,
    AgentState,
    StepOutput,
    map_structure,
)

CORE_SIZE = 256  # reference: experiment.py:118


def initial_state(batch_size: int, core_size: int = CORE_SIZE) -> AgentState:
    """Zero LSTM carry.  (reference: experiment.py:120-121)"""
    return AgentState(
        c=jnp.zeros((batch_size, core_size), jnp.float32),
        h=jnp.zeros((batch_size, core_size), jnp.float32),
    )


class _CoreStep(nn.Module):
    """One LSTM-core step with done-triggered state reset.

    The reset happens *before* the cell step, using the done flag of the
    incoming env output — matching the reference exactly
    (reference: experiment.py:230-234).
    """

    features: int

    @nn.compact
    def __call__(self, carry, xs):
        torso_out, done = xs
        keep = (1.0 - done)[:, None]  # initial state is zeros ⇒ reset = zero
        carry = jax.tree_util.tree_map(lambda c: keep * c, carry)
        new_carry, y = nn.OptimizedLSTMCell(self.features, name="lstm")(
            carry, torso_out)
        return new_carry, y


class _GateParams(nn.Module):
    """One gate's kernel (+bias), mirroring the param tree that
    ``flax.linen.OptimizedLSTMCell`` builds via its DenseParams
    children — same names, shapes, and initializers, so both core
    implementations share one checkpoint format."""

    features: int
    in_features: int
    use_bias: bool
    kernel_init: Any

    @nn.compact
    def __call__(self):
        kernel = self.param("kernel", self.kernel_init,
                            (self.in_features, self.features))
        bias = (self.param("bias", nn.initializers.zeros_init(),
                           (self.features,))
                if self.use_bias else None)
        return kernel, bias


class _PallasCoreParams(nn.Module):
    """Declares the 8 OptimizedLSTMCell gate params (ii/if/ig/io input
    kernels, hi/hf/hg/ho recurrent kernels + biases) and returns them
    concatenated as (Wi [D,4H], Wh [H,4H], b [4H]) in (i,f,g,o) order —
    the layout ops/lstm_pallas.lstm_unroll consumes."""

    features: int
    in_features: int

    @nn.compact
    def __call__(self):
        ks_i, ks_h, bs = [], [], []
        for comp in "ifgo":
            k, _ = _GateParams(
                self.features, self.in_features, False,
                nn.initializers.lecun_normal(), name=f"i{comp}")()
            ks_i.append(k)
            k, b = _GateParams(
                self.features, self.features, True,
                nn.initializers.orthogonal(), name=f"h{comp}")()
            ks_h.append(k)
            bs.append(b)
        return (jnp.concatenate(ks_i, axis=-1),
                jnp.concatenate(ks_h, axis=-1),
                jnp.concatenate(bs, axis=-1))


class _PallasCore(nn.Module):
    """The fused Pallas done-reset LSTM unroll (ops/lstm_pallas.py),
    parameter-compatible with the ``nn.scan(_CoreStep)`` path: both
    produce params under core/lstm/{ii..ho}."""

    features: int
    matmul_dtype: str = "float32"

    @nn.compact
    def __call__(self, carry, x, done):
        # Lazy like vtrace.py's pallas path: XLA-only consumers never
        # pay (or depend on) the Pallas TPU imports.
        from scalable_agent_tpu.ops import lstm_pallas

        wi, wh, b = _PallasCoreParams(
            self.features, x.shape[-1], name="lstm")()
        ys, (ct, ht) = lstm_pallas.lstm_unroll(
            jnp.asarray(x, jnp.float32), done, carry[0], carry[1],
            wi, wh, b, jax.default_backend() != "tpu",
            self.matmul_dtype)
        return (ct, ht), ys


class ImpalaAgent(nn.Module):
    """ConvNet/ResNet torso + LSTM(256) core + policy/baseline heads.

    ``__call__`` is the whole-trajectory unroll (the reference's
    ``Agent.unroll``, experiment.py:219-237), shared verbatim between actor
    inference (T=1) and learner training (T=unroll_length) — exactly as the
    reference shares one ``_build``/``unroll``.

    Inputs are time-major: actions [T, B] int32, env_outputs with
    reward [T, B], done [T, B], observation.frame [T, B, H, W, C] uint8,
    observation.instruction [T, B, L] int32 or None.
    """

    num_actions: int = 0
    torso_type: str = "shallow"
    use_instruction: bool = False
    core_size: int = CORE_SIZE
    # The ONE compute-dtype policy (f32 default; bfloat16 on TPU via
    # --compute_dtype): params stay float32, the torso/concat/head
    # matmuls run in compute_dtype, and the agent's OUTPUTS
    # (policy_logits, baseline) are upcast to f32 so every loss /
    # V-trace / optimizer reduction downstream stays f32.  The XLA
    # LSTM core is the one documented exception: flax's cell promotes
    # to the f32 params' dtype (the Pallas core's matmul precision is
    # core_matmul_dtype's job instead).
    compute_dtype: Any = jnp.float32
    # LSTM core implementation: "xla" = nn.scan over OptimizedLSTMCell;
    # "pallas" = the fused single-program unroll (ops/lstm_pallas.py).
    # Parameter trees are identical, so checkpoints are interchangeable.
    core_impl: str = "xla"
    # Operand precision for the Pallas core's gate/BPTT matmuls:
    # "float32" (bit-exact vs the flax cell) or "bfloat16" (2x MXU
    # rate, f32 accumulation).  Ignored by the xla core.
    core_matmul_dtype: str = "float32"
    # Stem-conv grad-W lowering: "xla" (plain nn.Conv) or "pallas"
    # (ops/conv_pallas.py im2col MXU kernel; interpret mode off-TPU).
    # Identical parameter trees — checkpoints are interchangeable.
    conv_backend: str = "xla"
    # Rematerialize the torso in the backward pass (jax.checkpoint via
    # nn.remat).  The fused single-forward update keeps the behaviour
    # logits and the loss's outputs from ONE unroll; remat keeps that
    # from costing peak activation memory at B=256.  Default OFF so
    # the default-path jaxpr (and the golden-loss anchor) is
    # untouched; the learner turns it on with the fused forward.
    remat_torso: bool = False
    # Composite policies: a TupleSpace mixing Discrete/Discretized
    # components (reference: TupleActionDistribution,
    # algorithms/utils/action_distributions.py:111-201).  When unset, the
    # policy is one Discrete(num_actions) head, the original layout.
    action_space: Optional[Any] = None

    @property
    def dist_spec(self) -> distributions.DistributionSpec:
        if self.action_space is not None:
            return distributions.spec_for_space(self.action_space)
        return distributions.DistributionSpec(sizes=(self.num_actions,))

    @property
    def num_logits(self) -> int:
        return self.dist_spec.num_logits

    @property
    def num_action_components(self) -> int:
        return self.dist_spec.num_components

    def zero_actions(self, batch: int) -> jnp.ndarray:
        """All-zeros last-action input at the agent's action layout
        ([B] for plain Discrete, [B, K] for composites)."""
        k = self.num_action_components
        shape = (batch,) if k == 1 else (batch, k)
        return jnp.zeros(shape, jnp.int32)

    @nn.compact
    def __call__(
        self,
        actions,
        env_outputs: StepOutput,
        core_state: AgentState,
    ) -> Tuple[Tuple[jax.Array, jax.Array], AgentState]:
        unroll_len, batch = actions.shape[:2]
        reward, _, done, observation = env_outputs
        frame = observation.frame
        spec = self.dist_spec

        # ---- Torso over the merged [T*B] batch (reference: _torso,
        # experiment.py:148-198, but batched over all timesteps at once).
        flat = lambda x: x.reshape((unroll_len * batch,) + x.shape[2:])
        torso_cls = TORSOS[self.torso_type]
        if self.remat_torso:
            # jax.checkpoint on the torso: activations are recomputed
            # in the backward pass instead of living across the whole
            # unroll+loss — what keeps the fused single-forward update
            # flat on peak memory at B=256.
            torso_cls = nn.remat(torso_cls)
        torso = torso_cls(dtype=self.compute_dtype,
                          conv_backend=self.conv_backend, name="convnet")
        conv_out = torso(flat(frame))  # [T*B, 256] compute_dtype

        clipped_reward = jnp.clip(
            jnp.asarray(flat(reward), jnp.float32), -1.0, 1.0)[:, None]
        one_hot_last_action = distributions.one_hot_actions(
            flat(actions), spec)
        parts = [conv_out, clipped_reward, one_hot_last_action]
        if self.use_instruction:
            instruction = observation.instruction
            parts.append(
                InstructionEncoder(name="instruction")(flat(instruction)))
        # Mixed-dtype concat promotes to f32; the policy casts back so
        # the core consumes compute_dtype activations (identity under
        # the f32 default — the golden anchor sees the same jaxpr
        # values).
        torso_out = jnp.asarray(
            jnp.concatenate(parts, axis=-1), self.compute_dtype)
        torso_out = torso_out.reshape((unroll_len, batch, -1))

        # ---- LSTM core: one fused scan over time with done-reset
        # (reference: experiment.py:228-237).
        carry = (core_state.c, core_state.h)
        done_f32 = jnp.asarray(done, jnp.float32)
        if self.core_impl == "pallas":
            carry, core_outputs = _PallasCore(
                self.core_size, matmul_dtype=self.core_matmul_dtype,
                name="core")(carry, torso_out, done_f32)
        elif self.core_impl == "xla":
            scan = nn.scan(
                _CoreStep,
                variable_broadcast="params",
                split_rngs={"params": False},
                in_axes=0,
                out_axes=0,
            )
            carry, core_outputs = scan(self.core_size, name="core")(
                carry, (torso_out, done_f32))
        else:
            raise ValueError(f"unknown core_impl: {self.core_impl!r}")
        new_state = AgentState(c=carry[0], h=carry[1])

        # ---- Heads (reference: _head, experiment.py:200-210), again on the
        # merged batch.
        core_flat = core_outputs.reshape((unroll_len * batch, -1))
        num_logits = self.num_logits
        # Heads run at compute_dtype; the OUTPUTS are upcast to f32 —
        # the loss/V-trace/optimizer side of the dtype policy never
        # sees bf16 (under the f32 default both casts are identities).
        policy_logits = jnp.asarray(
            nn.Dense(num_logits, dtype=self.compute_dtype,
                     name="policy_logits")(core_flat),
            jnp.float32).reshape((unroll_len, batch, num_logits))
        baseline = jnp.asarray(
            nn.Dense(1, dtype=self.compute_dtype, name="baseline")(
                core_flat),
            jnp.float32).reshape((unroll_len, batch))
        return (policy_logits, baseline), new_state


def actor_step(
    agent: ImpalaAgent,
    params,
    rng: jax.Array,
    last_action,
    env_output: StepOutput,
    core_state: AgentState,
) -> Tuple[AgentOutput, AgentState]:
    """One batched inference step: unroll T=1, sample an action.

    last_action [B] int32, env_output batched [B, ...].  Returns
    (AgentOutput with action [B], new core state).  Jit this (it is pure);
    the batching service calls it on gathered actor requests.
    (reference: Agent._build, experiment.py:212-217 + _head sampling
    :205-208)
    """
    expand = lambda x: x[None] if x is not None else None
    actions = expand(last_action)
    env_outputs = map_structure(expand, env_output)
    (policy_logits, baseline), new_state = agent.apply(
        params, actions, env_outputs, core_state)
    policy_logits = policy_logits[0]  # [B, num_logits]
    baseline = baseline[0]  # [B]
    # Composite spaces sample every component ([B, K]); plain Discrete
    # keeps the [B] layout.
    action = distributions.sample(rng, policy_logits, agent.dist_spec)
    return (
        AgentOutput(
            action=jnp.asarray(action, jnp.int32),
            policy_logits=policy_logits,
            baseline=baseline,
        ),
        new_state,
    )
