"""Minimal action/observation space algebra (no gym dependency).

Covers what the reference uses from gym plus its own extension: Discrete,
Box, Tuple composites mixing the two, and ``Discretized`` — a Discrete whose
indices map onto a uniform grid of a continuous range (reference:
algorithms/spaces/discretized.py:4-14, envs/doom/action_space.py:13-138).

gymnasium interop: ``from_gymnasium`` converts a gymnasium space so
gymnasium-backed simulators (ALE et al.) plug into the same actor runtime.
"""

from typing import Sequence, Tuple

import numpy as np


class Space:
    def sample(self, rng: np.random.Generator):
        raise NotImplementedError

    def contains(self, x) -> bool:
        raise NotImplementedError


class Discrete(Space):
    """{0, ..., n-1}."""

    def __init__(self, n: int):
        if n <= 0:
            raise ValueError(f"Discrete needs n > 0, got {n}")
        self.n = int(n)

    def sample(self, rng):
        return int(rng.integers(self.n))

    def contains(self, x):
        return 0 <= int(x) < self.n

    def __eq__(self, other):
        return type(other) is type(self) and other.n == self.n

    def __repr__(self):
        return f"Discrete({self.n})"


class Discretized(Discrete):
    """Discrete(n) whose indices map to a uniform grid on [min, max].

    (reference: algorithms/spaces/discretized.py:4-14)
    """

    def __init__(self, n: int, min_action: float, max_action: float):
        super().__init__(n)
        if n < 2:
            raise ValueError("Discretized needs n >= 2 for a grid")
        self.min_action = float(min_action)
        self.max_action = float(max_action)

    def __eq__(self, other):
        return (type(other) is type(self) and other.n == self.n
                and other.min_action == self.min_action
                and other.max_action == self.max_action)

    def to_continuous(self, discrete_action):
        step = (self.max_action - self.min_action) / (self.n - 1)
        return self.min_action + int(discrete_action) * step

    def __repr__(self):
        return (f"Discretized({self.n}, "
                f"[{self.min_action}, {self.max_action}])")


class Box(Space):
    """Continuous box with per-element bounds."""

    def __init__(self, low, high, shape=None, dtype=np.float32):
        if shape is None:
            shape = np.broadcast(np.asarray(low), np.asarray(high)).shape
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.low = np.broadcast_to(np.asarray(low, self.dtype), self.shape)
        self.high = np.broadcast_to(np.asarray(high, self.dtype), self.shape)

    def sample(self, rng):
        return rng.uniform(self.low, self.high, self.shape).astype(self.dtype)

    def contains(self, x):
        x = np.asarray(x)
        return (x.shape == self.shape and np.all(x >= self.low)
                and np.all(x <= self.high))

    def __eq__(self, other):
        return (isinstance(other, Box) and other.shape == self.shape
                and np.array_equal(other.low, self.low)
                and np.array_equal(other.high, self.high))

    def __repr__(self):
        return f"Box{self.shape}"


class TupleSpace(Space):
    """Composite of subspaces; actions are tuples.

    (the reference's gym.spaces.Tuple usage, envs/doom/action_space.py)
    """

    def __init__(self, spaces: Sequence[Space]):
        self.spaces = tuple(spaces)

    def sample(self, rng):
        return tuple(s.sample(rng) for s in self.spaces)

    def contains(self, x):
        return (len(x) == len(self.spaces)
                and all(s.contains(v) for s, v in zip(self.spaces, x)))

    def __eq__(self, other):
        return isinstance(other, TupleSpace) and other.spaces == self.spaces

    def __repr__(self):
        return f"TupleSpace{self.spaces}"


def calc_num_logits(space: Space) -> int:
    """Logits needed for a categorical (product) policy over ``space``.

    (reference: algorithms/utils/action_distributions.py:10-17)
    """
    if isinstance(space, Discrete):
        return space.n
    if isinstance(space, TupleSpace):
        return sum(calc_num_logits(s) for s in space.spaces)
    raise NotImplementedError(f"no categorical policy over {space!r}")


def calc_num_actions(space: Space) -> int:
    """Number of action components an agent must emit for ``space``."""
    if isinstance(space, Discrete):
        return 1
    if isinstance(space, TupleSpace):
        return sum(calc_num_actions(s) for s in space.spaces)
    raise NotImplementedError(f"no action layout for {space!r}")


def from_gymnasium(space) -> Space:
    """Convert a gymnasium space into ours (Discrete/Box/Tuple only)."""
    import gymnasium

    if isinstance(space, gymnasium.spaces.Discrete):
        return Discrete(int(space.n))
    if isinstance(space, gymnasium.spaces.Box):
        return Box(space.low, space.high, space.shape, space.dtype)
    if isinstance(space, gymnasium.spaces.Tuple):
        return TupleSpace([from_gymnasium(s) for s in space.spaces])
    raise NotImplementedError(f"unsupported gymnasium space {space!r}")
