"""The VizDoom simulator adapter.

The role of the reference's ``VizdoomEnv`` (reference:
envs/doom/doom_gym.py:52-562) on this framework's ``Environment``
protocol.  Behaviors reproduced:

- Lazy game construction: the ``vizdoom`` package imports on first
  ``reset``, and resolution/config may be adjusted by wrappers up until
  then (doom_gym.py:80-82, observation_space.py:10-48).
- Scenario configs load by file name; ``available_game_variables`` are
  parsed out of the .cfg so per-step info dicts carry named variables
  (doom_gym.py:200-223, 228-233).
- Composite action conversion: each Discrete subspace one-hots with
  index 0 as no-op, ``Discretized`` maps its index onto a continuous
  grid, ``Box`` components scale by the delta factor 7.5
  (doom_gym.py:277-308).
- ``skip_frames`` is passed to ``make_action`` — the simulator repeats
  natively, so this env declares ``native_action_repeats``
  (doom_gym.py:321-341, environments.py:111 for the DMLab analog).
- Black screen on the terminal step, info carried from the last live
  frame (doom_gym.py:223-226, 343-348).
- The VizDoom stale-variable bug workaround: DEATHCOUNT / HITCOUNT /
  DAMAGECOUNT don't reset on ``new_episode``; values from the previous
  episode are subtracted (doom_gym.py:310-319).

Scenario assets are NOT vendored: config files resolve against (in
order) an explicit ``scenarios_dir``, ``$DOOM_SCENARIOS_DIR``, the
installed ``vizdoom`` package's ``scenarios/`` directory, and a
``scenarios/`` directory next to this file, so the standard scenarios
work out of the box with a stock vizdoom install.
"""

import os
import re
from typing import Dict, Optional

import numpy as np

from scalable_agent_tpu.envs.core import Environment, make_observation
from scalable_agent_tpu.envs.spaces import (
    Box,
    Discrete,
    Discretized,
    TupleSpace,
)
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.types import Observation

# make_action delta-button scaling for Box components
# (reference: doom_gym.py:88)
DELTA_ACTIONS_SCALING_FACTOR = 7.5

_BUGGED_EPISODE_VARS = ("DEATHCOUNT", "HITCOUNT", "DAMAGECOUNT")


def resolve_scenario_path(config_file: str,
                          scenarios_dir: Optional[str] = None) -> str:
    """Find a scenario .cfg by name (see module docstring for order)."""
    candidates = []
    if scenarios_dir:
        candidates.append(os.path.join(scenarios_dir, config_file))
    env_dir = os.environ.get("DOOM_SCENARIOS_DIR")
    if env_dir:
        candidates.append(os.path.join(env_dir, config_file))
    try:
        import vizdoom

        candidates.append(os.path.join(
            os.path.dirname(vizdoom.__file__), "scenarios", config_file))
    except ImportError:
        pass
    candidates.append(os.path.join(
        os.path.dirname(__file__), "scenarios", config_file))
    for path in candidates:
        if os.path.isfile(path):
            return path
    raise FileNotFoundError(
        f"Doom scenario {config_file!r} not found; searched {candidates}. "
        f"Point scenarios_dir or $DOOM_SCENARIOS_DIR at a directory "
        f"containing the scenario .cfg/.wad files.")


def parse_variable_indices(config_path: str) -> Dict[str, int]:
    """available_game_variables = { A B C } -> {'A': 0, 'B': 1, 'C': 2}.

    (reference: doom_gym.py:200-223)
    """
    pattern = re.compile(r"available_game_variables\s*=\s*\{(.*)\}")
    indices: Dict[str, int] = {}
    with open(config_path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("#"):
                continue
            match = pattern.match(line)
            if match:
                names = match.group(1).split()
                indices.update({name: i for i, name in enumerate(names)})
                break
    return indices


def convert_actions(action_space, actions) -> list:
    """Composite gym-style action -> flattened VizDoom button list.

    (reference: doom_gym.py:277-308)
    """
    if isinstance(action_space, TupleSpace):
        spaces = action_space.spaces
    else:
        spaces = (action_space,)
        actions = (actions,)
    flattened = []
    for space, action in zip(spaces, actions):
        if isinstance(space, Box):
            flattened.extend(
                float(a) * DELTA_ACTIONS_SCALING_FACTOR
                for a in np.asarray(action).reshape(-1))
        elif isinstance(space, Discretized):
            flattened.append(space.to_continuous(action))
        elif isinstance(space, Discrete):
            one_hot = [0] * (space.n - 1)  # index 0 is the no-op
            if int(action) > 0:
                one_hot[int(action) - 1] = 1
            flattened.extend(one_hot)
        else:
            raise NotImplementedError(
                f"action subspace {space!r} is not supported")
    return flattened


class DoomEnv(Environment):
    """One VizDoom game instance behind the Environment protocol."""

    def __init__(
        self,
        action_space,
        config_file: str,
        skip_frames: int = 1,
        scenarios_dir: Optional[str] = None,
        async_mode: bool = False,
        record_to: Optional[str] = None,
        coord_limits=None,
        max_histogram_length: int = 200,
        show_automap: bool = False,
    ):
        self.action_space = action_space
        self.config_path = resolve_scenario_path(config_file, scenarios_dir)
        self.variable_indices = parse_variable_indices(self.config_path)
        self.skip_frames = max(1, int(skip_frames))
        # the simulator repeats natively via make_action(_, skip_frames)
        self.native_action_repeats = self.skip_frames
        self.async_mode = async_mode
        self.record_to = record_to
        self.game = None
        self._seed = 0
        self._rng = np.random.default_rng(0)
        # Adjustable until the first reset (SetDoomResolution wrapper).
        self.screen_w, self.screen_h, self.channels = 640, 480, 3
        self.screen_resolution_name = "RES_640X480"
        self._black = None
        self._prev_info: Dict[str, float] = {}
        self._last_episode_info: Optional[Dict[str, float]] = None
        self._num_episodes = 0
        # Multiplayer hooks (set by subclasses / wrappers).
        self.is_multiplayer = False
        self.bot_difficulty_mean = None
        self.bot_difficulty_std = 10

        # Positional-coverage histogram (reference: doom_gym.py:102-117,
        # 424-438): pass coord_limits=(x0, y0, x1, y1) to track where
        # the agent has been, aspect-scaled to max_histogram_length
        # bins on the longer side.  Needs POSITION_X/POSITION_Y among
        # available_game_variables.
        self.coord_limits = coord_limits
        self.max_histogram_length = int(max_histogram_length)
        self.current_histogram = self.previous_histogram = None
        if coord_limits:
            x = coord_limits[2] - coord_limits[0]
            y = coord_limits[3] - coord_limits[1]
            if x > y:
                len_x = self.max_histogram_length
                len_y = max(1, int(y / x * self.max_histogram_length))
            else:
                len_y = self.max_histogram_length
                len_x = max(1, int(x / y * self.max_histogram_length))
            self.current_histogram = np.zeros((len_x, len_y), np.int32)
            self.previous_histogram = np.zeros_like(self.current_histogram)

        # Engine top-down view (reference: doom_gym.py:171-189).
        self.show_automap = show_automap

    # -- spec --------------------------------------------------------------

    @property
    def observation_spec(self) -> Observation:
        return Observation(
            frame=TensorSpec(
                (self.screen_h, self.screen_w, self.channels),
                np.uint8, "frame"))

    def set_resolution(self, width: int, height: int, name: str):
        if self.game is not None:
            raise RuntimeError(
                "resolution must be set before the game initializes")
        self.screen_w, self.screen_h = width, height
        self.screen_resolution_name = name

    # -- lifecycle ---------------------------------------------------------

    def seed(self, seed: Optional[int]):
        if seed is not None:
            self._seed = int(seed)
            self._rng = np.random.default_rng(self._seed)

    def _make_game(self):
        """Build + init the DoomGame (reference: doom_gym.py:151-195)."""
        import vizdoom

        game = vizdoom.DoomGame()
        game.load_config(self.config_path)
        game.set_screen_resolution(
            getattr(vizdoom.ScreenResolution, self.screen_resolution_name))
        game.set_seed(int(self._rng.integers(0, 2**31 - 1)))
        game.set_window_visible(False)
        game.set_mode(vizdoom.Mode.ASYNC_PLAYER if self.async_mode
                      else vizdoom.Mode.PLAYER)
        if self.show_automap:
            # Object-level top-down map, centered, fixed orientation
            # (reference: doom_gym.py:171-189).
            game.set_automap_buffer_enabled(True)
            game.set_automap_mode(vizdoom.AutomapMode.OBJECTS)
            game.set_automap_rotate(False)
            game.set_automap_render_textures(False)
            game.add_game_args("+viz_am_center 1")
            game.add_game_args("+am_backcolor ffffff")
            game.add_game_args("+am_tswallcolor dddddd")
            game.add_game_args("+am_yourcolor ffffff")
            game.add_game_args("+am_cheat 0")
            game.add_game_args("+am_thingcolor 0000ff")
            game.add_game_args("+am_thingcolor_item 00ff00")
        self._customize_game(game)
        game.init()
        return game

    def _customize_game(self, game):
        """Subclass hook (multiplayer adds host/join args here)."""

    def _ensure_game(self):
        if self.game is None:
            self.game = self._init_serialized()

    def _init_serialized(self):
        """First game init, serialized ACROSS PROCESSES with a file
        lock: many workers initializing VizDoom simultaneously race on
        engine-side file extraction (reference: environments_doom.py:
        46-57 — FileLock + 10s-timeout retry loop).  fcntl.flock keeps
        it dependency-free.  Any environment where the lock cannot
        work — no fcntl (non-POSIX), unwritable lock path (another
        user's file), flock-unsupported filesystem — falls back to an
        UNLOCKED init, which is exactly the pre-lock behavior.
        """
        import errno
        import tempfile
        import time

        try:
            import fcntl
        except ImportError:
            return self._make_game()
        # Per-user path: /tmp is world-shared and another user's lock
        # file would be unwritable.
        lock_path = os.path.join(
            tempfile.gettempdir(),
            f"scalable_agent_tpu_doom_init_{os.getuid()}.lock")
        try:
            lock_file = open(lock_path, "a")
        except OSError:
            return self._make_game()
        attempt = 0
        with lock_file:
            while True:
                attempt += 1
                try:
                    fcntl.flock(lock_file, fcntl.LOCK_EX | fcntl.LOCK_NB)
                except OSError as exc:
                    if exc.errno not in (errno.EWOULDBLOCK, errno.EAGAIN,
                                         errno.EACCES):
                        # flock unsupported here (e.g. some NFS mounts):
                        # don't spin forever on an error that will never
                        # clear.
                        return self._make_game()
                    if attempt % 100 == 0:
                        from scalable_agent_tpu.utils import log

                        log.info(
                            "another process holds the Doom init lock "
                            "(attempt %d)", attempt)
                    time.sleep(0.1)
                    continue
                try:
                    return self._make_game()
                finally:
                    fcntl.flock(lock_file, fcntl.LOCK_UN)

    # -- helpers -----------------------------------------------------------

    def _black_screen(self) -> np.ndarray:
        if self._black is None or self._black.shape[:2] != (
                self.screen_h, self.screen_w):
            self._black = np.zeros(
                (self.screen_h, self.screen_w, self.channels), np.uint8)
        return self._black

    def _frame_from_state(self, state) -> np.ndarray:
        buf = state.screen_buffer
        if buf is None:
            return self._black_screen()
        return np.transpose(np.asarray(buf), (1, 2, 0))

    def _variables_dict(self, state) -> Dict[str, float]:
        values = state.game_variables
        if values is None:
            return {}
        return {name: float(values[idx])
                for name, idx in self.variable_indices.items()}

    def get_info(self, variables: Optional[Dict[str, float]] = None
                 ) -> Dict[str, float]:
        """Latest game-variable info (wrappers read this on reset —
        reference: doom_gym.py:228-233, additional_input.py:88-91)."""
        if variables is None:
            return dict(self._prev_info)
        return dict(variables)

    def _update_histogram(self, info: Dict[str, float], eps: float = 1e-8):
        """Bin the agent's (x, y) into the coverage histogram
        (reference: doom_gym.py:424-438)."""
        if self.current_histogram is None:
            return
        if "POSITION_X" not in info or "POSITION_Y" not in info:
            return
        x0, y0, x1, y1 = self.coord_limits
        dx = (info["POSITION_X"] - x0) / (x1 - x0)
        dy = (info["POSITION_Y"] - y0) / (y1 - y0)
        ix = int((dx - eps) * self.current_histogram.shape[0])
        iy = int((dy - eps) * self.current_histogram.shape[1])
        ix = min(max(ix, 0), self.current_histogram.shape[0] - 1)
        iy = min(max(iy, 0), self.current_histogram.shape[1] - 1)
        self.current_histogram[ix, iy] += 1

    def get_automap_buffer(self) -> Optional[np.ndarray]:
        """HWC automap frame, or None once the episode finished
        (reference: doom_gym.py:415-422)."""
        if self.game is None or self.game.is_episode_finished():
            return None
        state = self.game.get_state()
        if state is None or state.automap_buffer is None:
            return None
        return np.transpose(np.asarray(state.automap_buffer), (1, 2, 0))

    def _fix_bugged_variables(self, info: Dict[str, float]):
        """Subtract previous-episode values of counters VizDoom fails to
        reset on new_episode (reference: doom_gym.py:310-319)."""
        if self._last_episode_info is None:
            return
        for name in _BUGGED_EPISODE_VARS:
            if name in info:
                info[name] -= self._last_episode_info.get(name, 0.0)

    # -- protocol ----------------------------------------------------------

    def reset(self):
        self._ensure_game()
        if self.record_to is not None and not self.is_multiplayer:
            os.makedirs(self.record_to, exist_ok=True)
            demo = os.path.join(
                self.record_to, f"ep_{self._num_episodes:03d}_rec.lmp")
            self.game.new_episode(demo)
        else:
            self.game.new_episode()
        state = self.game.get_state()
        self._last_episode_info = dict(self._prev_info)
        self._prev_info = {}
        self._num_episodes += 1
        if self.current_histogram is not None:
            self.previous_histogram = self.current_histogram.copy()
            self.current_histogram.fill(0)
        frame = (self._frame_from_state(state) if state is not None
                 else self._black_screen())
        return make_observation(frame)

    def _post_action(self, reward, num_frames: int):
        """Shared bookkeeping after the game advanced (by make_action
        OR by a human in spectator mode): frame/info assembly, info
        carry, histogram, stale-variable fix."""
        done = self.game.is_episode_finished()
        info: Dict[str, float] = {"num_frames": num_frames}
        if not done:
            state = self.game.get_state()
            frame = self._frame_from_state(state)
            variables = self._variables_dict(state)
            info.update(self.get_info(variables))
            self._prev_info = dict(info)
            self._update_histogram(info)
        else:
            frame = self._black_screen()
            # done=True forbids get_state; report the last live info
            # (reference: doom_gym.py:343-348)
            info.update(self._prev_info)
        self._fix_bugged_variables(info)
        return (make_observation(frame), np.float32(reward), bool(done),
                info)

    def step(self, action):
        flattened = convert_actions(self.action_space, action)
        reward = self.game.make_action(flattened, self.skip_frames)
        return self._post_action(reward, self.skip_frames)

    def step_human(self):
        """One transition driven by the human's own input (game in a
        SPECTATOR mode); same bookkeeping as a policy step.  In ASYNC
        modes the engine runs on its own clock, so num_frames is the
        MEASURED tic delta, not an assumed 1."""
        before_tic = self.game.get_episode_time()
        before_reward = self.game.get_total_reward()
        self.game.advance_action()
        # Total-reward delta, not get_last_reward(): in ASYNC modes
        # several tics elapse per poll and last-reward only covers the
        # final one.
        reward = self.game.get_total_reward() - before_reward
        elapsed = max(1, int(self.game.get_episode_time())
                      - int(before_tic))
        return self._post_action(reward, elapsed)

    def render(self, mode: str = "rgb_array"):
        state = self.game.get_state() if self.game is not None else None
        if state is None:
            return self._black_screen()
        return self._frame_from_state(state)

    def close(self):
        if self.game is not None:
            try:
                self.game.close()
            finally:
                self.game = None
