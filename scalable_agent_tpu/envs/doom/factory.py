"""Doom env construction entry point (registry target for ``doom_*``).

(reference: envs/doom/doom_utils.py:261-268 ``make_doom_env`` +
:220-258 multiplayer routing)
"""

from typing import Optional

from scalable_agent_tpu.envs.doom.specs import (
    assemble_doom_env,
    doom_spec_by_name,
)


def make_doom_env(
    full_env_name: str,
    num_action_repeats: int = 4,
    width: int = 128,
    height: int = 72,
    num_agents: Optional[int] = None,
    num_bots: Optional[int] = None,
    num_humans: int = 0,
    **kwargs,
):
    """Build a Doom env by spec name.

    ``num_action_repeats`` maps onto VizDoom's native ``skip_frames``
    (the reference's cfg.env_frameskip).  Specs with multiple agents or
    bots route through the multiplayer layer: a UDP-networked game where
    player 0 hosts (reference: doom_utils.py:220-258).
    """
    spec = doom_spec_by_name(full_env_name)
    agents = spec.num_agents if num_agents is None else num_agents
    bots = spec.num_bots if num_bots is None else num_bots
    if agents > 1 or bots > 0:
        from scalable_agent_tpu.envs.doom.multiplayer import (
            make_doom_multiplayer_env,
        )

        return make_doom_multiplayer_env(
            spec, skip_frames=num_action_repeats, width=width,
            height=height, num_agents=agents, num_bots=bots,
            num_humans=num_humans, **kwargs)
    return assemble_doom_env(
        spec, skip_frames=num_action_repeats, width=width, height=height,
        **kwargs)
