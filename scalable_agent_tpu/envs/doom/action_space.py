"""The eight composite Doom action-space variants.

Faithful re-statements of the reference's spaces (reference:
envs/doom/action_space.py:13-138) over this framework's space algebra
(envs/spaces.py).  Each variant is a ``TupleSpace`` of independent
subspaces; index 0 of every Discrete subspace is a no-op, matching the
one-hot conversion in envs/doom/core.py.  The fully-categorical variants
(every component Discrete/Discretized) feed the tuple-categorical policy
heads directly (ops/distributions.py); the Box-turning variants exist
for env-surface parity — like the reference, the IMPALA policy has no
continuous head, so they are simulator-consumable but not trainable.
"""

from scalable_agent_tpu.envs.spaces import (
    Box,
    Discrete,
    Discretized,
    TupleSpace,
)


def doom_action_space_basic() -> TupleSpace:
    """Turn left/right x move forward/backward.
    (reference: action_space.py:13-27)"""
    return TupleSpace((
        Discrete(3),  # noop, turn left, turn right
        Discrete(3),  # noop, forward, backward
    ))


def doom_action_space() -> TupleSpace:
    """Full deathmatch space with continuous turning.
    (reference: action_space.py:28-54)"""
    return TupleSpace((
        Discrete(3),  # noop, forward, backward
        Discrete(3),  # noop, move right, move left
        Discrete(3),  # noop, prev weapon, next weapon
        Discrete(2),  # noop, attack
        Discrete(2),  # noop, sprint
        Box(-1.0, 1.0, (1,)),  # turn delta
    ))


def doom_action_space_discretized() -> TupleSpace:
    """(reference: action_space.py:57-65)"""
    return TupleSpace((
        Discrete(3),
        Discrete(3),
        Discrete(3),
        Discrete(2),
        Discrete(2),
        Discretized(11, min_action=-10.0, max_action=10.0),
    ))


def doom_action_space_discretized_no_weap() -> TupleSpace:
    """The doom_battle space (used in the SF paper).
    (reference: action_space.py:68-75)"""
    return TupleSpace((
        Discrete(3),
        Discrete(3),
        Discrete(2),
        Discrete(2),
        Discretized(11, min_action=-10.0, max_action=10.0),
    ))


def doom_action_space_continuous_no_weap() -> TupleSpace:
    """(reference: action_space.py:78-85)"""
    return TupleSpace((
        Discrete(3),
        Discrete(3),
        Discrete(2),
        Discrete(2),
        Box(-1.0, 1.0, (1,)),
    ))


def doom_action_space_discrete() -> TupleSpace:
    """(reference: action_space.py:88-96)"""
    return TupleSpace((
        Discrete(3),
        Discrete(3),
        Discrete(3),  # noop, turn right, turn left
        Discrete(3),
        Discrete(2),
        Discrete(2),
    ))


def doom_action_space_discrete_no_weap() -> TupleSpace:
    """(reference: action_space.py:99-106)"""
    return TupleSpace((
        Discrete(3),
        Discrete(3),
        Discrete(3),
        Discrete(2),
        Discrete(2),
    ))


def doom_action_space_full_discretized(with_use: bool = False) -> TupleSpace:
    """Weapon-selection space with discretized turning.
    (reference: action_space.py:109-138)"""
    spaces = [
        Discrete(3),  # noop, forward, backward
        Discrete(3),  # noop, move right, move left
        Discrete(8),  # noop, select weapons 1-7
        Discrete(2),  # noop, attack
        Discrete(2),  # noop, sprint
    ]
    if with_use:
        spaces.append(Discrete(2))  # noop, use
    spaces.append(Discretized(21, min_action=-12.5, max_action=12.5))
    return TupleSpace(spaces)
