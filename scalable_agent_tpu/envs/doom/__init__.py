"""VizDoom environment family.

The reference fork's distinguishing environment backend: IMPALA-on-VizDoom
(reference: environments_doom.py:33-97 and the vendored Sample-Factory
layer under envs/doom/).  Everything here is importable without the
``vizdoom`` pip package — the simulator loads lazily on first env
construction, so the rest of the framework (and the hermetic test suite,
which substitutes a fake ``vizdoom`` module) never needs it.
"""

from scalable_agent_tpu.envs.doom.action_space import (
    doom_action_space,
    doom_action_space_basic,
    doom_action_space_continuous_no_weap,
    doom_action_space_discrete,
    doom_action_space_discrete_no_weap,
    doom_action_space_discretized,
    doom_action_space_discretized_no_weap,
    doom_action_space_full_discretized,
)
from scalable_agent_tpu.envs.doom.specs import (
    DOOM_ENVS,
    DoomSpec,
    doom_spec_by_name,
)
from scalable_agent_tpu.envs.doom.factory import make_doom_env
