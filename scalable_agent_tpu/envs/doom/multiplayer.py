"""Networked multiplayer Doom: per-player games over VizDoom's UDP
netcode, lockstep stepping, and a vectorized multi-agent adapter.

Re-design of the reference multiplayer layer (reference:
envs/doom/multiplayer/doom_multiagent.py:25-220 per-player env,
doom_multiagent_wrapper.py:33-389 worker orchestration,
algorithms/utils/multi_agent.py:4-25 single-agent shim) for this
framework:

- ``DoomMultiplayerEnv`` extends ``DoomEnv`` with host/join game args
  (player 0 hosts ``-host N`` on a probed UDP port, others ``-join``),
  named or difficulty-sampled bots re-added every reset, and — in
  true multi-agent lockstep mode — ``set_action``/``advance_action``
  stepping where only the designated update step renders state.
- ``MultiAgentEnv`` runs one worker (thread) per player with a task
  protocol; game init is retried up to 25 attempts on a fresh port
  because VizDoom's UDP handshake wedges nondeterministically
  (reference: doom_multiagent_wrapper.py:225-273).
- ``MultiAgentVectorEnv`` is the aggregator (reference:
  multi_env.py:345-389): K lockstep games x A agents presented as one
  ``MultiEnv``-shaped batch of K*A ImpalaStream-accounted envs, so the
  ActorPool consumes multiplayer Doom exactly like any other env batch.
"""

import os
import queue as queue_lib
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from scalable_agent_tpu.envs.doom.core import DoomEnv, convert_actions
from scalable_agent_tpu.types import (
    Observation,
    StepOutput,
    StepOutputInfo,
)
from scalable_agent_tpu.utils import log
from scalable_agent_tpu.utils.net import (
    find_available_udp_port,
    is_udp_port_available,
)

DEFAULT_UDP_PORT = int(os.environ.get("DOOM_DEFAULT_UDP_PORT", 40300))

# consistent bot names (reference: doom_multiagent.py:52-61)
BOT_NAMES = (
    "Blazkowicz", "PerfectBlue", "PerfectRed", "PerfectGreen",
    "PerfectPurple", "PerfectYellow", "PerfectWhite", "PerfectLtGreen",
)


class DoomMultiplayerEnv(DoomEnv):
    """One player's view of a networked deathmatch."""

    def __init__(
        self,
        action_space,
        config_file: str,
        player_id: int,
        num_agents: int,
        max_num_players: int,
        num_bots: int,
        skip_frames: int = 1,
        respawn_delay: int = 0,
        port: Optional[int] = None,
        **kwargs,
    ):
        super().__init__(action_space, config_file,
                         skip_frames=skip_frames, **kwargs)
        self.player_id = player_id
        self.num_agents = num_agents
        self.max_num_players = max_num_players
        self.num_bots = num_bots
        self.respawn_delay = respawn_delay
        self.port = port if port is not None else DEFAULT_UDP_PORT
        self.update_state = True
        self.is_multiplayer = True
        self.hardest_bot = 100
        self.easiest_bot = 10

    def _is_server(self) -> bool:
        return self.player_id == 0

    def _customize_game(self, game):
        """Host/join args (reference: doom_multiagent.py:75-141)."""
        if self._is_server():
            if not is_udp_port_available(self.port):
                raise RuntimeError(f"UDP port {self.port} unavailable")
            game.add_game_args(" ".join([
                f"-host {self.max_num_players}",
                f"-port {self.port}",
                "-deathmatch",
                "+timelimit 4.0",
                "+sv_forcerespawn 1",
                "+sv_noautoaim 1",
                "+sv_respawnprotect 1",
                "+sv_spawnfarthest 1",
                "+sv_nocrouch 1",
                "+sv_nojump 1",
                "+sv_nofreelook 1",
                "+sv_noexit 1",
                f"+viz_respawn_delay {self.respawn_delay}",
                "+viz_connect_timeout 4",
            ]))
            game.add_game_args(
                f"+name AI{self.player_id}_host +colorset 0")
        else:
            game.add_game_args(
                f"-join 127.0.0.1:{self.port} +viz_connect_timeout 4 ")
            game.add_game_args(f"+name AI{self.player_id} +colorset 0")

    def _add_bots(self):
        """Fresh bots every episode — named, or difficulty-sampled when
        a curriculum set bot_difficulty_mean (reference:
        doom_multiagent.py:143-188)."""
        self.game.send_game_command("removebots")
        names = list(BOT_NAMES)
        self._rng.shuffle(names)
        used = set()
        for i in range(self.num_bots):
            if self.bot_difficulty_mean is None:
                suffix = f" {names[i]}" if i < len(names) else ""
                self.game.send_game_command(f"addbot{suffix}")
            else:
                diff = self._rng.normal(self.bot_difficulty_mean,
                                        self.bot_difficulty_std)
                diff = int(round(diff, -1))
                diff = min(self.hardest_bot,
                           max(self.easiest_bot, diff))
                while True:
                    name = f"BOT_{diff}_{self._rng.integers(0, max(1, self.num_bots))}"
                    if name not in used:
                        used.add(name)
                        break
                self.game.send_game_command(f"addbot {name}")

    def reset(self):
        obs = super().reset()
        if self._is_server() and self.num_bots > 0:
            self._add_bots()
        self.update_state = True
        return obs

    def step(self, action):
        if self.skip_frames > 1 or self.num_agents == 1:
            # single agent (+ maybe bots): plain make_action stepping
            # (reference: doom_multiagent.py:190-195)
            return super().step(action)
        # Lockstep multi-agent: every player advances exactly one tic;
        # only the final (update) tic renders state
        # (reference: doom_multiagent.py:197-220).
        self._ensure_game()
        self.game.set_action(convert_actions(self.action_space, action))
        self.game.advance_action(1, self.update_state)
        if not self.update_state:
            return None, None, None, None
        state = self.game.get_state()
        reward = self.game.get_last_reward()
        done = self.game.is_episode_finished()
        info: Dict[str, float] = {}
        if not done:
            frame = self._frame_from_state(state)
            info.update(self.get_info(self._variables_dict(state)))
            self._prev_info = dict(info)
        else:
            frame = self._black_screen()
            info.update(self._prev_info)
        self._fix_bugged_variables(info)
        return (Observation(frame=frame), np.float32(reward), bool(done),
                info)

    def _ensure_game(self):
        # DELIBERATELY bypasses the base class's cross-process init
        # lock (core.py _init_serialized): a multiplayer match's games
        # MUST initialize concurrently — the host's game.init() blocks
        # until every joiner connects, so serializing them would
        # deadlock the rendezvous.  Init races are covered by the
        # wrapper's retry-with-kill loop instead (the reference makes
        # the same trade: doom_multiagent_wrapper.py:225-273 retries,
        # no FileLock on the multiplayer path).
        if self.game is None:
            try:
                self.game = self._make_game()
            except Exception:
                log.warning("multiplayer game.init() failed "
                            "(player %d, port %d)", self.player_id,
                            self.port)
                raise


class _TaskType:
    INIT, TERMINATE, RESET, STEP, STEP_UPDATE, INFO = range(6)


class _PlayerWorker:
    """One thread driving one player's env (reference:
    doom_multiagent_wrapper.py:57-141).  Threads, not processes: the
    VizDoom games synchronize over UDP, and each game instance already
    runs its engine off-thread, so player workers mostly block."""

    def __init__(self, player_id: int, make_env_fn: Callable):
        self.player_id = player_id
        self.make_env_fn = make_env_fn
        self.task_queue: queue_lib.Queue = queue_lib.Queue()
        self.result_queue: queue_lib.Queue = queue_lib.Queue()
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        env = None
        while True:
            data, task = self.task_queue.get()
            try:
                if task == _TaskType.INIT:
                    env = self.make_env_fn(player_id=self.player_id,
                                           port=data)
                    env.reset()
                    self.result_queue.put(None)
                    continue
                if task == _TaskType.TERMINATE:
                    if env is not None:
                        env.close()
                    self.result_queue.put(None)
                    return
                if task == _TaskType.RESET:
                    self.result_queue.put(env.reset())
                elif task == _TaskType.INFO:
                    self.result_queue.put(
                        env.unwrapped.get_info())
                elif task in (_TaskType.STEP, _TaskType.STEP_UPDATE):
                    env.unwrapped.update_state = (
                        task == _TaskType.STEP_UPDATE)
                    self.result_queue.put(env.step(data))
                else:
                    raise ValueError(f"unknown task {task}")
            except Exception as exc:  # surface to the orchestrator
                self.result_queue.put(exc)
                if task == _TaskType.INIT:
                    continue


class MultiAgentEnv:
    """A agents in one networked match, stepped in lockstep.

    ``step(actions)`` takes a list of A actions and returns
    ``(obs_list, reward_list, done_list, info_list)``; when ALL agents
    are done the match resets and obs are the next episode's first
    frames (reference: doom_multiagent_wrapper.py:285-300).
    """

    INIT_ATTEMPTS = 25

    def __init__(self, num_agents: int, make_env_fn: Callable,
                 skip_frames: int = 4, port_base: Optional[int] = None,
                 port_increment: int = 1000):
        self.num_agents = num_agents
        self.skip_frames = skip_frames
        self._make_env_fn = make_env_fn
        self._port_base = port_base or DEFAULT_UDP_PORT
        self._port_increment = port_increment
        self._workers: Optional[List[_PlayerWorker]] = None
        # Spaces probed from a throwaway player env — construction is
        # cheap because the game itself initializes lazily (reference
        # queries a player_id=-1 temp env, doom_multiagent_wrapper.py:
        # 151-160).
        probe = make_env_fn(player_id=-1, port=None)
        self.action_space = probe.action_space
        self.observation_spec = probe.observation_spec
        probe.close()

    # -- init with retry ---------------------------------------------------

    def _try_init_once(self) -> bool:
        port = find_available_udp_port(self._port_base,
                                       increment=self._port_increment)
        self._workers = [
            _PlayerWorker(i, self._make_env_fn)
            for i in range(self.num_agents)
        ]
        for worker in self._workers:
            worker.task_queue.put((port, _TaskType.INIT))
            time.sleep(0.01)  # host must bind before joins arrive
        deadline = time.monotonic() + 15.0
        for worker in self._workers:
            try:
                result = worker.result_queue.get(
                    timeout=max(0.1, deadline - time.monotonic()))
            except queue_lib.Empty:
                return False
            if isinstance(result, Exception):
                log.warning("player %d init failed: %r",
                            worker.player_id, result)
                return False
        return True

    def _ensure_initialized(self):
        if self._workers is not None:
            return
        for attempt in range(self.INIT_ATTEMPTS):
            if self._try_init_once():
                log.debug("multiplayer env up after %d attempt(s)",
                          attempt + 1)
                return
            self._teardown_workers()
            time.sleep(0.5)
        raise RuntimeError(
            f"multiplayer env failed to initialize after "
            f"{self.INIT_ATTEMPTS} attempts")

    def _teardown_workers(self):
        if self._workers is None:
            return
        for worker in self._workers:
            worker.task_queue.put((None, _TaskType.TERMINATE))
        # Await the terminations: the TERMINATE task runs env.close(),
        # which now includes RecordingWrapper's final-episode flush —
        # fire-and-forget on daemon threads would race that write with
        # process exit (or with a caller reading recordings right after
        # close()).  Bounded join so a wedged VizDoom can't hang
        # teardown.
        for worker in self._workers:
            worker.thread.join(timeout=10.0)
        self._workers = None

    # -- lockstep protocol -------------------------------------------------

    def _await(self, data, task, timeout: float = 60.0):
        assert self._workers is not None
        if data is None:
            data = [None] * self.num_agents
        for worker, item in zip(self._workers, data):
            worker.task_queue.put((item, task))
        results = []
        for worker in self._workers:
            result = worker.result_queue.get(timeout=timeout)
            if isinstance(result, Exception):
                raise result
            results.append(result)
        return results

    def reset(self) -> List[Observation]:
        self._ensure_initialized()
        return self._await(None, _TaskType.RESET)

    def step(self, actions: List):
        self._ensure_initialized()
        # frameskip: repeat the action skip-1 times without state
        # updates, then one rendering step
        # (reference: doom_multiagent_wrapper.py:285-300)
        for _ in range(self.skip_frames - 1):
            self._await(actions, _TaskType.STEP)
        stepped = self._await(actions, _TaskType.STEP_UPDATE)
        obs = [s[0] for s in stepped]
        rewards = [float(s[1]) for s in stepped]
        dones = [bool(s[2]) for s in stepped]
        infos = [dict(s[3]) for s in stepped]
        for info in infos:
            info["num_frames"] = self.skip_frames
        if all(dones):
            obs = self._await(None, _TaskType.RESET)
        return obs, rewards, dones, infos

    def close(self):
        self._teardown_workers()


class MultiAgentWrapper:
    """1-agent shim so single-player code can drive a MultiAgentEnv
    (reference: algorithms/utils/multi_agent.py:4-25)."""

    def __init__(self, env: MultiAgentEnv):
        if env.num_agents != 1:
            raise ValueError("MultiAgentWrapper wraps 1-agent envs only")
        self.env = env

    def reset(self):
        return self.env.reset()[0]

    def step(self, action):
        obs, rewards, dones, infos = self.env.step([action])
        return obs[0], rewards[0], dones[0], infos[0]

    def close(self):
        self.env.close()


class MultiAgentVectorEnv:
    """K lockstep matches x A agents as one MultiEnv-shaped batch.

    The aggregator role (reference: multi_env.py:345-389): the ActorPool
    sees ``num_envs = K * A`` independent ImpalaStream-accounted envs;
    internally actions route to each match in lockstep.  Matches step
    sequentially in ``step_recv`` — each match's players already run on
    their own threads, so the games themselves overlap.
    """

    def __init__(self, make_multi_env_fns: List[Callable],
                 stats_episodes: int = 100):
        self._envs = [make() for make in make_multi_env_fns]
        self.num_agents = self._envs[0].num_agents
        self.num_envs = sum(e.num_agents for e in self._envs)
        self.episode_stats = deque(maxlen=stats_episodes)
        self._returns = np.zeros((self.num_envs,), np.float64)
        self._steps = np.zeros((self.num_envs,), np.int64)
        self._pending_actions = None
        # Known at construction (probed specs), so consumers that size
        # buffers up front — ActorPool's accum mode reads
        # frame_slab().shape in __init__ — work before any reset.
        self._frame_shape = tuple(
            self._envs[0].observation_spec.frame.shape)

    def _batch(self, obs_list, rewards, dones, emitted_info):
        frames = np.stack([np.asarray(o.frame) for o in obs_list])
        measurements = None
        if obs_list and obs_list[0].measurements is not None:
            measurements = np.stack(
                [np.asarray(o.measurements) for o in obs_list])
        returns, steps = emitted_info
        return StepOutput(
            reward=np.asarray(rewards, np.float32),
            info=StepOutputInfo(
                episode_return=np.asarray(returns, np.float32),
                episode_step=np.asarray(steps, np.int32)),
            done=np.asarray(dones, bool),
            observation=Observation(frame=frames, instruction=None,
                                    measurements=measurements),
        )

    def initial(self) -> StepOutput:
        obs = []
        for env in self._envs:
            obs.extend(env.reset())
        self._returns[:] = 0.0
        self._steps[:] = 0
        return self._batch(
            obs, np.zeros((self.num_envs,)),
            np.ones((self.num_envs,), bool),
            (self._returns.copy(), self._steps.copy()))

    def step_send(self, actions) -> None:
        actions = np.asarray(actions)
        if actions.shape[0] != self.num_envs:
            raise ValueError(
                f"got {actions.shape[0]} actions for {self.num_envs}")
        self._pending_actions = actions

    def step_recv(self) -> StepOutput:
        if self._pending_actions is None:
            raise RuntimeError("step_recv without step_send")
        actions = self._pending_actions
        self._pending_actions = None
        obs_all, rew_all, done_all = [], [], []
        index = 0
        for env in self._envs:
            per_agent = [actions[index + a] for a in range(env.num_agents)]
            obs, rewards, dones, _ = env.step(per_agent)
            obs_all.extend(obs)
            rew_all.extend(rewards)
            done_all.extend(dones)
            index += env.num_agents
        # ImpalaStream accounting: emitted info includes the final step;
        # carried accumulators reset on done (envs/core.py ImpalaStream).
        self._returns += np.asarray(rew_all)
        self._steps += 1
        emitted = (self._returns.copy(), self._steps.copy())
        for i, done in enumerate(done_all):
            if done:
                self.episode_stats.append(
                    (float(self._returns[i]), int(self._steps[i])))
                self._returns[i] = 0.0
                self._steps[i] = 0
        return self._batch(obs_all, rew_all, done_all, emitted)

    def step(self, actions) -> StepOutput:
        self.step_send(actions)
        return self.step_recv()

    def frame_slab(self) -> np.ndarray:
        return np.zeros((self.num_envs,) + self._frame_shape, np.uint8)

    def avg_episode_return(self) -> float:
        if not self.episode_stats:
            return float("nan")
        return float(np.mean([r for r, _ in self.episode_stats]))

    def close(self):
        for env in self._envs:
            env.close()


def make_doom_multiplayer_env(
    spec,
    skip_frames: int = 4,
    width: int = 128,
    height: int = 72,
    num_agents: Optional[int] = None,
    num_bots: Optional[int] = None,
    num_humans: int = 0,
    port_base: Optional[int] = None,
    port_increment: int = 1000,
    seed: Optional[int] = None,
    **kwargs,
):
    """Multiplayer routing (reference: doom_utils.py:220-258): >1 agent
    builds the lockstep MultiAgentEnv (frameskip handled by the
    wrapper, so per-player envs run skip=1); exactly one agent (vs
    bots) hosts a normal game and steps natively.  ``seed`` decorrelates
    matches: player seeds derive from it, so two matches built with
    different seeds play different games."""
    from scalable_agent_tpu.envs.doom.specs import assemble_doom_env

    agents = spec.num_agents if num_agents is None else num_agents
    bots = spec.num_bots if num_bots is None else num_bots
    max_players = agents + num_humans
    is_multiagent = agents > 1

    def make_player_env(player_id: int, port: Optional[int] = None):
        base = DoomMultiplayerEnv(
            spec.action_space, spec.config_file,
            player_id=player_id, num_agents=agents,
            max_num_players=max_players, num_bots=bots,
            skip_frames=1 if is_multiagent else skip_frames,
            respawn_delay=spec.respawn_delay, port=port,
        )
        if player_id >= 0:  # probe envs (player_id=-1) skip seeding
            # seed=0 is a valid explicit seed (only None means unset),
            # and the per-player field is wide enough (1000) that no
            # realistic num_agents can alias the match-seed digits.
            match_seed = 0 if seed is None else seed
            base.seed(match_seed * 1000 + player_id + 1)
        player_kwargs = dict(kwargs)
        # Per-player recording: every player writes its own episode
        # stream into <record_to>/player_NN — a shared directory would
        # interleave concurrent player threads' episode numbering
        # (role of the reference's record path,
        # envs/env_wrappers.py:433-497, which is single-agent only).
        # The wrapper goes OUTSIDE the assembled pipeline so recordings
        # carry what the policy saw (resized frames, shaped rewards) —
        # the same convention as single-agent eval recording
        # (envs/__init__.py make_impala_stream).  Probe envs
        # (player_id=-1) never record.
        record_to = player_kwargs.pop("record_to", None)
        assembled = assemble_doom_env(
            spec, width=width, height=height, env=base, num_bots=bots,
            **player_kwargs)
        if record_to and player_id >= 0:
            from scalable_agent_tpu.envs.wrappers import RecordingWrapper

            inner = assembled
            assembled = RecordingWrapper(
                inner, os.path.join(record_to, f"player_{player_id:02d}"))
            # assemble_doom_env pins native_action_repeats on its
            # outermost wrapper (wrappers don't forward arbitrary
            # attributes, specs.py) — re-establish the invariant on the
            # new outermost layer.
            assembled.native_action_repeats = getattr(
                inner, "native_action_repeats", 1)
        return assembled

    if is_multiagent:
        return MultiAgentEnv(agents, make_player_env,
                             skip_frames=skip_frames,
                             port_base=port_base,
                             port_increment=port_increment)
    port = find_available_udp_port(port_base or DEFAULT_UDP_PORT)
    return make_player_env(0, port=port)
