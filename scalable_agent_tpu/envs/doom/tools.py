"""Doom tooling: throughput sampling, observation grids, demo replay.

The reference ships small standalone drivers (reference:
envs/doom/sample_env.py:8-18 random-policy FPS sampler,
doom_render.py:5-34 observation grid, doom_play_demo.py:14-51 demo
replayer, play_doom.py:8-18 human play).  Equivalents here are plain
functions; run e.g.:

    python -m scalable_agent_tpu.envs.doom.tools sample doom_benchmark
"""

import math
import os
import sys
import time
from typing import List, Optional

import numpy as np

from scalable_agent_tpu.envs.doom.factory import make_doom_env
from scalable_agent_tpu.utils import log


def sample_env(env_name: str = "doom_benchmark", num_steps: int = 1000,
               num_action_repeats: int = 4, seed: int = 0) -> float:
    """Random-policy throughput probe; returns env frames/sec.

    (reference: sample_env.py:8-18)
    """
    env = make_doom_env(env_name, num_action_repeats=num_action_repeats)
    rng = np.random.default_rng(seed)
    try:
        env.reset()
        t0 = time.perf_counter()
        for _ in range(num_steps):
            _, _, done, _ = env.step(env.action_space.sample(rng))
            if done:
                env.reset()
        dt = time.perf_counter() - t0
        fps = num_steps * num_action_repeats / dt
        log.info("%s: %.1f env frames/s (%.1f agent steps/s)",
                 env_name, fps, num_steps / dt)
        return fps
    finally:
        env.close()


def concat_grid(frames: List[np.ndarray]) -> np.ndarray:
    """Tile per-agent frames into one image for rendering.

    (reference: doom_render.py:5-34)
    """
    if not frames:
        raise ValueError("no frames")
    n = len(frames)
    cols = int(math.ceil(math.sqrt(n)))
    rows = int(math.ceil(n / cols))
    h, w, c = frames[0].shape
    grid = np.zeros((rows * h, cols * w, c), frames[0].dtype)
    for i, frame in enumerate(frames):
        r, col = divmod(i, cols)
        grid[r * h:(r + 1) * h, col * w:(col + 1) * w] = frame
    return grid


def _reinit_game(env_name: str, mode, visible: bool = False,
                 num_action_repeats: int = 4):
    """(env, game) with the underlying DoomGame re-initialized in
    ``mode``: build the env pipeline, then close/reconfigure/re-init the
    raw game — the shared preamble for replay and human play."""
    env = make_doom_env(env_name, num_action_repeats=num_action_repeats)
    base = env.unwrapped
    base._ensure_game()
    game = base.game
    game.close()
    if visible:
        game.set_window_visible(True)
    game.set_mode(mode)
    game.init()
    return env, game


def replay_demo(env_name: str, demo_path: str,
                out_dir: Optional[str] = None,
                num_action_repeats: int = 4) -> int:
    """Replay a recorded .lmp demo, dumping frames as .npy files.

    (reference: doom_play_demo.py:14-51 — PNG via cv2 there; .npy here
    to avoid the image-codec dependency.)  Returns the frame count.
    """
    import vizdoom

    env, game = _reinit_game(env_name, vizdoom.Mode.PLAYER,
                             num_action_repeats=num_action_repeats)
    game.replay_episode(demo_path)
    frames = 0
    out_dir = out_dir or os.path.splitext(demo_path)[0] + "_frames"
    os.makedirs(out_dir, exist_ok=True)
    try:
        while not game.is_episode_finished():
            state = game.get_state()
            if state is not None and state.screen_buffer is not None:
                frame = np.transpose(state.screen_buffer, (1, 2, 0))
                np.save(os.path.join(out_dir, f"{frames:05d}.npy"), frame)
                frames += 1
            game.advance_action()
        return frames
    finally:
        env.close()


def play_human(env_name: str = "doom_basic", episodes: int = 1) -> None:
    """Interactive human play via VizDoom ASYNC_SPECTATOR mode (needs
    a display; the engine runs real-time at 35 tics/s and the human
    drives the VizDoom window directly).

    (reference: play_doom.py:8-18, doom_gym.py:465-542 — pynput
    keyboard capture there; SPECTATOR mode is VizDoom's native
    equivalent and needs no extra dependency.)
    """
    import vizdoom

    env, game = _reinit_game(env_name, vizdoom.Mode.ASYNC_SPECTATOR,
                             visible=True)
    try:
        for episode in range(episodes):
            game.new_episode()
            while not game.is_episode_finished():
                game.advance_action()
            log.info("episode %d reward: %.1f",
                     episode, game.get_total_reward())
    finally:
        env.close()


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__)
        return
    command, args = argv[0], argv[1:]
    if command == "sample":
        # sample <env_name> [num_steps] [num_action_repeats] [seed]
        sample_env(args[0] if args else "doom_benchmark",
                   *map(int, args[1:4]))
    elif command == "replay":
        # replay <env_name> <demo_path> [out_dir] [num_action_repeats]
        if len(args) < 2:
            raise SystemExit(
                "usage: replay <env_name> <demo_path> [out_dir] "
                "[num_action_repeats]")
        replay_demo(args[0], args[1], args[2] if len(args) > 2 else None,
                    *map(int, args[3:4]))
    elif command == "play":
        # play [env_name] [episodes]
        play_human(args[0] if args else "doom_basic", *map(int, args[1:2]))
    else:
        raise SystemExit(f"unknown command {command!r}")


if __name__ == "__main__":
    main()