"""Doom-specific wrappers: measurements input, reward shaping, bot
curriculum, multiplayer standings, resolution control.

Re-designs of the reference wrapper set over this framework's
``Environment``/``Observation`` protocol (reference: envs/doom/wrappers/
additional_input.py:7-96, reward_shaping.py:38-246, bot_difficulty.py:
6-57, multiplayer_stats.py:7-60, scenario_wrappers/
gathering_reward_shaping.py:4-33, observation_space.py:10-48).  All
shaping constants match the reference exactly — they are calibration
values the learned policies depend on.
"""

from collections import deque
from typing import Callable, Dict, Optional

import numpy as np

from scalable_agent_tpu.envs.core import Environment, Wrapper
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.utils import log

EPS = 1e-5
NUM_WEAPONS = 8

# Weapon preferences bias pickup/ammo/selection shaping toward stronger
# guns (reference: reward_shaping.py:10-34).
WEAPON_PREFERENCE = {2: 1, 3: 5, 4: 5, 5: 5, 6: 10, 7: 10}


def _weapon_delta_rewards() -> Dict[str, tuple]:
    rewards = {}
    for weapon in range(NUM_WEAPONS):
        pref = WEAPON_PREFERENCE.get(weapon, 1)
        rewards[f"WEAPON{weapon}"] = (+0.02 * pref, -0.01 * pref)
        rewards[f"AMMO{weapon}"] = (+0.0002 * pref, -0.0001 * pref)
    return rewards


def _selected_weapon_rewards() -> Dict[str, float]:
    return {f"SELECTED{w}": 0.0002 * WEAPON_PREFERENCE.get(w, 1)
            for w in range(NUM_WEAPONS)}


def _scheme(delta_overrides: Dict[str, tuple]) -> Dict[str, dict]:
    """A shaping scheme: per-variable (reward-per-unit-up, per-unit-down)
    deltas plus selected-weapon persistence rewards."""
    delta = dict(
        FRAGCOUNT=(+1, -1.5),
        DEATHCOUNT=(-0.75, +0.75),
        HITCOUNT=(+0.01, -0.01),
        DAMAGECOUNT=(+0.003, -0.003),
        HEALTH=(+0.005, -0.003),
        ARMOR=(+0.005, -0.001),
        **_weapon_delta_rewards(),
    )
    delta.update(delta_overrides)
    return dict(delta=delta, selected_weapon=_selected_weapon_rewards())


# (reference: reward_shaping.py:38-67)
REWARD_SHAPING_DEATHMATCH_V0 = _scheme({})
REWARD_SHAPING_DEATHMATCH_V1 = _scheme(dict(
    FRAGCOUNT=(+1, -0.001),
    DEATHCOUNT=(-1, +1),
    HITCOUNT=(0, 0),
    DAMAGECOUNT=(+0.01, -0.01),
    HEALTH=(+0.01, -0.01),
))
REWARD_SHAPING_BATTLE = _scheme(dict(AMMO2=(+0.02, -0.001)))


def true_reward_final_position(info: Dict) -> float:
    """Win = 1, anything else (incl. ties) = 0.
    (reference: reward_shaping.py:70-79)"""
    if info["LEADER_GAP"] == 0:
        return 0.0
    if info["FINAL_PLACE"] > 1:
        return 0.0
    return 1.0


def true_reward_frags(info: Dict) -> float:
    return float(info["FRAGCOUNT"])


class DoomRewardShaping(Wrapper):
    """Game-variable deltas -> shaped scalar reward; reports the
    unshaped "true" episode reward in ``info['true_reward']``.

    (reference: reward_shaping.py:86-246)
    """

    # caps so BFG/shotgun multi-hits don't dominate
    # (reference: reward_shaping.py:97)
    DELTA_LIMITS = dict(DAMAGECOUNT=200, HITCOUNT=5)

    def __init__(self, env: Environment, scheme: Optional[dict] = None,
                 true_reward_func: Optional[Callable] = None):
        super().__init__(env)
        self.scheme = scheme
        self.true_reward_func = true_reward_func
        self._prev_vars: Dict[str, float] = {}
        self._prev_dead = True
        self._orig_reward = 0.0
        self._selected_weapon = deque([], maxlen=5)
        self.reward_structure: Dict[str, float] = {}

    def _delta_rewards(self, info: Dict) -> float:
        reward = 0.0
        for name, (up, down) in self.scheme["delta"].items():
            if name not in self._prev_vars:
                continue
            delta = info.get(name, 0.0) - self._prev_vars[name]
            if name in self.DELTA_LIMITS:
                delta = min(delta, self.DELTA_LIMITS[name])
            if abs(delta) > EPS:
                shaped = delta * up if delta > EPS else -delta * down
                reward += shaped
                self.reward_structure[name] = (
                    self.reward_structure.get(name, 0.0) + shaped)
        return reward

    def _weapon_reward(self, selected: int, ammo: float) -> float:
        # reward keeping one weapon unholstered for 5 consecutive steps
        # (reference: reward_shaping.py:140-155)
        unholstered = (len(self._selected_weapon) > 4 and all(
            w == selected for w in self._selected_weapon))
        if ammo <= 0 or not unholstered:
            return 0.0
        reward = self.scheme["selected_weapon"].get(
            f"SELECTED{selected}", 0.0)
        key = f"weapon{selected}"
        self.reward_structure[key] = (
            self.reward_structure.get(key, 0.0) + reward)
        return reward

    def _shaping_reward(self, info: Dict, done: bool) -> float:
        if self.scheme is None:
            return 0.0
        selected = int(max(0, info.get("SELECTED_WEAPON", 0.0)))
        ammo = float(max(0.0, info.get("SELECTED_WEAPON_AMMO", 0.0)))
        self._selected_weapon.append(selected)
        just_respawned = self._prev_dead and not info.get("DEAD", 0.0)
        reward = 0.0
        if not done and not just_respawned:
            reward = self._delta_rewards(info)
            reward += self._weapon_reward(selected, ammo)
        return reward

    def reset(self):
        obs = self.env.reset()
        self._prev_vars = {}
        self._prev_dead = True
        self._orig_reward = 0.0
        self._selected_weapon.clear()
        self.reward_structure = {}
        return obs

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        if obs is None:  # lockstep multiplayer non-update tick
            return obs, reward, done, info
        self._orig_reward += float(reward)
        reward = np.float32(reward + self._shaping_reward(info, done))
        if self.scheme is not None:
            for name in self.scheme["delta"]:
                self._prev_vars[name] = info.get(name, 0.0)
        self._prev_dead = bool(info.get("DEAD", 0.0))
        if done:
            info["true_reward"] = (
                self._orig_reward if self.true_reward_func is None
                else self.true_reward_func(info))
        return obs, reward, done, info


class DoomAdditionalInput(Wrapper):
    """Expose DFP-scaled game-variable measurements as the observation's
    ``measurements`` vector (reference: additional_input.py:7-96; the
    reference uses a Dict obs space — here measurements are a first-class
    Observation field).
    """

    NUM_MEASUREMENTS = 7 + 2 * NUM_WEAPONS

    def __init__(self, env: Environment):
        super().__init__(env)
        self._vec = np.zeros((self.NUM_MEASUREMENTS,), np.float32)

    @property
    def observation_spec(self):
        return self.env.observation_spec._replace(
            measurements=TensorSpec(
                (self.NUM_MEASUREMENTS,), np.float32, "measurements"))

    def _measure(self, info: Dict) -> np.ndarray:
        v = self._vec
        selected = round(max(0, info.get("SELECTED_WEAPON", 0.0)))
        ammo = min(max(0.0, info.get("SELECTED_WEAPON_AMMO", 0.0)) / 15.0,
                   5.0)
        health = max(0.0, info.get("HEALTH", 0.0)) / 30.0
        v[0] = float(selected)
        v[1] = float(ammo)
        v[2] = float(health)
        v[3] = info.get("ARMOR", 0.0) / 30.0
        v[4] = info.get("USER2", 0.0) / 10.0  # kills (battle scenarios)
        v[5] = info.get("ATTACK_READY", 0.0)
        v[6] = info.get("PLAYER_COUNT", 1) / 5.0
        for w in range(NUM_WEAPONS):
            v[7 + w] = max(0.0, info.get(f"WEAPON{w}", 0.0))
            v[7 + NUM_WEAPONS + w] = min(
                max(0.0, info.get(f"AMMO{w}", 0.0)) / 15.0, 5.0)
        return v.copy()

    def reset(self):
        obs = self.env.reset()
        info = self.unwrapped.get_info()
        return obs._replace(measurements=self._measure(info))

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        if obs is None:  # lockstep multiplayer non-update tick
            return obs, reward, done, info
        return (obs._replace(measurements=self._measure(info)), reward,
                done, info)


class BotDifficultyWrapper(Wrapper):
    """Adaptive bot-skill curriculum from match standings.

    (reference: bot_difficulty.py:6-57.)  Note: like the reference,
    reset always publishes ``bot_difficulty_mean`` to the base env, so
    whenever this wrapper is in the pipeline (any bots>0 spec) the
    multiplayer env's named-bot fallback never fires — bots are always
    difficulty-sampled.  The named path only applies to bare
    DoomMultiplayerEnv usage.
    """

    MIN, MAX, STEP = 0, 150, 10

    def __init__(self, env: Environment,
                 initial_difficulty: Optional[int] = None):
        super().__init__(env)
        self.difficulty = (20 if initial_difficulty is None
                          else initial_difficulty)
        self._std = 10
        self._adaptive = initial_difficulty != self.MAX

    def _analyze_standings(self, info: Dict):
        if "FINAL_PLACE" not in info:
            return
        if info["FINAL_PLACE"] <= 1 and info.get("LEADER_GAP", 0.0) < 0:
            self.difficulty = min(self.difficulty + self.STEP, self.MAX)
        elif info["FINAL_PLACE"] >= int(info.get("PLAYER_COUNT", 1)) - 1:
            self.difficulty = max(self.difficulty - self.STEP, self.MIN)

    def reset(self):
        base = self.unwrapped
        if hasattr(base, "bot_difficulty_mean"):
            base.bot_difficulty_mean = self.difficulty
            base.bot_difficulty_std = self._std
        return self.env.reset()

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        if obs is None:  # lockstep multiplayer non-update tick
            return obs, reward, done, info
        if done and self._adaptive:
            self._analyze_standings(info)
        info["BOT_DIFFICULTY"] = self.difficulty
        return obs, reward, done, info


class MultiplayerStatsWrapper(Wrapper):
    """Derive KDR / FINAL_PLACE / LEADER_GAP from per-player fragcounts,
    refreshed every 20 steps and on done (reference:
    multiplayer_stats.py:7-60).
    """

    def __init__(self, env: Environment):
        super().__init__(env)
        self._timestep = 0
        self._extra: Dict[str, float] = {}

    def _update(self, info: Dict, done: bool):
        if (self._timestep % 20 == 0 or done) and "FRAGCOUNT" in info:
            extra = {"KDR": float(
                info.get("FRAGCOUNT", 0.0)
                / (info.get("DEATHCOUNT", 0.0) + 1))}
            player_count = int(info.get("PLAYER_COUNT", 1))
            player_num = int(info.get("PLAYER_NUM", 1))
            frags = [int(info.get(f"PLAYER{p}_FRAGCOUNT", -100000))
                     for p in range(1, player_count + 1)]
            order = list(np.argsort(frags))
            place = player_count - order.index(player_num - 1)
            extra["FINAL_PLACE"] = place
            if place > 1:
                extra["LEADER_GAP"] = (
                    max(frags) - frags[player_num - 1])
            elif player_count > 1:
                top_two = sorted(frags, reverse=True)
                extra["LEADER_GAP"] = top_two[1] - top_two[0]  # <= 0
            else:
                extra["LEADER_GAP"] = 0
            self._extra = extra
        info.update(self._extra)

    def reset(self):
        self._timestep = 0
        self._extra = {}
        return self.env.reset()

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        if obs is None:  # lockstep multiplayer non-update tick
            return obs, reward, done, info
        self._update(info, done)
        self._timestep += 1
        return obs, reward, done, info


class DoomGatheringRewardShaping(Wrapper):
    """+1 whenever health increases (gathering scenarios).

    (reference: scenario_wrappers/gathering_reward_shaping.py:4-33)
    """

    def __init__(self, env: Environment):
        super().__init__(env)
        self._prev_health = None

    def reset(self):
        self._prev_health = None
        return self.env.reset()

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        if obs is None:  # lockstep multiplayer non-update tick
            return obs, reward, done, info
        if info is not None and not done:
            health = info.get("HEALTH", 0.0)
            if (self._prev_health is not None
                    and health > self._prev_health):
                reward = np.float32(reward + 1.0)
            self._prev_health = health
        return obs, reward, done, info


# VizDoom's supported render resolutions (reference:
# observation_space.py:3-7 — names match vizdoom.ScreenResolution).
RESOLUTIONS = (
    "160x120", "200x125", "200x150", "256x144", "256x160", "256x192",
    "320x180", "320x200", "320x240", "320x256", "400x225", "400x250",
    "400x300", "512x288", "512x320", "512x384", "640x360", "640x400",
    "640x480", "800x450", "800x500", "800x600", "1024x576", "1024x640",
    "1024x768", "1280x720", "1280x800", "1280x960", "1280x1024",
    "1400x787", "1400x875", "1400x1050", "1600x900", "1600x1000",
    "1600x1200", "1920x1080",
)


def set_doom_resolution(env: DoomRewardShaping, resolution: str):
    """Configure the native render resolution before game init
    (reference: observation_space.py:10-48 — a wrapper there; a plain
    call here since our spec is a property of the base env)."""
    if resolution not in RESOLUTIONS:
        raise ValueError(
            f"unsupported VizDoom resolution {resolution!r}")
    width, height = (int(part) for part in resolution.split("x"))
    env.unwrapped.set_resolution(width, height, f"RES_{width}X{height}")
    log.debug("Doom native resolution set to %s", resolution)


class DoomExplorationWrapper(Wrapper):
    """Landmark-based exploration bonus (reference: wrappers/
    exploration.py:10-58): a pose (x, y, view angle) farther than
    ``threshold`` from every stored landmark — Euclidean distance plus
    half the wrapped angular difference — earns ``bonus`` intrinsic
    reward and becomes a landmark itself.  The bonus is surfaced via
    ``info['intrinsic_reward']`` and NOT added to the env reward,
    matching the reference; landmarks are randomly evicted past
    ``max_landmarks`` and cleared on reset.
    """

    def __init__(self, env: Environment, max_landmarks: int = 200,
                 threshold: float = 75.0, bonus: float = 0.1,
                 seed: int = 0):
        super().__init__(env)
        self.max_landmarks = int(max_landmarks)
        self.threshold = float(threshold)
        self.bonus = float(bonus)
        self._landmarks = []
        self._rng = np.random.default_rng(seed)

    def _intrinsic_reward(self, info: Dict) -> float:
        if "POSITION_X" not in info or "POSITION_Y" not in info:
            return 0.0
        x, y = info["POSITION_X"], info["POSITION_Y"]
        angle = info.get("ANGLE", 0.0)
        for lx, ly, la in self._landmarks:
            angle_diff = abs(angle - la)
            angle_diff = min(angle_diff, 360.0 - angle_diff)
            distance = np.hypot(x - lx, y - ly) + angle_diff / 2.0
            if distance < self.threshold:
                return 0.0
        self._landmarks.append((x, y, angle))
        while len(self._landmarks) > self.max_landmarks:
            del self._landmarks[int(self._rng.integers(
                0, len(self._landmarks)))]
        return self.bonus

    def reset(self):
        self._landmarks = []
        return self.env.reset()

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        info["intrinsic_reward"] = (
            info.get("intrinsic_reward", 0.0) + self._intrinsic_reward(info))
        return obs, reward, done, info


def _null_action(space):
    """A well-formed no-op for any composite action space (the human
    step ignores it, but intermediate wrappers see a valid action)."""
    from scalable_agent_tpu.envs.spaces import Box, TupleSpace

    if isinstance(space, TupleSpace):
        return tuple(_null_action(s) for s in space.spaces)
    if isinstance(space, Box):
        return np.zeros(space.shape, np.float32)
    return 0


class StepHumanInput(Wrapper):
    """Human-driven stepping: the policy's action is IGNORED and the
    game advances on the human's own input (the underlying DoomGame is
    re-initialized into SPECTATOR mode with a visible window on first
    use).  The human transition is substituted at the BASE env and then
    flows out through the full wrapper chain, so resize / measurements /
    reward shaping all apply exactly as they do to policy steps.
    (reference: wrappers/step_human_input.py:7-38 — there via
    mode='human' and a raw screen-buffer observation that bypassed the
    pipeline; SPECTATOR is VizDoom's native mechanism.)
    """

    def __init__(self, env: Environment):
        super().__init__(env)
        self._spectator = False

    def _ensure_spectator(self):
        import vizdoom

        base = self.unwrapped
        # A closed/recreated game (base.game is None) loses the mode —
        # re-arm spectator rather than trusting the stale flag.
        if self._spectator and base.game is not None:
            return
        base._ensure_game()
        game = base.game
        game.close()
        game.set_window_visible(True)
        # ASYNC: the engine runs at real-time 35 tics/s on its own
        # clock — sync SPECTATOR would only advance when the step loop
        # polls, freezing the game under the human's hands.
        game.set_mode(vizdoom.Mode.ASYNC_SPECTATOR)
        game.init()
        self._spectator = True

    def reset(self):
        self._ensure_spectator()
        return self.env.reset()

    def step(self, action):
        del action  # input comes from the human at the game window
        self._ensure_spectator()
        base = self.unwrapped
        # Substitute the human transition at the base env so it flows
        # out through the whole wrapper chain; the bookkeeping itself
        # lives in DoomEnv.step_human (shared with policy steps).
        base.step = lambda _action: base.step_human()
        try:
            return self.env.step(_null_action(base.action_space))
        finally:
            del base.step  # restore the class method
