"""Doom environment specs and the wrapper-assembly pipeline.

The reference's ``DoomSpec`` table and ``make_doom_env_impl`` pipeline
(reference: envs/doom/doom_utils.py:19-130 table, :141-217 pipeline)
rebuilt over this framework's wrapper set.  Spec names, scenario files,
action spaces, reward scaling, timeouts, and agent/bot counts match the
reference exactly.
"""

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

from scalable_agent_tpu.envs.doom import action_space as asp
from scalable_agent_tpu.envs.doom import wrappers as dw
from scalable_agent_tpu.envs.doom.core import DoomEnv
from scalable_agent_tpu.envs.spaces import Discrete, Space
from scalable_agent_tpu.envs.wrappers import (
    RecordingWrapper,
    ResizeWrapper,
    RewardScalingWrapper,
    TimeLimitWrapper,
)


@dataclasses.dataclass
class DoomSpec:
    """(reference: doom_utils.py:19-40)"""

    name: str
    config_file: str
    action_space: Space
    reward_scaling: float = 1.0
    default_timeout: int = -1
    num_agents: int = 1
    num_bots: int = 0
    respawn_delay: int = 0
    # [(wrapper_factory, kwargs)] applied after the standard pipeline
    extra_wrappers: Sequence[Tuple[Callable, dict]] = ()


ADDITIONAL_INPUT = (dw.DoomAdditionalInput, {})
BATTLE_REWARD_SHAPING = (
    dw.DoomRewardShaping,
    dict(scheme=dw.REWARD_SHAPING_BATTLE, true_reward_func=None))
BOTS_REWARD_SHAPING = (
    dw.DoomRewardShaping,
    dict(scheme=dw.REWARD_SHAPING_DEATHMATCH_V0,
         true_reward_func=dw.true_reward_frags))
DEATHMATCH_REWARD_SHAPING = (
    dw.DoomRewardShaping,
    dict(scheme=dw.REWARD_SHAPING_DEATHMATCH_V1,
         true_reward_func=dw.true_reward_final_position))


# (reference: doom_utils.py:49-130; same names/files/spaces/constants)
DOOM_ENVS: List[DoomSpec] = [
    DoomSpec("doom_basic", "basic.cfg", Discrete(1 + 3), 0.01, 300),
    DoomSpec("doom_corridor", "deadly_corridor.cfg", Discrete(1 + 7),
             0.01, 2100),
    DoomSpec("doom_gathering", "health_gathering.cfg", Discrete(1 + 3),
             0.01, 2100),
    DoomSpec("doom_two_colors_easy", "two_colors_easy.cfg",
             asp.doom_action_space_basic(),
             extra_wrappers=[(dw.DoomGatheringRewardShaping, {})]),
    DoomSpec("doom_two_colors_hard", "two_colors_hard.cfg",
             asp.doom_action_space_basic(),
             extra_wrappers=[(dw.DoomGatheringRewardShaping, {})]),
    DoomSpec("doom_dm", "cig.cfg", asp.doom_action_space(), 1.0,
             int(1e9), num_agents=8,
             extra_wrappers=[ADDITIONAL_INPUT, DEATHMATCH_REWARD_SHAPING]),
    DoomSpec("doom_dwango5", "dwango5_dm.cfg", asp.doom_action_space(),
             1.0, int(1e9), num_agents=8,
             extra_wrappers=[ADDITIONAL_INPUT, DEATHMATCH_REWARD_SHAPING]),
    DoomSpec("doom_battle", "battle_continuous_turning.cfg",
             asp.doom_action_space_discretized_no_weap(), 1.0, 2100,
             extra_wrappers=[ADDITIONAL_INPUT, BATTLE_REWARD_SHAPING]),
    DoomSpec("doom_battle2", "battle2_continuous_turning.cfg",
             asp.doom_action_space_discretized_no_weap(), 1.0, 2100,
             extra_wrappers=[ADDITIONAL_INPUT, BATTLE_REWARD_SHAPING]),
    DoomSpec("doom_deathmatch_bots", "dwango5_dm_continuous_weap.cfg",
             asp.doom_action_space_full_discretized(), 1.0, int(1e9),
             num_agents=1, num_bots=7,
             extra_wrappers=[ADDITIONAL_INPUT, BOTS_REWARD_SHAPING]),
    DoomSpec("doom_duel", "ssl2.cfg",
             asp.doom_action_space_full_discretized(with_use=True), 1.0,
             int(1e9), num_agents=2, num_bots=0, respawn_delay=2,
             extra_wrappers=[ADDITIONAL_INPUT, DEATHMATCH_REWARD_SHAPING]),
    DoomSpec("doom_deathmatch_full", "freedm.cfg",
             asp.doom_action_space_full_discretized(with_use=True), 1.0,
             int(1e9), num_agents=4, num_bots=4, respawn_delay=2,
             extra_wrappers=[ADDITIONAL_INPUT, DEATHMATCH_REWARD_SHAPING]),
    # The throughput-benchmark convention: 128x72 agent input, 4-skip,
    # 160x120 native, simple Discrete(9) space
    # (reference: doom_utils.py:125-129)
    DoomSpec("doom_benchmark", "battle.cfg", Discrete(1 + 8), 1.0, 2100),
]

_BY_NAME = {spec.name: spec for spec in DOOM_ENVS}


def doom_spec_by_name(name: str) -> DoomSpec:
    try:
        return _BY_NAME[name]
    except KeyError:
        raise ValueError(
            f"unknown Doom env {name!r}; known: {sorted(_BY_NAME)}"
        ) from None


def assemble_doom_env(
    spec: DoomSpec,
    skip_frames: int = 4,
    width: int = 128,
    height: int = 72,
    resolution: Optional[str] = None,
    wide_aspect_ratio: bool = False,
    episode_horizon: Optional[int] = None,
    record_to: Optional[str] = None,
    scenarios_dir: Optional[str] = None,
    async_mode: bool = False,
    env: Optional[DoomEnv] = None,
    num_bots: Optional[int] = None,
    coord_limits=None,
    show_automap: bool = False,
):
    """The single-player wrapper pipeline (reference:
    doom_utils.py:141-217): recording -> multiplayer stats -> bot
    difficulty -> native resolution -> resize -> time limit -> extra
    wrappers -> reward scaling.  ``env`` injects a pre-built base env
    (the multiplayer per-player factory uses this)."""
    if env is None:
        env = DoomEnv(spec.action_space, spec.config_file,
                      skip_frames=skip_frames,
                      scenarios_dir=scenarios_dir,
                      async_mode=async_mode,
                      coord_limits=coord_limits,
                      show_automap=show_automap)
    bots = spec.num_bots if num_bots is None else num_bots
    wrapped = env
    if record_to is not None:
        wrapped = RecordingWrapper(wrapped, record_to)
    wrapped = dw.MultiplayerStatsWrapper(wrapped)
    if bots > 0:
        wrapped = dw.BotDifficultyWrapper(wrapped)
    native = resolution or ("256x144" if wide_aspect_ratio else "160x120")
    dw.set_doom_resolution(wrapped, native)
    spec_shape = wrapped.observation_spec.frame.shape
    if (spec_shape[0], spec_shape[1]) != (height, width):
        wrapped = ResizeWrapper(wrapped, height, width, grayscale=False)
    timeout = spec.default_timeout
    if episode_horizon is not None and episode_horizon > 0:
        timeout = episode_horizon
    if timeout > 0:
        wrapped = TimeLimitWrapper(wrapped, limit=timeout)
    for wrapper_factory, kwargs in spec.extra_wrappers:
        wrapped = wrapper_factory(wrapped, **kwargs)
    if spec.reward_scaling != 1.0:
        wrapped = RewardScalingWrapper(wrapped, spec.reward_scaling)
    # Surface the base env's native frameskip on the OUTERMOST wrapper:
    # make_impala_stream reads this attribute to avoid stacking a second
    # SkipFramesWrapper on top of make_action's skip_frames (wrappers
    # don't forward arbitrary attributes).
    wrapped.native_action_repeats = env.native_action_repeats
    return wrapped
