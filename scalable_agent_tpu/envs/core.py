"""Environment protocols: gym-like core, auto-reset stream, IMPALA stream.

Three layers, mirroring the reference's stack but host-side and TF-free:

1. ``Environment`` — the simulator-facing gym-like API (reset/step/close)
   that wrappers compose over (the role gym plays for the vendored
   Sample-Factory layer, reference: envs/doom/doom_gym.py).
2. ``StreamAdapter`` — auto-reset stream: ``initial() -> obs``,
   ``step(a) -> (reward, done, obs)`` where obs after a done is the first
   observation of the *next* episode (the contract of PyProcessDmLab/Doom,
   reference: environments.py:103-117, environments_doom.py:69-76).
3. ``ImpalaStream`` — adds episode_return/episode_step accounting and emits
   ``StepOutput`` pytrees, resetting counters after a done (the reference's
   ``FlowEnvironment``, environments.py:149-233 — minus the flow token,
   which only exists to serialize steps inside a TF graph; host Python is
   already sequential).
"""

from typing import Any, Dict, Optional, Tuple

import numpy as np

from scalable_agent_tpu.envs.spaces import Space
from scalable_agent_tpu.types import Observation, StepOutput, StepOutputInfo


class Environment:
    """Gym-like simulator API.

    ``step`` returns (observation, reward, done, info-dict); ``done`` folds
    termination and truncation together, as the reference's gym-0.x-era
    envs do.
    """

    action_space: Space
    observation_spec: Any  # pytree of TensorSpec

    def seed(self, seed: Optional[int]) -> None:
        pass

    def reset(self) -> Any:
        raise NotImplementedError

    def step(self, action) -> Tuple[Any, float, bool, Dict]:
        raise NotImplementedError

    def render(self, mode: str = "rgb_array"):
        raise NotImplementedError

    def close(self) -> None:
        pass


class Wrapper(Environment):
    """Pass-through base for env wrappers."""

    def __init__(self, env: Environment):
        self.env = env

    @property
    def action_space(self):
        return self.env.action_space

    @property
    def observation_spec(self):
        return self.env.observation_spec

    @property
    def unwrapped(self):
        return getattr(self.env, "unwrapped", self.env)

    def seed(self, seed):
        return self.env.seed(seed)

    def reset(self):
        return self.env.reset()

    def step(self, action):
        return self.env.step(action)

    def render(self, mode: str = "rgb_array"):
        return self.env.render(mode)

    def close(self):
        return self.env.close()


class StreamAdapter:
    """Auto-reset stream over an ``Environment``.

    Contract (reference: environments.py:103-117): ``step`` returns
    (reward, done, observation); when done, the observation is the first
    one of the freshly reset next episode.
    """

    def __init__(self, env: Environment):
        self._env = env

    @property
    def env(self) -> Environment:
        return self._env

    @property
    def observation_spec(self):
        return self._env.observation_spec

    @property
    def action_space(self):
        return self._env.action_space

    def initial(self):
        return self._env.reset()

    def step(self, action):
        observation, reward, done, _ = self._env.step(action)
        if done:
            observation = self._env.reset()
        return np.float32(reward), bool(done), observation

    def close(self):
        self._env.close()


class BenchmarkStream:
    """Random-policy stream wrapper for throughput measurement.

    Substitutes a random action for whatever the agent chose, so measured
    FPS is independent of policy behavior (reference:
    environments.py:104-110, experiment.py:88 ``benchmark_mode``).
    """

    def __init__(self, stream: StreamAdapter, seed: int = 0):
        self._stream = stream
        self._rng = np.random.default_rng(seed)

    @property
    def observation_spec(self):
        return self._stream.observation_spec

    @property
    def action_space(self):
        return self._stream.action_space

    def initial(self):
        return self._stream.initial()

    def step(self, action):
        return self._stream.step(self.action_space.sample(self._rng))

    def close(self):
        self._stream.close()


class ImpalaStream:
    """StepOutput stream with episode accounting.

    ``initial()`` emits StepOutput(reward=0, info=(0, 0), done=True,
    initial observation) — done=True marks "start of an episode" exactly as
    the reference's FlowEnvironment.initial does (environments.py:179-196).
    ``step(action)`` accumulates episode_return/episode_step in the emitted
    info and zeroes the carried counters after a done
    (environments.py:198-233).
    """

    def __init__(self, stream):
        self._stream = stream
        self._info = StepOutputInfo(np.float32(0.0), np.int32(0))

    @property
    def observation_spec(self):
        return self._stream.observation_spec

    @property
    def action_space(self):
        return self._stream.action_space

    def initial(self) -> StepOutput:
        observation = self._stream.initial()
        self._info = StepOutputInfo(np.float32(0.0), np.int32(0))
        return StepOutput(
            reward=np.float32(0.0),
            info=self._info,
            done=np.bool_(True),
            observation=observation,
        )

    def step(self, action) -> StepOutput:
        reward, done, observation = self._stream.step(action)
        new_info = StepOutputInfo(
            episode_return=np.float32(self._info.episode_return + reward),
            episode_step=np.int32(self._info.episode_step + 1),
        )
        # Emitted info includes the final step; carried info resets on done
        # (reference: environments.py:224-230).
        self._info = (StepOutputInfo(np.float32(0.0), np.int32(0))
                      if done else new_info)
        return StepOutput(
            reward=np.float32(reward),
            info=new_info,
            done=np.bool_(done),
            observation=observation,
        )

    def close(self):
        self._stream.close()


def make_observation(frame, instruction=None) -> Observation:
    """Wrap simulator outputs into the canonical Observation pytree."""
    return Observation(frame=frame, instruction=instruction)
