"""Env construction registry with name-prefix dispatch.

The reference dispatches on name prefixes — ``doom_*``/``atari_*``/
``dmlab_*`` (reference: envs/create_env.py:1-19).  Here families register
themselves; heavyweight simulator families are imported lazily so a missing
pip package only fails when that family is actually requested.
"""

from typing import Callable, Dict, Optional, Tuple

from scalable_agent_tpu.envs.core import Environment

_FACTORIES: Dict[str, Tuple[Callable[..., Environment], bool]] = {}


def register_family(prefix: str, factory: Callable[..., Environment],
                    consumes_action_repeats: bool = False):
    """Register ``factory(full_name, **kwargs)`` for env names ``prefix*``.

    ``consumes_action_repeats``: the family applies action repeats
    natively (simulator-side, like DMLab's ``num_steps`` or Atari's
    skip pipeline) and accepts a ``num_action_repeats`` kwarg.  Families
    without it are wrapped by ``make_impala_stream`` instead and never
    see the kwarg — so third-party factories need no boilerplate.
    """
    _FACTORIES[prefix] = (factory, consumes_action_repeats)


def _lookup(full_env_name: str):
    for prefix, entry in sorted(
            _FACTORIES.items(), key=lambda kv: -len(kv[0])):
        if full_env_name.startswith(prefix):
            return entry
    raise ValueError(
        f"unknown env name {full_env_name!r}; registered prefixes: "
        f"{sorted(_FACTORIES)}")


def family_consumes_repeats(full_env_name: str) -> bool:
    return _lookup(full_env_name)[1]


def create_env(full_env_name: str, **kwargs) -> Environment:
    """Instantiate an env by prefix-dispatched name.

    (reference: envs/create_env.py:1-19)
    """
    return _lookup(full_env_name)[0](full_env_name, **kwargs)


def _make_fake(full_env_name: str, **kwargs) -> Environment:
    from scalable_agent_tpu.envs.fake import FakeEnv

    # Fake levels with a device twin read their parameters from the
    # DEVICE_LEVELS registry entry (envs/device/fake.py) — ONE copy of
    # the defaults, so probe_env's host spec and make_device_env can
    # never skew.  (Import is lazy: env worker subprocesses import this
    # module and must not pull the jax-importing device package until a
    # device level is actually requested — fake levels only touch it on
    # construction, in the parent.)
    from scalable_agent_tpu.envs.device.protocol import DEVICE_LEVELS

    entry = DEVICE_LEVELS.get(full_env_name)
    if entry is not None:
        for key, value in entry.defaults.items():
            kwargs.setdefault(key, value)
    elif full_env_name == "fake_tuple":
        # Composite action space: Tuple(Discrete, Discretized) — the
        # hermetic stand-in for Doom's composite spaces
        # (reference: envs/doom/action_space.py:13-138).  Host-only: no
        # device twin, so its defaults live here.
        from scalable_agent_tpu.envs.spaces import (
            Discrete, Discretized, TupleSpace)

        kwargs.setdefault("height", 16)
        kwargs.setdefault("width", 16)
        kwargs.setdefault("episode_length", 10)
        kwargs.setdefault("action_space", TupleSpace(
            [Discrete(3), Discretized(5, -1.0, 1.0)]))
    return FakeEnv(**kwargs)


def _lazy_family(family: str, module: str, attr: str):
    """Factory that imports its simulator module on first use and turns a
    missing module/pip package into a clear error instead of a raw
    ModuleNotFoundError deep inside an env worker."""

    def factory(full_env_name: str, **kwargs) -> Environment:
        import importlib

        try:
            mod = importlib.import_module(module)
        except ImportError as exc:
            raise ValueError(
                f"env family {family!r} is not available here: importing "
                f"{module} failed ({exc}).  Its simulator package is an "
                f"optional dependency.") from exc
        return getattr(mod, attr)(full_env_name, **kwargs)

    return factory


# Device-native levels (device_grid_*, device_minatar_* — the
# DEVICE_LEVELS registry, envs/device/protocol.py): the host twin is
# the HostDeviceEnv adapter driving the same XLA transition function
# with batch 1, so probe_env/eval and the device env agree by
# construction.  Lazy like the simulator families — the adapter jits,
# so it imports jax.
_make_device = _lazy_family(
    "device_", "scalable_agent_tpu.envs.device.host",
    "make_host_device_env")
_make_doom = _lazy_family(
    "doom_", "scalable_agent_tpu.envs.doom.factory", "make_doom_env")
_make_atari = _lazy_family(
    "atari_", "scalable_agent_tpu.envs.atari", "make_atari_env")
_make_dmlab = _lazy_family(
    "dmlab_", "scalable_agent_tpu.envs.dmlab", "make_dmlab_env")
_make_gym = _lazy_family(
    "gym_", "scalable_agent_tpu.envs.gym_adapter", "make_gym_env")


register_family("fake_", _make_fake, consumes_action_repeats=True)
register_family("device_", _make_device, consumes_action_repeats=True)
register_family("doom_", _make_doom, consumes_action_repeats=True)
register_family("atari_", _make_atari, consumes_action_repeats=True)
register_family("dmlab_", _make_dmlab, consumes_action_repeats=True)
register_family("gym_", _make_gym)
