"""Process-isolated environment execution.

Successor of the reference's ``py_process.py`` (reference:
py_process.py:62-222), re-designed for a host-runtime world:

- The reference proxies each env method call through a ``tf.py_func`` that
  blocks a TF-graph thread on a pipe.  Here the proxy is plain Python —
  the actor runtime is host code, so no graph plumbing is needed — but the
  process contract is kept: child-side exceptions are marshalled back and
  re-raised in the parent (py_process.py:129-131,171-177), ``close()`` runs
  on the child env at shutdown even after errors (py_process.py:155-159),
  and construction errors surface in ``start()``.

- Large observation frames travel through a ``multiprocessing.shared_memory``
  block instead of being pickled through the pipe — the pipe carries only
  the small fields, and the strict request/response protocol (at most one
  outstanding step) keeps the single frame slot coherent.  This is the
  TPU-feeding optimization:
  actor batch assembly memcpys straight out of shared memory into the
  staging buffer.

``EnvProcess`` hosts the *stream* protocol (initial/step/close, auto-reset)
— the same surface PyProcessDmLab/PyProcessDoom expose (reference:
environments.py:99-117).
"""

import multiprocessing as mp
import pickle
import traceback
from multiprocessing import shared_memory
from typing import Any, Callable, Optional

import numpy as np

_STEP = 0
_INITIAL = 1
_CLOSE = 2
_SPECS = 3
_PREDICT = 4  # speculative lookahead (vector.py MultiEnv.predict)


class RemoteEnvError(RuntimeError):
    """An exception raised inside the env worker process."""


def _dumps_exception(exc: BaseException) -> bytes:
    return pickle.dumps(
        RemoteEnvError(
            f"{type(exc).__name__}: {exc}\n"
            f"--- worker traceback ---\n{traceback.format_exc()}"))


def _worker_main(conn, make_stream_pickled: bytes, shm_name: Optional[str],
                 frame_spec=None):
    """Child process server loop.  (reference: py_process.py:142-177)"""
    stream = None
    shm = None
    try:
        try:
            make_stream = pickle.loads(make_stream_pickled)
            stream = make_stream()
            if shm_name is not None:
                shm = shared_memory.SharedMemory(name=shm_name)
            conn.send((True, None))
        except Exception as exc:  # constructor failure -> parent start()
            conn.send((False, _dumps_exception(exc)))
            return

        frame_view = (
            None if shm is None else np.ndarray(
                frame_spec.shape, frame_spec.dtype, buffer=shm.buf))

        def strip_frame(step_output):
            """Move the frame to shared memory (if enabled); lighten the rest."""
            frame = np.asarray(step_output.observation.frame)
            if shm is not None:
                # The slab view is built from the declared spec; a
                # mismatched env frame must fail loudly, not corrupt.
                frame_spec.validate(frame)
                frame_view[...] = frame
                return step_output._replace(
                    observation=step_output.observation._replace(frame=None))
            return step_output

        while True:
            request = conn.recv()
            kind = request[0]
            try:
                if kind == _INITIAL:
                    conn.send((True, strip_frame(stream.initial())))
                elif kind == _STEP:
                    conn.send((True, strip_frame(stream.step(request[1]))))
                elif kind == _SPECS:
                    conn.send((True, (stream.observation_spec,
                                      stream.action_space)))
                elif kind == _CLOSE:
                    break
                else:
                    raise ValueError(f"unknown request kind {kind}")
            except Exception as exc:
                conn.send((False, _dumps_exception(exc)))
    except (EOFError, KeyboardInterrupt):
        pass
    finally:
        # close() must run even on error paths (reference:
        # py_process.py:155-159).
        if stream is not None:
            try:
                stream.close()
            except Exception:
                pass
        if shm is not None:
            shm.close()
        conn.close()


class EnvProcess:
    """A stream env running in a child process.

    ``make_stream`` must be a picklable zero-arg callable returning an
    object with ``initial()/step(action)/close()`` plus
    ``observation_spec``/``action_space`` (e.g.
    ``StreamAdapter(create_env(...))``).

    If ``frame_spec`` is given, frames move via shared memory; otherwise
    they are pickled through the pipe.
    """

    def __init__(self, make_stream: Callable[[], Any], frame_spec=None,
                 ctx: Optional[str] = None):
        self._make_stream = make_stream
        self._frame_spec = frame_spec
        # spawn, not fork: the parent is the (multithreaded) JAX actor
        # process; forking it can deadlock the child on XLA/PJRT mutexes.
        self._ctx = mp.get_context(ctx or "spawn")
        self._process = None
        self._conn = None
        self._shm = None
        self._frame_view = None
        self._pending = False

    def start(self) -> "EnvProcess":
        if self._process is not None:
            raise RuntimeError("already started")
        if self._frame_spec is not None:
            nbytes = int(np.prod(self._frame_spec.shape)
                         * np.dtype(self._frame_spec.dtype).itemsize)
            self._shm = shared_memory.SharedMemory(create=True, size=nbytes)
            self._frame_view = np.ndarray(
                self._frame_spec.shape, self._frame_spec.dtype,
                buffer=self._shm.buf)
        parent_conn, child_conn = self._ctx.Pipe()
        self._process = self._ctx.Process(
            target=_worker_main,
            args=(child_conn, pickle.dumps(self._make_stream),
                  self._shm.name if self._shm else None, self._frame_spec),
            daemon=True,
        )
        self._process.start()
        child_conn.close()
        self._conn = parent_conn
        try:
            ok, payload = self._conn.recv()
        except EOFError:
            # Child died before the handshake (e.g. native simulator
            # segfault): still release pipe/process/shared memory.
            self._teardown()
            raise RemoteEnvError(
                "env worker died during construction (no handshake)")
        if not ok:
            self._teardown()
            raise pickle.loads(payload)
        return self

    def _roundtrip(self, request):
        self._conn.send(request)
        ok, payload = self._conn.recv()
        if not ok:
            raise pickle.loads(payload)
        return payload

    def _restore_frame(self, step_output):
        if self._shm is not None:
            return step_output._replace(
                observation=step_output.observation._replace(
                    frame=self._frame_view.copy()))
        return step_output

    def frame_buffer(self) -> Optional[np.ndarray]:
        """Zero-copy view of the shared frame slot (valid until next call)."""
        return self._frame_view

    def specs(self):
        return self._roundtrip((_SPECS,))

    def initial(self):
        return self._restore_frame(self._roundtrip((_INITIAL,)))

    def step(self, action):
        return self._restore_frame(self._roundtrip((_STEP, action)))

    def step_send(self, action) -> None:
        """Async half: dispatch a step without waiting for the result.

        At most one step may be outstanding: the shared-memory slot holds
        exactly one frame, so pipelining two sends would pair step N's
        reward with step N+1's observation.
        """
        if self._pending:
            raise RuntimeError("step_send while a step is outstanding")
        self._pending = True
        self._conn.send((_STEP, action))

    def step_ready(self, timeout: float = 0.0) -> bool:
        """Async completion probe: True when a dispatched step's reply
        is readable (``step_recv`` will not block); False with no step
        outstanding.  The single-env analogue of the per-worker
        readiness polling MultiEnv exposes through
        ``worker_connection`` (which the actor service drives with
        ``multiprocessing.connection.wait``)."""
        if not self._pending:
            return False
        return self._conn.poll(timeout)

    def step_recv(self):
        """Async half: collect a previously dispatched step."""
        if not self._pending:
            raise RuntimeError("step_recv without step_send")
        self._pending = False
        ok, payload = self._conn.recv()
        if not ok:
            raise pickle.loads(payload)
        return self._restore_frame(payload)

    def _teardown(self):
        if self._conn is not None:
            self._conn.close()
            self._conn = None
        if self._process is not None:
            self._process.join(timeout=5)
            if self._process.is_alive():
                self._process.kill()
                self._process.join(timeout=5)
            self._process = None
        if self._shm is not None:
            self._shm.close()
            self._shm.unlink()
            self._shm = None
            self._frame_view = None

    def close(self):
        if self._conn is not None:
            try:
                self._conn.send((_CLOSE,))
            except (BrokenPipeError, OSError):
                pass
        self._teardown()

    @property
    def alive(self) -> bool:
        return self._process is not None and self._process.is_alive()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc_info):
        self.close()
