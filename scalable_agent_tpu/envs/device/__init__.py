"""In-graph (on-device) environments: envs as pure XLA functions.

The reference steps its environments *inside* the TF graph through
``tf.py_func`` pipes to subprocesses (reference: py_process.py:97-112,
environments.py:149-233) — the graph stalls on the host every step.  The
TPU-native inversion: an environment whose transition function is
expressible in XLA runs ON the accelerator, vectorized over the batch,
inside the same jitted program as agent inference — an entire unroll (or
the whole train step) becomes ONE device launch with zero per-step
host↔device traffic.  This is the standard JAX-RL architecture
(gymnax/Brax-style) and is what lets the framework saturate a chip whose
host link is slow (e.g. a remote TPU attachment).

Package layout (docs/environments.md is the narrative version):

- ``protocol``: the DeviceEnv contract + the DEVICE_LEVELS registry +
  ``make_device_env`` — the single source of level defaults that
  envs/registry.py's host twins and the driver's ingraph validation
  also consult.  JAX-FREE: env worker subprocesses read it.
- ``fake``: ``DeviceFakeEnv``, the bit-exact mirror of envs/fake.py
  (zero-simulator-cost benchmark + hermetic test backend).
- ``world``: the shared chassis for hand-written worlds (vmapping,
  action repeats, auto-reset, accounting, hashed randomness).
- ``gridworld`` / ``minatar``: the real XLA worlds —
  ``device_grid_*`` (procedural key-door) and ``device_minatar_*``
  (Atari-lite object-channel games).
- ``host``: ``HostDeviceEnv``, the gym-like adapter that makes any
  device level a host ``Environment`` (probe_env/eval/registry).
- ``conformance``: the protocol checks every registered level must
  pass (tests/test_device_conformance.py runs the full matrix).
- ``accounting``: the ``devtel/env/*`` episode telemetry every device
  env shares (obs/device_telemetry.py instruments).

Attribute access is lazy (PEP 562): importing this package — which
envs/registry.py's jax-free worker path does to read the level-defaults
table — pulls in NO jax-importing module until a world class or the
telemetry helpers are actually touched.
"""

import importlib

_EXPORTS = {
    "DEVICE_LEVELS": "protocol",
    "DeviceEnvSpec": "protocol",
    "DeviceLevel": "protocol",
    "device_level_names": "protocol",
    "make_device_env": "protocol",
    "register_device_level": "protocol",
    "DeviceEnvState": "fake",
    "DeviceFakeEnv": "fake",
    "DeviceGridState": "gridworld",
    "DeviceGridWorld": "gridworld",
    "DeviceAsterix": "minatar",
    "DeviceBreakout": "minatar",
    "DeviceWorld": "world",
    "HostDeviceEnv": "host",
    "make_host_device_env": "host",
    "env_telemetry_spec": "accounting",
    "record_episode_telemetry": "accounting",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    module_name = _EXPORTS.get(name)
    if module_name is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    module = importlib.import_module(f"{__name__}.{module_name}")
    value = getattr(module, name)
    globals()[name] = value  # cache: subsequent accesses skip this hook
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
