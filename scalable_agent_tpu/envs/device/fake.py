"""``DeviceFakeEnv``: the [B]-vectorized pure-XLA mirror of envs/fake.py.

``DeviceFakeEnv`` mirrors the host ``FakeEnv`` (envs/fake.py) transition
math EXACTLY — same frames, rewards, episode boundaries, auto-reset and
episode accounting as ``ImpalaStream(StreamAdapter(FakeEnv(...)))`` — so
on-device rollouts are interchangeable with host rollouts
(tests/test_device_env.py asserts step-by-step equality).  It also serves
as the zero-simulator-cost throughput benchmark backend (the role of the
reference's ``doom_benchmark`` spec, envs/doom/doom_utils.py:125-129).

Integer caveat: the host FakeEnv mixes seeds with Python bigints; the
device mirror uses int32.  The cue/frame arithmetic reduces the seed
modulo its modulus BEFORE multiplying, so it is exact for ANY int32
seed; only the length-jitter mix still multiplies the raw seed, so
jittered envs require ``seed < 2**31 / 1000003`` (seed <= 2147).
``initial()`` checks the applicable bound.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scalable_agent_tpu.envs.device.protocol import DeviceEnvSpec
from scalable_agent_tpu.envs.spaces import Discrete
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.types import (
    Observation,
    StepOutput,
    StepOutputInfo,
)

__all__ = ["DeviceEnvState", "DeviceFakeEnv"]


class DeviceEnvState(NamedTuple):
    """Per-env simulator + episode-accounting state, all [B]."""

    seed: jax.Array  # i32, fixed per env
    episode: jax.Array  # i32
    step: jax.Array  # i32, simulator step within the episode
    episode_return: jax.Array  # f32, ImpalaStream carried accumulator
    episode_step: jax.Array  # i32, agent steps within the episode


class DeviceFakeEnv:
    """[B]-vectorized pure-function mirror of ``envs.fake.FakeEnv``.

    ``initial(seeds)`` and ``step(state, action)`` are pure jnp functions
    usable under ``jit``/``scan``/``vmap``; both return
    ``(DeviceEnvState, StepOutput)`` with the exact field semantics of
    the host ``ImpalaStream`` (reward sums over native action repeats,
    done folds termination, observation after done is the next episode's
    first frame, emitted info includes the final step while the carried
    accounting resets — reference: environments.py:103-117, 198-233).
    """

    def __init__(
        self,
        height: int = 72,
        width: int = 96,
        channels: int = 3,
        num_actions: int = 9,
        episode_length: int = 10,
        length_jitter: int = 0,
        num_action_repeats: int = 1,
        reward_mode: str = "schedule",
    ):
        self.height = height
        self.width = width
        self.channels = channels
        self.num_actions = num_actions
        self.episode_length = episode_length
        self.length_jitter = length_jitter
        self.num_action_repeats = max(1, int(num_action_repeats))
        if reward_mode not in ("schedule", "bandit", "memory"):
            raise ValueError(f"unknown reward_mode {reward_mode!r}")
        self.reward_mode = reward_mode
        self.action_space = Discrete(num_actions)
        self.observation_spec = Observation(
            frame=TensorSpec((height, width, channels), np.uint8, "frame"),
            instruction=None)
        # Seed bound for exact host-mirror arithmetic: every seed term
        # in _cue/_frame reduces the seed modulo its modulus BEFORE
        # multiplying, so any int32 seed is exact there; only the
        # length-jitter mix still multiplies the raw seed (the host
        # computes ``seed * 1000003`` in bigints) and keeps the tight
        # bound.
        self.max_seed = ((2**31 - 1) // 1000003 if length_jitter > 0
                         else 2**31 - 1)

    @property
    def spec(self) -> DeviceEnvSpec:
        return DeviceEnvSpec(
            observation_spec=self.observation_spec,
            action_space=self.action_space,
            num_actions=self.num_actions)

    # -- pure transition math (mirrors FakeEnv line by line) ---------------

    def _episode_len(self, seed, episode):
        if self.length_jitter <= 0:
            return jnp.full_like(episode, self.episode_length)
        # Modular arithmetic term-by-term: identical to the host's
        # bigint ``(seed*1000003 + episode*7919) % m`` but int32-safe for
        # ANY episode count (seed*1000003 is bounded by the constructor
        # guard; (m-1)*(7919%m) stays far below 2**31 for m <= 2**15).
        m = self.length_jitter + 1
        mix = ((seed * 1000003) % m + (episode % m) * (7919 % m)) % m
        return self.episode_length + mix

    def _cue(self, seed, episode, step):
        """Rewarded action index, [B] i32 — term-by-term mod of the
        host's ``(seed*131 + episode*29 [+ step*13]) % A`` (FakeEnv._cue,
        envs/fake.py): exact vs the host bigints, int32-overflow-free.
        The seed is reduced modulo ``a`` BEFORE the multiply —
        ``(seed * 131) % a`` itself overflows int32 above seed ~16.4M
        and silently diverged from the host there."""
        a = self.num_actions
        mix = (seed % a) * (131 % a) + (episode % a) * (29 % a)
        if self.reward_mode == "bandit":
            mix = mix + (step % a) * (13 % a)
        return mix % a

    def _frame(self, seed, episode, step, action):
        """uint8 [B, H, W, C]: constant base with 3 encoded pixels
        (FakeEnv._frame, envs/fake.py).  Same term-by-term mod-251
        arithmetic: exact vs the host bigints, overflow-free for any
        episode/step count.  Bandit/memory modes fill with the scaled
        cue instead (FakeEnv._fill_value)."""
        if self.reward_mode == "schedule":
            # Same mod-before-multiply discipline as _cue: seed * 131
            # would overflow int32 above ~16.4M.
            base = ((seed % 251) * (131 % 251) + (episode % 251) * 17
                    + (step % 251) * 7) % 251
        else:
            scale = 255 // max(1, self.num_actions - 1)
            base = self._cue(seed, episode, step) * scale
            if self.reward_mode == "memory":
                base = jnp.where(step == 0, base, 128)
        base = base.astype(jnp.uint8)
        b = base.shape[0]
        frame = jnp.broadcast_to(
            base[:, None, None, None],
            (b, self.height, self.width, self.channels))
        frame = frame.at[:, 0, 0, 0].set((episode % 256).astype(jnp.uint8))
        frame = frame.at[:, 0, 1, 0].set((step % 256).astype(jnp.uint8))
        frame = frame.at[:, 0, 2, 0].set((action % 256).astype(jnp.uint8))
        return frame

    def initial(self, seeds) -> Tuple[DeviceEnvState, StepOutput]:
        """Reset all envs: episode 0, step 0 — ImpalaStream.initial()
        emits reward 0, zero info, done=True ("start of episode")."""
        if not isinstance(seeds, jax.core.Tracer):
            host_seeds = np.asarray(seeds)
            if (np.abs(host_seeds) > self.max_seed).any():
                raise ValueError(
                    f"device FakeEnv seeds must stay below "
                    f"{self.max_seed} for exact host-mirror arithmetic")
        seeds = jnp.asarray(seeds, jnp.int32)
        b = seeds.shape[0]

        # One DISTINCT buffer per leaf: sharing one zeros array across
        # leaves makes any later donation of the containing pytree fail
        # with "attempt to donate the same buffer twice".
        def zero_i():
            return jnp.zeros((b,), jnp.int32)

        def zero_f():
            return jnp.zeros((b,), jnp.float32)

        state = DeviceEnvState(
            seed=seeds, episode=zero_i(), step=zero_i(),
            episode_return=zero_f(), episode_step=zero_i())
        output = StepOutput(
            reward=zero_f(),
            info=StepOutputInfo(
                episode_return=zero_f(), episode_step=zero_i()),
            done=jnp.ones((b,), bool),
            observation=Observation(
                frame=self._frame(seeds, state.episode, state.step,
                                  state.episode_step),
                instruction=None),
        )
        return state, output

    def step(self, state: DeviceEnvState, action
             ) -> Tuple[DeviceEnvState, StepOutput]:
        """One agent step = ``num_action_repeats`` masked simulator
        sub-steps with summed rewards and early stop on done, then
        auto-reset (StreamAdapter) and episode accounting (ImpalaStream).
        """
        action = jnp.asarray(action, jnp.int32)
        if action.ndim > 1:  # composite: frame encoding uses component 0
            action = action[:, 0]
        ep_len = self._episode_len(state.seed, state.episode)
        step = state.step
        reward = jnp.zeros_like(state.episode_return)
        done = jnp.zeros_like(step, dtype=bool)
        for _ in range(self.num_action_repeats):
            active = ~done
            if self.reward_mode != "schedule":
                # Pre-increment cue: the one visible in the observation
                # the agent acted on (FakeEnv.step, envs/fake.py).
                cue = self._cue(state.seed, state.episode, step)
                reward = reward + jnp.where(
                    active & (action == cue), 1.0, 0.0)
            step = step + active.astype(jnp.int32)
            sub_done = active & (step >= ep_len)
            if self.reward_mode == "schedule":
                reward = reward + jnp.where(
                    active, 0.1 * (step % 3).astype(jnp.float32), 0.0)
                reward = reward + jnp.where(sub_done, 1.0, 0.0)
            done = done | sub_done

        # Emitted info includes the final step; carried state resets on
        # done (ImpalaStream.step, envs/core.py).
        emitted_return = state.episode_return + reward
        emitted_step = state.episode_step + 1
        # Auto-reset: new episode, step 0, observation is its first frame
        # built with action=0 (StreamAdapter.step -> FakeEnv.reset).
        new_episode = state.episode + done.astype(jnp.int32)
        new_step = jnp.where(done, 0, step)
        obs_action = jnp.where(done, 0, action)
        new_state = DeviceEnvState(
            seed=state.seed,
            episode=new_episode,
            step=new_step,
            episode_return=jnp.where(done, 0.0, emitted_return),
            episode_step=jnp.where(done, 0, emitted_step),
        )
        output = StepOutput(
            reward=reward,
            info=StepOutputInfo(
                episode_return=emitted_return,
                episode_step=emitted_step),
            done=done,
            observation=Observation(
                frame=self._frame(state.seed, new_episode, new_step,
                                  obs_action),
                instruction=None),
        )
        return new_state, output
