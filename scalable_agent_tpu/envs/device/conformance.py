"""Conformance harness: the DeviceEnv protocol, enforced mechanically.

Every level registered in DEVICE_LEVELS must pass every check here
(tests/test_device_conformance.py parametrizes the full matrix, and the
registry-closure lint in tests/test_hotpath_lint.py fails the suite if
a level is registered without a conformance parametrization).  The
checks are plain functions over an ``env_factory`` (a zero-arg callable
returning a FRESH env instance) so the bench and ad-hoc world authors
can run them outside pytest:

    from scalable_agent_tpu.envs.device import conformance
    conformance.run_conformance(lambda: MyWorld())

What is pinned (the protocol contract, envs/device/protocol.py):

- ``spec``: initial/step output shapes and dtypes match the declared
  spec for ANY seeds (seeds select content, never structure).
- ``determinism``: the trajectory is a bit-exact function of
  (seeds, actions) — identical across a per-step ``jit`` loop, a
  ``lax.scan``, and a fresh env instance.
- ``autoreset``: emitted-vs-carried episode accounting — emitted info
  includes the final step (``episode_step >= 1`` after initial, return
  sums the whole episode), the carried accounting restarts after done,
  and ``done & episode_step > 0`` is a valid finished-episode detector
  (initial's done=True rows carry step 0).
- ``zero_host_sync``: a compiled rollout issues no device→host
  materialization and no host→device transfer (the PR 12 spies +
  ``jax.transfer_guard("disallow")``).
- ``donation``: the full ``(state, output)`` carry donates cleanly,
  twice — no aliased buffers anywhere in the pytree (initial's
  distinct-buffer rule AND the step program's output buffers).
"""

import contextlib
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "CHECKS",
    "check_autoreset",
    "check_determinism",
    "check_donation",
    "check_spec",
    "check_zero_host_sync",
    "conformance_seeds",
    "materialization_spy",
    "run_conformance",
]


def conformance_seeds(env, batch: int, salt: int = 0) -> np.ndarray:
    """A spread of valid seeds INCLUDING the env's documented
    ``max_seed`` bound (the length-jitter-bounded DeviceFakeEnv is the
    reason this is part of the harness: the bound edge must stay
    exact, not just small seeds).  ``salt`` selects a DIFFERENT
    multiset (not a permutation), so the spec check's two legs probe
    genuinely distinct seed values."""
    max_seed = int(getattr(env, "max_seed", 2**31 - 1))
    seeds = (np.arange(batch, dtype=np.int64) * (91757 + 2 * salt)
             + 7 + 104729 * salt) % (max_seed + 1)
    seeds[-1] = max_seed
    return seeds.astype(np.int32)


def _actions(env, batch: int, steps: int, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, env.num_actions,
                        size=(steps, batch)).astype(np.int32)


def _scan_rollout(env):
    """jitted ``(state, actions [T, B]) -> (final_state, outputs)``."""
    import jax

    def run(state, actions):
        return jax.lax.scan(env.step, state, actions)

    return jax.jit(run)


def _leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(
        tree, is_leaf=lambda x: x is None)


# -- checks ------------------------------------------------------------------


def check_spec(env_factory: Callable[[], object], batch: int = 5,
               steps: int = 3) -> None:
    """Shapes/dtypes match ``spec`` and are seed-independent."""
    import jax

    env = env_factory()
    spec = env.spec
    assert spec.action_space.n == spec.num_actions, (
        "spec.action_space must agree with spec.num_actions")
    frame_spec = spec.observation_spec.frame

    def assert_output(out, where):
        frame = out.observation.frame
        assert tuple(frame.shape) == (batch,) + tuple(frame_spec.shape), (
            f"{where}: frame {tuple(frame.shape)} != spec "
            f"[B]+{tuple(frame_spec.shape)}")
        assert frame.dtype == frame_spec.dtype, (
            f"{where}: frame dtype {frame.dtype} != {frame_spec.dtype}")
        assert out.reward.shape == (batch,), where
        assert out.reward.dtype == np.float32, where
        assert out.done.shape == (batch,), where
        assert out.done.dtype == np.bool_, where
        assert out.info.episode_return.dtype == np.float32, where
        assert out.info.episode_step.dtype == np.int32, where

    step = jax.jit(env.step)
    for tag, salt in (("seeds_a", 0), ("seeds_b", 1)):
        seeds = conformance_seeds(env, batch, salt=salt)
        state, out = env.initial(seeds)
        assert_output(out, f"{tag} initial")
        assert bool(np.asarray(out.done).all()), (
            f"{tag}: initial must emit done=True (start-of-episode)")
        assert not np.asarray(out.info.episode_step).any(), (
            f"{tag}: initial must emit episode_step 0")
        assert not np.asarray(out.reward).any(), (
            f"{tag}: initial must emit reward 0")
        actions = _actions(env, batch, steps)
        for t in range(steps):
            state, out = step(state, actions[t])
            assert_output(out, f"{tag} step {t}")


def check_determinism(env_factory: Callable[[], object], batch: int = 4,
                      steps: int = 33) -> None:
    """Bit-exact across jit/scan boundaries and env re-instantiation."""
    import jax

    env = env_factory()
    seeds = conformance_seeds(env, batch)
    actions = _actions(env, batch, steps)

    # Path A: per-step jit loop.
    step = jax.jit(env.step)
    state, _ = env.initial(seeds)
    loop_outs = []
    for t in range(steps):
        state, out = step(state, actions[t])
        loop_outs.append(jax.tree_util.tree_map(
            lambda x: None if x is None else np.asarray(x), out,
            is_leaf=lambda x: x is None))
    # Path B: one lax.scan.
    state_b, _ = env.initial(seeds)
    _, scan_outs = _scan_rollout(env)(state_b, actions)
    # Path C: a FRESH env instance, scanned.
    env_c = env_factory()
    state_c, _ = env_c.initial(seeds)
    _, scan_outs_c = _scan_rollout(env_c)(state_c, actions)

    for t in range(steps):
        for name, a, b, c in (
                ("frame", loop_outs[t].observation.frame,
                 scan_outs.observation.frame[t],
                 scan_outs_c.observation.frame[t]),
                ("reward", loop_outs[t].reward, scan_outs.reward[t],
                 scan_outs_c.reward[t]),
                ("done", loop_outs[t].done, scan_outs.done[t],
                 scan_outs_c.done[t]),
                ("episode_return", loop_outs[t].info.episode_return,
                 scan_outs.info.episode_return[t],
                 scan_outs_c.info.episode_return[t]),
                ("episode_step", loop_outs[t].info.episode_step,
                 scan_outs.info.episode_step[t],
                 scan_outs_c.info.episode_step[t])):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"jit-loop vs scan: {name} diverges at t={t}")
            np.testing.assert_array_equal(
                np.asarray(b), np.asarray(c),
                err_msg=f"scan vs fresh-instance scan: {name} diverges "
                        f"at t={t}")


def check_autoreset(env_factory: Callable[[], object], batch: int = 4,
                    steps: Optional[int] = None) -> None:
    """Emitted-vs-carried accounting + auto-reset invariants.  The
    window sizes itself to the level's horizon so every level crosses
    at least one episode boundary."""
    env = env_factory()
    if steps is None:
        # One horizon + slack guarantees every env crosses at least one
        # episode boundary (no episode outlives episode_length).
        horizon = (int(getattr(env, "episode_length", 32))
                   + int(getattr(env, "length_jitter", 0)))
        repeats = int(getattr(env, "num_action_repeats", 1))
        steps = max(16, -(-horizon // repeats) + 4)
    seeds = conformance_seeds(env, batch)
    actions = _actions(env, batch, steps, seed=1)
    state, out0 = env.initial(seeds)
    _, outs = _scan_rollout(env)(state, actions)
    reward = np.asarray(outs.reward)
    done = np.asarray(outs.done)
    ep_return = np.asarray(outs.info.episode_return)
    ep_step = np.asarray(outs.info.episode_step)

    assert (ep_step >= 1).all(), (
        "emitted episode_step must include the step just taken (>= 1 "
        "after initial) — `done & episode_step > 0` is the finished-"
        "episode detector and a 0 here breaks episode accounting")
    finished = 0
    for b in range(batch):
        expect_return, expect_step = 0.0, 0
        for t in range(steps):
            expect_return = np.float32(expect_return + reward[t, b])
            expect_step += 1
            np.testing.assert_allclose(
                ep_return[t, b], expect_return, rtol=1e-6,
                err_msg=f"emitted episode_return env {b} t={t} (must "
                        f"include the final step's reward)")
            assert ep_step[t, b] == expect_step, (
                f"emitted episode_step env {b} t={t}: {ep_step[t, b]} "
                f"!= {expect_step}")
            if done[t, b]:
                # Carried accounting resets: the NEXT emission starts a
                # fresh episode.
                expect_return, expect_step = 0.0, 0
                finished += 1
    assert finished > 0, (
        f"no episode finished in {steps} steps — the autoreset check "
        f"has no power; lower the level's episode_length or raise "
        f"`steps`")


@contextlib.contextmanager
def materialization_spy():
    """Spy every Python-level D2H materialization path on jax arrays —
    ``_value``, ``__array__`` — yielding the list of calls observed.
    THE one shared copy of the PR 12 instrumentation (the zero-sync
    tests in tests/test_device_telemetry.py and tests/test_replay.py
    delegate here), so a jaxlib upgrade that moves the materialization
    surface is fixed in one place."""
    import jaxlib.xla_extension as xe

    cls = xe.ArrayImpl
    calls: List[str] = []
    orig_value = cls.__dict__["_value"]
    orig_array = cls.__array__

    def spy_value(self):
        calls.append("_value")
        return orig_value.fget(self)

    def spy_array(self, *args, **kwargs):
        calls.append("__array__")
        return orig_array(self, *args, **kwargs)

    cls._value = property(spy_value)
    cls.__array__ = spy_array
    try:
        yield calls
    finally:
        cls._value = orig_value
        cls.__array__ = orig_array


def check_zero_host_sync(env_factory: Callable[[], object],
                         batch: int = 4, steps: int = 16) -> None:
    """A compiled rollout runs with zero host syncs: no device→host
    materialization (spied) and no host→device transfer
    (``jax.transfer_guard("disallow")`` hard-errors them)."""
    import jax
    import jax.numpy as jnp

    env = env_factory()
    seeds = conformance_seeds(env, batch)
    state, _ = env.initial(seeds)
    actions = jnp.asarray(_actions(env, batch, steps))
    rollout = _scan_rollout(env)
    state, _ = rollout(state, actions)  # pays the compile
    with materialization_spy() as calls:
        with jax.transfer_guard("disallow"):
            state, outs = rollout(state, actions)
    assert calls == [], (
        f"env rollout materialized device values on the host: {calls} "
        f"— a host callback or eager read is hiding in the step path")
    # The harness itself still reads results — outside the guard.
    assert np.isfinite(np.asarray(outs.reward)).all()


def check_donation(env_factory: Callable[[], object], batch: int = 4,
                   steps: int = 8) -> None:
    """The FULL (state, output) carry donates cleanly, twice: once for
    ``initial()``'s buffers (the distinct-buffer rule) and once for the
    step program's own outputs."""
    import jax
    import jax.numpy as jnp

    env = env_factory()
    seeds = conformance_seeds(env, batch)

    def run(carry, actions):
        def body(c, a):
            state, _ = c
            state, out = env.step(state, a)
            return (state, out), None

        carry, _ = jax.lax.scan(body, carry, actions)
        return carry

    run_jit = jax.jit(run, donate_argnums=(0,))
    actions = jnp.asarray(_actions(env, batch, steps))
    carry = env.initial(seeds)
    # Call 1 donates initial()'s buffers; call 2 donates the step
    # program's outputs.  Aliased leaves fail either call with
    # "attempt to donate the same buffer twice".
    carry = run_jit(carry, actions)
    carry = run_jit(carry, actions)
    assert np.asarray(carry[1].info.episode_step).min() >= 1


CHECKS: Dict[str, Callable[..., None]] = {
    "spec": check_spec,
    "determinism": check_determinism,
    "autoreset": check_autoreset,
    "zero_host_sync": check_zero_host_sync,
    "donation": check_donation,
}


def run_conformance(env_factory: Callable[[], object],
                    checks: Optional[Sequence[str]] = None) -> List[str]:
    """Run ``checks`` (default: all) against a fresh-env factory;
    raises AssertionError on the first violation, returns the names of
    the checks that ran."""
    names = list(checks) if checks is not None else sorted(CHECKS)
    for name in names:
        CHECKS[name](env_factory)
    return names
