"""MinAtar-style Atari-lite worlds as pure XLA transition functions.

Object-channel 10x10 frames, ``lax``/``jnp``-only dynamics, optional
sticky actions — the MinAtar reduction of the Atari games (Young &
Tian's MinAtar testbed), here reimplemented from scratch as DeviceEnv
protocol citizens so the whole game steps inside the fused in-graph
program.  These are *style* reimplementations, not bit-mirrors of the
MinAtar package: the point is a real (branchy, stateful) workload on
the device, with enough game structure to carry learning curves.

Randomness is the hashed counter stream from envs/device/world.py —
every draw a pure function of ``(seed, episode, step, tag)`` — so
trajectories stay bit-deterministic across jit/scan boundaries.
``sticky_prob > 0`` repeats the previous action with that probability
(the Machado et al. stochasticity protocol), drawn from the same
stream.

- ``device_minatar_breakout``: 3 brick rows, a one-row paddle, a
  diagonally bouncing ball; +1 per brick; losing the ball ends the
  episode; a cleared wall respawns.  Channels: paddle, ball, trail,
  bricks.
- ``device_minatar_asterix``: 8 entity lanes spawn left/right movers
  (1-in-3 gold); touching gold is +1, touching an enemy ends the
  episode.  Channels: player, enemies, gold.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from scalable_agent_tpu.envs.device.world import (
    DeviceWorld,
    _rand_below,
    _uniform,
)
from scalable_agent_tpu.envs.spaces import Discrete
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.types import Observation

__all__ = ["DeviceAsterix", "DeviceBreakout"]

_GRID = 10


class _MinAtarBase(DeviceWorld):
    """Constructor shared by the two games (``num_actions`` and
    ``num_channels`` are per-game class attributes)."""

    def __init__(self, episode_length: int = 128,
                 sticky_prob: float = 0.0,
                 num_action_repeats: int = 1):
        self.episode_length = int(episode_length)
        self.sticky_prob = float(sticky_prob)
        if not 0.0 <= self.sticky_prob < 1.0:
            raise ValueError(
                f"sticky_prob must be in [0, 1), got {sticky_prob}")
        self.num_action_repeats = max(1, int(num_action_repeats))
        self.max_seed = 2**31 - 1
        self.action_space = Discrete(self.num_actions)
        self.observation_spec = Observation(
            frame=TensorSpec((_GRID, _GRID, self.num_channels), np.uint8,
                             "frame"),
            instruction=None)

    def _effective_action(self, state, action):
        """Sticky actions: repeat ``last_action`` with ``sticky_prob``
        (compiled out entirely at the 0.0 default)."""
        if self.sticky_prob <= 0.0:
            return action
        u = _uniform(state.seed, state.episode, state.step, 101)
        return jnp.where(u < self.sticky_prob, state.last_action, action)


class DeviceBreakoutState(NamedTuple):
    seed: jax.Array  # i32
    episode: jax.Array  # i32
    step: jax.Array  # i32, simulator step within the episode
    episode_return: jax.Array  # f32, carried accumulator
    episode_step: jax.Array  # i32, agent steps within the episode
    ball_r: jax.Array  # i32
    ball_c: jax.Array  # i32
    dir_r: jax.Array  # i32 +-1
    dir_c: jax.Array  # i32 +-1
    paddle_c: jax.Array  # i32
    trail_r: jax.Array  # i32, previous ball cell
    trail_c: jax.Array  # i32
    bricks: jax.Array  # i32 [3, 10]
    last_action: jax.Array  # i32, for sticky actions


class DeviceBreakout(_MinAtarBase):
    """Breakout on a 10x10 grid: actions {noop, left, right}."""

    num_actions = 3
    num_channels = 4

    def _reset_one(self, seed, episode) -> DeviceBreakoutState:
        zero = jnp.int32(0)
        ball_c = _rand_below(_GRID, seed, episode, 11)
        dir_c = 1 - 2 * _rand_below(2, seed, episode, 12)
        return DeviceBreakoutState(
            seed=jnp.asarray(seed, jnp.int32),
            episode=jnp.asarray(episode, jnp.int32),
            step=zero, episode_return=jnp.float32(0.0),
            episode_step=zero,
            ball_r=jnp.int32(3), ball_c=ball_c,
            dir_r=jnp.int32(1), dir_c=dir_c,
            paddle_c=jnp.int32(_GRID // 2),
            trail_r=jnp.int32(3), trail_c=ball_c,
            bricks=jnp.ones((3, _GRID), jnp.int32),
            last_action=zero)

    def _substep_one(self, state: DeviceBreakoutState, action):
        # Paddle: left/right on the bottom row.
        paddle = jnp.clip(
            state.paddle_c + jnp.where(action == 1, -1, 0)
            + jnp.where(action == 2, 1, 0), 0, _GRID - 1)
        # Side-wall bounce first: flip dir_c when the move would leave.
        cand_c = state.ball_c + state.dir_c
        dir_c = jnp.where((cand_c < 0) | (cand_c >= _GRID),
                          -state.dir_c, state.dir_c)
        new_c = state.ball_c + dir_c
        cand_r = state.ball_r + state.dir_r
        dir_r = jnp.where(cand_r < 0, -state.dir_r, state.dir_r)
        new_r = state.ball_r + dir_r
        # Brick hit (rows 1..3): remove it, score, bounce back in r.
        in_bricks = (new_r >= 1) & (new_r <= 3)
        brick_row = jnp.clip(new_r - 1, 0, 2)
        hit = in_bricks & (state.bricks[brick_row, new_c] > 0)
        bricks = state.bricks.at[brick_row, new_c].set(
            jnp.where(hit, 0, state.bricks[brick_row, new_c]))
        reward = hit.astype(jnp.float32)
        dir_r = jnp.where(hit, -dir_r, dir_r)
        new_r = jnp.where(hit, state.ball_r, new_r)
        # Bottom row: paddle saves (bounce), otherwise the ball is lost.
        at_bottom = new_r >= _GRID - 1
        saved = at_bottom & (new_c == paddle)
        dir_r = jnp.where(saved, -dir_r, dir_r)
        new_r = jnp.where(saved, state.ball_r, new_r)
        lost = at_bottom & ~saved
        # Cleared wall respawns (the next wave).
        cleared = bricks.sum() == 0
        bricks = jnp.where(cleared, jnp.ones_like(bricks), bricks)
        step = state.step + 1
        terminated = lost | (step >= self.episode_length)
        new_state = state._replace(
            step=step, ball_r=new_r, ball_c=new_c, dir_r=dir_r,
            dir_c=dir_c, paddle_c=paddle, trail_r=state.ball_r,
            trail_c=state.ball_c, bricks=bricks, last_action=action)
        return new_state, reward, terminated

    def _frame_one(self, state: DeviceBreakoutState) -> jnp.ndarray:
        rr = jnp.arange(_GRID)[:, None]
        cc = jnp.arange(_GRID)[None, :]
        paddle = ((rr == _GRID - 1)
                  & (cc == state.paddle_c)).astype(jnp.uint8) * 255
        ball = ((rr == state.ball_r)
                & (cc == state.ball_c)).astype(jnp.uint8) * 255
        trail = ((rr == state.trail_r)
                 & (cc == state.trail_c)).astype(jnp.uint8) * 255
        bricks = jnp.zeros((_GRID, _GRID), jnp.int32)
        bricks = bricks.at[1:4, :].set(state.bricks)
        bricks = (bricks * 255).astype(jnp.uint8)
        return jnp.stack([paddle, ball, trail, bricks], axis=-1)


_SLOTS = 8  # concurrent entity lanes in asterix
_SPAWN_EVERY = 3  # simulator steps between spawn attempts


class DeviceAsterixState(NamedTuple):
    seed: jax.Array  # i32
    episode: jax.Array  # i32
    step: jax.Array  # i32
    episode_return: jax.Array  # f32
    episode_step: jax.Array  # i32
    player_r: jax.Array  # i32
    player_c: jax.Array  # i32
    ent_active: jax.Array  # i32 [_SLOTS]
    ent_r: jax.Array  # i32 [_SLOTS]
    ent_c: jax.Array  # i32 [_SLOTS]
    ent_dir: jax.Array  # i32 [_SLOTS] +-1
    ent_gold: jax.Array  # i32 [_SLOTS]
    last_action: jax.Array  # i32


class DeviceAsterix(_MinAtarBase):
    """Asterix on a 10x10 grid: actions {noop, up, down, left, right};
    dodge horizontally streaming enemies, collect gold."""

    num_actions = 5
    num_channels = 3

    def _reset_one(self, seed, episode) -> DeviceAsterixState:
        zero = jnp.int32(0)

        def slots():
            return jnp.zeros((_SLOTS,), jnp.int32)

        return DeviceAsterixState(
            seed=jnp.asarray(seed, jnp.int32),
            episode=jnp.asarray(episode, jnp.int32),
            step=zero, episode_return=jnp.float32(0.0),
            episode_step=zero,
            player_r=jnp.int32(_GRID // 2),
            player_c=jnp.int32(_GRID // 2),
            ent_active=slots(), ent_r=slots(), ent_c=slots(),
            ent_dir=jnp.ones((_SLOTS,), jnp.int32), ent_gold=slots(),
            last_action=zero)

    def _substep_one(self, state: DeviceAsterixState, action):
        # Player: clamped 4-way move inside the lane rows [1, 8].
        drow = jnp.where(action == 1, -1, 0) + jnp.where(action == 2, 1, 0)
        dcol = jnp.where(action == 3, -1, 0) + jnp.where(action == 4, 1, 0)
        pr = jnp.clip(state.player_r + drow, 1, _GRID - 2)
        pc = jnp.clip(state.player_c + dcol, 0, _GRID - 1)
        # Collision check 1 of 2 (MinAtar order: player moves, check,
        # entities move, check again): against PRE-MOVE entity cells,
        # so a player and an entity exchanging cells in one sub-step
        # still collide instead of phasing through each other.
        pre_colliding = ((state.ent_active > 0) & (state.ent_r == pr)
                         & (state.ent_c == pc))
        # Entities stream one cell in their direction; leaving the grid
        # frees the slot.
        ec = state.ent_c + state.ent_dir * state.ent_active
        off = (ec < 0) | (ec >= _GRID)
        active = state.ent_active * (1 - off.astype(jnp.int32))
        # Spawn attempt every _SPAWN_EVERY steps into a rotating slot.
        # Eligibility keys on the PRE-MOVE occupancy: a slot freed this
        # very sub-step (off-grid exit, possibly while pre-colliding
        # with the player) must not be refilled before the collision
        # masks below consume its old entity's gold flag — the spawn
        # waits for the slot's next rotation instead.
        step = state.step + 1
        slot = (step // _SPAWN_EVERY) % _SLOTS
        want_spawn = ((step % _SPAWN_EVERY == 0)
                      & (state.ent_active[slot] == 0))
        s_row = 1 + _rand_below(_GRID - 2, state.seed, state.episode,
                                step, 21)
        s_dir = 1 - 2 * _rand_below(2, state.seed, state.episode, step,
                                    22)
        s_gold = (_rand_below(3, state.seed, state.episode, step, 23)
                  == 0).astype(jnp.int32)
        s_col = jnp.where(s_dir > 0, 0, _GRID - 1)
        onehot = ((jnp.arange(_SLOTS) == slot).astype(jnp.int32)
                  * want_spawn.astype(jnp.int32))
        active = active * (1 - onehot) + onehot
        er = state.ent_r * (1 - onehot) + s_row * onehot
        ec = ec * (1 - onehot) + s_col * onehot
        edir = state.ent_dir * (1 - onehot) + s_dir * onehot
        egold = state.ent_gold * (1 - onehot) + s_gold * onehot
        # Collision check 2 of 2, at the post-move positions.  The
        # spawned slot cannot pre-collide (spawn eligibility above
        # keys on pre-move occupancy), so the pre mask composes with
        # the post-move gold/active arrays slot-by-slot.
        colliding = pre_colliding | (
            (active > 0) & (er == pr) & (ec == pc))
        gold_hit = colliding & (egold > 0)
        enemy_hit = colliding & (egold == 0)
        # One reward per collected gold (two converging golds pay 2).
        reward = gold_hit.sum().astype(jnp.float32)
        active = active * (1 - gold_hit.astype(jnp.int32))
        terminated = enemy_hit.any() | (step >= self.episode_length)
        new_state = state._replace(
            step=step, player_r=pr, player_c=pc, ent_active=active,
            ent_r=er, ent_c=ec, ent_dir=edir, ent_gold=egold,
            last_action=action)
        return new_state, reward, terminated

    def _frame_one(self, state: DeviceAsterixState) -> jnp.ndarray:
        rr = jnp.arange(_GRID)[:, None]
        cc = jnp.arange(_GRID)[None, :]
        player = ((rr == state.player_r)
                  & (cc == state.player_c)).astype(jnp.uint8) * 255
        ent = ((rr[:, :, None] == state.ent_r[None, None, :])
               & (cc[:, :, None] == state.ent_c[None, None, :])
               & (state.ent_active[None, None, :] > 0))
        enemies = (ent & (state.ent_gold[None, None, :] == 0)).any(-1)
        gold = (ent & (state.ent_gold[None, None, :] > 0)).any(-1)
        return jnp.stack(
            [player,
             enemies.astype(jnp.uint8) * 255,
             gold.astype(jnp.uint8) * 255], axis=-1)
