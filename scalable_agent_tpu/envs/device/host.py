"""Host twin of any device level: a gym-like ``Environment`` adapter.

Device-native worlds (``device_grid_*``, ``device_minatar_*``) have no
hand-written host implementation — their transition function IS the XLA
program.  ``HostDeviceEnv`` drives that same function with batch 1
under ``jit`` on whatever backend jax has, exposing the standard
``Environment`` reset/step surface, so ``probe_env``, eval fleets, and
the ``envs/registry.py`` prefix dispatch all work unchanged for device
levels — and "host twin matches device env" holds by construction
instead of by a mirrored reimplementation (the DeviceFakeEnv approach,
which only exists because FakeEnv predates the device layer).

Auto-reset note: the device protocol emits the NEXT episode's first
observation on done; ``reset()`` here returns that already-emitted
observation instead of advancing the env again, which composes with
``StreamAdapter`` (envs/core.py) into exactly the device stream.
"""

from typing import Optional, Tuple

import numpy as np

from scalable_agent_tpu.envs.core import Environment

__all__ = ["HostDeviceEnv", "make_host_device_env"]


class HostDeviceEnv(Environment):
    """See module docstring.  ``env`` is any DeviceEnv protocol object
    (envs/device/protocol.py)."""

    def __init__(self, env, seed: int = 0):
        import jax

        self._env = env
        self._seed = int(seed)
        self.action_space = env.action_space
        self.observation_spec = env.observation_spec
        self.native_action_repeats = env.num_action_repeats
        # Pinned to the CPU backend: this adapter is constructed inside
        # spawned env-worker subprocesses (the host pipeline's MultiEnv
        # fleets and eval workers), where the default backend would
        # initialize the TPU runtime in a CHILD while the parent holds
        # the chip — the constraint envs/__init__.py documents.  A host
        # twin is host-side simulation by definition; only the in-graph
        # backend runs the env on the accelerator.
        self._cpu = jax.local_devices(backend="cpu")[0]
        self._step_fn = jax.jit(env.step, backend="cpu")
        self._state = None
        self._last_obs = None

    def seed(self, seed: Optional[int]):
        if seed is not None:
            self._seed = int(seed)
            self._state = None  # next reset() starts the new stream

    def _obs(self, output):
        frame = np.asarray(output.observation.frame[0])
        from scalable_agent_tpu.envs.core import make_observation

        return make_observation(frame)

    def reset(self):
        if self._state is None:
            import jax

            # initial() runs eagerly — keep its ops on the CPU backend
            # too (the jitted step is already pinned).
            with jax.default_device(self._cpu):
                self._state, output = self._env.initial(
                    np.asarray([self._seed], np.int32))
            self._last_obs = self._obs(output)
        # After a done step the device env has already auto-reset and
        # emitted the new episode's first frame — hand it back.
        return self._last_obs

    def step(self, action) -> Tuple[object, float, bool, dict]:
        if self._state is None:
            raise RuntimeError("step() before reset()")
        arr = np.asarray(action)
        if arr.ndim > 0:  # composite: component 0 drives the world
            arr = arr.reshape(-1)[0]
        self._state, output = self._step_fn(
            self._state, np.asarray([arr], np.int32))
        self._last_obs = self._obs(output)
        return (self._last_obs, np.float32(output.reward[0]),
                bool(output.done[0]), {})

    def render(self, mode: str = "rgb_array"):
        if self._last_obs is None:
            self.reset()
        return self._last_obs.frame


def make_host_device_env(full_env_name: str, **kwargs) -> HostDeviceEnv:
    """The ``device_`` family factory envs/registry.py dispatches to.
    Kwargs the host pipeline threads for other families (height/width/
    with_instruction) pass through to ``make_device_env``, which
    resolves them per level (``accepts`` filter; a truthy
    with_instruction gets its documented clear error)."""
    from scalable_agent_tpu.envs.device.protocol import make_device_env

    num_action_repeats = int(kwargs.pop("num_action_repeats", 1))
    return HostDeviceEnv(make_device_env(
        full_env_name, num_action_repeats=num_action_repeats, **kwargs))
