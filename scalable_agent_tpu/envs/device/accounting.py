"""Device-resident episode accounting shared by every device env.

The host pipeline's episodes surface through MultiEnv ring buffers; a
device env's episodes would otherwise surface ONLY through the fused
step's metrics dict — invisible to the registry/prom/report plane.
These instruments ride the fused program's donated telemetry pytree
(obs/device_telemetry.py) instead: counters for finished episodes and
agent steps, and bucketed return/length histograms whose exact
sum/count give exact means at any bucket resolution — fetched once per
log interval, published as ``devtel/env/*``.
"""

from typing import Dict

import jax.numpy as jnp

from scalable_agent_tpu.obs.device_telemetry import DeviceTelemetry
from scalable_agent_tpu.types import StepOutput

__all__ = ["env_telemetry_spec", "record_episode_telemetry"]


def env_telemetry_spec() -> DeviceTelemetry:
    """The one ``devtel/env/*`` instrument set (see module docstring)."""
    return (
        DeviceTelemetry("env")
        .counter("episodes", "episodes finished on device")
        .counter("steps", "agent steps executed on device")
        .histogram(
            "episode_return",
            (-10.0, -1.0, 0.0, 1.0, 2.0, 5.0, 10.0, 30.0, 100.0),
            "per-episode return at episode end (emitted accounting)")
        .histogram(
            "episode_length",
            (5.0, 10.0, 30.0, 100.0, 300.0, 1000.0, 3000.0),
            "per-episode agent steps at episode end")
    )


def record_episode_telemetry(spec: DeviceTelemetry, tel: Dict,
                             env_outputs: StepOutput) -> Dict:
    """Fold a ``[T, B]`` (or ``[B]``) StepOutput sequence into the env
    telemetry — pure jnp, safe inside the fused jitted step.

    Episode-end detection matches the fused trainer's metrics
    accounting exactly (runtime/ingraph.py): ``done & episode_step >
    0`` — the initial-reset ``done=True`` rows carry step 0 and must
    not count as finished episodes."""
    done = env_outputs.done
    steps = env_outputs.info.episode_step
    finished = jnp.logical_and(done, steps > 0)
    tel = spec.inc(tel, "episodes",
                   finished.sum().astype(jnp.float32))
    tel = spec.inc(tel, "steps", jnp.float32(done.size))
    tel = spec.observe(tel, "episode_return",
                       env_outputs.info.episode_return, where=finished)
    tel = spec.observe(tel, "episode_length",
                       steps.astype(jnp.float32), where=finished)
    return tel
