"""The DeviceEnv protocol + the DEVICE_LEVELS registry.

A *device environment* is an environment whose transition function IS an
XLA program: ``initial``/``step`` are pure jnp functions over ``[B]``
batched state, usable under ``jit``/``scan``/``vmap``, so an entire
unroll (or the whole fused train step, runtime/ingraph.py) compiles into
ONE device launch with zero per-step host↔device traffic.  This module
is the contract every such world implements and the single registry
every consumer — ``make_device_env``, ``envs/registry.py``'s host twin
family, the driver's ``--train_backend=ingraph`` validation, the
conformance harness, and ``bench_device_env`` — consults.

The protocol (enforced mechanically by envs/device/conformance.py on
every registered level):

- ``spec`` describes shapes/dtypes/action space; outputs must match it
  for ANY seed (seeds select content, never structure).
- ``initial(seeds) -> (state, StepOutput[B])`` resets all envs.  The
  emitted output has ``done=True`` ("start of episode", the reference's
  FlowEnvironment.initial), reward 0, and zeroed episode info.
- ``step(state, action) -> (state, StepOutput[B])`` advances one agent
  step (= ``num_action_repeats`` simulator sub-steps, rewards summed,
  early stop on termination) and AUTO-RESETS: when ``done``, the
  emitted observation is already the NEXT episode's first frame (the
  StreamAdapter contract, envs/core.py), so the T+1-overlap trajectory
  layout needs no host-side reset step.
- Episode accounting is emitted-vs-carried (ImpalaStream): the emitted
  ``info`` INCLUDES the final step (``episode_step >= 1`` after
  initial, ``episode_return`` sums the whole episode), while the
  carried state resets to zero on done.  Finished-episode detection is
  ``done & (info.episode_step > 0)`` — initial's done=True rows carry
  step 0 and never count.
- Determinism: the trajectory is a pure function of (seeds, actions) —
  bit-identical across jit/scan boundaries and env re-instantiation.
- Donation safety: every array leaf of ``(state, output)`` is a
  DISTINCT buffer (no aliasing), so the fused trainer can donate the
  full carry without "donate the same buffer twice".
- Zero host syncs: nothing in ``initial``/``step`` may materialize a
  device value or call back into the host (the hot-path lint,
  tests/test_hotpath_lint.py, covers this package).

See docs/environments.md for the worked walkthrough.
"""

from typing import Callable, Dict, NamedTuple, Tuple, Union

from scalable_agent_tpu.envs.spaces import Discrete
from scalable_agent_tpu.types import Observation

# NOTE: this module is JAX-FREE by design, and its registrations below
# name their world classes as lazy "module:attr" strings: env worker
# subprocesses reach the level-defaults table through
# envs/registry.py's fake family without importing jax (spawn latency,
# and the TPU runtime must never initialize in children).  The world
# modules only load when an env is actually constructed.

__all__ = [
    "DEVICE_LEVELS",
    "DeviceEnvSpec",
    "DeviceLevel",
    "device_level_names",
    "make_device_env",
    "register_device_level",
]


class DeviceEnvSpec(NamedTuple):
    """Seed-independent structure of a device env's interface."""

    observation_spec: Observation  # pytree of TensorSpec
    action_space: Discrete
    num_actions: int


class DeviceLevel(NamedTuple):
    """One registered device level.

    ``defaults`` are the level's constructor parameters — the ONE copy
    both ``make_device_env`` and the host-twin factories in
    envs/registry.py read, so the device env and ``probe_env``'s host
    spec can never skew.  ``factory`` is the world class/callable, or a
    lazy ``"module:attr"`` string resolved on first construction.
    ``accepts`` names the config-level override knobs (``height``/
    ``width``/``num_actions``) this level honors; overrides outside it
    are ignored — a gridworld's frame geometry is fixed by its
    dynamics, not by ``--height``.
    """

    name: str
    factory: Union[str, Callable[..., object]]
    defaults: Dict[str, object]
    accepts: Tuple[str, ...]
    description: str

    def build(self, **params):
        factory = self.factory
        if isinstance(factory, str):
            import importlib

            module, _, attr = factory.partition(":")
            factory = getattr(importlib.import_module(module), attr)
        return factory(**params)


DEVICE_LEVELS: Dict[str, DeviceLevel] = {}


def register_device_level(name: str,
                          factory: Union[str, Callable[..., object]],
                          defaults: Dict[str, object],
                          accepts: Tuple[str, ...] = (),
                          description: str = "") -> None:
    """Register a device level.  Double registration raises — a level's
    defaults must have exactly one home."""
    if name in DEVICE_LEVELS:
        raise ValueError(f"device level {name!r} already registered")
    DEVICE_LEVELS[name] = DeviceLevel(
        name=name, factory=factory, defaults=dict(defaults),
        accepts=tuple(accepts), description=description)


def device_level_names() -> Tuple[str, ...]:
    return tuple(sorted(DEVICE_LEVELS))


def make_device_env(level_name: str, height: int = 0, width: int = 0,
                    num_actions: int = 0, num_action_repeats: int = 1,
                    with_instruction: bool = False,
                    **kwargs):
    """Device-env factory for levels expressible as pure XLA functions
    (the in-graph training backend, runtime/ingraph.py + driver
    --train_backend=ingraph).

    Level parameters come from the DEVICE_LEVELS entry — the same
    defaults envs/registry.py's host twins consult.  ``height``/
    ``width``/``num_actions`` of 0 mean "use the level default"; a
    nonzero override is honored only when the level's registry entry
    ``accepts`` that knob (the driver passes its config values for
    every level, and a world with dynamics-fixed geometry must not be
    silently resized into nonsense).  Explicit ``**kwargs`` always win
    — they address the constructor directly, for tests and benches.

    Levels whose simulators live in external processes (doom_/dmlab_/
    atari_) cannot run in-graph; asking for one is a clear error, not a
    silent fallback.
    """
    if with_instruction:
        raise ValueError(
            "device envs do not emit instruction observations")
    entry = DEVICE_LEVELS.get(level_name)
    if entry is None:
        raise ValueError(
            f"level {level_name!r} has no device (in-graph) "
            f"implementation; device-expressible levels: "
            f"{sorted(DEVICE_LEVELS)}")
    params = dict(entry.defaults)
    for knob, value in (("height", height), ("width", width),
                        ("num_actions", num_actions)):
        if value and knob in entry.accepts:
            params[knob] = value
    params.update(kwargs)
    return entry.build(num_action_repeats=num_action_repeats, **params)


# -- the registry --------------------------------------------------------

# The fake family (envs/device/fake.py — bit-exact mirrors of
# envs/fake.py; their host twins in envs/registry.py read THESE
# defaults).
register_device_level(
    "fake_benchmark", "scalable_agent_tpu.envs.device.fake:DeviceFakeEnv",
    dict(height=72, width=96, episode_length=1000, num_actions=9),
    accepts=("height", "width", "num_actions"),
    description="zero-simulator-cost throughput benchmark fake")
register_device_level(
    "fake_small", "scalable_agent_tpu.envs.device.fake:DeviceFakeEnv",
    dict(height=16, width=16, episode_length=10, num_actions=9),
    accepts=("height", "width", "num_actions"),
    description="small deterministic fake for smoke tests")
register_device_level(
    "fake_bandit", "scalable_agent_tpu.envs.device.fake:DeviceFakeEnv",
    dict(height=16, width=16, episode_length=16, num_actions=4,
         reward_mode="bandit"),
    accepts=("height", "width", "num_actions"),
    description="learnable contextual bandit (learning-proof level)")
register_device_level(
    "fake_memory", "scalable_agent_tpu.envs.device.fake:DeviceFakeEnv",
    dict(height=16, width=16, episode_length=8, num_actions=4,
         reward_mode="memory"),
    accepts=("height", "width", "num_actions"),
    description="first-frame-cue memory task (LSTM done-reset proof)")

# The real worlds (device-native; their host twins are the
# envs/device/host.py adapter driving the same transition function).
register_device_level(
    "device_grid_small",
    "scalable_agent_tpu.envs.device.gridworld:DeviceGridWorld",
    dict(grid_size=5, view=5, cell_px=3, episode_length=24),
    description="5x5 key-door gridworld, near-full observability — the "
                "short-run learnability level")
register_device_level(
    "device_grid_large",
    "scalable_agent_tpu.envs.device.gridworld:DeviceGridWorld",
    dict(grid_size=11, view=5, cell_px=3, episode_length=96),
    description="11x11 key-door gridworld, partial observation window")
register_device_level(
    "device_minatar_breakout",
    "scalable_agent_tpu.envs.device.minatar:DeviceBreakout",
    dict(episode_length=128, sticky_prob=0.0),
    description="MinAtar-style breakout: object-channel 10x10 frames, "
                "pure-lax dynamics")
register_device_level(
    "device_minatar_asterix",
    "scalable_agent_tpu.envs.device.minatar:DeviceAsterix",
    dict(episode_length=128, sticky_prob=0.0),
    description="MinAtar-style asterix: streaming enemies/gold, "
                "hash-spawned")
