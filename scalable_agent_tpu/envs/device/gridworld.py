"""``DeviceGridWorld``: a procedurally-generated key-door gridworld in XLA.

The first *real* device world (ROADMAP item 1): unlike ``DeviceFakeEnv``
(whose transition function is a handful of scalar mods — zero simulator
cost), every step here does actual work — layout hashing, collision
logic, partial-observation rendering — all expressed as pure ``jnp`` so
the whole thing batch-vectorizes over ``[B]`` and fuses into the
in-graph megastep.

World (one episode):

- A ``grid_size x grid_size`` room split by a vertical wall whose
  column, door row, agent start, key, and goal positions are all hashed
  from ``(seed, episode)`` — every episode is a fresh layout, every
  layout solvable by construction (key and agent share the near side,
  the goal sits behind the wall, the door is always in the wall).
- Actions: 4 (up / down / left / right).  Moving into the border or the
  wall is a no-op; the door cell only admits an agent carrying the key.
- Sparse rewards: +0.5 picking up the key, +0.5 the first pass through
  the door, +1.0 reaching the goal (terminates).  Episodes also
  truncate at ``episode_length`` simulator steps.
- Observation: a ``view x view`` window centered on the agent (cells
  outside the room render as wall), upscaled ``cell_px`` pixels per
  cell into the uint8 frame.  Channels: R = walls/door (closed 160,
  open 64, wall 255), G = key (255) + the agent marker at the center
  (128, 192 when carrying the key), B = goal (255).

Layout hashing is counter-based (envs/device/world.py), so ANY int32
seed is valid — there is no host twin whose bigint arithmetic must be
mirrored (the host-side view of this world is the generic adapter in
envs/device/host.py, which steps THIS function).
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scalable_agent_tpu.envs.device.world import DeviceWorld, _rand_below
from scalable_agent_tpu.envs.spaces import Discrete
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.types import Observation

__all__ = ["DeviceGridState", "DeviceGridWorld"]


class DeviceGridState(NamedTuple):
    """Per-env state, all [B] (vmapped scalars internally)."""

    seed: jax.Array  # i32, fixed per env
    episode: jax.Array  # i32
    step: jax.Array  # i32, simulator step within the episode
    episode_return: jax.Array  # f32, carried accumulator
    episode_step: jax.Array  # i32, agent steps within the episode
    row: jax.Array  # i32 agent position
    col: jax.Array  # i32
    has_key: jax.Array  # i32 0/1
    door_open: jax.Array  # i32 0/1


# Action deltas: up, down, left, right.  Kept as numpy (no jax array
# materialization at import time); use sites lift to jnp so traced
# actions can index.
_DROW = np.array([-1, 1, 0, 0], np.int32)
_DCOL = np.array([0, 0, -1, 1], np.int32)


class DeviceGridWorld(DeviceWorld):
    """See module docstring.  ``initial``/``step`` follow the DeviceEnv
    protocol (envs/device/protocol.py): pure jnp, auto-reset, emitted-
    vs-carried episode accounting."""

    num_channels = 3

    def __init__(self, grid_size: int = 7, view: int = 5,
                 cell_px: int = 3, episode_length: int = 48,
                 num_action_repeats: int = 1):
        if grid_size < 5:
            raise ValueError("grid_size must be >= 5 (2 cells per side "
                             "of the wall)")
        if view % 2 != 1:
            raise ValueError("view must be odd (agent-centered window)")
        self.grid_size = int(grid_size)
        self.view = int(view)
        self.cell_px = int(cell_px)
        self.episode_length = int(episode_length)
        self.num_action_repeats = max(1, int(num_action_repeats))
        self.num_actions = 4
        self.max_seed = 2**31 - 1
        self.action_space = Discrete(self.num_actions)
        side = self.view * self.cell_px
        self.observation_spec = Observation(
            frame=TensorSpec((side, side, self.num_channels), np.uint8,
                             "frame"),
            instruction=None)

    # -- layout (pure function of seed, episode) ---------------------------

    def _layout(self, seed, episode):
        """(wall_col, door_row, agent_r, agent_c, key_r, key_c,
        goal_r, goal_c) — scalars i32, solvable by construction."""
        g = self.grid_size
        wall = 2 + _rand_below(max(1, g - 4), seed, episode, 1)
        door = _rand_below(g, seed, episode, 2)
        # Near side: cols [0, wall) — agent and key, distinct cells.
        near = wall * g
        agent_idx = _rand_below(near, seed, episode, 3)
        key_idx = _rand_below(near - 1, seed, episode, 4)
        key_idx = jnp.where(key_idx >= agent_idx, key_idx + 1, key_idx)
        agent_r, agent_c = agent_idx // wall, agent_idx % wall
        key_r, key_c = key_idx // wall, key_idx % wall
        # Far side: cols (wall, g).
        far_w = g - wall - 1
        goal_idx = _rand_below(far_w * g, seed, episode, 5)
        goal_r = goal_idx // far_w
        goal_c = wall + 1 + goal_idx % far_w
        return wall, door, agent_r, agent_c, key_r, key_c, goal_r, goal_c

    # -- rendering ---------------------------------------------------------

    def _frame_one(self, state: DeviceGridState) -> jnp.ndarray:
        """uint8 [view*px, view*px, 3] window centered on the agent."""
        g, v = self.grid_size, self.view
        wall, door, _, _, key_r, key_c, goal_r, goal_c = self._layout(
            state.seed, state.episode)
        half = v // 2
        rows = state.row - half + jnp.arange(v, dtype=jnp.int32)
        cols = state.col - half + jnp.arange(v, dtype=jnp.int32)
        rr = rows[:, None]  # [v, 1]
        cc = cols[None, :]  # [1, v]
        outside = (rr < 0) | (rr >= g) | (cc < 0) | (cc >= g)
        on_wall_col = cc == wall
        is_door = on_wall_col & (rr == door)
        is_wall = outside | (on_wall_col & ~is_door)
        is_key = ((rr == key_r) & (cc == key_c)
                  & (state.has_key == 0) & ~outside)
        is_goal = (rr == goal_r) & (cc == goal_c) & ~outside

        red = jnp.where(
            is_wall, 255,
            jnp.where(is_door & ~outside,
                      jnp.where(state.door_open > 0, 64, 160), 0))
        green = jnp.where(is_key, 255, 0)
        # Agent marker at the window center; carrying the key brightens
        # it so the inventory bit is observable.
        center = jnp.arange(v) == half
        at_center = center[:, None] & center[None, :]
        green = jnp.where(
            at_center, jnp.where(state.has_key > 0, 192, 128), green)
        blue = jnp.where(is_goal, 255, 0)
        cells = jnp.stack([red, green, blue], axis=-1).astype(jnp.uint8)
        px = self.cell_px
        return jnp.repeat(jnp.repeat(cells, px, axis=0), px, axis=1)

    # -- transitions -------------------------------------------------------

    def _reset_one(self, seed, episode) -> DeviceGridState:
        _, _, agent_r, agent_c, _, _, _, _ = self._layout(seed, episode)
        zero = jnp.int32(0)
        return DeviceGridState(
            seed=jnp.asarray(seed, jnp.int32),
            episode=jnp.asarray(episode, jnp.int32),
            step=zero, episode_return=jnp.float32(0.0),
            episode_step=zero, row=agent_r, col=agent_c,
            has_key=zero, door_open=zero)

    def _substep_one(self, state: DeviceGridState, action
                     ) -> Tuple[DeviceGridState, jnp.ndarray, jnp.ndarray]:
        """One simulator sub-step: (new_state, reward, terminated)."""
        g = self.grid_size
        wall, door, _, _, key_r, key_c, goal_r, goal_c = self._layout(
            state.seed, state.episode)
        nr = jnp.clip(state.row + jnp.asarray(_DROW)[action], 0, g - 1)
        nc = jnp.clip(state.col + jnp.asarray(_DCOL)[action], 0, g - 1)
        into_door = (nc == wall) & (nr == door)
        blocked = ((nc == wall) & ~into_door) | (
            into_door & (state.has_key == 0))
        nr = jnp.where(blocked, state.row, nr)
        nc = jnp.where(blocked, state.col, nc)

        picked = ((nr == key_r) & (nc == key_c)
                  & (state.has_key == 0) & (nc < wall))
        opened = into_door & ~blocked & (state.door_open == 0)
        reached = (nr == goal_r) & (nc == goal_c)
        reward = (0.5 * picked.astype(jnp.float32)
                  + 0.5 * opened.astype(jnp.float32)
                  + 1.0 * reached.astype(jnp.float32))
        step = state.step + 1
        terminated = reached | (step >= self.episode_length)
        new_state = state._replace(
            row=nr, col=nc, step=step,
            has_key=jnp.maximum(state.has_key,
                                picked.astype(jnp.int32)),
            door_open=jnp.maximum(state.door_open,
                                  opened.astype(jnp.int32)))
        return new_state, reward, terminated
