"""Shared machinery for hand-written device worlds.

``DeviceWorld`` is the common chassis under the gridworld and MinAtar
families: subclasses implement three single-env pure functions —
``_reset_one(seed, episode) -> state``, ``_substep_one(state, action)
-> (state, reward, terminated)``, ``_frame_one(state) -> uint8 frame``
— and the base supplies the full DeviceEnv protocol surface: [B]
vmapping, the action-repeat loop (masked sub-steps, summed rewards,
early stop), auto-reset, the emitted-vs-carried episode accounting, and
the donation-safe ``initial``.

Subclass state NamedTuples must carry the five accounting fields the
protocol's consumers read (``seed``, ``episode``, ``step``,
``episode_return``, ``episode_step``); everything else is game state.

Randomness is hashed, not carried: ``_mix``/``_rand_below``/``_uniform``
are counter-based draws (FNV-1a + murmur avalanche in uint32 —
wraparound multiply is defined XLA behavior), pure functions of
whatever (seed, episode, step, tag) terms the caller mixes.  No PRNG
key threads through the state, so trajectories are bit-deterministic
across jit/scan boundaries and resume-exact, and ANY int32 seed is
valid (``max_seed`` is the full int32 range).
"""

from typing import Tuple

import jax
import jax.numpy as jnp

from scalable_agent_tpu.envs.device.protocol import DeviceEnvSpec
from scalable_agent_tpu.types import (
    Observation,
    StepOutput,
    StepOutputInfo,
)

__all__ = ["DeviceWorld", "_mix", "_rand_below", "_uniform"]


def _mix(*terms) -> jnp.ndarray:
    """FNV-1a over int32 terms + a murmur-style avalanche, uint32."""
    h = jnp.uint32(2166136261)
    for t in terms:
        h = (h ^ jnp.asarray(t).astype(jnp.uint32)) * jnp.uint32(16777619)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0x5BD1E995)
    return h ^ (h >> 15)


def _rand_below(n, *terms) -> jnp.ndarray:
    """Hashed i32 in [0, n).  ``n`` may be traced (>= 1)."""
    return (_mix(*terms) % jnp.asarray(n, jnp.uint32)).astype(jnp.int32)


def _uniform(*terms) -> jnp.ndarray:
    """Hashed f32 in [0, 1)."""
    return (_mix(*terms) >> 8).astype(jnp.float32) * (1.0 / (1 << 24))


class DeviceWorld:
    """Protocol chassis; see module docstring.  Subclasses set
    ``num_actions``, ``action_space``, ``observation_spec``,
    ``episode_length``, ``num_action_repeats``, ``max_seed``."""

    @property
    def spec(self) -> DeviceEnvSpec:
        return DeviceEnvSpec(
            observation_spec=self.observation_spec,
            action_space=self.action_space,
            num_actions=self.num_actions)

    def _effective_action(self, state, action):
        """Hook for action stochasticity (sticky actions); identity by
        default."""
        return action

    # -- single-env composition --------------------------------------------

    def _step_one(self, state, action) -> Tuple[object, StepOutput]:
        action = jnp.asarray(action, jnp.int32)
        reward = jnp.float32(0.0)
        done = jnp.bool_(False)
        sim = state
        for _ in range(self.num_action_repeats):
            eff = self._effective_action(sim, action)
            nxt, r, term = self._substep_one(sim, eff)
            active = ~done
            # Masked sub-step: once done, later repeats are no-ops.
            sim = jax.tree_util.tree_map(
                lambda new, old: jnp.where(active, new, old), nxt, sim)
            reward = reward + jnp.where(active, r, 0.0)
            done = done | (active & term)

        # Emitted info includes the final step; carried state resets on
        # done (the ImpalaStream contract, envs/core.py).
        emitted_return = state.episode_return + reward
        emitted_step = state.episode_step + 1
        carried = sim._replace(episode_return=emitted_return,
                               episode_step=emitted_step)
        # Auto-reset: the emitted observation after done is the NEXT
        # episode's first frame (StreamAdapter contract).
        reset = self._reset_one(state.seed, state.episode + 1)
        new_state = jax.tree_util.tree_map(
            lambda a, b: jnp.where(done, a, b), reset, carried)
        output = StepOutput(
            reward=reward,
            info=StepOutputInfo(
                episode_return=emitted_return,
                episode_step=emitted_step),
            done=done,
            observation=Observation(
                frame=self._frame_one(new_state), instruction=None),
        )
        return new_state, output

    # -- the [B] protocol surface ------------------------------------------

    def initial(self, seeds) -> Tuple[object, StepOutput]:
        seeds = jnp.asarray(seeds, jnp.int32)
        b = seeds.shape[0]
        state = jax.vmap(self._reset_one)(
            seeds, jnp.zeros((b,), jnp.int32))
        # One DISTINCT buffer per leaf (the envs/device donation
        # lesson): vmap broadcasts equal constant leaves (step /
        # episode_step / last_action are all zeros) from the SAME
        # traced value, and donating a pytree with aliased leaves fails
        # with "attempt to donate the same buffer twice".
        state = jax.tree_util.tree_map(jnp.copy, state)

        def zero_i():
            return jnp.zeros((b,), jnp.int32)

        def zero_f():
            return jnp.zeros((b,), jnp.float32)

        output = StepOutput(
            reward=zero_f(),
            info=StepOutputInfo(
                episode_return=zero_f(), episode_step=zero_i()),
            done=jnp.ones((b,), bool),
            observation=Observation(
                frame=jax.vmap(self._frame_one)(state),
                instruction=None),
        )
        return state, output

    def step(self, state, action) -> Tuple[object, StepOutput]:
        action = jnp.asarray(action, jnp.int32)
        if action.ndim > 1:  # composite: use component 0
            action = action[:, 0]
        return jax.vmap(self._step_one)(state, action)
