"""Generic observation/reward/control wrappers.

TPU-native re-design of the reference's wrapper library (reference:
envs/env_wrappers.py — frame stack :58-115, skip :118-142, skip+stack
:145-166, normalize :169-205, resize/grayscale :208-267, vertical crop
:270-290, reward scaling :293-300, time limit :303-334, remaining-time obs
:337-365, HWC→CHW :368-420, reward clip :423-430, episode recording
:433-497).

Differences by design:
- Wrappers act on the canonical ``Observation`` pytree (frame +
  optional instruction) instead of bare gym arrays.
- Default pixel layout stays HWC: TPU convs are NHWC-native, so the
  reference's HWC→CHW conversion (a torch-ism) is available for parity but
  never used in the TPU path.
- Frame stacking stacks along the channel axis so the agent's conv input
  remains one [H, W, C*k] image — one big MXU-friendly conv instead of a
  ragged list.
"""

import json
import os
from collections import deque
from typing import Optional

import numpy as np

from scalable_agent_tpu.envs.core import Environment, Wrapper
from scalable_agent_tpu.envs.spec import TensorSpec


def _resize_frame(frame: np.ndarray, height: int, width: int) -> np.ndarray:
    try:
        import cv2

        out = cv2.resize(frame, (width, height),
                         interpolation=cv2.INTER_AREA)
        if out.ndim == 2:
            out = out[:, :, None]
        return out
    except ImportError:
        # Nearest-neighbor numpy fallback.
        h, w = frame.shape[:2]
        rows = (np.arange(height) * h // height)
        cols = (np.arange(width) * w // width)
        return frame[rows][:, cols]


class ObservationWrapper(Wrapper):
    """Base for wrappers that only rewrite observations: subclasses
    implement ``_transform`` once and both reset/step stay consistent.

    A ``None`` observation passes through untouched — lockstep
    multiplayer envs emit (None, None, None, None) on non-update ticks
    (reference: env_wrappers.py:240-242, doom_multiagent.py:207-208).
    """

    def _transform(self, observation):
        raise NotImplementedError

    def reset(self):
        return self._transform(self.env.reset())

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        if obs is None:
            return obs, reward, done, info
        return self._transform(obs), reward, done, info


class ResizeWrapper(ObservationWrapper):
    """Resize frames (optionally grayscale, optionally add channel dim).

    (reference: envs/env_wrappers.py:208-267)
    """

    def __init__(self, env: Environment, height: int, width: int,
                 grayscale: bool = False):
        super().__init__(env)
        self._height, self._width = height, width
        self._grayscale = grayscale
        frame_spec = env.observation_spec.frame
        channels = 1 if grayscale else frame_spec.shape[-1]
        self._spec = env.observation_spec._replace(
            frame=TensorSpec((height, width, channels), frame_spec.dtype,
                             frame_spec.name))

    @property
    def observation_spec(self):
        return self._spec

    def _transform(self, observation):
        frame = observation.frame
        if self._grayscale and frame.shape[-1] == 3:
            frame = np.asarray(
                frame @ np.array([0.299, 0.587, 0.114]), frame.dtype
            )[..., None]
        if frame.shape[:2] != (self._height, self._width):
            frame = _resize_frame(frame, self._height, self._width)
        return observation._replace(frame=frame)


class FrameStackWrapper(Wrapper):
    """Stack the last k frames along the channel axis.

    (reference: envs/env_wrappers.py:58-115; channel-stacking instead of a
    list so the conv torso sees one [H, W, C*k] tensor)
    """

    def __init__(self, env: Environment, stack: int):
        super().__init__(env)
        self._stack = stack
        self._frames = deque(maxlen=stack)
        frame_spec = env.observation_spec.frame
        h, w, c = frame_spec.shape
        self._spec = env.observation_spec._replace(
            frame=TensorSpec((h, w, c * stack), frame_spec.dtype,
                             frame_spec.name))

    @property
    def observation_spec(self):
        return self._spec

    def _emit(self, observation):
        return observation._replace(
            frame=np.concatenate(list(self._frames), axis=-1))

    def reset(self):
        observation = self.env.reset()
        for _ in range(self._stack):
            self._frames.append(observation.frame)
        return self._emit(observation)

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        if obs is None:  # lockstep multiplayer non-update tick
            return obs, reward, done, info
        self._frames.append(obs.frame)
        return self._emit(obs), reward, done, info


class SkipFramesWrapper(Wrapper):
    """Repeat each action k times, summing rewards.

    (reference: envs/env_wrappers.py:118-142)
    """

    def __init__(self, env: Environment, skip_frames: int):
        super().__init__(env)
        self._skip = skip_frames

    def step(self, action):
        total_reward, done, info = 0.0, False, {}
        obs = None
        for _ in range(self._skip):
            obs, reward, done, info = self.env.step(action)
            if obs is None:  # lockstep multiplayer non-update tick
                return obs, reward, done, info
            total_reward += float(reward)
            if done:
                break
        return obs, np.float32(total_reward), done, info


class SkipAndStackWrapper(Wrapper):
    """Frameskip + stack combined.  (reference: envs/env_wrappers.py:145-166)"""

    def __init__(self, env: Environment, skip_frames: int = 4,
                 stack_frames: int = 3):
        super().__init__(FrameStackWrapper(
            SkipFramesWrapper(env, skip_frames), stack_frames))


class NormalizeWrapper(ObservationWrapper):
    """uint8 frames -> float32 in [-1, 1].

    (reference: envs/env_wrappers.py:169-205.)  NOTE: the TPU path never
    uses this — normalization happens on-device inside the torso
    (models/networks.py) so uint8 rides the host→TPU link at 1/4 the bytes.
    """

    def __init__(self, env: Environment):
        super().__init__(env)
        frame_spec = env.observation_spec.frame
        self._spec = env.observation_spec._replace(
            frame=TensorSpec(frame_spec.shape, np.float32, frame_spec.name))

    @property
    def observation_spec(self):
        return self._spec

    def _transform(self, observation):
        frame = observation.frame.astype(np.float32) / 128.0 - 1.0
        return observation._replace(frame=frame)


class VerticalCropWrapper(ObservationWrapper):
    """Crop frames vertically to a centered band.

    (reference: envs/env_wrappers.py:270-290)
    """

    def __init__(self, env: Environment, crop_h: int):
        super().__init__(env)
        frame_spec = env.observation_spec.frame
        h, w, c = frame_spec.shape
        if crop_h > h:
            raise ValueError(f"crop_h {crop_h} > frame height {h}")
        self._top = (h - crop_h) // 2
        self._crop_h = crop_h
        self._spec = env.observation_spec._replace(
            frame=TensorSpec((crop_h, w, c), frame_spec.dtype,
                             frame_spec.name))

    @property
    def observation_spec(self):
        return self._spec

    def _transform(self, observation):
        frame = observation.frame[self._top:self._top + self._crop_h]
        return observation._replace(frame=frame)


class RewardScalingWrapper(Wrapper):
    """Multiply rewards by a constant.  (reference: envs/env_wrappers.py:293-300)"""

    def __init__(self, env: Environment, scale: float):
        super().__init__(env)
        self._scale = float(scale)

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        if obs is None:  # lockstep multiplayer non-update tick
            return obs, reward, done, info
        return obs, np.float32(reward * self._scale), done, info


class ClipRewardWrapper(Wrapper):
    """Clip rewards to [-1, 1].  (reference: envs/env_wrappers.py:423-430)"""

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        if obs is None:  # lockstep multiplayer non-update tick
            return obs, reward, done, info
        return obs, np.float32(np.clip(reward, -1.0, 1.0)), done, info


class TimeLimitWrapper(Wrapper):
    """Terminate episodes after a step budget (+- deterministic variation).

    (reference: envs/env_wrappers.py:303-334; the reference randomizes the
    limit per episode to decorrelate resets across a vectorized batch)
    """

    TERMINATED_BY_TIMER = "timer"

    def __init__(self, env: Environment, limit: int, random_variation: int = 0,
                 seed: int = 0):
        super().__init__(env)
        self._limit = limit
        self._variation = random_variation
        self._rng = np.random.default_rng(seed)
        self._this_limit = limit
        self._steps = 0

    def _draw_limit(self):
        if self._variation <= 0:
            return self._limit
        return int(self._limit
                   + self._rng.integers(-self._variation, self._variation + 1))

    def reset(self):
        self._steps = 0
        self._this_limit = self._draw_limit()
        return self.env.reset()

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        if obs is None:  # lockstep multiplayer non-update tick
            return obs, reward, done, info
        self._steps += 1
        if not done and self._steps >= self._this_limit:
            done = True
            info[self.TERMINATED_BY_TIMER] = True
        return obs, reward, done, info


class PixelFormatWrapper(ObservationWrapper):
    """HWC <-> CHW conversion.

    (reference: envs/env_wrappers.py:368-420.)  Exists for parity with
    torch-layout consumers; the TPU path stays HWC (NHWC convs).
    """

    def __init__(self, env: Environment, to_format: str = "CHW"):
        super().__init__(env)
        if to_format != "CHW":
            raise ValueError("only CHW conversion supported")
        frame_spec = env.observation_spec.frame
        h, w, c = frame_spec.shape
        self._spec = env.observation_spec._replace(
            frame=TensorSpec((c, h, w), frame_spec.dtype, frame_spec.name))

    @property
    def observation_spec(self):
        return self._spec

    def _transform(self, observation):
        return observation._replace(
            frame=np.transpose(observation.frame, (2, 0, 1)))


class RecordingWrapper(Wrapper):
    """Record episodes: frames as .npy + actions/rewards as JSON.

    (reference: envs/env_wrappers.py:433-497 records PNG frames +
    actions.json; .npy avoids an image-codec dependency)
    """

    def __init__(self, env: Environment, record_to: str):
        super().__init__(env)
        self._dir = record_to
        os.makedirs(record_to, exist_ok=True)
        # Continue numbering past any existing recordings: a respawned
        # env worker re-runs this constructor on the same directory, and
        # restarting at 0 would overwrite already-recorded episodes.
        existing = [
            int(name[len("episode_"):])
            for name in os.listdir(record_to)
            if name.startswith("episode_")
            and name[len("episode_"):].isdigit()
        ]
        self._episode = max(existing, default=-1)
        # Whether THIS instance has reset yet: the first reset must
        # always advance past ``_episode`` (which may point at a
        # previous worker's last recording), while later stepless
        # resets reuse their number.  Gating the advance on the episode
        # counter instead conflated the two and made a respawned worker
        # overwrite the last recorded episode.
        self._has_reset = False
        self._frames = []
        self._actions = []
        self._rewards = []

    def _flush(self):
        # Gate on recorded ACTIONS, not frames: a reset-reset sequence
        # with no steps between (multiplayer worker INIT reset followed
        # by the aggregator's initial()) leaves one lone reset frame,
        # and flushing it would pollute every stream with a degenerate
        # 0-action leading episode.
        if self._episode >= 0 and self._actions:
            ep_dir = os.path.join(self._dir, f"episode_{self._episode:05d}")
            os.makedirs(ep_dir, exist_ok=True)
            np.save(os.path.join(ep_dir, "frames.npy"),
                    np.stack(self._frames))
            with open(os.path.join(ep_dir, "episode.json"), "w") as f:
                json.dump({
                    "actions": [np.asarray(a).tolist()
                                for a in self._actions],
                    "rewards": [float(r) for r in self._rewards],
                }, f)

    def reset(self):
        # The first reset of THIS instance numbers past whatever is
        # already on disk (a respawned worker must not overwrite the
        # previous instance's last episode); after that, advance only
        # past episodes that actually stepped — a stepless reset (see
        # _flush) reuses its number, so recordings stay consecutive.
        if not self._has_reset:
            self._has_reset = True
            self._episode += 1
        elif self._actions:
            self._flush()
            self._episode += 1
        self._frames, self._actions, self._rewards = [], [], []
        observation = self.env.reset()
        self._frames.append(np.asarray(observation.frame))
        return observation

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        if obs is None:  # lockstep multiplayer non-update tick
            return obs, reward, done, info
        self._frames.append(np.asarray(obs.frame))
        self._actions.append(action)
        self._rewards.append(reward)
        return obs, reward, done, info

    def close(self):
        self._flush()
        return self.env.close()


class RemainingTimeWrapper(ObservationWrapper):
    """Expose normalized remaining time as an extra observation channel.

    (reference: envs/env_wrappers.py:337-365 adds a scalar to a Dict obs;
    here it is painted into the last channel of the frame's bottom row to
    keep the observation a single tensor for the TPU path)
    """

    def __init__(self, env: Environment, limit: int):
        super().__init__(env)
        self._limit = limit
        self._steps = 0

    def _transform(self, observation):
        frame = np.array(observation.frame)
        fraction_left = max(0.0, 1.0 - self._steps / self._limit)
        frame[-1, :, -1] = np.asarray(
            fraction_left * 255, frame.dtype)
        return observation._replace(frame=frame)

    def reset(self):
        self._steps = 0
        return super().reset()

    def step(self, action):
        self._steps += 1
        return super().step(action)
