from scalable_agent_tpu.envs.core import (
    BenchmarkStream,
    Environment,
    ImpalaStream,
    StreamAdapter,
    Wrapper,
)
from scalable_agent_tpu.envs.fake import FakeEnv
from scalable_agent_tpu.envs.registry import create_env, register_family
from scalable_agent_tpu.envs.spec import TensorSpec, spec_of
from scalable_agent_tpu.envs.vector import MultiEnv
from scalable_agent_tpu.envs.worker import EnvProcess, RemoteEnvError


def make_impala_stream(env_name: str, seed: int = 0,
                       benchmark_mode: bool = False, **kwargs):
    """Name -> seeded ImpalaStream; picklable via functools.partial.

    The one-stop factory the actor runtime and env workers use
    (the role of create_environment, reference: experiment.py:430-459).
    """
    env = create_env(env_name, **kwargs)
    env.seed(seed)
    stream = StreamAdapter(env)
    if benchmark_mode:
        stream = BenchmarkStream(stream, seed=seed)
    return ImpalaStream(stream)
