from scalable_agent_tpu.envs.core import (
    BenchmarkStream,
    Environment,
    ImpalaStream,
    StreamAdapter,
    Wrapper,
)
# NOTE: envs.device (the in-graph env layer) is deliberately NOT
# re-exported here: this package __init__ is imported by spawned env
# worker subprocesses, which must stay jax-free (spawn latency, and the
# TPU runtime must never initialize in children).  Import it as
# ``from scalable_agent_tpu.envs import device`` on the parent side only.
from scalable_agent_tpu.envs.fake import FakeEnv
from scalable_agent_tpu.envs.registry import (
    create_env,
    family_consumes_repeats,
    register_family,
)
from scalable_agent_tpu.envs.spec import TensorSpec, spec_of
from scalable_agent_tpu.envs.vector import MultiEnv
from scalable_agent_tpu.envs.worker import EnvProcess, RemoteEnvError


def make_impala_stream(env_name: str, seed: int = 0,
                       benchmark_mode: bool = False,
                       num_action_repeats: int = 1,
                       record_to: str = "", **kwargs):
    """Name -> seeded ImpalaStream; picklable via functools.partial.

    The one-stop factory the actor runtime and env workers use
    (the role of create_environment, reference: experiment.py:430-459).

    ``num_action_repeats`` makes each agent step drive the simulator that
    many times (summed rewards) — the reference applies this natively in
    its DMLab adapter (``num_steps``, reference: environments.py:111) and
    via frameskip wrappers elsewhere.  Adapters that already repeat
    internally (e.g. the Atari skip-4 pipeline, Doom's skip_frames
    make_action) declare ``native_action_repeats`` and are not
    double-wrapped.
    """
    if family_consumes_repeats(env_name):
        kwargs["num_action_repeats"] = num_action_repeats
    env = create_env(env_name, **kwargs)
    env.seed(seed)
    native = getattr(env, "native_action_repeats", 1)
    if num_action_repeats > 1 and num_action_repeats != native:
        if native != 1:
            raise ValueError(
                f"{env_name!r} applies {native} native action repeats; "
                f"cannot also request {num_action_repeats}")
        from scalable_agent_tpu.envs.wrappers import SkipFramesWrapper
        env = SkipFramesWrapper(env, num_action_repeats)
    if record_to:
        # Works for every family (the Doom pipeline can also record
        # pre-wrapper frames via its own spec-level record_to).
        from scalable_agent_tpu.envs.wrappers import RecordingWrapper
        env = RecordingWrapper(env, record_to)
    stream = StreamAdapter(env)
    if benchmark_mode:
        stream = BenchmarkStream(stream, seed=seed)
    return ImpalaStream(stream)
