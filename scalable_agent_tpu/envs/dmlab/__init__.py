"""DeepMind Lab environment family (optional ``deepmind_lab`` dependency).

Role of the reference's two DMLab adapters in one module:

- ``PyProcessDmLab`` (reference: environments.py:66-140): production
  IMPALA adapter — [RGB_INTERLEAVED, INSTR] observations, seeded resets
  from a per-env RandomState, native action repeats via ``num_steps``,
  the 9-action DEFAULT_ACTION_SET, level cache.
- ``DmlabGymEnv`` (reference: envs/dmlab/dmlab_utils.py:50-135): the
  vendored Sample-Factory adapter — spec table (dmlab_sparse etc.),
  hardware renderer, 5-action classic set.

Differences by design:

- One ``DmLabEnv`` implements the framework ``Environment`` protocol;
  auto-reset/episode accounting live in the stream layer (envs/core.py),
  not in the adapter.
- The INSTR string is hashed host-side to fixed int32 token ids (TPU/XLA
  cannot consume strings; utils/text.py) — the reference ships strings
  into the TF graph and hashes there (experiment.py:123-132).
- Benchmark-mode random actions are the stream layer's BenchmarkStream
  (envs/core.py), not an adapter flag (reference: environments.py:104-110).
"""

import dataclasses
import os
import shutil
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from scalable_agent_tpu.envs.core import Environment
from scalable_agent_tpu.envs.spaces import Discrete
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.types import Observation
from scalable_agent_tpu.utils.text import MAX_INSTRUCTION_LEN, hash_instruction

# 7-dof native action vectors (look_lr, look_ud, strafe, forward, fire,
# jump, crouch).  Published calibration constants that must match the
# reference for parity (reference: environments.py:53-63).
DEFAULT_ACTION_SET = (
    (0, 0, 0, 1, 0, 0, 0),    # Forward
    (0, 0, 0, -1, 0, 0, 0),   # Backward
    (0, 0, -1, 0, 0, 0, 0),   # Strafe Left
    (0, 0, 1, 0, 0, 0, 0),    # Strafe Right
    (-20, 0, 0, 0, 0, 0, 0),  # Look Left
    (20, 0, 0, 0, 0, 0, 0),   # Look Right
    (-20, 0, 0, 1, 0, 0, 0),  # Look Left + Forward
    (20, 0, 0, 1, 0, 0, 0),   # Look Right + Forward
    (0, 0, 0, 0, 1, 0, 0),    # Fire
)

# The vendored SF adapter's reduced set (reference: dmlab_utils.py:15-21).
CLASSIC_ACTION_SET = (
    (0, 0, 0, 0, 0, 0, 0),    # Idle
    (0, 0, 0, 1, 0, 0, 0),    # Forward
    (0, 0, 0, -1, 0, 0, 0),   # Backward
    (-20, 0, 0, 0, 0, 0, 0),  # Look Left
    (20, 0, 0, 0, 0, 0, 0),   # Look Right
)

DEFAULT_CACHE_DIR = os.environ.get(
    "DMLAB_LEVEL_CACHE", "/tmp/dmlab_level_cache")


class LevelCache:
    """Compiled-level cache handed to deepmind_lab.Lab: DMLab calls
    ``fetch(key, pk3_path)`` before compiling a level and ``write`` after
    (reference: environments.py:33-50, dmlab_utils.py:24-47)."""

    def __init__(self, cache_dir: str = DEFAULT_CACHE_DIR):
        self._cache_dir = cache_dir
        os.makedirs(cache_dir, exist_ok=True)

    def fetch(self, key: str, pk3_path: str) -> bool:
        path = os.path.join(self._cache_dir, key)
        if os.path.isfile(path):
            shutil.copyfile(path, pk3_path)
            return True
        return False

    def write(self, key: str, pk3_path: str) -> None:
        path = os.path.join(self._cache_dir, key)
        if not os.path.isfile(path):
            shutil.copyfile(pk3_path, path)


@dataclasses.dataclass(frozen=True)
class DmLabSpec:
    name: str
    level: str
    extra_cfg: Tuple[Tuple[str, str], ...] = ()


# The vendored SF spec table (reference: dmlab_utils.py:136-144).
DMLAB_ENVS = (
    DmLabSpec("dmlab_sparse",
              "contributed/dmlab30/explore_goal_locations_large"),
    DmLabSpec("dmlab_very_sparse",
              "contributed/dmlab30/explore_goal_locations_large",
              (("minGoalDistance", "10"),)),
    DmLabSpec("dmlab_sparse_doors",
              "contributed/dmlab30/explore_obstructed_goals_large"),
    DmLabSpec("dmlab_nonmatch",
              "contributed/dmlab30/rooms_select_nonmatching_object"),
    DmLabSpec("dmlab_watermaze",
              "contributed/dmlab30/rooms_watermaze"),
)


def resolve_level(full_env_name: str) -> Tuple[str, Dict[str, str]]:
    """``dmlab_*`` name -> (level path, extra config).

    Resolution order: the SF spec table, then any DMLab-30 level name
    (train or test variant, envs/dmlab30.py), then a raw level path after
    the prefix (e.g. ``dmlab_contributed/dmlab30/rooms_watermaze``).
    """
    for spec in DMLAB_ENVS:
        if spec.name == full_env_name:
            return spec.level, dict(spec.extra_cfg)
    short = full_env_name[len("dmlab_"):]
    from scalable_agent_tpu.envs import dmlab30

    if short in dmlab30.ALL_LEVELS or short in dmlab30._BY_TEST_NAME:
        return f"contributed/dmlab30/{short}", {}
    if "/" in short:
        return short, {}
    raise ValueError(
        f"unknown DMLab env {full_env_name!r}: not an SF spec, a DMLab-30 "
        f"level, or a raw level path")


class DmLabEnv(Environment):
    """deepmind_lab.Lab behind the framework Environment protocol."""

    def __init__(
        self,
        level: str,
        width: int = 96,
        height: int = 72,
        action_set: Sequence[Tuple[int, ...]] = DEFAULT_ACTION_SET,
        num_action_repeats: int = 1,
        seed: int = 0,
        config: Optional[Dict[str, str]] = None,
        renderer: str = "hardware",
        level_cache: Optional[LevelCache] = None,
        with_instruction: bool = True,
        instruction_len: int = MAX_INSTRUCTION_LEN,
        runfiles_path: Optional[str] = None,
    ):
        import deepmind_lab

        if runfiles_path:
            deepmind_lab.set_runfiles_path(runfiles_path)
        self._obs_names = (["RGB_INTERLEAVED", "INSTR"] if with_instruction
                           else ["RGB_INTERLEAVED"])
        full_config = {"width": str(width), "height": str(height)}
        full_config.update(
            {k: str(v) for k, v in (config or {}).items()})
        self._lab = deepmind_lab.Lab(
            level=level,
            observations=self._obs_names,
            config=full_config,
            renderer=renderer,
            level_cache=(LevelCache() if level_cache is None
                         else level_cache),
        )
        self._action_list = np.array(action_set, dtype=np.intc)
        # Native repeats: one agent step = num_action_repeats simulator
        # steps through Lab's own num_steps (reference: environments.py:111)
        # — make_impala_stream sees this attribute and skips its wrapper.
        self.native_action_repeats = int(num_action_repeats)
        self._num_steps = int(num_action_repeats)
        self._random_state = np.random.RandomState(seed=seed)
        self._with_instruction = with_instruction
        self._instruction_len = instruction_len
        self.action_space = Discrete(len(self._action_list))
        self.observation_spec = Observation(
            frame=TensorSpec((height, width, 3), np.uint8, "frame"),
            instruction=(TensorSpec((instruction_len,), np.int32,
                                    "instruction")
                         if with_instruction else None))

    def seed(self, seed: Optional[int]) -> None:
        if seed is not None:
            self._random_state = np.random.RandomState(seed=int(seed))

    def _observe(self) -> Observation:
        obs = self._lab.observations()
        instruction = None
        if self._with_instruction:
            instr = obs.get("INSTR", "")
            if isinstance(instr, bytes):
                instr = instr.decode("utf-8", errors="replace")
            instruction = hash_instruction(
                str(instr), max_len=self._instruction_len)
        return Observation(
            frame=np.asarray(obs["RGB_INTERLEAVED"], np.uint8),
            instruction=instruction)

    def reset(self) -> Observation:
        # Seeded per-episode resets (reference: environments.py:92-93).
        self._lab.reset(seed=int(
            self._random_state.randint(0, 2 ** 31 - 1)))
        return self._observe()

    def step(self, action):
        reward = self._lab.step(
            self._action_list[int(action)], num_steps=self._num_steps)
        done = not self._lab.is_running()
        if done:
            # A finished Lab episode has no observations; emit the spec's
            # zero frame (the stream layer resets immediately after).
            observation = Observation(
                frame=np.zeros(self.observation_spec.frame.shape, np.uint8),
                instruction=(np.zeros((self._instruction_len,), np.int32)
                             if self._with_instruction else None))
        else:
            observation = self._observe()
        return observation, float(reward), bool(done), {
            "num_frames": self._num_steps}

    def render(self, mode: str = "rgb_array"):
        return np.asarray(
            self._lab.observations()["RGB_INTERLEAVED"], np.uint8)

    def close(self):
        self._lab.close()


def make_dmlab_env(full_env_name: str, width: int = 96, height: int = 72,
                   num_action_repeats: int = 1, seed: int = 0,
                   dataset_path: str = "", renderer: str = "hardware",
                   with_instruction: bool = True,
                   **kwargs) -> Environment:
    """Name -> DmLabEnv.  Registered under the ``dmlab_`` prefix.

    ``dataset_path`` feeds the psychlab datasets config key exactly as the
    reference threads it (reference: experiment.py:445-449).
    """
    level, extra_cfg = resolve_level(full_env_name)
    config = dict(extra_cfg)
    if dataset_path:
        config["datasetPath"] = dataset_path
    config.update({k: str(v) for k, v in kwargs.items()})
    return DmLabEnv(
        level=level, width=width, height=height,
        num_action_repeats=num_action_repeats, seed=seed, config=config,
        renderer=renderer, with_instruction=with_instruction)
