"""Level-cache warmer: repeatedly reset DMLab envs across worker
processes so the compiled-level cache fills before a training run.

(reference: envs/dmlab/dmlab_populate_cache.py:8-30 — 64 envs x 16
workers resetting in a loop)

Run: python -m scalable_agent_tpu.envs.dmlab.populate_cache \
        --level_name=dmlab_watermaze --num_envs=64 --num_workers=16
"""

import argparse
import multiprocessing as mp

from scalable_agent_tpu.utils import log


def _worker(level_name: str, width: int, height: int, seed: int,
            num_resets: int, counter) -> None:
    from scalable_agent_tpu.envs.dmlab import make_dmlab_env

    env = make_dmlab_env(level_name, width=width, height=height, seed=seed)
    try:
        for _ in range(num_resets):
            env.reset()
            with counter.get_lock():
                counter.value += 1
    finally:
        env.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--level_name", default="dmlab_watermaze")
    parser.add_argument("--num_envs", type=int, default=64)
    parser.add_argument("--num_workers", type=int, default=16)
    parser.add_argument("--width", type=int, default=96)
    parser.add_argument("--height", type=int, default=72)
    args = parser.parse_args(argv)

    resets_per_worker = max(1, args.num_envs // args.num_workers)
    ctx = mp.get_context("spawn")
    counter = ctx.Value("i", 0)
    procs = [
        ctx.Process(target=_worker,
                    args=(args.level_name, args.width, args.height,
                          1000 + i, resets_per_worker, counter),
                    daemon=True)
        for i in range(args.num_workers)
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join()
    log.info("generated %d environments into the level cache",
             counter.value)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
