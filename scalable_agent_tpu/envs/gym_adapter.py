"""Gymnasium bridge: run any gymnasium env inside this framework.

The reference's env zoo is built on gym-0.x envs consumed directly
(reference: envs/atari/atari_utils.py:39-55 ``gym.make`` + wrappers).
Here a single adapter maps the modern gymnasium API (5-tuple steps,
reset(seed=...)) onto the framework's ``Environment`` protocol, and the
``gym_*`` registry family makes every installed gymnasium env a usable
level name (e.g. ``gym_CartPole-v1``) — including vector-observation
envs, whose frames come from ``render()`` so the pixel-based IMPALA agent
can train on them.
"""

from typing import Optional

import numpy as np

from scalable_agent_tpu.envs.core import Environment
from scalable_agent_tpu.envs.spaces import Discrete
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.types import Observation


def _is_image_space(space) -> bool:
    shape = getattr(space, "shape", None)
    dtype = getattr(space, "dtype", None)
    return (shape is not None and len(shape) == 3 and shape[-1] in (1, 3)
            and dtype is not None and np.dtype(dtype) == np.uint8)


class GymnasiumEnv(Environment):
    """Wrap a gymnasium env (instance or id) as a framework Environment.

    - 5-tuple steps fold (terminated, truncated) into one ``done`` (the
      gym-0.x contract the rest of the stack uses, envs/core.py).
    - Seeding follows the gymnasium idiom: the seed is applied on the next
      ``reset`` and cleared after, so later resets draw fresh episodes.
    - If the observation is not an image, frames come from
      ``render()`` (render_mode='rgb_array' is requested at make time).
    """

    def __init__(self, env, render_frames: Optional[bool] = None):
        if isinstance(env, str):
            import gymnasium

            try:
                env = gymnasium.make(env, render_mode="rgb_array")
            except TypeError:
                env = gymnasium.make(env)
        self._env = env
        if not hasattr(env.action_space, "n"):
            raise ValueError(
                f"only discrete action spaces are supported, got "
                f"{env.action_space}")
        self.action_space = Discrete(int(env.action_space.n))
        self._render_frames = (
            not _is_image_space(env.observation_space)
            if render_frames is None else render_frames)
        if self._render_frames:
            # Probe one render to learn the frame shape.
            self._env.reset(seed=0)
            frame = np.asarray(self._env.render())
            if frame.ndim != 3:
                raise ValueError(
                    f"render() must produce an [H, W, C] frame, got shape "
                    f"{frame.shape}")
            frame_shape = frame.shape
        else:
            frame_shape = tuple(env.observation_space.shape)
        self.observation_spec = Observation(
            frame=TensorSpec(frame_shape, np.uint8, "frame"),
            instruction=None)
        self._seed: Optional[int] = None

    def seed(self, seed: Optional[int]) -> None:
        self._seed = None if seed is None else int(seed)

    def _observe(self, obs) -> Observation:
        if self._render_frames:
            frame = np.asarray(self._env.render(), np.uint8)
        else:
            frame = np.asarray(obs, np.uint8)
        return Observation(frame=frame, instruction=None)

    def reset(self) -> Observation:
        if self._seed is not None:
            obs, _ = self._env.reset(seed=self._seed)
            self._seed = None
        else:
            obs, _ = self._env.reset()
        return self._observe(obs)

    def step(self, action):
        obs, reward, terminated, truncated, info = self._env.step(
            int(action))
        return (self._observe(obs), float(reward),
                bool(terminated or truncated), dict(info))

    def render(self, mode: str = "rgb_array"):
        return self._env.render()

    def close(self):
        self._env.close()


def make_gym_env(full_env_name: str, height: Optional[int] = None,
                 width: Optional[int] = None, **kwargs) -> Environment:
    """``gym_<gymnasium id>`` -> adapted env, resized if height/width
    given.  Registered under the ``gym_`` prefix (envs/registry.py)."""
    env_id = full_env_name[len("gym_"):]
    env = GymnasiumEnv(env_id)
    if height is not None and width is not None:
        from scalable_agent_tpu.envs.wrappers import ResizeWrapper

        env = ResizeWrapper(env, height, width)
    return env
