"""Tensor specifications for pre-declared env output shapes/dtypes.

The reference declares env-method output specs statically so the TF graph
can be built before the env subprocess exists (``_tensor_specs``,
reference: py_process.py:30-36, environments.py:122-140).  The TPU-native
framework needs the same thing for a different reason: actor-side
trajectory buffers and device staging arrays are pre-allocated from these
specs, and jitted functions need static shapes.
"""

from typing import Any, NamedTuple, Tuple

import numpy as np


class TensorSpec(NamedTuple):
    """Shape + dtype (+ debug name) of one array-valued field."""

    shape: Tuple[int, ...]
    dtype: Any
    name: str = ""

    def zeros(self) -> np.ndarray:
        return np.zeros(self.shape, dtype=self.dtype)

    def validate(self, value) -> np.ndarray:
        value = np.asarray(value)
        if tuple(value.shape) != tuple(self.shape):
            raise ValueError(
                f"spec {self.name or '<unnamed>'}: shape {value.shape} != "
                f"declared {self.shape}")
        if np.dtype(value.dtype) != np.dtype(self.dtype):
            raise ValueError(
                f"spec {self.name or '<unnamed>'}: dtype {value.dtype} != "
                f"declared {np.dtype(self.dtype)}")
        return value


def spec_of(value, name: str = "") -> TensorSpec:
    """Spec describing a concrete numpy value."""
    value = np.asarray(value)
    return TensorSpec(shape=tuple(value.shape), dtype=value.dtype, name=name)
