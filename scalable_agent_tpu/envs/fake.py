"""Deterministic fake environment for hermetic tests and benchmarks.

The reference has no fake backend — every test that needs an env spins a
real simulator (SURVEY §4) — which makes the full actor→learner loop
untestable without VizDoom/DMLab installed.  This env closes that gap:

- Transitions are a pure function of (seed, episode_index, step_index), so
  trajectories are reproducible golden data.
- Rewards follow a fixed per-step schedule with a terminal bonus; episode
  length is fixed (optionally jittered deterministically per episode).
- Observation frames encode (episode, step, action) in their first pixels,
  so tests can assert exactly which transition produced a frame.

Also serves as the throughput benchmark backend (the role of the
reference's `doom_benchmark` spec, envs/doom/doom_utils.py:125-129) with
zero simulator cost.
"""

from typing import Optional, Tuple

import numpy as np

from scalable_agent_tpu.envs.core import Environment, make_observation
from scalable_agent_tpu.envs.spaces import Discrete, Space
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.types import Observation


class FakeEnv(Environment):
    """Deterministic episodic environment.

    Three reward modes:

    - ``"schedule"`` (default): reward at step t (1-based) is
      ``0.1 * (t % 3)``; the terminal step adds +1.  Rewards ignore the
      action — deterministic golden data, NOT learnable.
    - ``"bandit"``: a contextual bandit.  Every frame is filled with a
      per-step cue value; the action matching the cue earns +1, others 0.
      A uniform-random policy earns ``episode_length / num_actions`` per
      episode, the optimal policy ``episode_length`` — the gap is the
      learning signal the end-to-end learning tests (tests/
      test_learning.py) assert on, standing in for the reference's
      published learning curves (reference: README.md:36-44).
    - ``"memory"``: like bandit, but the cue is fixed per episode and
      shown ONLY in the episode's first frame (later frames are blank
      mid-gray).  Solving it requires the LSTM to latch the cue across
      the episode — and a broken done-reset leaks the previous episode's
      latched cue, so learning collapses toward chance; this is the
      red-test for the core's done-reset semantics.

    Episode length = ``episode_length`` (+ per-episode deterministic
    jitter of 0..length_jitter).  Frames are uint8 [H, W, C] with
    pixel[0,0,0] = episode index % 256, pixel[0,1,0] = step index % 256,
    pixel[0,2,0] = last action % 256, and the rest mode-dependent
    (deterministic pattern / cue fill).
    """

    def __init__(
        self,
        height: int = 72,
        width: int = 96,
        channels: int = 3,
        num_actions: int = 9,
        episode_length: int = 10,
        length_jitter: int = 0,
        seed: int = 0,
        with_instruction: bool = False,
        instruction_len: int = 16,
        action_space: Optional[Space] = None,
        num_action_repeats: int = 1,
        reward_mode: str = "schedule",
    ):
        self._h, self._w, self._c = height, width, channels
        # Native action repeats, like DMLab's ``num_steps`` (reference:
        # environments.py:111): one ``step`` call advances the simulator
        # ``num_action_repeats`` sub-steps with summed rewards and
        # early-stop on done — bit-identical to wrapping a repeats=1
        # FakeEnv in SkipFramesWrapper, but one Python call instead of k.
        self.native_action_repeats = max(1, int(num_action_repeats))
        # Composite spaces (TupleSpace) exercise the tuple-distribution
        # path hermetically (reference tests need real Doom for this).
        self.action_space = action_space or Discrete(num_actions)
        if reward_mode not in ("schedule", "bandit", "memory"):
            raise ValueError(f"unknown reward_mode {reward_mode!r}")
        if reward_mode != "schedule" and not isinstance(
                self.action_space, Discrete):
            raise ValueError(
                f"reward_mode {reward_mode!r} needs a Discrete action "
                f"space (the cue is an action index)")
        self._reward_mode = reward_mode
        # Cues index the ACTUAL action space: a caller passing an
        # explicit Discrete(n) must get reachable cues (and the
        # documented random floor episode_length/n), regardless of the
        # num_actions arg.
        self._num_actions = (self.action_space.n
                             if isinstance(self.action_space, Discrete)
                             else num_actions)
        self._episode_length = episode_length
        self._length_jitter = length_jitter
        self._seed = seed
        self._episode = -1
        self._step = 0
        self._with_instruction = with_instruction
        self._instruction_len = instruction_len
        frame_spec = TensorSpec((height, width, channels), np.uint8, "frame")
        instr_spec = (
            TensorSpec((instruction_len,), np.int32, "instruction")
            if with_instruction else None)
        self.observation_spec = Observation(
            frame=frame_spec, instruction=instr_spec)

    def seed(self, seed: Optional[int]):
        if seed is not None:
            self._seed = int(seed)

    def _episode_len(self) -> int:
        if self._length_jitter <= 0:
            return self._episode_length
        # Deterministic per-(seed, episode) jitter.
        mix = (self._seed * 1000003 + self._episode * 7919) % (
            self._length_jitter + 1)
        return self._episode_length + mix

    def _cue(self, step: int) -> int:
        """The rewarded action for (seed, episode, step).  Plain modular
        arithmetic so the device mirror (envs/device/fake.py) reproduces it
        exactly in int32.  Memory mode drops the step term: one cue per
        episode."""
        mix = self._seed * 131 + self._episode * 29
        if self._reward_mode == "bandit":
            mix += step * 13
        return mix % self._num_actions

    def _fill_value(self) -> int:
        """The frame's fill byte: the mode's learning signal."""
        if self._reward_mode == "schedule":
            return (self._seed * 131 + self._episode * 17
                    + self._step * 7) % 251
        scale = 255 // max(1, self._num_actions - 1)
        if self._reward_mode == "memory" and self._step != 0:
            return 128  # cue hidden after the first frame
        return self._cue(self._step) * scale

    def _frame(self, action: int) -> np.ndarray:
        base = self._fill_value()
        frame = np.full((self._h, self._w, self._c), base, dtype=np.uint8)
        frame[0, 0, 0] = self._episode % 256
        frame[0, 1, 0] = self._step % 256
        frame[0, 2, 0] = action % 256
        return frame

    def _observation(self, action: int) -> Observation:
        instruction = None
        if self._with_instruction:
            instruction = np.zeros((self._instruction_len,), np.int32)
            instruction[0] = 1 + (self._episode % 100)
        return make_observation(self._frame(action), instruction)

    def reset(self):
        self._episode += 1
        self._step = 0
        return self._observation(action=0)

    def step(self, action) -> Tuple[Observation, float, bool, dict]:
        arr = np.asarray(action)
        if arr.ndim == 0:
            action = int(arr)
        else:  # composite: one index per subspace
            action = tuple(int(a) for a in arr)
        if not self.action_space.contains(action):
            raise ValueError(f"action {action} outside {self.action_space}")
        if isinstance(action, tuple):
            action = action[0]  # frame encoding uses the first component
        reward = 0.0
        done = False
        episode_len = self._episode_len()
        for _ in range(self.native_action_repeats):
            # Bandit/memory: the cue the agent SAW is the pre-increment
            # state's (the observation emitted before this call), so
            # reward is computed before advancing.
            if self._reward_mode != "schedule":
                reward += 1.0 if action == self._cue(self._step) else 0.0
            self._step += 1
            done = self._step >= episode_len
            if self._reward_mode == "schedule":
                reward += 0.1 * (self._step % 3) + (1.0 if done else 0.0)
            if done:
                break
        return self._observation(action), np.float32(reward), done, {}

    def render(self, mode: str = "rgb_array"):
        return self._frame(action=0)
