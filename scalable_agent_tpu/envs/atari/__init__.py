"""Atari environment family (ALE via gymnasium, optional dependency).

Role of the reference's Atari adapter (reference:
envs/atari/atari_utils.py:16-55): a spec table of benchmark games and the
canonical preprocessing pipeline — NoFrameskip base env, resize to 84x84
grayscale, skip-4 + stack-4.  Differences by design:

- Frames stay HWC (TPU convs are NHWC-native); the reference emits CHW
  for torch.
- Frameskip is declared via ``native_action_repeats`` so
  ``make_impala_stream`` does not double-apply action repeats
  (envs/__init__.py).
- Works against either the legacy ``*NoFrameskip-v4`` ids (ale-py legacy
  registration) or the modern ``ALE/<Game>-v5`` ids (forced to
  deterministic no-skip, no-sticky-action settings so semantics match).
"""

import dataclasses
from typing import Optional

from scalable_agent_tpu.envs.core import Environment

ATARI_W = ATARI_H = 84


@dataclasses.dataclass(frozen=True)
class AtariSpec:
    name: str
    env_id: str  # legacy NoFrameskip id
    default_timeout: Optional[int] = None

    @property
    def ale_v5_id(self) -> str:
        base = self.env_id.replace("NoFrameskip-v4", "")
        return f"ALE/{base}-v5"


# The reference's benchmark set (reference: envs/atari/atari_utils.py:16-28).
ATARI_ENVS = (
    AtariSpec("atari_montezuma", "MontezumaRevengeNoFrameskip-v4",
              default_timeout=18000),
    AtariSpec("atari_pong", "PongNoFrameskip-v4"),
    AtariSpec("atari_qbert", "QbertNoFrameskip-v4"),
    AtariSpec("atari_breakout", "BreakoutNoFrameskip-v4"),
    AtariSpec("atari_spaceinvaders", "SpaceInvadersNoFrameskip-v4"),
    AtariSpec("atari_asteroids", "AsteroidsNoFrameskip-v4"),
    AtariSpec("atari_gravitar", "GravitarNoFrameskip-v4"),
    AtariSpec("atari_mspacman", "MsPacmanNoFrameskip-v4"),
    # NB: the gym registry casing is "Seaquest", not "SeaQuest" (the
    # reference's table carries the unregistered spelling).
    AtariSpec("atari_seaquest", "SeaquestNoFrameskip-v4"),
)


def atari_env_by_name(name: str) -> AtariSpec:
    for spec in ATARI_ENVS:
        if spec.name == name:
            return spec
    raise ValueError(
        f"unknown Atari env {name!r}; known: "
        f"{[s.name for s in ATARI_ENVS]}")


def _make_base_env(spec: AtariSpec):
    """gymnasium env with NO environment-side frameskip (the pipeline owns
    skipping, as the reference asserts 'NoFrameskip' in the id)."""
    import gymnasium

    try:
        return gymnasium.make(spec.env_id)
    except gymnasium.error.Error:
        # Modern ALE namespace ids: default v5 settings use frameskip 4
        # and sticky actions — force deterministic no-skip semantics.
        return gymnasium.make(
            spec.ale_v5_id, frameskip=1, repeat_action_probability=0.0)


def make_atari_env(full_env_name: str, skip_frames: int = 4,
                   stack_frames: int = 4, height: int = ATARI_H,
                   width: int = ATARI_W, **kwargs) -> Environment:
    """Name -> preprocessed env: resize->grayscale->skip+stack.

    (reference: envs/atari/atari_utils.py:39-55)
    """
    from scalable_agent_tpu.envs.gym_adapter import GymnasiumEnv
    from scalable_agent_tpu.envs.wrappers import (
        ResizeWrapper,
        SkipAndStackWrapper,
        TimeLimitWrapper,
    )

    # The frameskip requested by the runtime is consumed natively here.
    skip_frames = int(kwargs.pop("num_action_repeats", skip_frames))
    spec = atari_env_by_name(full_env_name)
    env = GymnasiumEnv(_make_base_env(spec), render_frames=False)
    if spec.default_timeout is not None:
        # Counts raw simulator steps (pre-skip), like the reference's
        # _max_episode_steps override (atari_utils.py:44-45).
        env = TimeLimitWrapper(env, spec.default_timeout)
    env = ResizeWrapper(env, height, width, grayscale=True)
    env = SkipAndStackWrapper(env, skip_frames=skip_frames,
                              stack_frames=stack_frames)
    env.native_action_repeats = skip_frames
    return env
