"""Vectorized environments: N streams sharded over W worker processes.

Successor of the reference's ``MultiEnv`` (reference:
algorithms/utils/multi_env.py:42-225) re-shaped for feeding a TPU:

- Each worker process hosts ``N / W`` *ImpalaStream* envs and steps them
  sequentially; the parent scatters actions and gathers batched
  ``StepOutput``s (same sharding idea as multi_env.py:214-218).
- All frames land in ONE shared-memory slab laid out [N, H, W, C] — batch
  assembly for device transfer is a single contiguous read; nothing big
  crosses a pipe.
- ``step_send``/``step_recv`` split lets the actor runtime overlap env
  simulation with TPU inference (the overlap the reference buys with its
  C++ dynamic batcher + async TF ops).
- Episode stats are read off completed episodes' StepOutputInfo and kept
  in a ring buffer (reference: multi_env.py:298-386 stats machinery).
"""

import multiprocessing as mp
import pickle
import threading
from collections import deque
from multiprocessing import shared_memory
from typing import Callable, List, Optional, Sequence

import numpy as np

from scalable_agent_tpu.envs.worker import (
    _CLOSE,
    _INITIAL,
    _PREDICT,
    _STEP,
    RemoteEnvError,
    _dumps_exception,
)
from scalable_agent_tpu.types import (
    Observation,
    StepOutput,
    StepOutputInfo,
)


def _reseeded(make_stream_fns, generation: int):
    """Respawned workers must not replay identical episode streams: for
    the standard ``functools.partial(make_impala_stream, seed=...)``
    factories, shift the seed per generation; opaque factories pass
    through unchanged (reference analog: the multiplayer init-retry
    re-creates envs with fresh state,
    doom_multiagent_wrapper.py:225-273)."""
    if generation <= 0:
        return list(make_stream_fns)
    import functools

    out = []
    for make in make_stream_fns:
        if (isinstance(make, functools.partial)
                and "seed" in (make.keywords or {})):
            kwargs = dict(make.keywords)
            kwargs["seed"] = kwargs["seed"] + 90001 * generation
            make = functools.partial(make.func, *make.args, **kwargs)
        out.append(make)
    return out


def _vec_worker_main(conn, make_streams_pickled: bytes, shm_name: str,
                     slab_shape, slab_dtype, first_index: int,
                     generation: int = 0):
    """Hosts a contiguous slice of the env batch.  One process, k envs."""
    streams = []
    shm = None
    try:
        try:
            make_streams = _reseeded(
                pickle.loads(make_streams_pickled), generation)
            streams = [make() for make in make_streams]
            shm = shared_memory.SharedMemory(name=shm_name)
            slab = np.ndarray(slab_shape, slab_dtype, buffer=shm.buf)
            conn.send((True, None))
        except Exception as exc:
            conn.send((False, _dumps_exception(exc)))
            return

        k = len(streams)

        def run_all(step_of_stream):
            """Apply per-stream, gather small fields, frames -> slab."""
            rewards = np.zeros((k,), np.float32)
            dones = np.zeros((k,), bool)
            returns = np.zeros((k,), np.float32)
            steps = np.zeros((k,), np.int32)
            instructions = []
            measurements = []
            for i, stream in enumerate(streams):
                out = step_of_stream(i, stream)
                rewards[i] = out.reward
                dones[i] = out.done
                returns[i] = out.info.episode_return
                steps[i] = out.info.episode_step
                slab[first_index + i] = out.observation.frame
                instructions.append(out.observation.instruction)
                measurements.append(out.observation.measurements)
            return (rewards, dones, returns, steps,
                    _maybe_stack(instructions),
                    _maybe_stack(measurements))

        # A freshly (re)spawned worker has never started its episodes.
        # Auto-priming on _STEP means the PARENT never has to eagerly
        # reset a respawned worker: the first _STEP after a respawn
        # returns initial outputs (done=True, episode_step=0 — the
        # VISIBLE episode boundary).  _PREDICT refuses instead of
        # quietly priming — lookahead from a silently restarted episode
        # would splice into the caller's old-episode trajectory with no
        # done flag.  The flag only flips after run_all succeeds, so a
        # failed initial() leaves the worker honestly uninitialized.
        initialized = False
        while True:
            request = conn.recv()
            kind = request[0]
            try:
                if kind == _INITIAL:
                    payload = run_all(lambda i, stream: stream.initial())
                    initialized = True
                    conn.send((True, payload))
                elif kind == _STEP:
                    if initialized:
                        actions = request[1]
                        payload = run_all(
                            lambda i, stream: stream.step(actions[i]))
                    else:
                        payload = run_all(
                            lambda i, stream: stream.initial())
                        initialized = True
                    conn.send((True, payload))
                elif kind == _PREDICT:
                    if not initialized:
                        raise RuntimeError(
                            "predict() on a freshly (re)started worker: "
                            "its episodes have not begun — run a real "
                            "step()/initial() first (the restart "
                            "surfaces there as done=True)")
                    conn.send((True, _predict_all(streams, request[1])))
                elif kind == _CLOSE:
                    break
                else:
                    raise ValueError(f"unknown request kind {kind}")
            except Exception as exc:
                conn.send((False, _dumps_exception(exc)))
    except (EOFError, KeyboardInterrupt):
        pass
    finally:
        for stream in streams:
            try:
                stream.close()
            except Exception:
                pass
        if shm is not None:
            shm.close()
        conn.close()


def _maybe_stack(items: List) -> Optional[np.ndarray]:
    if not items or items[0] is None:
        return None
    return np.stack(items)


def _predict_all(streams, actions):
    """Speculative one-step lookahead (reference: multi_env.py:118-147):
    each candidate action steps a ``deepcopy`` of the real stream, so
    the real env state is untouched.  Returns per-(env, candidate)
    frames/rewards/dones; clones are discarded immediately."""
    import copy

    frames, rewards, dones = [], [], []
    for i, stream in enumerate(streams):
        fr, rw, dn = [], [], []
        for action in actions[i]:
            if hasattr(stream, "clone"):
                clone = stream.clone()
            else:
                try:
                    clone = copy.deepcopy(stream)
                except Exception as exc:
                    raise RuntimeError(
                        "predict() needs clone-capable envs: the stream "
                        "is not deepcopy-able and has no clone() hook "
                        "(native-handle simulators like VizDoom cannot "
                        "be cloned)") from exc
            try:
                out = clone.step(action)
                fr.append(out.observation.frame)
                rw.append(np.float32(out.reward))
                dn.append(bool(out.done))
            finally:
                try:
                    clone.close()
                except Exception:
                    pass
        frames.append(np.stack(fr))
        rewards.append(rw)
        dones.append(dn)
    return (np.stack(frames), np.asarray(rewards, np.float32),
            np.asarray(dones, bool))


class MultiEnv:
    """N ImpalaStream envs across W processes with a shared frame slab.

    ``make_stream_fns``: one picklable zero-arg factory per env, each
    returning an ImpalaStream-protocol object.  ``frame_spec`` declares the
    per-env frame shape/dtype (all envs must agree).
    """

    def __init__(
        self,
        make_stream_fns: Sequence[Callable],
        frame_spec,
        num_workers: Optional[int] = None,
        stats_episodes: int = 100,
        ctx: Optional[str] = None,
        max_respawns: int = 16,
        respawn_window_s: float = 600.0,
        env_labels: Optional[Sequence[str]] = None,
    ):
        self.num_envs = len(make_stream_fns)
        # Per-env level labels for multi-task training (reference spreads
        # actors over all 30 DMLab levels, experiment.py:552-555; per-level
        # episode attribution feeds the training suite score, :634-667).
        if env_labels is not None and len(env_labels) != self.num_envs:
            raise ValueError(
                f"{len(env_labels)} env_labels for {self.num_envs} envs")
        self.env_labels = list(env_labels) if env_labels else None
        num_workers = min(num_workers or self.num_envs, self.num_envs)
        # spawn, not fork: see EnvProcess — the parent runs JAX.
        self._ctx = mp.get_context(ctx or "spawn")
        self._frame_spec = frame_spec
        self._slab_shape = (self.num_envs,) + tuple(frame_spec.shape)
        nbytes = int(np.prod(self._slab_shape)
                     * np.dtype(frame_spec.dtype).itemsize)
        self._shm = shared_memory.SharedMemory(create=True, size=nbytes)
        self._slab = np.ndarray(self._slab_shape, frame_spec.dtype,
                                buffer=self._shm.buf)

        # Fault tolerance: a worker process dying takes down only its
        # slice — it is respawned with generation-shifted seeds and its
        # envs restart from fresh episodes (SURVEY §5.3; the reference
        # kills+recreates stuck workers, doom_multiagent_wrapper.py:
        # 225-273).  The budget detects crash LOOPS, not lifetime faults:
        # more than ``max_respawns`` deaths of the SAME worker within
        # ``respawn_window_s`` aborts; rare independent deaths spread
        # over a long run recover indefinitely.
        self.max_respawns = max_respawns
        self.respawn_window_s = respawn_window_s
        self.total_respawns = 0  # lifetime stat, never limits recovery

        # Shard envs over workers as evenly as possible.
        base, extra = divmod(self.num_envs, num_workers)
        sizes = [base + (1 if w < extra else 0) for w in range(num_workers)]
        self._slices = []
        self._fns_pickled = []
        self._generations = []
        self._respawn_times = []
        self._procs = []
        self._conns = []
        self._send_locks = []
        start = 0
        for w, size in enumerate(sizes):
            sl = slice(start, start + size)
            self._slices.append(sl)
            self._fns_pickled.append(
                pickle.dumps(list(make_stream_fns[sl])))
            self._generations.append(0)
            self._respawn_times.append(deque())
            self._procs.append(None)
            self._conns.append(None)
            # Per-worker send lock (RLock so a caller can wrap its own
            # check-then-send critical section around worker_send): the
            # per-worker async API lets one thread dispatch steps while
            # another drains replies, and a respawn's send+recv
            # handshake must never interleave with a concurrent send.
            self._send_locks.append(threading.RLock())
            self._spawn_worker(w)
            start += size
        failures = []
        for conn in self._conns:
            try:
                ok, payload = conn.recv()
            except EOFError:
                failures.append(RemoteEnvError(
                    "env worker died during construction (no handshake)"))
                continue
            if not ok:
                failures.append(pickle.loads(payload))
        if failures:
            self.close()
            raise failures[0]

        # Ring buffer of (episode_return, episode_length) for finished
        # episodes (reference: multi_env.py:298-386).
        self.episode_stats = deque(maxlen=stats_episodes)
        # Drain queue of (label, return, length), fed only when env_labels
        # is set; consumers pop (ActorPool.drain_level_stats) so every
        # completed episode is attributed exactly once.
        self.level_episode_stats = deque(maxlen=max(1000, stats_episodes))
        self._pending = False

    def _spawn_worker(self, w: int) -> None:
        parent_conn, child_conn = self._ctx.Pipe()
        proc = self._ctx.Process(
            target=_vec_worker_main,
            args=(child_conn, self._fns_pickled[w], self._shm.name,
                  self._slab_shape, np.dtype(self._frame_spec.dtype),
                  self._slices[w].start, self._generations[w]),
            daemon=True,
        )
        proc.start()
        child_conn.close()
        self._procs[w] = proc
        self._conns[w] = parent_conn

    def _respawn_worker(self, w: int) -> None:
        """Replace a dead worker: fresh process, shifted seeds, blocking
        handshake.  Raises RemoteEnvError when worker ``w`` has died more
        than ``max_respawns`` times within ``respawn_window_s``."""
        import time as _time

        from scalable_agent_tpu.utils import log

        now = _time.monotonic()
        times = self._respawn_times[w]
        while times and now - times[0] > self.respawn_window_s:
            times.popleft()
        times.append(now)
        self.total_respawns += 1
        if len(times) > self.max_respawns:
            raise RemoteEnvError(
                f"env worker {w} crash-looping: {len(times)} deaths in "
                f"{self.respawn_window_s:.0f}s (budget {self.max_respawns})")
        log.warning(
            "env worker %d (envs %d:%d) died — respawning "
            "(%d in window, %d lifetime)",
            w, self._slices[w].start, self._slices[w].stop,
            len(times), self.total_respawns)
        # Recovery-matrix visibility (docs/robustness.md): respawns get
        # the same counter + flight-recorder treatment as every other
        # self-healing path, so a chaos run's artifacts account for
        # each injected worker_kill.
        from scalable_agent_tpu.obs import get_flight_recorder, get_registry

        get_registry().counter(
            "env/worker_respawns_total",
            "env worker processes respawned after dying").inc()
        get_flight_recorder().record(
            "worker_respawn", f"worker-{w}",
            {"deaths_in_window": len(times),
             "lifetime": self.total_respawns})
        try:
            self._conns[w].close()
        except OSError:
            pass
        proc = self._procs[w]
        if proc is not None and proc.is_alive():
            proc.kill()
            proc.join(timeout=5)
        self._generations[w] += 1
        self._spawn_worker(w)
        try:
            ok, payload = self._conns[w].recv()
        except EOFError:
            raise RemoteEnvError(
                f"env worker {w} died again during respawn handshake")
        if not ok:
            raise pickle.loads(payload)

    # -- protocol ----------------------------------------------------------

    def _recv_payload(self, w: int):
        """One worker's reply with the shared fault handling: a worker
        dead mid-step is respawned and its slice's fresh initial
        outputs substituted (done=True marks the episode boundary; the
        aborted episode records no stats — episode_step stays 0).
        Returns ``(payload, None)`` or ``(None, remote_error)``."""
        conn = self._conns[w]
        try:
            ok, payload = conn.recv()
        except (EOFError, OSError):
            with self._send_locks[w]:
                if self._conns[w] is conn:
                    self._respawn_worker(w)
                    self._conns[w].send((_INITIAL,))
                # else: a concurrent sender already noticed the death,
                # respawned, and primed _INITIAL under this lock — a
                # second respawn would kill the healthy replacement
                # and double-charge the budget for one death.  Either
                # way the primed initial reply is pending below.
            ok, payload = self._conns[w].recv()
        if not ok:
            return None, pickle.loads(payload)
        return payload, None

    def _record_done_stats(self, offset: int, dones, steps, returns):
        """Completed-episode accounting for a slice whose global env
        indices start at ``offset`` (skips initial() pseudo-dones)."""
        for i in np.nonzero(dones)[0]:
            if steps[i] > 0:
                self.episode_stats.append(
                    (float(returns[i]), int(steps[i])))
                if self.env_labels is not None:
                    self.level_episode_stats.append(
                        (self.env_labels[offset + i], float(returns[i]),
                         int(steps[i])))

    def _gather(self) -> StepOutput:
        rewards = np.zeros((self.num_envs,), np.float32)
        dones = np.zeros((self.num_envs,), bool)
        returns = np.zeros((self.num_envs,), np.float32)
        steps = np.zeros((self.num_envs,), np.int32)
        instructions = None
        measurements = None
        errors = []
        for w, sl in enumerate(self._slices):
            payload, error = self._recv_payload(w)
            if error is not None:
                # Keep draining the remaining workers so the pipes stay
                # aligned; the first error surfaces after the sweep.
                errors.append(error)
                continue
            r, d, ret, st, instr, meas = payload
            rewards[sl], dones[sl], returns[sl], steps[sl] = r, d, ret, st
            if instr is not None:
                if instructions is None:
                    instructions = np.zeros(
                        (self.num_envs,) + instr.shape[1:], instr.dtype)
                instructions[sl] = instr
            if meas is not None:
                if measurements is None:
                    measurements = np.zeros(
                        (self.num_envs,) + meas.shape[1:], meas.dtype)
                measurements[sl] = meas
        if errors:
            raise errors[0]
        self._record_done_stats(0, dones, steps, returns)
        return StepOutput(
            reward=rewards,
            info=StepOutputInfo(episode_return=returns, episode_step=steps),
            done=dones,
            observation=Observation(
                frame=self._slab.copy(), instruction=instructions,
                measurements=measurements),
        )

    def initial(self) -> StepOutput:
        for w in range(len(self._conns)):
            with self._send_locks[w]:
                try:
                    self._conns[w].send((_INITIAL,))
                except (BrokenPipeError, OSError):
                    self._respawn_worker(w)
                    self._conns[w].send((_INITIAL,))
        return self._gather()

    def step_send(self, actions) -> None:
        actions = np.asarray(actions)
        if actions.shape[0] != self.num_envs:
            raise ValueError(
                f"got {actions.shape[0]} actions for {self.num_envs} envs")
        for w, sl in enumerate(self._slices):
            self.worker_send(w, actions[sl])
        self._pending = True

    def step_recv(self) -> StepOutput:
        if not self._pending:
            raise RuntimeError("step_recv without step_send")
        self._pending = False
        return self._gather()

    # -- per-worker async protocol -----------------------------------------
    # The continuous-batching actor service (runtime/service.py) steps
    # each worker's env slice independently: a finished worker's
    # observations flow out the moment its reply lands, without waiting
    # for siblings — the per-step group barrier the grouped path pays
    # in ``step_recv`` does not exist here.  Thread model: one thread
    # may send (worker_send) while another drains replies (worker_recv)
    # — opposite directions of the duplex pipe, serialized per worker
    # by the send lock only where a respawn handshake needs it.

    @property
    def num_workers(self) -> int:
        return len(self._slices)

    def worker_slices(self) -> List[slice]:
        """Per-worker env index ranges, in batch order."""
        return list(self._slices)

    def worker_connection(self, w: int):
        """The worker's parent-side pipe end, for
        ``multiprocessing.connection.wait`` readiness polling."""
        return self._conns[w]

    def worker_lock(self, w: int):
        """The worker's send RLock — callers wrap check-then-send
        critical sections (e.g. the service's stale-generation gate)
        around ``worker_send``."""
        return self._send_locks[w]

    def worker_generation(self, w: int) -> int:
        """The worker's respawn generation (bumped by every
        ``_respawn_worker``, always under the send lock on concurrent
        paths).  The actor service stamps requests with it so a step
        computed for a PRE-respawn worker is discarded instead of
        dispatched — a respawn's _INITIAL prime already has a reply in
        flight, and dispatching on top of it would double-book the
        strict request/reply protocol."""
        return self._generations[w]

    def _slice_output(self, w: int, payload) -> StepOutput:
        sl = self._slices[w]
        rewards, dones, returns, steps, instructions, measurements = payload
        self._record_done_stats(sl.start, dones, steps, returns)
        return StepOutput(
            reward=rewards,
            info=StepOutputInfo(episode_return=returns,
                                episode_step=steps),
            done=dones,
            observation=Observation(
                frame=self._slab[sl].copy(), instruction=instructions,
                measurements=measurements),
        )

    def worker_send(self, w: int, actions) -> None:
        """Dispatch one step to worker ``w``'s env slice ([k] actions).
        A dead worker is respawned and primed with its initial outputs
        instead of the lost step (same payload layout)."""
        actions = np.asarray(actions)
        sl = self._slices[w]
        if actions.shape[0] != sl.stop - sl.start:
            raise ValueError(
                f"got {actions.shape[0]} actions for worker {w}'s "
                f"{sl.stop - sl.start} envs")
        with self._send_locks[w]:
            try:
                self._conns[w].send((_STEP, actions))
            except (BrokenPipeError, OSError):
                self._respawn_worker(w)
                self._conns[w].send((_INITIAL,))

    def worker_recv(self, w: int) -> StepOutput:
        """Collect worker ``w``'s outstanding reply as a slice-shaped
        [k, ...] StepOutput (frames copied from the slab slice;
        episode stats recorded with global env indices)."""
        payload, error = self._recv_payload(w)
        if error is not None:
            raise error
        return self._slice_output(w, payload)

    def worker_initial(self, w: int) -> StepOutput:
        """(Re)start worker ``w``'s episodes and return its slice's
        initial outputs."""
        with self._send_locks[w]:
            try:
                self._conns[w].send((_INITIAL,))
            except (BrokenPipeError, OSError):
                self._respawn_worker(w)
                self._conns[w].send((_INITIAL,))
        return self.worker_recv(w)

    def resync(self) -> None:
        """Best-effort pipe re-alignment after an exception of unknown
        provenance (the actor retry path): drain stale worker replies
        so the next ``initial()``/``step_send`` doesn't read one as its
        own.  Deliberately NOT gated on ``_pending`` — ``step_recv``
        clears the flag BEFORE ``_gather``, so a failure mid-gather
        (e.g. one worker's respawn budget raising after half the
        replies were read) leaves undrained replies with ``_pending``
        already False.  Each pipe is drained until it stays quiet for a
        bounded window; errors are swallowed — if the envs are truly
        broken, the retry's next step surfaces them against the
        respawn budget."""
        self._pending = False
        for conn in self._conns:
            if conn is None:
                continue
            try:
                # 1s quiet period: long enough for a genuinely
                # in-flight step reply to land (so it can't arrive
                # AFTER the drain and desync the next unroll), bounded
                # so a dead pipe costs the retry path one second.
                while conn.poll(1.0):
                    conn.recv()
            except (EOFError, OSError):
                continue

    def step(self, actions) -> StepOutput:
        self.step_send(actions)
        return self.step_recv()

    def predict(self, imagined_action_lists):
        """Speculative one-step lookahead over candidate actions
        (reference: multi_env.py:118-147, 314-342 ``predict``):
        ``imagined_action_lists`` holds K candidate actions per env;
        each steps a deep-copied clone of the real env, leaving real
        state untouched.  Returns (frames [N, K, H, W, C],
        rewards [N, K], dones [N, K]).  Frames travel over the pipe,
        not the slab — the slab still holds the last REAL step."""
        if self._pending:
            raise RuntimeError(
                "predict() between step_send and step_recv would "
                "desynchronize the worker pipes; finish the step first")
        actions = np.asarray(imagined_action_lists)
        if actions.shape[0] != self.num_envs:
            raise ValueError(
                f"got {actions.shape[0]} action lists for "
                f"{self.num_envs} envs")
        # Dead workers are recorded during the fan-out, every healthy
        # worker's reply is drained (keeping all pipes in sync even if
        # a respawn later fails), and only then are the dead respawned
        # — after which the first error propagates.  Respawned workers
        # are NOT reset here: the slab keeps the last REAL frames, the
        # worker refuses further predict()s until a real step, and the
        # episode boundary (done=True) surfaces on that step.
        sent, dead = [], []
        for w, sl in enumerate(self._slices):
            try:
                self._conns[w].send((_PREDICT, actions[sl]))
                sent.append(w)
            except (BrokenPipeError, OSError):
                dead.append(w)
        frames, rewards, dones, errors = [], [], [], []
        for w in sent:
            try:
                ok, payload = self._conns[w].recv()
            except (EOFError, OSError):
                dead.append(w)
                continue
            if not ok:
                errors.append(pickle.loads(payload))
                continue
            f, r, d = payload
            frames.append(f)
            rewards.append(r)
            dones.append(d)
        for w in dead:
            self._respawn_worker(w)
            errors.append(RemoteEnvError(
                f"env worker {w} died around predict; respawned (its "
                f"envs restart at the next step, surfacing done=True) "
                f"— step before retrying"))
        if errors:
            raise errors[0]
        return (np.concatenate(frames), np.concatenate(rewards),
                np.concatenate(dones))

    def frame_slab(self) -> np.ndarray:
        """Zero-copy [N, H, W, C] view (valid until the next step)."""
        return self._slab

    def avg_episode_return(self) -> float:
        if not self.episode_stats:
            return float("nan")
        return float(np.mean([r for r, _ in self.episode_stats]))

    def avg_episode_length(self) -> float:
        if not self.episode_stats:
            return float("nan")
        return float(np.mean([l for _, l in self.episode_stats]))

    def close(self):
        for conn in self._conns:
            try:
                conn.send((_CLOSE,))
            except (BrokenPipeError, OSError):
                pass
        for proc in self._procs:
            proc.join(timeout=5)
            if proc.is_alive():
                proc.kill()
                proc.join(timeout=5)
        for conn in self._conns:
            conn.close()
        self._conns, self._procs = [], []
        if self._shm is not None:
            self._shm.close()
            self._shm.unlink()
            self._shm = None
