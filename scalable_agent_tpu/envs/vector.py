"""Vectorized environments: N streams sharded over W worker processes.

Successor of the reference's ``MultiEnv`` (reference:
algorithms/utils/multi_env.py:42-225) re-shaped for feeding a TPU:

- Each worker process hosts ``N / W`` *ImpalaStream* envs and steps them
  sequentially; the parent scatters actions and gathers batched
  ``StepOutput``s (same sharding idea as multi_env.py:214-218).
- All frames land in ONE shared-memory slab laid out [N, H, W, C] — batch
  assembly for device transfer is a single contiguous read; nothing big
  crosses a pipe.
- ``step_send``/``step_recv`` split lets the actor runtime overlap env
  simulation with TPU inference (the overlap the reference buys with its
  C++ dynamic batcher + async TF ops).
- Episode stats are read off completed episodes' StepOutputInfo and kept
  in a ring buffer (reference: multi_env.py:298-386 stats machinery).
"""

import multiprocessing as mp
import pickle
from collections import deque
from multiprocessing import shared_memory
from typing import Callable, List, Optional, Sequence

import numpy as np

from scalable_agent_tpu.envs.worker import (
    _CLOSE,
    _INITIAL,
    _STEP,
    RemoteEnvError,
    _dumps_exception,
)
from scalable_agent_tpu.types import (
    Observation,
    StepOutput,
    StepOutputInfo,
)


def _vec_worker_main(conn, make_streams_pickled: bytes, shm_name: str,
                     slab_shape, slab_dtype, first_index: int):
    """Hosts a contiguous slice of the env batch.  One process, k envs."""
    streams = []
    shm = None
    try:
        try:
            make_streams = pickle.loads(make_streams_pickled)
            streams = [make() for make in make_streams]
            shm = shared_memory.SharedMemory(name=shm_name)
            slab = np.ndarray(slab_shape, slab_dtype, buffer=shm.buf)
            conn.send((True, None))
        except Exception as exc:
            conn.send((False, _dumps_exception(exc)))
            return

        k = len(streams)

        def run_all(step_of_stream):
            """Apply per-stream, gather small fields, frames -> slab."""
            rewards = np.zeros((k,), np.float32)
            dones = np.zeros((k,), bool)
            returns = np.zeros((k,), np.float32)
            steps = np.zeros((k,), np.int32)
            instructions = []
            measurements = []
            for i, stream in enumerate(streams):
                out = step_of_stream(i, stream)
                rewards[i] = out.reward
                dones[i] = out.done
                returns[i] = out.info.episode_return
                steps[i] = out.info.episode_step
                slab[first_index + i] = out.observation.frame
                instructions.append(out.observation.instruction)
                measurements.append(out.observation.measurements)
            return (rewards, dones, returns, steps,
                    _maybe_stack(instructions),
                    _maybe_stack(measurements))

        while True:
            request = conn.recv()
            kind = request[0]
            try:
                if kind == _INITIAL:
                    conn.send((True, run_all(
                        lambda i, stream: stream.initial())))
                elif kind == _STEP:
                    actions = request[1]
                    conn.send((True, run_all(
                        lambda i, stream: stream.step(actions[i]))))
                elif kind == _CLOSE:
                    break
                else:
                    raise ValueError(f"unknown request kind {kind}")
            except Exception as exc:
                conn.send((False, _dumps_exception(exc)))
    except (EOFError, KeyboardInterrupt):
        pass
    finally:
        for stream in streams:
            try:
                stream.close()
            except Exception:
                pass
        if shm is not None:
            shm.close()
        conn.close()


def _maybe_stack(items: List) -> Optional[np.ndarray]:
    if not items or items[0] is None:
        return None
    return np.stack(items)


class MultiEnv:
    """N ImpalaStream envs across W processes with a shared frame slab.

    ``make_stream_fns``: one picklable zero-arg factory per env, each
    returning an ImpalaStream-protocol object.  ``frame_spec`` declares the
    per-env frame shape/dtype (all envs must agree).
    """

    def __init__(
        self,
        make_stream_fns: Sequence[Callable],
        frame_spec,
        num_workers: Optional[int] = None,
        stats_episodes: int = 100,
        ctx: Optional[str] = None,
    ):
        self.num_envs = len(make_stream_fns)
        num_workers = min(num_workers or self.num_envs, self.num_envs)
        # spawn, not fork: see EnvProcess — the parent runs JAX.
        self._ctx = mp.get_context(ctx or "spawn")
        self._frame_spec = frame_spec
        slab_shape = (self.num_envs,) + tuple(frame_spec.shape)
        nbytes = int(np.prod(slab_shape)
                     * np.dtype(frame_spec.dtype).itemsize)
        self._shm = shared_memory.SharedMemory(create=True, size=nbytes)
        self._slab = np.ndarray(slab_shape, frame_spec.dtype,
                                buffer=self._shm.buf)

        # Shard envs over workers as evenly as possible.
        base, extra = divmod(self.num_envs, num_workers)
        sizes = [base + (1 if w < extra else 0) for w in range(num_workers)]
        self._slices = []
        self._procs = []
        self._conns = []
        start = 0
        for w, size in enumerate(sizes):
            sl = slice(start, start + size)
            self._slices.append(sl)
            parent_conn, child_conn = self._ctx.Pipe()
            proc = self._ctx.Process(
                target=_vec_worker_main,
                args=(child_conn,
                      pickle.dumps(list(make_stream_fns[sl])),
                      self._shm.name, slab_shape,
                      np.dtype(frame_spec.dtype), start),
                daemon=True,
            )
            proc.start()
            child_conn.close()
            self._procs.append(proc)
            self._conns.append(parent_conn)
            start += size
        failures = []
        for conn in self._conns:
            try:
                ok, payload = conn.recv()
            except EOFError:
                failures.append(RemoteEnvError(
                    "env worker died during construction (no handshake)"))
                continue
            if not ok:
                failures.append(pickle.loads(payload))
        if failures:
            self.close()
            raise failures[0]

        # Ring buffer of (episode_return, episode_length) for finished
        # episodes (reference: multi_env.py:298-386).
        self.episode_stats = deque(maxlen=stats_episodes)
        self._pending = False

    # -- protocol ----------------------------------------------------------

    def _gather(self) -> StepOutput:
        rewards = np.zeros((self.num_envs,), np.float32)
        dones = np.zeros((self.num_envs,), bool)
        returns = np.zeros((self.num_envs,), np.float32)
        steps = np.zeros((self.num_envs,), np.int32)
        instructions = None
        measurements = None
        errors = []
        for conn, sl in zip(self._conns, self._slices):
            ok, payload = conn.recv()
            if not ok:
                errors.append(pickle.loads(payload))
                continue
            r, d, ret, st, instr, meas = payload
            rewards[sl], dones[sl], returns[sl], steps[sl] = r, d, ret, st
            if instr is not None:
                if instructions is None:
                    instructions = np.zeros(
                        (self.num_envs,) + instr.shape[1:], instr.dtype)
                instructions[sl] = instr
            if meas is not None:
                if measurements is None:
                    measurements = np.zeros(
                        (self.num_envs,) + meas.shape[1:], meas.dtype)
                measurements[sl] = meas
        if errors:
            raise errors[0]
        for i in np.nonzero(dones)[0]:
            if steps[i] > 0:  # skip initial() pseudo-done
                self.episode_stats.append(
                    (float(returns[i]), int(steps[i])))
        return StepOutput(
            reward=rewards,
            info=StepOutputInfo(episode_return=returns, episode_step=steps),
            done=dones,
            observation=Observation(
                frame=self._slab.copy(), instruction=instructions,
                measurements=measurements),
        )

    def initial(self) -> StepOutput:
        for conn in self._conns:
            conn.send((_INITIAL,))
        return self._gather()

    def step_send(self, actions) -> None:
        actions = np.asarray(actions)
        if actions.shape[0] != self.num_envs:
            raise ValueError(
                f"got {actions.shape[0]} actions for {self.num_envs} envs")
        for conn, sl in zip(self._conns, self._slices):
            conn.send((_STEP, actions[sl]))
        self._pending = True

    def step_recv(self) -> StepOutput:
        if not self._pending:
            raise RuntimeError("step_recv without step_send")
        self._pending = False
        return self._gather()

    def step(self, actions) -> StepOutput:
        self.step_send(actions)
        return self.step_recv()

    def frame_slab(self) -> np.ndarray:
        """Zero-copy [N, H, W, C] view (valid until the next step)."""
        return self._slab

    def avg_episode_return(self) -> float:
        if not self.episode_stats:
            return float("nan")
        return float(np.mean([r for r, _ in self.episode_stats]))

    def avg_episode_length(self) -> float:
        if not self.episode_stats:
            return float("nan")
        return float(np.mean([l for _, l in self.episode_stats]))

    def close(self):
        for conn in self._conns:
            try:
                conn.send((_CLOSE,))
            except (BrokenPipeError, OSError):
                pass
        for proc in self._procs:
            proc.join(timeout=5)
            if proc.is_alive():
                proc.kill()
                proc.join(timeout=5)
        for conn in self._conns:
            conn.close()
        self._conns, self._procs = [], []
        if self._shm is not None:
            self._shm.close()
            self._shm.unlink()
            self._shm = None
