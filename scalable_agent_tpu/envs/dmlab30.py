"""DMLab-30 level metadata and human-normalized scoring.

Parity port of the reference's scoring module (reference: dmlab30.py:27-218)
with one structural change: instead of three parallel tables
(LEVEL_MAPPING / HUMAN_SCORES / RANDOM_SCORES), each level carries one
record — (test-level alias, human score, random score) — so the tables
cannot drift out of sync.  The numeric constants are the published DMLab-30
calibration values (IMPALA paper, arXiv:1802.01561) and must match the
reference exactly for score parity.
"""

from typing import Dict, NamedTuple, Optional, Sequence

import numpy as np


class LevelRecord(NamedTuple):
    test_level: str  # levels with train/test splits score under this name
    human: float
    random: float


# Training-level name -> record.  Order matches the canonical DMLab-30 list.
LEVELS: Dict[str, LevelRecord] = {
    "rooms_collect_good_objects_train": LevelRecord(
        "rooms_collect_good_objects_test", 10.0, 0.073),
    "rooms_exploit_deferred_effects_train": LevelRecord(
        "rooms_exploit_deferred_effects_test", 85.65, 8.501),
    "rooms_select_nonmatching_object": LevelRecord(
        "rooms_select_nonmatching_object", 65.9, 0.312),
    "rooms_watermaze": LevelRecord("rooms_watermaze", 54.0, 4.065),
    "rooms_keys_doors_puzzle": LevelRecord(
        "rooms_keys_doors_puzzle", 53.8, 4.135),
    "language_select_described_object": LevelRecord(
        "language_select_described_object", 389.5, -0.07),
    "language_select_located_object": LevelRecord(
        "language_select_located_object", 280.7, 1.929),
    "language_execute_random_task": LevelRecord(
        "language_execute_random_task", 254.05, -5.913),
    "language_answer_quantitative_question": LevelRecord(
        "language_answer_quantitative_question", 184.5, -0.33),
    "lasertag_one_opponent_small": LevelRecord(
        "lasertag_one_opponent_small", 12.65, -0.224),
    "lasertag_three_opponents_small": LevelRecord(
        "lasertag_three_opponents_small", 18.55, -0.214),
    "lasertag_one_opponent_large": LevelRecord(
        "lasertag_one_opponent_large", 18.6, -0.083),
    "lasertag_three_opponents_large": LevelRecord(
        "lasertag_three_opponents_large", 31.5, -0.102),
    "natlab_fixed_large_map": LevelRecord(
        "natlab_fixed_large_map", 36.9, 2.173),
    "natlab_varying_map_regrowth": LevelRecord(
        "natlab_varying_map_regrowth", 24.45, 2.989),
    "natlab_varying_map_randomized": LevelRecord(
        "natlab_varying_map_randomized", 42.35, 7.346),
    "skymaze_irreversible_path_hard": LevelRecord(
        "skymaze_irreversible_path_hard", 100.0, 0.1),
    "skymaze_irreversible_path_varied": LevelRecord(
        "skymaze_irreversible_path_varied", 100.0, 14.4),
    "psychlab_arbitrary_visuomotor_mapping": LevelRecord(
        "psychlab_arbitrary_visuomotor_mapping", 58.75, 0.163),
    "psychlab_continuous_recognition": LevelRecord(
        "psychlab_continuous_recognition", 58.3, 0.224),
    "psychlab_sequential_comparison": LevelRecord(
        "psychlab_sequential_comparison", 39.5, 0.129),
    "psychlab_visual_search": LevelRecord(
        "psychlab_visual_search", 78.5, 0.085),
    "explore_object_locations_small": LevelRecord(
        "explore_object_locations_small", 74.45, 3.575),
    "explore_object_locations_large": LevelRecord(
        "explore_object_locations_large", 65.65, 4.673),
    "explore_obstructed_goals_small": LevelRecord(
        "explore_obstructed_goals_small", 206.0, 6.76),
    "explore_obstructed_goals_large": LevelRecord(
        "explore_obstructed_goals_large", 119.5, 2.61),
    "explore_goal_locations_small": LevelRecord(
        "explore_goal_locations_small", 267.5, 7.66),
    "explore_goal_locations_large": LevelRecord(
        "explore_goal_locations_large", 194.5, 3.14),
    "explore_object_rewards_few": LevelRecord(
        "explore_object_rewards_few", 77.7, 2.073),
    "explore_object_rewards_many": LevelRecord(
        "explore_object_rewards_many", 106.7, 2.438),
}

TRAIN_LEVELS: Sequence[str] = tuple(LEVELS)
TEST_LEVELS: Sequence[str] = tuple(r.test_level for r in LEVELS.values())
ALL_LEVELS = frozenset(TRAIN_LEVELS) | frozenset(TEST_LEVELS)

_BY_TEST_NAME = {r.test_level: r for r in LEVELS.values()}


def compute_human_normalized_score(
    level_returns: Dict[str, Sequence[float]],
    per_level_cap: Optional[float],
) -> float:
    """Mean human-normalized score (%) over the DMLab-30 suite.

    ``level_returns``: level name (train or test variant) -> list of
    episode returns.  Train-variant returns score under their test-level
    calibration (reference: dmlab30.py:186-218).  Levels outside the suite
    are ignored; every suite level must be present with >= 1 return.
    ``per_level_cap``: per-level percentage cap (e.g. 100.0), or None.
    """
    by_test: Dict[str, Sequence[float]] = {}
    for name, returns in level_returns.items():
        record = LEVELS.get(name)
        test_name = record.test_level if record else name
        if test_name in _BY_TEST_NAME:
            by_test[test_name] = returns

    missing = set(_BY_TEST_NAME) - set(by_test)
    if missing:
        raise ValueError(f"missing levels: {sorted(missing)}")
    empty = [name for name, returns in by_test.items() if len(returns) == 0]
    if empty:
        raise ValueError(f"missing returns for levels: {sorted(empty)}")

    scores = []
    for test_name, returns in by_test.items():
        record = _BY_TEST_NAME[test_name]
        score = (np.mean(returns) - record.random) / (
            record.human - record.random) * 100.0
        if per_level_cap is not None:
            score = min(score, per_level_cap)
        scores.append(score)
    return float(np.mean(scores))
