"""Experiment driver: train/test entry points and CLI.

The role of the reference's ``experiment.py`` driver (reference:
experiment.py:479-733) without its TF1 machinery: no sessions, no in-graph
queues — a host loop wiring ActorPool → device prefetch → Learner, with
checkpointing, metrics, and DMLab-30 scoring.

Run:
    python -m scalable_agent_tpu.driver --mode=train \
        --level_name=fake_benchmark --total_environment_frames=100000
    python -m scalable_agent_tpu.driver --mode=test --logdir=...

Actor runtime flags (docs/performance.md, "Continuous-batching actor
service"):
    --actor=grouped|service
        ``grouped`` (default) is the lockstep ActorPool: one thread per
        env group, the slowest env worker gates its whole group each
        step.  ``service`` is the continuous-batching actor service
        (runtime/service.py): env workers stream observations out the
        moment they finish, ONE inference thread batches whatever
        arrived (bucketed shapes, device-resident LSTM state slab), and
        per-env trajectory packers feed the same queue/transport — no
        per-step group barrier.
    --service_max_batch=N
        Largest service device batch (envs per inference call); 0 =
        auto (all of this process's envs).

Transport flags (docs/performance.md, "The trajectory transport"):
    --transport=packed|per_leaf
        How host trajectory batches reach the mesh.  ``packed`` (the
        default) flattens every Trajectory leaf into one contiguous,
        dtype-segmented, 128-byte-aligned staging buffer — a single H2D
        copy per batch — and restores the pytree with a jitted on-device
        unpack; ``per_leaf`` is the seed path (one device_put per leaf),
        preserved bit-for-bit for golden comparisons.
    --inflight_updates=W
        Bounded in-flight dispatch window: the update loop keeps up to W
        updates dispatched-but-unmaterialized and blocks only when the
        window is full, so batch k+1's pack/upload overlaps update k on
        the device.  2 (the default) pipelines one update deep with
        exact FIFO metrics accounting; 1 forces strict per-update
        lock-step (debugging, not throughput).

Self-healing flags (docs/robustness.md):
    --nonfinite_tolerance=N   consecutive non-finite (skipped) updates
        before rolling back to the last verified checkpoint; with
        --no_rollback the run exits 71 instead.
    --actor_max_restarts=K    bounded actor-thread respawn budget with
        capped exponential backoff.
    --chaos_spec='point@i[:j...];...'   deterministic fault injection
        (runtime/faults.py) for chaos testing the recovery paths; also
        accepts 'point@t=30s' (time trigger) and 'point@p=0.01'
        (seeded per-evaluation probability) entries.
    --chaos_channel           tail <logdir>/chaos_inject.jsonl for
        runtime-injected one-shot faults — the chaos soak engine's
        (runtime/soak.py) injection path into an already-running run.
    --compile_cache_dir=DIR   JAX persistent compilation cache: a
        relaunch/restart of the same program compiles from disk, which
        is what keeps elastic-reshard MTTR flat (docs/robustness.md).

Fleet fault-domain flags (runtime/fleet.py, docs/robustness.md):
    --peer_timeout_s=T        multi-process peer heartbeat deadline: a
        peer silent for T seconds triggers forensics + exit 72 in every
        survivor instead of an unbounded collective hang.
    --preemption_grace_s=G    SIGTERM raises a fleet-wide preemption
        flag; all processes drain and take ONE coordinated final
        checkpoint within G seconds, then exit 0 (frame-exact resume).
        0 restores the legacy dump-and-exit(143).
    --collective_timeout_s=C  deadline on each blocking cross-process
        point (0 = auto); --coordinator_init_timeout_s bounds the
        initialize retry loop.

Elastic membership flags (runtime/elastic.py, docs/robustness.md):
    --elastic                 supervisor mode: own N worker processes,
        convert a fleet-fatal (exit 72) or preemption into a RESHARD —
        relaunch the survivors as an (N-1)-process fleet resuming from
        the newest verified checkpoint — and scale back to N when the
        lost slot rejoins (graceful drain at a checkpoint boundary).
        Equivalent: python -m scalable_agent_tpu.runtime.elastic.
    --elastic_restart_budget / --elastic_stable_s   consecutive-restart
        cap with capped backoff; the budget resets once an epoch stays
        up elastic_stable_s.
    --elastic_rejoin_delay_s  how long a lost slot stays out before it
        may rejoin (touch <logdir>/rejoin.<slot> to force it early).
"""

import dataclasses
import functools
import json
import os
import queue as queue_lib
import threading
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from scalable_agent_tpu.config import Config, apply_env_overrides
from scalable_agent_tpu.envs import (
    MultiEnv,
    create_env,
    make_impala_stream,
)
from scalable_agent_tpu.envs import dmlab30
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.models import (
    CONV_BACKENDS,
    ImpalaAgent,
    actor_step,
    initial_state,
)
from scalable_agent_tpu.obs import (
    MetricsHTTPServer,
    MetricsWriter,
    PrometheusExporter,
    StallAttributor,
    configure_flight_recorder,
    configure_ledger,
    configure_tracer,
    configure_watchdog,
    get_flight_recorder,
    get_ledger,
    get_registry,
    get_tracer,
    get_watchdog,
    install_crash_handlers,
)
from scalable_agent_tpu.parallel import MeshSpec, make_mesh
from scalable_agent_tpu.runtime import (
    ActorPool,
    InflightWindow,
    Learner,
    LearnerHyperparams,
    NonFiniteTracker,
    TrainState,
    Trajectory,
    configure_faults,
    configure_fleet,
)
from scalable_agent_tpu.runtime.checkpoint import CheckpointManager
from scalable_agent_tpu.runtime.exit_codes import (
    NONFINITE_EXIT_CODE,
    SENTINEL_EXIT_CODE,
)
from scalable_agent_tpu.runtime.faults import (
    CHANNEL_NAME,
    get_fault_injector,
    throughput_sag_s,
)
from scalable_agent_tpu.types import (
    AgentOutput,
    AgentState,
    Observation,
    StepOutput,
    StepOutputInfo,
)
from scalable_agent_tpu.utils import Timing, log


def env_kwargs(config: Config, name: Optional[str] = None) -> dict:
    """Per-family constructor kwargs (the reference threads width/height/
    etc. through create_environment, experiment.py:430-459)."""
    name = name or config.level_name
    if name.startswith(("fake_", "dmlab_")):
        kwargs = {"height": config.height, "width": config.width,
                  "with_instruction": config.use_instruction}
        if name.startswith("dmlab_"):
            kwargs.update(dataset_path=config.dataset_path,
                          renderer=config.renderer)
        return kwargs
    if name.startswith(("atari_", "gym_", "doom_")):
        return {"height": config.height, "width": config.width}
    return {}


def resolve_mesh_data(config: Config) -> int:
    """The data-axis size train() will actually use — shared by the
    mesh construction and every "auto" kernel-choice estimate so they
    can never disagree."""
    n_devices = len(jax.devices())
    non_data = config.mesh_seq * config.mesh_model
    if jax.process_count() > 1:
        # Multi-host meshes must span EVERY process's devices: a
        # truncated device list would exclude whole processes, whose
        # local batch shards then have no addressable home in
        # make_array_from_process_local_data.
        mesh_data = config.mesh_data or n_devices // non_data
        if mesh_data * non_data != n_devices:
            raise ValueError(
                f"multi-host mesh (data={mesh_data}, "
                f"seq={config.mesh_seq}, model={config.mesh_model}) "
                f"must cover all {n_devices} global devices")
        return mesh_data
    # Single process: the shared auto-sizing rule (parallel/mesh.py) —
    # the largest data axis such that data*seq divides the batch, out
    # of the devices left after seq/model take theirs.  Elastic
    # restarts lean on this: a fleet relaunched with a different
    # process/device count resizes its mesh here with no operator
    # input.
    from scalable_agent_tpu.parallel.mesh import auto_data_axis

    return config.mesh_data or auto_data_axis(
        config.batch_size, n_devices, seq=config.mesh_seq,
        model=config.mesh_model)


def resolve_core_impl(config: Config) -> str:
    """"auto" defers to the shared fused-kernel policy
    (parallel/mesh.py fused_kernels_profitable), sized from the mesh
    train() will build (the agent is built before the mesh exists)."""
    if config.core_impl != "auto":
        return config.core_impl
    num = (resolve_mesh_data(config) * config.mesh_seq
           * config.mesh_model)
    from scalable_agent_tpu.parallel.mesh import fused_kernels_profitable
    return "pallas" if fused_kernels_profitable(num_devices=num) else "xla"


def resolve_conv_backend(config: Config) -> str:
    """"auto" = the Pallas grad-W stem on TPU, plain XLA elsewhere
    (off-TPU the kernel would run under the Pallas interpreter — the
    same code path tier-1 tests, but not a production lowering)."""
    if config.conv_backend != "auto":
        if config.conv_backend not in CONV_BACKENDS:
            raise ValueError(
                f"conv_backend must be auto or one of {CONV_BACKENDS}, "
                f"got {config.conv_backend!r}")
        return config.conv_backend
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def resolve_core_matmul_dtype(config: Config, core_impl: str) -> str:
    """"auto" follows the dtype policy: the pallas core's MXU matmuls
    run at compute_dtype (f32 accumulation either way); the xla core
    always trains at the f32 params' precision, so auto resolves to
    float32 there and the flag stays inert."""
    if config.core_matmul_dtype != "auto":
        return config.core_matmul_dtype
    if core_impl != "pallas":
        return "float32"
    return ("bfloat16"
            if jnp.dtype(config.compute_dtype) == jnp.dtype(jnp.bfloat16)
            else "float32")


def resolve_remat_torso(config: Config) -> bool:
    """"auto" = remat on TPU (where the fused single-forward update's
    peak activation memory at B=256 is the concern), off elsewhere."""
    if config.remat_torso not in ("auto", "on", "off"):
        raise ValueError(
            f"remat_torso must be auto, on, or off, got "
            f"{config.remat_torso!r}")
    if config.remat_torso != "auto":
        return config.remat_torso == "on"
    return jax.default_backend() == "tpu"


def build_agent(config: Config, action_space) -> ImpalaAgent:
    """Policy heads derive from the probed action space — one Discrete
    head or a composite tuple-categorical (ops/distributions.py)."""
    core_impl = resolve_core_impl(config)
    core_matmul_dtype = resolve_core_matmul_dtype(config, core_impl)
    if core_matmul_dtype not in ("float32", "bfloat16"):
        raise ValueError(
            f"core_matmul_dtype must be auto, float32, or bfloat16, "
            f"got {core_matmul_dtype!r}")
    if core_matmul_dtype != "float32" and core_impl != "pallas":
        import warnings

        warnings.warn(
            f"core_matmul_dtype={core_matmul_dtype!r} only "
            f"affects the pallas core; this run resolves to "
            f"core_impl={core_impl!r} and trains at float32",
            stacklevel=2)
    return ImpalaAgent(
        action_space=action_space,
        torso_type=config.torso_type,
        use_instruction=config.use_instruction,
        compute_dtype=jnp.dtype(config.compute_dtype),
        core_impl=core_impl,
        core_matmul_dtype=core_matmul_dtype,
        conv_backend=resolve_conv_backend(config),
        remat_torso=resolve_remat_torso(config),
    )


def training_level_names(config: Config) -> List[str]:
    """The level list training spreads env slots over.

    ``--level_name=dmlab30 --mode=train`` is multi-task: env slot e runs
    train level ``e % 30`` (the reference assigns actor i level
    ``level_names[i % len]``, experiment.py:552-555, with the train-
    variant list for train mode, :711-717).  Anything else trains one
    level."""
    if config.level_name == "dmlab30":
        return [f"dmlab_{name}" for name in dmlab30.TRAIN_LEVELS]
    return [config.level_name]


def probe_env(config: Config):
    """Open one env to read (observation_spec, action_space,
    num_agents), then tear it down.  num_agents > 1 marks a lockstep
    multi-agent level (create_env returns a MultiAgentEnv there)."""
    env = create_env(config.level_name, **env_kwargs(config))
    try:
        return (env.observation_spec, env.action_space,
                getattr(env, "num_agents", 1))
    finally:
        env.close()


def zero_trajectory(config: Config, observation_spec, agent: ImpalaAgent,
                    batch: int = 1, t_plus_1: int = 2) -> Trajectory:
    """All-zeros [t_plus_1, batch] trajectory for shape-only use: the
    [2, 1] default initializes params; the live-MFU cost analysis lowers
    the update at the run's REAL [T+1, B] shape."""
    frame_spec = observation_spec.frame

    def zeros(shape, dtype):
        return np.zeros((t_plus_1, batch) + tuple(shape), dtype)

    instruction = None
    if observation_spec.instruction is not None:
        instr_spec = observation_spec.instruction
        instruction = zeros(instr_spec.shape, instr_spec.dtype)
    num_components = agent.num_action_components
    action_shape = () if num_components == 1 else (num_components,)
    return Trajectory(
        agent_state=AgentState(
            c=np.zeros((batch, 256), np.float32),
            h=np.zeros((batch, 256), np.float32)),
        env_outputs=StepOutput(
            reward=zeros((), np.float32),
            info=StepOutputInfo(
                episode_return=zeros((), np.float32),
                episode_step=zeros((), np.int32)),
            done=zeros((), bool),
            observation=Observation(
                frame=zeros(frame_spec.shape, frame_spec.dtype),
                instruction=instruction),
        ),
        agent_outputs=AgentOutput(
            action=zeros(action_shape, np.int32),
            policy_logits=zeros((agent.num_logits,), np.float32),
            baseline=zeros((), np.float32)),
    )


def match_port_scheme(total_matches: int):
    """UDP port scheme shared by every concurrent-match constructor
    (training groups AND eval fleets): each match probes its own
    residue class — base ``DEFAULT_UDP_PORT + stride*index``, increment
    ``stride*total`` — so concurrent inits can't race each other, with
    >= ~4 retry probes per match kept under the 65536 ceiling.

    Returns ``stride``; raises when ``total_matches`` exhausts the port
    space above DEFAULT_UDP_PORT."""
    from scalable_agent_tpu.envs.doom.multiplayer import DEFAULT_UDP_PORT

    stride = max(1, min(1000, 25000 // max(1, 8 * total_matches)))
    retries = (65536 - DEFAULT_UDP_PORT - stride * total_matches) // (
        stride * total_matches)
    if retries < 2:
        raise ValueError(
            f"{total_matches} concurrent matches do not fit the UDP "
            f"port space above {DEFAULT_UDP_PORT} with retry headroom; "
            f"reduce the fleet or lower DOOM_DEFAULT_UDP_PORT")
    return stride


def make_env_groups(config: Config, frame_spec: TensorSpec,
                    num_agents: int = 1,
                    level_names: Optional[List[str]] = None
                    ) -> List[MultiEnv]:
    """num_actors envs as groups of batch_size (each group = one learner
    batch; >= 2 groups so env simulation and TPU inference overlap).

    ``frame_spec`` is the PROBED post-wrapper spec — pipelines change the
    channel count (e.g. Atari's grayscale stack-4 emits [84, 84, 4]), so
    the shared-memory slab layout cannot be assumed 3-channel.

    Multi-agent levels (``num_agents > 1``, from probe_env — e.g.
    ``doom_dm``, where ``create_env`` returns a lockstep
    ``MultiAgentEnv``, not an Environment) route to
    ``MultiAgentVectorEnv`` groups — K matches x A agents per group,
    each agent one batch slot (the role of the reference's
    ``create_multi_env`` dispatch, envs/env_utils.py:6-20)."""
    group_size = config.group_size()
    num_groups = max(1, config.num_actors // group_size)
    level_names = level_names or [config.level_name]

    if num_agents > 1:
        if len(level_names) > 1:
            raise ValueError(
                "multi-task training is not supported for multi-agent "
                "levels")
        if config.benchmark_mode:
            raise ValueError(
                "benchmark_mode is not supported for multi-agent levels")
        if group_size % num_agents:
            raise ValueError(
                f"batch_size {group_size} must be a multiple of the "
                f"level's num_agents ({num_agents})")
        from scalable_agent_tpu.envs.doom.multiplayer import (
            DEFAULT_UDP_PORT,
            MultiAgentVectorEnv,
        )

        matches = group_size // num_agents
        # Per-match seed (player seeds derive from it) and DISJOINT
        # port-search sequences, both GLOBALLY unique across multi-host
        # processes: the base stride shrinks as the global match count
        # grows so every base stays under the 65535 UDP limit, and each
        # match's fallback increment is stride * total, keeping every
        # match's probes in its own residue class — concurrent group
        # init (any host) can't race another match's host.
        proc = jax.process_index()
        total_global = num_groups * matches * jax.process_count()
        stride = match_port_scheme(total_global)

        def match_index(g: int, m: int) -> int:
            return proc * num_groups * matches + g * matches + m

        return [
            MultiAgentVectorEnv([
                functools.partial(
                    create_env, config.level_name,
                    num_action_repeats=config.num_action_repeats,
                    # Non-overlapping seed fields: one globally-unique
                    # match index scales the run seed, so no two matches
                    # (any host) can derive the same per-player seeds.
                    seed=config.seed * total_global + match_index(g, m),
                    port_base=(DEFAULT_UDP_PORT
                               + stride * match_index(g, m)),
                    port_increment=stride * total_global,
                    **env_kwargs(config))
                for m in range(matches)
            ])
            for g in range(num_groups)
        ]

    groups = []
    # GLOBAL env slot (multi-host: each process owns a disjoint slot
    # range) round-robins the level list so every level gets an equal
    # share of actors across the whole job — per-host indexing would
    # make every host train the same level prefix (reference assigns by
    # global actor id, experiment.py:552-555).
    slot_base = jax.process_index() * num_groups * group_size
    for g in range(num_groups):
        labels = [
            level_names[(slot_base + g * group_size + i)
                        % len(level_names)]
            for i in range(group_size)
        ]
        fns = [
            functools.partial(
                make_impala_stream, labels[i],
                seed=config.seed * 100000 + g * 1000 + i,
                benchmark_mode=config.benchmark_mode,
                num_action_repeats=config.num_action_repeats,
                **env_kwargs(config, labels[i]))
            for i in range(group_size)
        ]
        groups.append(MultiEnv(
            fns, frame_spec,
            num_workers=config.num_env_workers_per_group,
            env_labels=labels))
    return groups


def to_trajectory(actor_output) -> Trajectory:
    return Trajectory(
        agent_state=actor_output.agent_state,
        env_outputs=actor_output.env_outputs,
        agent_outputs=actor_output.agent_outputs,
    )


def start_prefetch(pool, learner, staged: queue_lib.Queue,
                   stop: threading.Event) -> threading.Thread:
    """Start the device-prefetch stage: pulls ActorPool trajectories,
    places them sharded on device, and stages them one deep — the
    reference's StagingArea +1-step policy lag (experiment.py:587-597).
    Exceptions surface through the staged queue."""

    def prefetch_loop():
        watchdog = get_watchdog()
        try:
            while not stop.is_set():
                # Every bounded wait below re-touches, so the prefetch
                # heartbeat only goes stale when the thread truly wedges
                # (e.g. inside a hung device placement).
                watchdog.touch()
                try:
                    out = pool.get_trajectory(timeout=0.5)
                except queue_lib.Empty:
                    continue
                traj = learner.put_trajectory(to_trajectory(out))
                # Re-bind the provenance record (this thread's current,
                # set by get_trajectory) to the PLACED object the main
                # loop will pull off the staged queue.
                ledger = get_ledger()
                tid = ledger.current()
                if tid is not None:
                    ledger.bind(id(traj), tid)
                while not stop.is_set():
                    watchdog.touch()
                    try:
                        staged.put(traj, timeout=0.5)
                        break
                    except queue_lib.Full:
                        continue
        except Exception as exc:  # surface in the consumer loop
            recorder = get_flight_recorder()
            recorder.record("exception", type(exc).__name__,
                            {"where": "prefetch"})
            recorder.dump_all(f"exception:{type(exc).__name__}:prefetch")
            staged.put(exc)
        finally:
            watchdog.suspend()

    thread = threading.Thread(target=prefetch_loop, daemon=True,
                              name="prefetch")
    thread.start()
    return thread


def _host_scalar(x) -> float:
    """Scalar metric -> host float, multi-host safe (replicated global
    arrays are not fully addressable; the local copy is)."""
    if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
        return float(np.asarray(x.addressable_shards[0].data))
    return float(np.asarray(x))


def _resolve_roofline_peak() -> Optional[float]:
    """Per-chip roofline peak (obs/ledger.py PEAK_FLOPS), overridable
    via SCALABLE_AGENT_LEDGER_MFU_PEAK so the full MFU/kernel path is
    exercisable on the CPU rig.  None when the chip is unknown and no
    override is set."""
    from scalable_agent_tpu.obs.ledger import peak_flops_per_chip

    peak = peak_flops_per_chip(jax.local_devices()[0].device_kind)
    override = os.environ.get("SCALABLE_AGENT_LEDGER_MFU_PEAK")
    if override:
        try:
            peak = float(override)
        except ValueError:
            pass
    return peak


def _harvest_kernel_ledger(config: Config, lower_fn,
                           executions: int,
                           profile_dir: Optional[str] = None,
                           out_name: Optional[str] = None
                           ) -> Optional[dict]:
    """Join a finished trace window with the compiled update's HLO +
    cost analysis into the per-kernel roofline ledger:
    ``<logdir>/<out_name>`` plus ``kernel/*`` registry gauges
    (obs/kernels.py; the worst-kernel verdict also feeds the stall
    line).  Defaults serve the scheduled ``--profile_dir`` window
    (``kernels.json``); the run-health plane passes its own window's
    trace dir and ``kernels.<anomaly_id>.json`` so both backends can
    harvest a programmatic mid-run window through the same path.
    Pays one AOT compile of the update — acceptable inside an explicit
    profiling window, and the only sanctioned way to read the
    optimized HLO whose instruction names the trace events carry.
    Never raises: the ledger is forensics, not the training path.
    Returns the harvested table (None on any failure)."""
    from scalable_agent_tpu.obs import kernels as kernels_lib

    profile_dir = profile_dir or config.profile_dir
    out_name = out_name or kernels_lib.KERNELS_JSON_NAME
    try:
        compiled = lower_fn().compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float((cost or {}).get("flops", 0.0))
        hlo_text = compiled.as_text()
    except Exception:
        log.exception("kernel ledger: update compile/cost read failed")
        return None
    try:
        table = kernels_lib.harvest(
            profile_dir, hlo_text, flops,
            _resolve_roofline_peak(), config.logdir,
            registry=get_registry(), executions=executions,
            extra={"device_kind": jax.local_devices()[0].device_kind,
                   "logdir": config.logdir},
            out_name=out_name)
    except Exception:
        log.exception("kernel ledger harvest failed")
        return None
    if table is None:
        log.warning("kernel ledger: no trace files under %s",
                    profile_dir)
        return None
    log.info(
        "kernel ledger: %d kernels joined (%.0f%% of event time), "
        "dominant %s (%.0f%% of kernel time), worst %s (mfu %s) — "
        "%s/%s",
        len(table["kernels"]), 100 * table["matched_time_frac"],
        table.get("dominant_kernel"),
        100 * (table.get("dominant_time_share") or 0.0),
        table.get("worst_kernel"),
        (f"{table['worst_kernel_mfu']:.3f}"
         if table.get("worst_kernel_mfu") is not None else "n/a"),
        config.logdir, out_name)
    return table


def _configure_live_mfu(ledger, lower_fn, num_devices: int,
                        updates_per_execution: int = 1):
    """Arm the ledger's live ``ledger/mfu`` gauge (obs/ledger.py).

    FLOPs per update come from the LOWERED (uncompiled) update
    program's cost analysis — tracing cost only, a few seconds at
    startup, no second XLA compile — and the per-chip peak from the
    shared roofline table in obs/ledger.py (the same one bench.py's MFU
    uses, so a run's gauge and the bench headline share a denominator).
    Skipped when the chip's peak is unknown (the CPU fallback — the
    gauge then stays at 0, and no test pays the lowering); the
    SCALABLE_AGENT_LEDGER_MFU_PEAK env var overrides the peak so the
    full path is exercisable anywhere.

    ``updates_per_execution``: the in-graph megaloop runs K updates
    per dispatched program, but XLA's cost analysis counts a lax.scan
    body ONCE regardless of trip count — so the lowered flops cover
    one update while a retired ledger record covers K; the gauge
    scales the numerator by K to stay honest."""
    peak = _resolve_roofline_peak()
    if not peak:
        return
    try:
        cost = lower_fn().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float((cost or {}).get("flops", 0.0))
    except Exception as exc:  # an obs gauge must never kill training
        log.info("live MFU gauge disabled (cost analysis failed): %s",
                 exc)
        return
    flops *= max(1, int(updates_per_execution))
    if flops > 0:
        ledger.configure_mfu(flops, peak, num_devices)
        log.info("live MFU gauge armed: %.3g flops/record against "
                 "%.3g peak flops/s x %d device(s)",
                 flops, peak, num_devices)


@dataclasses.dataclass
class _ObsHandles:
    """Everything _setup_observability wires and _teardown unwinds."""

    registry: object
    prom: Optional[PrometheusExporter]
    http: Optional[MetricsHTTPServer] = None
    uninstall_handlers: Optional[callable] = None


def _setup_observability(config: Config, coordinator: bool) -> _ObsHandles:
    """Wire the obs subsystem for one training run: the span tracer
    (--trace -> <logdir>/trace.p<proc>.<pid>.json), JAX recompile/memory
    hooks on the global registry, a per-process Prometheus snapshot file
    (the coordinator keeps the plain metrics.prom name), the flight
    recorder + crash handlers (SIGTERM/SIGINT, unhandled exceptions),
    the watchdog (--watchdog_timeout_s), and the optional live scrape
    endpoint (--metrics_http_port)."""
    proc = jax.process_index()
    if config.trace:
        # Per-(process, pid) file names: N processes of one run share
        # the logdir, and two runs pointed at the same logdir must not
        # clobber each other's trace.  obs/aggregate.py merges them.
        name = f"trace.p{proc}.{os.getpid()}.json"
        configure_tracer(os.path.join(config.logdir, name),
                         process_index=proc)
    registry = get_registry().install_jax_hooks()
    prom_name = "metrics.prom" if coordinator else f"metrics.p{proc}.prom"
    prom = PrometheusExporter(
        registry, os.path.join(config.logdir, prom_name))
    # Failure forensics: the ring buffer dumps (with all-thread stacks
    # and a final prom snapshot) on SIGTERM/SIGINT, unhandled
    # exceptions, and watchdog stalls.
    recorder = configure_flight_recorder(config.logdir,
                                         process_index=proc,
                                         registry=registry)
    recorder.exporter = prom
    uninstall = install_crash_handlers(recorder)
    configure_watchdog(config.watchdog_timeout_s, registry=registry,
                       abort=config.watchdog_abort,
                       flight_recorder=recorder)
    http = None
    if config.metrics_http_port:
        try:
            http = MetricsHTTPServer(registry,
                                     config.metrics_http_port + proc,
                                     logdir=config.logdir)
            log.info("serving Prometheus metrics on :%d/metrics "
                     "(+ /anomalies, /health)", http.port)
        except OSError as exc:  # a taken port must not kill training
            log.error("metrics HTTP endpoint unavailable on port %d: %s",
                      config.metrics_http_port + proc, exc)
    return _ObsHandles(registry=registry, prom=prom, http=http,
                       uninstall_handlers=uninstall)


def _teardown_observability(config: Config, handles: _ObsHandles):
    """Dump forensics if we are unwinding an exception, then flush the
    trace tail and the final metrics snapshot and unwind the hooks."""
    import sys

    recorder = get_flight_recorder()
    exc = sys.exc_info()[1]
    if exc is not None and not isinstance(exc, (SystemExit,
                                                KeyboardInterrupt)):
        # Exceptions unwinding through train() dump here, while every
        # thread whose stack explains the failure is still alive.
        recorder.dump_all(f"exception:{type(exc).__name__}")
    elif recorder.pending_dump_reason:
        # A signal handler requested the dump: its in-handler attempt
        # may have been abandoned (bounded join) if the interrupted
        # frame held a tracer/instrument lock — this stack is clean,
        # so complete/refresh it now.
        recorder.dump_all(recorder.pending_dump_reason)
    configure_watchdog(None)
    if handles.http is not None:
        handles.http.close()
    if config.trace:
        configure_tracer(None)  # closes (and flushes) the file tracer
    if handles.prom is not None:
        handles.prom.dump()
    if handles.uninstall_handlers is not None:
        handles.uninstall_handlers()


class _HealthPlane:
    """Driver-side state of the run-health plane (obs/health.py): the
    ``HealthMonitor`` plus the single in-flight anomaly-triggered
    profiling window, shared by BOTH backends so their wiring cannot
    drift.  The monitor arbitrates (budget, cooldown, one window at a
    time); this class owns the jax.profiler start/stop and the
    ``_harvest_kernel_ledger`` call against the window's own trace dir
    and ``kernels.<anomaly_id>.json`` name.  Inert (every method a
    no-op) when ``--health`` is off."""

    def __init__(self, config: Config, backend: str):
        self.monitor = None
        self.window_id: Optional[str] = None
        self.window_dir: Optional[str] = None
        self.window_stop_at: Optional[int] = None
        self._config = config
        if not config.health:
            return
        from scalable_agent_tpu.obs.health import (
            HealthMonitor,
            default_detectors,
        )

        self.monitor = HealthMonitor(
            default_detectors(
                backend=backend,
                warmup=config.health_warmup_intervals,
                alpha=config.health_ewma_alpha,
                z_threshold=config.health_z_threshold,
                rel_threshold=config.health_rel_threshold),
            logdir=config.logdir,
            registry=get_registry(),
            cooldown_s=config.health_cooldown_s,
            max_windows=config.health_max_windows)
        if config.health_baseline_dir:
            bench_dir = (None if config.health_baseline_dir == "auto"
                         else config.health_baseline_dir)
            try:
                source = self.monitor.prime_from_bench(bench_dir)
            except Exception:
                log.exception("health baseline priming failed")
                source = None
            if source:
                log.info("health detectors primed from committed "
                         "round %s", source)

    @property
    def active(self) -> bool:
        return self.monitor is not None

    @property
    def window_open(self) -> bool:
        return self.window_stop_at is not None

    def step(self, metrics, update: int, verdict=None, evidence=None):
        """One detector pass at log cadence.  Never raises — health is
        forensics, not the training path."""
        if self.monitor is None:
            return
        try:
            self.monitor.step(metrics=metrics, update=update,
                              verdict=verdict, evidence=evidence)
        except Exception:
            log.exception("health detector step failed")

    def maybe_open_window(self, updates: int) -> bool:
        """Open the pending anomaly's profiling window (if any): its
        own trace dir under the logdir, stop scheduled
        ``health_window_updates`` updates from now."""
        if self.monitor is None or self.window_open:
            return False
        anomaly_id = self.monitor.poll_window()
        if anomaly_id is None:
            return False
        trace_dir = os.path.join(self._config.logdir,
                                 f"health_profile.{anomaly_id}")
        try:
            jax.profiler.start_trace(trace_dir)
        except Exception:
            log.exception("health profile window failed to start")
            return False
        get_tracer().set_annotate(True)
        self.window_id = anomaly_id
        self.window_dir = trace_dir
        self.window_stop_at = (updates
                               + self._config.health_window_updates)
        self.monitor.note_window_open(anomaly_id, trace_dir)
        log.info("health: auto-profile window %s open through update "
                 "%d (%s)", anomaly_id, self.window_stop_at, trace_dir)
        return True

    def close_window(self, lower_fn, executions: Optional[int] = None):
        """Stop the window's trace and harvest its kernel ledger into
        ``kernels.<anomaly_id>.json``, finalizing the anomaly record
        with the worst-kernel delta vs the run's baseline window."""
        if self.monitor is None or not self.window_open:
            return
        anomaly_id, trace_dir = self.window_id, self.window_dir
        self.window_id = self.window_dir = self.window_stop_at = None
        try:
            jax.profiler.stop_trace()
        except Exception:
            log.exception("health profile window failed to stop")
        get_tracer().set_annotate(False)
        out_name = f"kernels.{anomaly_id}.json"
        table = _harvest_kernel_ledger(
            self._config, lower_fn,
            executions=(executions if executions is not None
                        else self._config.health_window_updates),
            profile_dir=trace_dir, out_name=out_name)
        self.monitor.note_window_result(
            anomaly_id, table,
            kernels_json=(os.path.join(self._config.logdir, out_name)
                          if table else None))

    def note_baseline(self, table: Optional[dict]):
        """The scheduled ``--profile_dir`` window's kernel table — the
        reference the anomaly windows' deltas are computed against."""
        if self.monitor is not None and table:
            self.monitor.note_baseline_kernels(table)

    def finalize(self):
        """Teardown: stop a still-open window's trace (no harvest —
        the run is ending) and flush open anomaly records."""
        if self.monitor is None:
            return
        if self.window_open:
            self.window_id = self.window_dir = None
            self.window_stop_at = None
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            get_tracer().set_annotate(False)
        try:
            self.monitor.flush()
        except Exception:
            log.exception("health flush failed")


# NONFINITE_EXIT_CODE (71, re-exported above from runtime/exit_codes.py
# — the one registry for watchdog 70 / non-finite 71 / fleet 72): a run
# ended by the non-finite guard with --no_rollback, or with no
# checkpoint left to roll back to.  Distinct codes let a supervisor
# tell a numeric divergence from a hang from a lost peer.


def _rollback_or_exit(config: Config, ckpt: CheckpointManager,
                      learner: Learner, state: TrainState,
                      tracker: NonFiniteTracker,
                      reason: str = "nonfinite",
                      exit_code: int = NONFINITE_EXIT_CODE):
    """A guard's tolerance is exhausted (``reason``: the non-finite
    streak, or the numerics sentinel's surviving breach): restore the
    newest VERIFIED checkpoint (watchdog suspended across the read) and
    return ``(state, updates, frames)`` on the rolled-back timeline —
    or raise ``SystemExit(exit_code)`` (71 non-finite / 73 sentinel)
    when rollback is disabled or impossible."""
    recorder = get_flight_recorder()
    registry = get_registry()
    guard = ("sentinel" if reason == "sentinel"
             else "non-finite guard")
    if config.no_rollback:
        log.error(
            "%s: rollback wanted and --no_rollback is set — exiting %d",
            guard, exit_code)
        recorder.record("rollback", "disabled",
                        {"streak": tracker.tolerance, "reason": reason})
        recorder.dump_all(f"{reason}:no_rollback")
        raise SystemExit(exit_code)
    watchdog = get_watchdog()
    # A long Orbax read is recovery, not a wedge: the learner heartbeat
    # must not trip stalled_thread (or --watchdog_abort) mid-restore.
    watchdog.suspend("learner")
    from scalable_agent_tpu.runtime.checkpoint import (
        CheckpointIntegrityError,
    )

    try:
        restored = ckpt.restore(target=state)
    except CheckpointIntegrityError as exc:
        # Checkpoints exist but none verified: with the tolerance
        # already exhausted there is nothing to roll back to — same
        # terminal outcome as having no checkpoint at all.
        log.error("%s: %s", guard, exc)
        restored = None
    if restored is None:
        log.error(
            "%s: rollback wanted and no restorable checkpoint under "
            "%s — exiting %d", guard, config.logdir, exit_code)
        recorder.record("rollback", "no_checkpoint", {"reason": reason})
        recorder.dump_all(f"{reason}:no_checkpoint")
        raise SystemExit(exit_code)
    step, host_state = restored
    # Zero the streak so the restored timeline gets the full tolerance
    # again (the checkpoint may have been saved mid-streak).
    host_state = host_state._replace(
        nonfinite_streak=np.zeros_like(
            np.asarray(host_state.nonfinite_streak)))
    state = learner.place_state(host_state)
    registry.counter(
        "learner/rollbacks_total",
        "rollbacks to the last good checkpoint after a guard's "
        "tolerance was exhausted (non-finite streak or sentinel "
        "breach)").inc()
    frames = _host_scalar(state.env_frames)
    recorder.record("rollback", "restored",
                    {"step": step, "env_frames": frames,
                     "reason": reason})
    tracker.rebase(_host_scalar(state.nonfinite_skips))
    watchdog.touch("learner")
    log.warning(
        "%s: rolled back to checkpoint step %d (%.0f frames)",
        guard, step, frames)
    return state, step, frames


def _setup_compile_cache(config: Config):
    """Arm JAX's persistent compilation cache (--compile_cache_dir).

    MTTR engineering (docs/robustness.md): an elastic relaunch pays the
    fresh process's first compile before its first metrics row, so the
    epochs-log ``mttr`` is dominated by compile time.  With the cache
    armed, epoch 0 populates it and every relaunch's compile is a disk
    read.  The floor knobs are zeroed so even the small CPU test
    programs cache — the production TPU programs clear any floor."""
    if not config.compile_cache_dir:
        return
    os.makedirs(config.compile_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir",
                      config.compile_cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


def _arm_faults(config: Config):
    """Arm the chaos injector for this run: the --chaos_spec triggers
    plus, under --chaos_channel, the <logdir>/chaos_inject.jsonl
    runtime channel (the soak engine's injection path)."""
    configure_faults(
        config.chaos_spec,
        channel_path=(os.path.join(config.logdir, CHANNEL_NAME)
                      if config.chaos_channel else None),
        seed=config.seed,
        process_id=max(0, config.distributed_process_id))


def _write_mttr_breakdown(config: Config, restore_s: float,
                          compile_s: float):
    """Publish this process's startup-cost segments for the elastic
    supervisor's MTTR decomposition (runtime/elastic.py reads the file
    at the recovery beacon and folds the segments into the epochs-log
    ``mttr`` record).  Coordinator only; atomic replace."""
    if jax.process_index() != 0:
        return
    from scalable_agent_tpu.runtime.elastic import MTTR_BREAKDOWN_NAME

    payload = {"epoch": int(config.fleet_epoch),
               "restore_s": round(restore_s, 3),
               "compile_s": round(compile_s, 3),
               "t_unix": time.time()}
    path = os.path.join(config.logdir, MTTR_BREAKDOWN_NAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except OSError:
        log.exception("mttr breakdown write failed (non-fatal)")


def train(config: Config) -> Dict[str, float]:
    """Train until total_environment_frames.  Returns final metrics.

    Multi-host: run the SAME command on every host with
    --distributed_coordinator/--distributed_num_processes/
    --distributed_process_id set (or JAX_* env vars).  Every process
    runs its own actor pool contributing 1/P of each global batch; the
    learner update is one SPMD program over the global device mesh
    (parallel/distributed.py; role of the reference's learner+actor
    jobs, experiment.py:497-512)."""
    from scalable_agent_tpu.parallel.distributed import (
        initialize_distributed,
        is_coordinator,
    )

    if config.train_backend == "ingraph":
        return train_ingraph(config)
    if config.train_backend != "host":
        raise ValueError(
            f"unknown train_backend {config.train_backend!r} "
            f"(host | ingraph)")

    initialize_distributed(
        config.distributed_coordinator or None,
        config.distributed_num_processes or None,
        config.distributed_process_id
        if config.distributed_process_id >= 0 else None,
        init_timeout_s=config.coordinator_init_timeout_s)

    config = apply_env_overrides(config)
    if is_coordinator():
        config.save()
    _setup_compile_cache(config)
    # Chaos harness: arm the deterministic fault-injection points and
    # (under --chaos_channel) the runtime injection channel (no-op with
    # neither configured); disarmed again in the finally so one run's
    # spec can't leak into the next in-process run.
    _arm_faults(config)
    # Observability comes up BEFORE the actor pool so its threads are
    # born with the live tracer and watchdog (spans/heartbeats from the
    # very first unroll); the try below owns teardown from this point
    # on, so a failure anywhere in construction still flushes/closes
    # the trace file and dumps the flight recorder.
    obs_handles = _setup_observability(config, is_coordinator())
    registry, prom = obs_handles.registry, obs_handles.prom
    # Fleet fault domains (runtime/fleet.py): peer heartbeats over the
    # jax.distributed KV store, collective deadlines, and the SIGTERM
    # preemption-grace protocol.  Up BEFORE the learner/restore so a
    # peer lost during the (collective) restore or first compile is
    # already bounded; its SIGTERM handler layers over the crash
    # handlers _setup_observability just installed.
    fleet = configure_fleet(
        config.peer_timeout_s,
        preemption_grace_s=config.preemption_grace_s,
        collective_timeout_s=config.collective_timeout_s,
        registry=registry,
        recorder=get_flight_recorder(),
        epoch=config.fleet_epoch,
        logdir=config.logdir)
    # Pipeline ledger (obs/ledger.py): per-trajectory provenance
    # records stamped at every stage boundary below, derived into
    # per-stage rates/ρ, the staleness histogram, and the live MFU
    # gauge at each log interval.  Configured fresh per run so one
    # run's open records can never leak into the next.
    ledger = configure_ledger(
        registry=registry,
        frames_per_trajectory=config.frames_per_update(),
        logdir=config.logdir,
        process_index=jax.process_index())
    pool = prefetch_thread = writer = ckpt = learner = None
    sentinel = None
    prefetch_stop = threading.Event()
    profiling = False
    completed = False
    metrics = {}
    # Run-health plane (obs/health.py): detectors at log cadence plus
    # the anomaly-triggered profiling window.  Constructed before the
    # try so the finally's flush always sees it.
    health = _HealthPlane(config, backend="host")
    injector = get_fault_injector()
    try:
        level_names = training_level_names(config)
        multi_task = len(level_names) > 1
        probe_config = (
            dataclasses.replace(config, level_name=level_names[0])
            if multi_task else config)
        observation_spec, action_space, num_agents = probe_env(
            probe_config)
        agent = build_agent(config, action_space)

        learner = build_training_learner(config, agent)
        # Device-resident replay (runtime/replay.py): every fresh
        # batch's packed upload also lands in the slab, and
        # --replay_ratio replayed updates ride behind each fresh one —
        # None (and nothing allocated) when the dial is at 0.
        replay = build_replay(config, learner)
        # Numerics sentinel (runtime/sentinel.py): shadow audits of the
        # optimized hot path against the reference arm every
        # --sentinel_interval updates, param fingerprints at the
        # decision-broadcast cadence, and the degradation ladder on
        # breach.  None (and no jitted program changes anywhere) when
        # the dial is at 0 — the default path stays bit-exact.
        sentinel = build_sentinel(config, agent, learner, action_space)

        # gloo (the multi-process CPU collectives transport) pairs ops
        # by ARRIVAL order per process-pair: no two programs with
        # collectives may ever be in flight at once, or their ops
        # mispair across processes and abort the whole fleet with a
        # size mismatch.  TPU/GPU streams serialize collectives in
        # issue order, so only the CPU rig pays these explicit
        # materialization barriers (here and in the update loop).
        cpu_lockstep = (jax.process_count() > 1
                        and jax.devices()[0].platform == "cpu")

        ckpt = CheckpointManager(config.logdir,
                                 config.checkpoint_interval_s,
                                 config.checkpoint_keep)
        example = zero_trajectory(config, observation_spec, agent)
        state = learner.init(jax.random.key(config.seed), example)
        if cpu_lockstep:
            # init is a global-mesh program whose collectives would
            # otherwise still be draining when restore()'s has_any
            # broadcast posts its own ops.
            jax.block_until_ready(state)
        restore_t0 = time.monotonic()
        restored = ckpt.restore(target=state)
        if restored is not None:
            start_updates, host_state = restored
            state = learner.place_state(host_state)
            if cpu_lockstep:
                jax.block_until_ready(state)
            # Topology-agnostic resume (runtime/elastic.py): when this
            # fleet's process/device layout differs from the one that
            # wrote the checkpoint (an elastic reshard), the placed
            # state is gathered back and re-verified against the
            # per-leaf CRC manifest — collective, so every process
            # reaches it together (restore() guarantees `restored` is
            # non-None on all of them together).
            ckpt.verify_after_reshard(start_updates, state)
            fleet.note_checkpoint(start_updates)
            log.info("restored checkpoint at update %d (%.0f frames)",
                     start_updates, _host_scalar(state.env_frames))
        else:
            start_updates = 0
        restore_s = time.monotonic() - restore_t0

        # Live MFU numerator: lower (don't compile) the update once at
        # the run's REAL [T+1, local_B] shape for its cost-analysis
        # FLOPs.  The denominator is this PROCESS'S share of the mesh
        # (local devices), matching the local-batch numerator — each
        # process then gauges its own chips' utilization, and the
        # aggregator's MAX fold shows the busiest process.  No-op on
        # chips without a roofline entry (CPU).
        mfu_example = zero_trajectory(
            config, observation_spec, agent,
            batch=max(1, config.batch_size // jax.process_count()),
            t_plus_1=config.unroll_length + 1)
        _configure_live_mfu(
            ledger, lambda: learner.lower_update(state, mfu_example),
            max(1, learner.mesh.devices.size // jax.process_count()))
        del mfu_example

        env_groups = make_env_groups(config, observation_spec.frame,
                                     num_agents=num_agents,
                                     level_names=level_names)
        if config.actor == "service":
            # Continuous-batching actor service (runtime/service.py):
            # same queue/get_trajectory surface as the pool, so the
            # prefetch stage and everything downstream are unchanged.
            from scalable_agent_tpu.runtime.service import ActorService

            if config.inference_mode != "structural":
                raise ValueError(
                    f"--actor=service owns its inference (one "
                    f"continuous-batching thread); inference_mode="
                    f"{config.inference_mode!r} applies to "
                    f"--actor=grouped only")
            pool = ActorService(
                agent, env_groups, config.unroll_length,
                level_name=config.level_name, seed=config.seed,
                max_batch=config.service_max_batch,
                max_restarts=config.actor_max_restarts)
        else:
            pool = ActorPool(
                agent, env_groups, config.unroll_length,
                level_name=config.level_name, seed=config.seed,
                inference_mode=config.inference_mode,
                observation_spec=observation_spec,
                fused_shards=config.accum_fused_shards,
                max_restarts=config.actor_max_restarts)
        pool.set_params(state.params)
        pool.start()

        # Device prefetch stage: stages the next batch while the current
        # update runs (the reference's StagingArea +1-step policy lag,
        # experiment.py:587-597).
        staged: queue_lib.Queue = queue_lib.Queue(maxsize=1)
        prefetch_thread = start_prefetch(pool, learner, staged,
                                         prefetch_stop)

        stall = StallAttributor(registry)
        # Non-finite guard policy: the jitted update carries the skip
        # counters in its metrics (runtime/learner.py); this tracker
        # reads them at log time — the fetch the loop already pays —
        # and arbitrates rollback vs exit 71.  Baseline at the restored
        # state's cumulative count: a resumed run must not re-count the
        # previous run's lifetime skips into this process's counter.
        nonfinite = NonFiniteTracker(config.nonfinite_tolerance,
                                     registry=registry)
        nonfinite.rebase(_host_scalar(state.nonfinite_skips))
        actor_steps_counter = registry.counter("actor/agent_steps_total")
        actor_fps_gauge = registry.gauge(
            "actor/fps", "env frames/s generated by this host's actors")
        learner_fps_gauge = registry.gauge(
            "learner/fps", "env frames/s consumed by the learner")
        writer = (MetricsWriter(config.logdir, registry=registry)
                  if is_coordinator() else None)
        timing = Timing()
        # Per-interval stage sums for the stall attributor (the display
        # `timing` keeps moving averages; attribution needs THIS
        # interval).
        interval = Timing()
        actor_steps_at_last_log = actor_steps_counter.value
        updates = start_updates
        frames_per_update = config.frames_per_update()
        # The restored TrainState's env_frames (which drives the LR
        # schedule) is authoritative — recomputing
        # updates*frames_per_update from the CURRENT config would
        # silently disagree if batch_size/unroll_length/
        # num_action_repeats changed between runs.
        frames = _host_scalar(state.env_frames)
        last_log = time.monotonic()
        frames_at_last_log = frames
        # Multi-task: per-level returns accumulated toward the TRAINING
        # suite score, cleared after each score like the reference
        # (experiment.py:652-667).
        suite_returns: Dict[str, List[float]] = (
            {name: [] for name in dmlab30.TRAIN_LEVELS}
            if multi_task else {})
        # Device-level tracing (SURVEY §5.1): --profile_dir captures a
        # jax.profiler trace of updates [profile_start_update,
        # +profile_num_updates) viewable in TensorBoard/XProf — the tool
        # for locating host↔device stalls the Timing counters can't
        # attribute.
        watchdog = get_watchdog()
        # Bounded in-flight dispatch (runtime/transport.py): up to
        # --inflight_updates updates stay dispatched-but-unmaterialized;
        # the loop blocks ("retire") only when the window fills, so the
        # next batch's staging overlaps the running update while
        # backpressure and per-update metrics ordering stay exact.
        # Same gloo arrival-order hazard as above: neither two
        # overlapping update executions (inflight window) nor an async
        # update racing the loop's next blocking broadcast may coexist
        # on the CPU rig.
        inflight_updates = config.inflight_updates
        if inflight_updates > 1 and cpu_lockstep:
            log.warning(
                "inflight_updates=%d downgraded to 1: multi-process "
                "CPU (gloo) runs mispair collectives from overlapping "
                "update executions", inflight_updates)
            inflight_updates = 1
        inflight = InflightWindow(inflight_updates,
                                  registry=registry)
        rollback_wanted = False
        # Compile windows are recovery/startup cost, not wedges: the
        # first dispatch (cold or relaunch compile) and the re-jit
        # after a sentinel ladder demotion (~13s measured) run with the
        # learner heartbeat suspended — the same treatment rollback
        # restore gets — so a tight --watchdog_timeout_s doesn't read
        # them as hangs.  The post-update touch re-arms.
        rejit_pending = True
        first_dispatch_t0 = None
        while frames < config.total_environment_frames:
            if (config.profile_dir and not profiling
                    and not health.window_open
                    and updates - start_updates
                    == config.profile_start_update):
                jax.profiler.start_trace(config.profile_dir)
                # Host spans annotate into the device capture only while
                # it records (TraceAnnotation is ~100x a span; see
                # Tracer.set_annotate).
                get_tracer().set_annotate(True)
                profiling = True
                profile_stop_at = updates + config.profile_num_updates
            # Disarm the learner heartbeat while blocked on the staged
            # queue: starvation is the stall attributor's domain, and a
            # wedged UPSTREAM thread's own stale heartbeat names the
            # culprit — the learner waiting on it is a symptom.
            watchdog.suspend("learner")
            with timing.time_avg("wait_batch"), \
                    interval.add_time("wait_batch"), \
                    get_tracer().span("learner/wait_batch", cat="learner"):
                traj = staged.get()
            watchdog.touch("learner")
            if isinstance(traj, Exception):
                raise traj
            # Recover the batch's provenance record; the in-flight
            # window owns its end (retire stamps + close, or the
            # rollback discard's retired=False close).
            ledger_tid = ledger.lookup(id(traj))
            audit_snap = None
            if sentinel is not None and sentinel.audit_due(updates):
                # Pre-update snapshot for the shadow audit below: the
                # hot update donates its input state, so the audit
                # needs its own buffers (the trajectory is not
                # donated and rides through as-is).
                audit_snap = sentinel.snapshot(state)
            if rejit_pending:
                watchdog.suspend("learner")
                rejit_pending = False
                if updates == start_updates:
                    first_dispatch_t0 = time.monotonic()
            with timing.time_avg("update"), interval.add_time("update"):
                state, dispatched = learner.update(state, traj)
                # Chaos: a deterministic mid-run slowdown (thermal
                # throttle / noisy neighbor stand-in) the health plane
                # must catch — occurrences count fresh update
                # dispatches.  Inside the update timing block so the
                # stall attributor reads it as a slow device.
                if injector.active and injector.should_fire(
                        "throughput_sag"):
                    time.sleep(throughput_sag_s())
            if ledger_tid is not None:
                ledger.stamp(ledger_tid, "dispatch")
            inflight.push(dispatched, ledger_id=ledger_tid)
            if cpu_lockstep:
                # Materialize the WHOLE update before the loop can
                # reach another cross-process point (decision
                # broadcast, save collective): metrics resolving does
                # not mean the program's last all-reduce has drained,
                # and gloo mispairs anything that arrives alongside it.
                jax.block_until_ready(state)
            watchdog.touch("learner")
            if first_dispatch_t0 is not None:
                # Startup-cost beacon for the supervisor's MTTR
                # decomposition: the first dispatch blocks through the
                # update's compile, so its wall time is the compile
                # segment.
                _write_mttr_breakdown(config, restore_s,
                                      time.monotonic()
                                      - first_dispatch_t0)
                first_dispatch_t0 = None
            if audit_snap is not None:
                # Shadow audit: recompute this batch's grads + param
                # delta through the reference arm on device and compare
                # (one D2H bool at audit cadence).  Runs BEFORE the
                # replay updates below so the delta compare sees the
                # fresh update's params, and may demote the ladder —
                # in which case the next update re-jits on the demoted
                # learner (the prefetch thread keeps the old learner's
                # transport; its placed trajectories feed the new
                # learner unchanged — computation follows data).
                # The reference arm's own compile (first audit) and the
                # compare are recovery machinery, not progress the
                # heartbeat should time — suspend like rollback
                # restore; the touch below re-arms.
                watchdog.suspend("learner")
                with timing.time_avg("audit"), \
                        interval.add_time("audit"):
                    state = sentinel.audit(audit_snap, traj, state,
                                           updates)
                audit_snap = None
                if sentinel.consume_swap():
                    # Flush the old hot path's devtel before dropping
                    # it, then adopt the demoted learner.  The replay
                    # slab's lineage is suspect (filled by the breached
                    # path) — drop it and re-warm.
                    learner.publish_device_telemetry()
                    learner = sentinel.learner
                    agent = sentinel.agent
                    if replay is not None:
                        replay.flush()
                    # The demoted rung re-jits inside the next dispatch
                    # (~13s measured): suspend across it too.
                    rejit_pending = True
                watchdog.touch("learner")
            # The size gate covers the re-warm-up window after a
            # rollback/demotion flush: the slab refills from the
            # prefetch thread's uploads, and until the first lands the
            # replayed updates are simply skipped (fresh training
            # continues at ratio 0) rather than sampling an empty ring.
            if replay is not None and replay.size >= 1:
                # The off-policy dial: R replayed updates behind every
                # fresh batch — on-device sample + unpack + update,
                # env_frames held (fresh frames count exactly once),
                # metrics through the same in-flight window with no
                # provenance record (the batch's frames were accounted
                # at fresh consumption; its AGE lands in
                # ledger/staleness_replayed_s at sample time).
                for _ in range(config.replay_ratio):
                    with timing.time_avg("update"), \
                            interval.add_time("update"), \
                            get_tracer().span("learner/replay_update",
                                              cat="learner"):
                        rtraj = replay.sample()
                        state, dispatched = learner.update(
                            state, rtraj, fresh=False)
                    inflight.push(dispatched, ledger_id=None)
                    updates += 1
                    if inflight.full:
                        with timing.time_avg("retire"), \
                                interval.add_time("retire"), \
                                fleet.collective("retire_update"):
                            metrics = inflight.retire()
                    watchdog.touch("learner")
            pool.set_params(state.params, version=updates)
            updates += 1
            frames += frames_per_update
            if inflight.full:
                # Materialize the OLDEST in-flight update's metrics
                # (FIFO, so the logged metrics always belong to a known
                # update and env_frames accounting is exact); this is
                # the loop's only device wait — in a multi-process run
                # it materializes the cross-host all-reduce, so a peer
                # lost mid-update surfaces (and is attributed) here.
                with timing.time_avg("retire"), \
                        interval.add_time("retire"), \
                        fleet.collective("retire_update"):
                    metrics = inflight.retire()
            watchdog.touch("learner")
            if profiling and updates >= profile_stop_at:
                jax.block_until_ready(dispatched["total_loss"])
                jax.profiler.stop_trace()
                get_tracer().set_annotate(False)
                profiling = False
                log.info("profiler trace written to %s",
                         config.profile_dir)
                # Per-kernel roofline ledger over the window just
                # captured (obs/kernels.py): rebuild the zero example
                # at the update's real shapes for the lowering — the
                # state/trajectory in flight carry the same avals.
                kernel_example = zero_trajectory(
                    config, observation_spec, agent,
                    batch=max(1,
                              config.batch_size // jax.process_count()),
                    t_plus_1=config.unroll_length + 1)
                # The harvest re-pays the production-shape AOT compile
                # (multi-minute on TPU) on this thread: disarm the
                # learner heartbeat across it like every other healthy
                # long pause — the next loop touch re-arms.
                watchdog.suspend("learner")
                table = _harvest_kernel_ledger(
                    config,
                    lambda: learner.lower_update(state, kernel_example),
                    executions=config.profile_num_updates)
                # The scheduled window doubles as the health plane's
                # baseline: anomaly windows report their worst-kernel
                # delta against it.
                health.note_baseline(table)
                del kernel_example
            if health.window_open and updates >= health.window_stop_at:
                # An anomaly-triggered profiling window just completed:
                # same stop/harvest discipline as the scheduled window,
                # but into kernels.<anomaly_id>.json and back into the
                # anomaly record.
                jax.block_until_ready(dispatched["total_loss"])
                kernel_example = zero_trajectory(
                    config, observation_spec, agent,
                    batch=max(1,
                              config.batch_size // jax.process_count()),
                    t_plus_1=config.unroll_length + 1)
                watchdog.suspend("learner")
                health.close_window(
                    lambda: learner.lower_update(state, kernel_example))
                del kernel_example

            now = time.monotonic()
            if now - last_log >= config.log_interval_s:
                if not metrics:
                    # Nothing has fallen out of the in-flight window
                    # yet (the first W-1 updates): log the newest
                    # dispatched update rather than an empty dict —
                    # the log-time fetch below is the sync the seed
                    # loop always paid here.
                    metrics = dispatched
                # The log-time fetches (host scalars here, the devtel/
                # sentinel publishes below) drain the device queue —
                # which, right after an audit or ladder demotion,
                # carries the recovery path's compiles.  That wait is
                # device backlog, not a wedged learner: disarm across
                # the fetch section; the touch after ledger.publish
                # re-arms.
                watchdog.suspend("learner")
                host_metrics = {k: _host_scalar(v)
                                for k, v in metrics.items()}
                # Only RECORD the verdict here: the log gate runs on
                # local wall clocks, and acting inside it would let
                # multi-host processes enter the collective restore on
                # different iterations.  The rollback itself happens at
                # the fixed per-iteration point below.
                if nonfinite.observe(host_metrics):
                    rollback_wanted = True
                fps = (frames - frames_at_last_log) / (now - last_log)
                host_metrics["fps"] = fps
                stats = pool.episode_stats()
                if stats:
                    host_metrics["episode_return"] = float(
                        np.mean([r for r, _ in stats]))
                    host_metrics["episode_frames"] = float(
                        np.mean([l for _, l in stats])
                        * config.num_action_repeats)
                # Per-level attribution (reference logs
                # <level>/episode_return and /episode_frames per episode,
                # experiment.py:634-650; interval means here).
                for level, entries in pool.drain_level_stats().items():
                    host_metrics[f"{level}/episode_return"] = float(
                        np.mean([r for r, _ in entries]))
                    host_metrics[f"{level}/episode_frames"] = float(
                        np.mean([l for _, l in entries])
                        * config.num_action_repeats)
                    if multi_task:
                        bare = (level[len("dmlab_"):]
                                if level.startswith("dmlab_") else level)
                        if bare in suite_returns:
                            suite_returns[bare].extend(
                                r for r, _ in entries)
                if multi_task and suite_returns and min(
                        len(v) for v in suite_returns.values()) >= 1:
                    # Every level reported since the last score: emit the
                    # capped/uncapped human-normalized TRAINING score and
                    # clear (reference: experiment.py:652-667).
                    host_metrics["dmlab30/training_no_cap"] = (
                        dmlab30.compute_human_normalized_score(
                            suite_returns, per_level_cap=None))
                    host_metrics["dmlab30/training_cap_100"] = (
                        dmlab30.compute_human_normalized_score(
                            suite_returns, per_level_cap=100.0))
                    log.info(
                        "dmlab30 training score — no cap: %.2f cap 100: "
                        "%.2f", host_metrics["dmlab30/training_no_cap"],
                        host_metrics["dmlab30/training_cap_100"])
                    suite_returns = {
                        name: [] for name in dmlab30.TRAIN_LEVELS}
                # Separate actor-FPS vs learner-FPS: the learner's
                # consumption rate (`fps`) can hide an actor surplus or
                # deficit that the queue currently masks.
                actor_steps = actor_steps_counter.value
                actor_fps = ((actor_steps - actor_steps_at_last_log)
                             * config.num_action_repeats / (now - last_log))
                actor_steps_at_last_log = actor_steps
                actor_fps_gauge.set(actor_fps)
                learner_fps_gauge.set(fps)
                host_metrics["actor_fps"] = actor_fps
                # Machine-readable timing snapshot (Timing.summary): the
                # same numbers as the log line, str-parse-free.
                timing_summary = timing.summary()
                host_metrics.update(
                    {f"timing/{k}": v for k, v in timing_summary.items()})
                # Device telemetry: the ONE fetch the on-device
                # instruments ever cost (a few hundred bytes at log
                # cadence), folded into the registry as devtel/* so it
                # rides the writer/prom dumps below.
                learner.publish_device_telemetry()
                if sentinel is not None:
                    sentinel.publish()
                # Ledger derivation BEFORE stall attribution, so the
                # verdict line carries this interval's dominant-stage
                # share (rates/ρ/staleness/MFU land in the registry and
                # ride the writer/prom dumps below).
                ledger.publish()
                watchdog.touch("learner")
                # Stall attribution over THIS interval's stage sums.
                interval_summary = interval.summary()
                interval.clear()
                category, evidence = stall.attribute(
                    interval_summary.get("wait_batch", 0.0),
                    interval_summary.get("update", 0.0),
                    retire_s=interval_summary.get("retire", 0.0))
                # Health detectors over the registry stream plus this
                # interval's host metrics, with the verdict and ledger
                # attribution captured at trip time; a fresh trip may
                # arm a profiling window, opened here (next update
                # onward profiles) unless the scheduled window is live.
                if health.active:
                    health.step(
                        {**registry.snapshot(), **host_metrics},
                        update=updates, verdict=category,
                        evidence=evidence)
                    if not profiling:
                        health.maybe_open_window(updates)
                if writer is not None:
                    writer.write(updates, host_metrics)
                    writer.write_registry(updates)
                if prom is not None:
                    prom.dump()
                log.info(
                    "update %d frames %.3g fps %.0f (actors %.0f) "
                    "loss %.3f return %s | %s | %s",
                    updates, frames, fps, actor_fps,
                    host_metrics.get("total_loss", float("nan")),
                    f"{host_metrics.get('episode_return', float('nan')):.2f}",
                    " ".join(f"{k} {v:.4f}s"
                             for k, v in timing_summary.items()),
                    StallAttributor.describe(category, evidence))
                last_log, frames_at_last_log = now, frames
            # Rollback AND preemption decisions at a point EVERY
            # process reaches on the SAME iteration, with the
            # coordinator's verdict broadcast — the divergent-local-
            # clocks discipline maybe_save applies to its save decision
            # — so the collective restore inside _rollback_or_exit (or
            # the coordinated preemption drain) is entered by all
            # processes together.  The multi-host broadcast is gated on
            # the update counter (identical on every process, unlike
            # wall clocks) every 8 updates, so the hot loop doesn't pay
            # a second per-update collective; the added detection
            # latency is dwarfed by the log-interval gate above for
            # rollback and by the grace window for preemption.  A
            # SIGTERM'd process must NOT act on its local flag alone:
            # entering the final-save collective while peers keep
            # training is exactly the unpaired-collective hang this
            # layer exists to prevent — the KV flag carries the signal
            # to the coordinator, whose broadcast verdict commits
            # everyone at once.
            do_rollback = rollback_wanted
            rollback_reason = "nonfinite"
            do_preempt = fleet.preemption_requested()
            # Param fingerprint at the decision-broadcast cadence: an
            # update-counter gate (identical on every process, unlike
            # wall clocks) so the multi-process allgather below is
            # issued on the same iteration everywhere — the gloo
            # arrival-order discipline of the broadcast it rides with.
            fingerprint = None
            if sentinel is not None and updates % 8 == 0:
                fingerprint = sentinel.local_fingerprint(state.params)
            if jax.process_count() > 1:
                do_rollback = do_preempt = False
                if updates % 8 == 0:
                    from jax.experimental import multihost_utils

                    with fleet.collective("decision_broadcast"):
                        verdict = multihost_utils.broadcast_one_to_all(
                            np.asarray([rollback_wanted,
                                        fleet.preemption_requested()]))
                        if fingerprint is not None:
                            gathered = multihost_utils.process_allgather(
                                np.asarray([fingerprint], np.float64))
                    do_rollback = bool(verdict[0])
                    do_preempt = bool(verdict[1])
                    if (fingerprint is not None
                            and sentinel.check_fingerprints(gathered)):
                        # Replicas disagree bit-exact: SDC or a
                        # divergent replica.  Every process sees the
                        # same gathered set, so every process reaches
                        # this verdict together — no extra broadcast.
                        do_rollback = True
                        rollback_reason = "sentinel"
            if sentinel is not None and sentinel.rollback_pending:
                # An audit breach survived the full degradation ladder:
                # the sentinel wants the newest verified checkpoint.
                # The audit cadence is update-counter gated, so every
                # process set this flag on the same iteration —
                # SPMD-consistent without a broadcast.
                do_rollback = True
                rollback_reason = "sentinel"
            if do_preempt:
                # Coordinated preemption drain: fall through to the
                # normal shutdown tail below — in-flight window
                # drained, ONE forced verified checkpoint (whose
                # internal broadcast/allgather every process now
                # reaches together), clean exit 0.  The fleet monitor's
                # grace deadline bounds this whole tail with exit 72.
                fleet.note_preempt_decision(updates)
                log.warning(
                    "preemption drain: stopping at update %d "
                    "(%.3g frames) for the coordinated final "
                    "checkpoint", updates, frames)
                break
            if do_rollback:
                rollback_wanted = False
                state, updates, frames = _rollback_or_exit(
                    config, ckpt, learner, state, nonfinite,
                    reason=rollback_reason,
                    exit_code=(SENTINEL_EXIT_CODE
                               if rollback_reason == "sentinel"
                               else NONFINITE_EXIT_CODE))
                # Nothing from the abandoned timeline may leak forward:
                # drop in-flight metrics (without blocking on them),
                # flush the replay slab (its trajectories are the
                # abandoned lineage's — stale-lineage samples must not
                # feed post-restore updates; the off-policy dial
                # re-warms from fresh batches), and republish the
                # restored weights.
                inflight.discard()
                metrics = {}
                if replay is not None:
                    replay.flush()
                if sentinel is not None and rollback_reason == "sentinel":
                    sentinel.note_rollback()
                pool.set_params(state.params, version=updates)
                last_log = time.monotonic()
                frames_at_last_log = frames
                interval.clear()
                continue
            if ckpt.maybe_save(updates, state):
                # The membership verdict (fleet_epoch.json) names the
                # newest resumable step — the elastic supervisor's
                # answer to "where will the resharded fleet resume".
                fleet.note_checkpoint(updates)
        # Disarm before the shutdown tail (final forced checkpoint,
        # pool joins, writer close): a slow-but-healthy shutdown must
        # not read as a stalled_thread wedge — and must never be
        # os._exit'ed mid-checkpoint under --watchdog_abort.
        watchdog.suspend("learner")
        # Drain the in-flight window so the returned metrics are the
        # NEWEST update's (the lock-step loop's contract).
        drained = inflight.drain()
        if drained is not None:
            metrics = drained
        if ckpt.maybe_save(updates, state, force=True):
            fleet.note_checkpoint(updates)
        completed = True
    finally:
        # Membership verdict FIRST: an exception unwinding a
        # multi-process run is usually a peer's death arriving as an
        # aborted collective, and jax's own client fatal (SIGABRT) can
        # end this process anywhere in the teardown below — the
        # elastic supervisor's epoch-stamped verdict must already be
        # on disk by then (fleet.note_fatal_error no-ops on clean
        # exits, single-process runs, and when the monitor's richer
        # verdict already landed).
        import sys as _sys

        _exc = _sys.exc_info()[1]
        if _exc is not None and not isinstance(
                _exc, (SystemExit, KeyboardInterrupt)):
            fleet.note_fatal_error(_exc)
        # Disarm the watchdog for the WHOLE teardown tail — the
        # exception path skips the loop-exit suspend above, and pool
        # joins/writer/ckpt closes must never be os._exit(70)'d by a
        # heartbeat that simply stopped because the run is ending.
        # (The exception dump in _teardown_observability still runs.)
        configure_watchdog(None)
        configure_faults("")  # chaos spec must not outlive its run
        if profiling:
            jax.profiler.stop_trace()
        # Health teardown: stop a still-open anomaly window's trace and
        # append the final state of open anomaly records, BEFORE the
        # obs teardown's final prom dump so health/* counters land in
        # the last snapshot.
        health.finalize()
        prefetch_stop.set()
        # Construction may have failed partway — clean up whatever
        # exists (None-guards), and always flush/close the obs state.
        if pool is not None:
            pool.stop()
        if prefetch_thread is not None:
            prefetch_thread.join(timeout=5)
        # Ledger finalize AFTER the pipeline threads stopped (no new
        # stamps) and BEFORE the obs teardown's final prom dump, so the
        # snapshot shows the swept state: in-pipeline records closed as
        # abandoned, zero open records on a clean exit, last derivation
        # published, ledger.p<proc>.json on disk.
        try:
            get_ledger().finalize()
        except Exception:
            log.exception("ledger finalize failed")
        # Final device-telemetry publish BEFORE the teardown's prom
        # dump: a run (or run tail) shorter than log_interval_s never
        # hit the interval gate, and the final metrics.prom would show
        # devtel/* absent or frozen at the last fetch.  Guarded — on
        # the exception path the device buffers may be donated husks.
        if learner is not None:
            try:
                learner.publish_device_telemetry()
            except Exception:
                log.exception("final device-telemetry publish failed")
        if sentinel is not None:
            try:
                sentinel.publish()
            except Exception:
                log.exception("final sentinel-telemetry publish failed")
        if writer is not None:
            writer.close()
        if ckpt is not None:
            ckpt.close()
        _teardown_observability(config, obs_handles)
        if completed and jax.process_count() > 1:
            # No process may exit (tearing down the coordination
            # service) until every process finished its checkpoint IO.
            # Skipped on the EXCEPTION path: a failed process must not
            # block in a barrier its healthy peers (stuck inside their
            # own collectives) can never reach — dying fast surfaces
            # the error and unblocks everyone.
            from jax.experimental import multihost_utils

            with fleet.collective("train_exit_barrier"):
                multihost_utils.sync_global_devices("train_exit")
        # Fleet teardown LAST: peer-loss detection and the preemption
        # grace deadline must cover the whole teardown tail — a peer
        # dying during the final save or exit barrier is still a
        # bounded exit 72, not a hang.
        configure_fleet(None)
    return {k: _host_scalar(v) for k, v in metrics.items()}


def build_training_learner(config: Config, agent: ImpalaAgent):
    """Validation + mesh + Learner construction shared by BOTH train
    backends (host and ingraph), so their hyperparameters and checks can
    never drift."""
    mesh_data = resolve_mesh_data(config)
    if config.batch_size % (mesh_data * config.mesh_seq):
        raise ValueError(
            f"batch_size {config.batch_size} not divisible by the "
            f"batch-sharding axes data*seq = "
            f"{mesh_data * config.mesh_seq}")
    if config.transport not in ("packed", "per_leaf"):
        raise ValueError(
            f"unknown transport {config.transport!r} (packed | per_leaf)")
    if config.actor not in ("grouped", "service"):
        raise ValueError(
            f"unknown actor {config.actor!r} (grouped | service)")
    transport = config.transport
    if (transport == "packed" and jax.process_count() > 1
            and jax.devices()[0].platform == "cpu"):
        # Multi-process CPU collectives ride gloo, which pairs ops by
        # arrival order: the packed transport's jitted unpack (prefetch
        # thread) running concurrently with the update's all-reduce
        # (main thread) mispairs them and aborts the whole fleet with a
        # gloo size-mismatch.  TPU/GPU streams serialize collectives in
        # issue order, so only the CPU test rig needs the downgrade.
        log.warning(
            "transport=packed downgraded to per_leaf: multi-process "
            "CPU (gloo) runs mispair the concurrent unpack program's "
            "ops with the update's collectives")
        transport = "per_leaf"
    if config.inflight_updates < 1:
        raise ValueError(
            f"inflight_updates must be >= 1, got "
            f"{config.inflight_updates}")
    if config.updates_per_dispatch < 1:
        raise ValueError(
            f"updates_per_dispatch must be >= 1, got "
            f"{config.updates_per_dispatch}")
    if (config.updates_per_dispatch > 1
            and config.train_backend != "ingraph"):
        raise ValueError(
            "--updates_per_dispatch is the in-graph megaloop knob "
            "(train_backend=ingraph); the host backend pipelines via "
            "--inflight_updates instead")
    if config.loss not in ("vtrace", "impact"):
        raise ValueError(
            f"unknown loss {config.loss!r} (vtrace | impact)")
    if config.replay_ratio < 0:
        raise ValueError(
            f"replay_ratio must be >= 0, got {config.replay_ratio}")
    if config.replay_ratio > 0 and config.replay_capacity < 1:
        raise ValueError(
            f"replay_capacity must be >= 1 with replay enabled, got "
            f"{config.replay_capacity}")
    if (config.replay_ratio > 0 and config.train_backend == "host"
            and transport != "packed"):
        # The host backend's replay insert IS the packed upload landing
        # in the slab (runtime/replay.py); the per-leaf path has no
        # single device buffer to tap.  This also covers the
        # multi-process-CPU gloo downgrade above.
        raise ValueError(
            "replay_ratio > 0 requires --transport=packed on the host "
            "backend (the replay slab is fed by the packed upload)")
    if config.mesh_seq > 1 and config.unroll_length % config.mesh_seq:
        raise ValueError(
            f"unroll_length {config.unroll_length} not divisible by "
            f"seq-axis size {config.mesh_seq} (time-sharded V-trace "
            f"chunks the unroll evenly)")
    devices = jax.devices()[:mesh_data * config.mesh_seq
                            * config.mesh_model]
    mesh = make_mesh(MeshSpec(data=mesh_data, seq=config.mesh_seq,
                              model=config.mesh_model),
                     devices=devices)
    hp = LearnerHyperparams(
        entropy_cost=config.entropy_cost,
        baseline_cost=config.baseline_cost,
        discounting=config.discounting,
        reward_clipping=config.reward_clipping,
        learning_rate=config.learning_rate,
        total_environment_frames=config.total_environment_frames,
        rmsprop_decay=config.rmsprop_decay,
        rmsprop_momentum=config.rmsprop_momentum,
        rmsprop_epsilon=config.rmsprop_epsilon,
    )
    # The mesh is reachable as learner.mesh; returning just the Learner
    # keeps one source of truth.
    return Learner(agent, hp, mesh, config.frames_per_update(),
                   scan_impl=config.scan_impl,
                   transport=transport,
                   learn_telemetry=config.learn_telemetry,
                   loss=config.loss,
                   target_update_interval=config.target_update_interval,
                   impact_clip_epsilon=config.impact_clip_epsilon,
                   fused_forward=config.fused_forward)


def build_replay(config: Config, learner: Learner):
    """The device replay slab for one training run (None when replay is
    off — the dial's zero position allocates nothing).  Host backend:
    the slab stores the packed transport's uploaded buffers and samples
    unpack through the transport's existing jitted unpack; the insert
    tap carries the current ledger record's birth stamp so
    ``ledger/staleness_replayed_s`` measures true frame age."""
    if config.replay_ratio <= 0:
        return None
    from scalable_agent_tpu.runtime.replay import DeviceReplayBuffer

    transport = learner._transport
    from scalable_agent_tpu.runtime.transport import PackedTransport

    if not isinstance(transport, PackedTransport):
        raise ValueError(
            "replay requires the packed transport on the host backend")
    replay = DeviceReplayBuffer(
        config.replay_capacity, seed=config.seed,
        postprocess=transport.unpack)

    def sink(device_buf):
        ledger = get_ledger()
        tid = ledger.current()
        birth = ledger.birth_us(tid) if tid is not None else None
        replay.insert(device_buf, birth_us=birth)

    transport.set_upload_sink(sink)
    return replay


def build_sentinel(config: Config, agent, learner, action_space):
    """The numerics sentinel for one training run (None when
    ``--sentinel_interval=0``, the default — nothing constructed,
    nothing jitted, no hot-path change).  Shared by both train
    backends; the rebuild closure routes every ladder rung and the
    reference arm through the SAME agent/learner factories as the
    original construction, so a demoted path is exactly the path the
    corresponding flags would have built."""
    if config.sentinel_interval <= 0:
        return None
    from scalable_agent_tpu.runtime.sentinel import NumericsSentinel

    def rebuild(cfg):
        rebuilt_agent = build_agent(cfg, action_space)
        return rebuilt_agent, build_training_learner(cfg, rebuilt_agent)

    return NumericsSentinel(config, agent, learner, rebuild)


# How many fused updates may be dispatched-but-unretired before the
# in-graph loop forces one materialization to retire them: safely under
# the ledger's 8192 open-record capacity, and high enough that the
# log-interval fetch almost always fires first.
_INGRAPH_PENDING_CAP = 2048


def train_ingraph(config: Config) -> Dict[str, float]:
    """Fused in-graph training: rollout + update as ONE jitted device
    program per dispatch (runtime/ingraph.py — K = updates_per_dispatch
    fused updates per launch), for levels whose simulator is
    expressible in XLA (envs/device/, the DEVICE_LEVELS registry).

    Checkpoint cadence, metrics names, LR schedule, and resume semantics
    match the host loop exactly — the two backends share the Learner and
    CheckpointManager — so `--train_backend=ingraph` is a drop-in flag.
    (Replaces the whole host actor pipeline the reference is built
    around, experiment.py:479-672, with zero per-step host↔device
    traffic.)
    """
    from scalable_agent_tpu.envs.device import make_device_env
    from scalable_agent_tpu.runtime import InGraphTrainer

    # This dispatch runs BEFORE jax.distributed would initialize, so
    # check the config flags too — process_count() alone is still 1
    # here even when the user asked for a distributed run, and silently
    # training P independent duplicate runs into one logdir would be
    # far worse than this error.
    if (jax.process_count() > 1 or config.distributed_coordinator
            or config.distributed_num_processes > 0):
        raise ValueError(
            "train_backend=ingraph is single-process (the host backend "
            "covers multi-host training)")
    if config.actor == "service":
        raise ValueError(
            "train_backend=ingraph has no host actor pipeline; "
            "--actor=service applies to the host backend")
    if config.replay_ratio > 0 and config.updates_per_dispatch > 1:
        raise ValueError(
            "replay_ratio > 0 requires --updates_per_dispatch=1: "
            "replayed updates interleave with fresh ones between "
            "dispatches (runtime/ingraph.py)")
    if config.sentinel_interval > 0 and config.updates_per_dispatch > 1:
        raise ValueError(
            "sentinel_interval > 0 requires --updates_per_dispatch=1: "
            "the shadow audit snapshots state at update granularity "
            "(runtime/sentinel.py)")
    config = apply_env_overrides(config)
    config.save()
    _setup_compile_cache(config)
    _arm_faults(config)  # disarmed again in the finally

    # Probe the HOST twin of the level so action/observation specs stay
    # in lock-step with the device env.  For the fake family the twin
    # is the mirrored envs/fake.py implementation; for device-native
    # levels (device_*) it is the HostDeviceEnv adapter driving the
    # same transition function, so agreement is by construction.
    observation_spec, action_space, _ = probe_env(config)
    agent = build_agent(config, action_space)
    env = make_device_env(
        config.level_name, height=config.height, width=config.width,
        # Composite spaces have no .n; make_device_env rejects their
        # levels with a clear error before num_actions matters.
        num_actions=getattr(action_space, "n", 0),
        num_action_repeats=config.num_action_repeats,
        with_instruction=config.use_instruction)
    host_frame = tuple(observation_spec.frame.shape)
    device_frame = tuple(env.observation_spec.frame.shape)
    if host_frame != device_frame:
        raise ValueError(
            f"host/device observation drift: host frame {host_frame} "
            f"!= device mirror {device_frame} (envs/fake.py and "
            f"envs/device/ must stay in lock-step)")

    learner = build_training_learner(config, agent)
    # The sentinel's shadow audit consumes the dispatch's emitted
    # trajectory, so arming it turns emission on like replay does.
    emitting = config.replay_ratio > 0 or config.sentinel_interval > 0
    trainer = InGraphTrainer(
        agent, learner, env, config.unroll_length,
        config.batch_size, seed=config.seed,
        emit_trajectory=emitting,
        updates_per_dispatch=config.updates_per_dispatch)
    sentinel = build_sentinel(config, agent, learner, action_space)
    # Device replay for the fused backend: the unroll's device-born
    # Trajectory pytree goes straight into the slab (no transport in
    # this backend, so no packed buffer to store — the per-leaf slabs
    # carry the same batch sharding the rollout constrains).
    replay = None
    if config.replay_ratio > 0:
        from scalable_agent_tpu.runtime.replay import DeviceReplayBuffer

        replay = DeviceReplayBuffer(config.replay_capacity,
                                    seed=config.seed)
    state, carry = trainer.init(jax.random.key(config.seed))

    ckpt = CheckpointManager(config.logdir, config.checkpoint_interval_s,
                             config.checkpoint_keep)
    restore_t0 = time.monotonic()
    restored = ckpt.restore(target=state)
    if restored is not None:
        start_updates, host_state = restored
        state = learner.place_state(host_state)
        # Same topology-agnostic resume contract as the host backend
        # (single-process here, so a reshard means a device-count
        # change — e.g. a debug resume of an 8-device run on 1).
        ckpt.verify_after_reshard(start_updates, state)
        log.info("restored checkpoint at update %d (%.0f frames); the "
                 "device env rollout restarts from fresh episodes (like "
                 "the host pipeline's env processes)",
                 start_updates, _host_scalar(state.env_frames))
    else:
        start_updates = 0
    restore_s = time.monotonic() - restore_t0

    timing = Timing()
    updates = start_updates
    # One dispatch = K fused updates (the megaloop): the host loop's
    # counters, ledger records, and checkpoint/preemption decisions all
    # advance at dispatch granularity.
    updates_per_dispatch = config.updates_per_dispatch
    frames_per_dispatch = (config.frames_per_update()
                           * updates_per_dispatch)
    frames = _host_scalar(state.env_frames)
    last_log = time.monotonic()
    frames_at_last_log = frames
    metrics = {}
    # Setup immediately before the try that owns teardown: nothing can
    # raise in between, so the trace file can't leak.
    obs_handles = _setup_observability(config, coordinator=True)
    registry, prom = obs_handles.registry, obs_handles.prom
    # Single-process fleet: only the preemption-grace protocol arms
    # (no peers to heartbeat) — SIGTERM drains to one final verified
    # checkpoint inside --preemption_grace_s instead of dump-and-die.
    fleet = configure_fleet(
        config.peer_timeout_s,
        preemption_grace_s=config.preemption_grace_s,
        collective_timeout_s=config.collective_timeout_s,
        registry=registry,
        recorder=get_flight_recorder(),
        epoch=config.fleet_epoch,
        logdir=config.logdir)
    # Ledger in the fused backend: there is no host pipeline to stamp,
    # but the records are no longer degenerate — each update opens a
    # record at dispatch, and the whole in-flight stream retires at the
    # NEXT log-interval metrics fetch (the loop's only real device
    # sync), so birth→retire measures the true dispatch-to-
    # materialization latency of the fused stream (the device segment
    # = the in-flight window, matching the host backend's semantics)
    # and the retire rate drives the live MFU gauge honestly.
    ledger = configure_ledger(
        registry=registry,
        # One ledger record per DISPATCH: its frame volume is the K
        # fused updates' worth, so retire-rate-derived MFU and fps stay
        # honest under the megaloop.
        frames_per_trajectory=frames_per_dispatch,
        logdir=config.logdir,
        process_index=0)
    _configure_live_mfu(
        ledger,
        lambda: trainer.train_step.lower(state, carry, np.int32(0)),
        learner.mesh.devices.size,
        updates_per_execution=updates_per_dispatch)
    profiling = False
    profile_stop_at = None
    if restored is not None:
        fleet.note_checkpoint(start_updates)
    watchdog = get_watchdog()
    nonfinite = NonFiniteTracker(config.nonfinite_tolerance,
                                 registry=registry)
    # A resumed run must not re-count the checkpoint's lifetime skips.
    nonfinite.rebase(_host_scalar(state.nonfinite_skips))
    # Run-health plane, same wiring as the host backend (no stall
    # attributor here — the fused loop has no host pipeline to time,
    # so anomaly records carry the ledger attribution only).
    health = _HealthPlane(config, backend="ingraph")
    injector = get_fault_injector()
    try:
        # Context-managed writer: the JSONL handle can't leak when the
        # loop (or checkpointing) raises.
        with MetricsWriter(config.logdir, registry=registry) as writer:
            # Updates dispatched but not yet known-materialized: their
            # ledger records retire together at the next metrics fetch.
            pending_tids: List[int] = []
            # Same compile-window discipline as the host backend: the
            # first dispatch and the post-demotion trainer re-jit run
            # with the learner heartbeat suspended.
            rejit_pending = True
            first_dispatch_t0 = None
            while frames < config.total_environment_frames:
                if (config.profile_dir and not profiling
                        and not health.window_open
                        and profile_stop_at is None
                        and updates - start_updates
                        >= config.profile_start_update):
                    # Same --profile_dir window as the host backend —
                    # the capture the kernel ledger joins below.  >=,
                    # not ==: the megaloop advances ``updates`` in
                    # strides of K, which need not land exactly on
                    # profile_start_update (the one-shot gate is the
                    # still-None profile_stop_at).
                    jax.profiler.start_trace(config.profile_dir)
                    get_tracer().set_annotate(True)
                    profiling = True
                    profile_stop_at = updates + config.profile_num_updates
                ledger_tid = ledger.open("ingraph",
                                         config.level_name)
                if rejit_pending:
                    watchdog.suspend("learner")
                    rejit_pending = False
                    if updates == start_updates:
                        first_dispatch_t0 = time.monotonic()
                with timing.time_avg("update"), \
                        get_tracer().span("learner/train_step",
                                          cat="learner"):
                    # The update counter keys the rollout rng
                    # (jax.random.fold_in), so resume continues the exact
                    # action-sampling stream the interrupted run would
                    # have used.
                    if sentinel is not None and sentinel.audit_due(
                            updates):
                        # Pre-update snapshot for the shadow audit
                        # below — train_step donates (state, carry),
                        # so the audit needs its own buffers.
                        audit_snap = sentinel.snapshot(state)
                    else:
                        audit_snap = None
                    if not emitting:
                        state, carry, metrics = trainer.train_step(
                            state, carry, np.int32(updates))
                    else:
                        state, carry, metrics, fresh_traj = (
                            trainer.train_step(state, carry,
                                               np.int32(updates)))
                ledger.stamp(ledger_tid, "dispatch")
                pending_tids.append(ledger_tid)
                if first_dispatch_t0 is not None:
                    # Startup-cost beacon for the supervisor's MTTR
                    # decomposition (the first dispatch blocks through
                    # the megaloop's compile).
                    _write_mttr_breakdown(config, restore_s,
                                          time.monotonic()
                                          - first_dispatch_t0)
                    first_dispatch_t0 = None
                # Chaos: the same deterministic mid-run slowdown as the
                # host backend (occurrences count dispatches), timed as
                # update work so the interval's fps sag is attributable.
                if injector.active and injector.should_fire(
                        "throughput_sag"):
                    with timing.time_avg("update"):
                        time.sleep(throughput_sag_s())
                if audit_snap is not None:
                    # Shadow audit on the dispatch's emitted trajectory
                    # (same batch the fused update trained on), before
                    # any replay updates move the params.  The
                    # reference arm's own compile (first audit) is
                    # recovery machinery — heartbeat suspended, same as
                    # rollback restore; the touch below re-arms.
                    watchdog.suspend("learner")
                    with timing.time_avg("audit"):
                        state = sentinel.audit(audit_snap, fresh_traj,
                                               state, updates)
                    audit_snap = None
                    if sentinel.consume_swap():
                        # Adopt the demoted learner: rebuild the fused
                        # trainer around it (one re-jit at the next
                        # dispatch).  The rollout carry is env-side
                        # state and rides through unchanged — the
                        # rollout rng is keyed by the update counter,
                        # so the action stream stays continuous.  The
                        # replay slab's lineage is suspect; drop it.
                        # (Device telemetry rides the trainer CARRY in
                        # this backend and survives the swap as-is.)
                        learner = sentinel.learner
                        agent = sentinel.agent
                        trainer = InGraphTrainer(
                            agent, learner, env, config.unroll_length,
                            config.batch_size, seed=config.seed,
                            emit_trajectory=emitting,
                            updates_per_dispatch=updates_per_dispatch)
                        if replay is not None:
                            replay.flush()
                        # The rebuilt trainer re-jits at the next
                        # dispatch (~13s measured) — suspend across it.
                        rejit_pending = True
                    watchdog.touch("learner")
                if sentinel is not None and sentinel.rollback_pending:
                    # A breach survived the full degradation ladder:
                    # roll back to the newest verified checkpoint (or
                    # exit 73).  Single-process backend — no broadcast
                    # needed before acting.
                    state, updates, frames = _rollback_or_exit(
                        config, ckpt, learner, state, nonfinite,
                        reason="sentinel",
                        exit_code=SENTINEL_EXIT_CODE)
                    sentinel.note_rollback()
                    if replay is not None:
                        replay.flush()
                    if carry.streak_peak is not None:
                        carry = carry._replace(
                            streak_peak=jnp.zeros((), jnp.float32))
                    last_log = time.monotonic()
                    frames_at_last_log = frames
                    continue
                if replay is not None:
                    # Same off-policy dial as the host backend: the
                    # fresh unroll lands in the slab, then R replayed
                    # updates (env_frames held, no provenance record —
                    # only their age is observed) chase it.  The
                    # replayed dict carries loss keys only — the FRESH
                    # step's metrics keep the log line's episode stats,
                    # with the loss readings taken from the last
                    # replayed update (the freshest param state).
                    replay.insert(fresh_traj)
                    for _ in range(config.replay_ratio):
                        with timing.time_avg("update"), \
                                get_tracer().span(
                                    "learner/replay_update",
                                    cat="learner"):
                            rtraj = replay.sample()
                            state, tel, replay_metrics = (
                                trainer.replay_step(
                                    state, carry.telemetry, rtraj))
                            carry = carry._replace(telemetry=tel)
                            metrics = dict(metrics, **replay_metrics)
                        updates += 1
                # Bound the open-record stream: a fused run fast enough
                # to dispatch thousands of updates inside one log
                # interval would overflow the ledger's open-record
                # table (8192) and trip its eviction/truncation path.
                # One explicit materialization per _INGRAPH_PENDING_CAP
                # updates retires the whole window honestly (the device
                # stream is in-order) — in the common case the
                # log-interval fetch below fires first and this never
                # runs.
                if len(pending_tids) >= _INGRAPH_PENDING_CAP:
                    jax.block_until_ready(metrics["total_loss"])
                    for tid in pending_tids:
                        ledger.close(tid, retired=True)
                    pending_tids.clear()
                watchdog.touch("learner")
                updates += updates_per_dispatch
                frames += frames_per_dispatch
                if profiling and updates >= profile_stop_at:
                    jax.block_until_ready(metrics["total_loss"])
                    # The sync above materialized every pending
                    # dispatch; retire them NOW, before the harvest's
                    # multi-minute AOT compile below would inflate
                    # their birth→retire stamps (and the staleness
                    # histogram) by compile time the updates never saw.
                    for tid in pending_tids:
                        ledger.close(tid, retired=True)
                    pending_tids.clear()
                    jax.profiler.stop_trace()
                    get_tracer().set_annotate(False)
                    profiling = False
                    log.info("profiler trace written to %s",
                             config.profile_dir)
                    # Disarm the heartbeat across the harvest's AOT
                    # compile (multi-minute on TPU) — the loop's touch
                    # below re-arms.
                    watchdog.suspend("learner")
                    # ``executions`` is the UPDATE count in the trace
                    # window: XLA's cost analysis counts a lax.scan
                    # body once regardless of trip count (verified:
                    # K=8 lowers to ~the K=1 flops), so flops_total ≈
                    # one update's flops — and the window runs whole
                    # dispatches, ceil(profile_num_updates / K) of
                    # them, each K updates' device time.
                    profiled_dispatches = -(-config.profile_num_updates
                                            // updates_per_dispatch)
                    health.note_baseline(_harvest_kernel_ledger(
                        config,
                        lambda: trainer.train_step.lower(
                            state, carry, np.int32(0)),
                        executions=(profiled_dispatches
                                    * updates_per_dispatch)))
                if (health.window_open
                        and updates >= health.window_stop_at):
                    # Anomaly-triggered window: same sync + retire +
                    # heartbeat discipline as the scheduled stop above.
                    jax.block_until_ready(metrics["total_loss"])
                    for tid in pending_tids:
                        ledger.close(tid, retired=True)
                    pending_tids.clear()
                    watchdog.suspend("learner")
                    window_dispatches = -(-config.health_window_updates
                                          // updates_per_dispatch)
                    health.close_window(
                        lambda: trainer.train_step.lower(
                            state, carry, np.int32(0)),
                        executions=(window_dispatches
                                    * updates_per_dispatch))
                now = time.monotonic()
                if now - last_log >= config.log_interval_s:
                    host_metrics = _finalize_ingraph_metrics(
                        metrics, config)
                    # The fetch above materialized the newest update;
                    # the device stream is in-order, so every pending
                    # dispatch has retired by now.
                    for tid in pending_tids:
                        ledger.close(tid, retired=True)
                    pending_tids.clear()
                    # Device telemetry (env episodes + learner update
                    # instruments riding the donated carry): the one
                    # obs fetch, folded into the registry for the prom
                    # dump below.
                    trainer.publish_telemetry(carry)
                    if sentinel is not None:
                        sentinel.publish()
                    ledger.publish()
                    if nonfinite.observe(host_metrics):
                        state, updates, frames = _rollback_or_exit(
                            config, ckpt, learner, state, nonfinite)
                        # The rollout carry is env-side state, not
                        # params — it rides through the rollback like
                        # the host backend's env processes do.  The
                        # in-graph streak peak and the replay slab are
                        # the abandoned timeline's: reset both so
                        # neither a stale peak nor stale-lineage
                        # samples leak past the restore.
                        if replay is not None:
                            replay.flush()
                        if carry.streak_peak is not None:
                            carry = carry._replace(
                                streak_peak=jnp.zeros((), jnp.float32))
                        last_log = time.monotonic()
                        frames_at_last_log = frames
                        continue
                    fps = (frames - frames_at_last_log) / (now - last_log)
                    host_metrics["fps"] = fps
                    registry.gauge(
                        "learner/fps",
                        "env frames consumed per second").set(fps)
                    timing_summary = timing.summary()
                    host_metrics.update({f"timing/{k}": v
                                         for k, v in timing_summary.items()})
                    # Run-health step rides the same cadence; no stall
                    # attributor in the fused loop, so records carry
                    # ledger attribution only (verdict=None).
                    if health.active:
                        health.step(
                            {**registry.snapshot(), **host_metrics},
                            update=updates)
                        if not profiling:
                            health.maybe_open_window(updates)
                    writer.write(updates, host_metrics)
                    # Registry snapshot rows (obs/ prefix): the per-
                    # interval devtel/learn/* series obs.report's
                    # staleness↔clipping join and obs.diagnose read —
                    # the host backend has always written these.
                    writer.write_registry(updates)
                    if prom is not None:
                        prom.dump()
                    log.info(
                        "update %d frames %.3g fps %.0f loss %.3f return "
                        "%s | %s",
                        updates, frames, fps,
                        host_metrics.get("total_loss", float("nan")),
                        f"{host_metrics.get('episode_return', float('nan')):.2f}",
                        " ".join(f"{k} {v:.4f}s"
                                 for k, v in timing_summary.items()))
                    last_log, frames_at_last_log = now, frames
                if sentinel is not None and updates % 8 == 0:
                    # Param fingerprint at the host backend's broadcast
                    # cadence.  Single-process, so there is no peer to
                    # compare against — the gauge (and the
                    # replica_diverge chaos point's occurrence
                    # counting) still ride it, and a postmortem can
                    # diff two runs' series.
                    sentinel.local_fingerprint(state.params)
                if fleet.preemption_requested():
                    # Same per-iteration decision point as the host
                    # backend (single-process, so no broadcast): fall
                    # through to the forced final save below and exit
                    # cleanly inside the grace window.
                    fleet.note_preempt_decision(updates)
                    log.warning(
                        "preemption drain: stopping at update %d "
                        "(%.3g frames) for the final checkpoint",
                        updates, frames)
                    break
                if ckpt.maybe_save(updates, state):
                    fleet.note_checkpoint(updates)
            # Same shutdown-tail disarm as the host backend: the final
            # forced save must not trip (or be aborted by) the watchdog.
            watchdog.suspend("learner")
            if pending_tids and metrics:
                # Clean-exit drain: one final materialization retires
                # every still-pending record (otherwise finalize()
                # would sweep real retires as "abandoned").
                _finalize_ingraph_metrics(metrics, config)
                for tid in pending_tids:
                    ledger.close(tid, retired=True)
                pending_tids.clear()
            if ckpt.maybe_save(updates, state, force=True):
                fleet.note_checkpoint(updates)
    finally:
        # Same verdict-first contract as train(): the membership
        # verdict must beat any teardown abort (no-op single-process).
        import sys as _sys

        _exc = _sys.exc_info()[1]
        if _exc is not None and not isinstance(
                _exc, (SystemExit, KeyboardInterrupt)):
            fleet.note_fatal_error(_exc)
        configure_watchdog(None)  # same teardown-tail disarm as train()
        configure_faults("")
        if profiling:
            jax.profiler.stop_trace()
        health.finalize()
        try:
            get_ledger().finalize()
        except Exception:
            log.exception("ledger finalize failed")
        # Final telemetry publish BEFORE the teardown's prom dump — on
        # BOTH exit paths: a run (or run tail) shorter than
        # log_interval_s never hit the interval gate, and a crash's
        # final metrics.prom would show devtel/* absent or frozen at
        # the last fetch while host counters show the true totals.
        # Guarded — an exception mid-train_step leaves ``carry``
        # holding donated husks.
        try:
            trainer.publish_telemetry(carry)
        except Exception:
            log.exception("final device-telemetry publish failed")
        if sentinel is not None:
            try:
                sentinel.publish()
            except Exception:
                log.exception("final sentinel-telemetry publish failed")
        ckpt.close()
        _teardown_observability(config, obs_handles)
        configure_fleet(None)  # after obs: covers the whole tail
    return _finalize_ingraph_metrics(metrics, config)


def _finalize_ingraph_metrics(metrics, config: Config) -> Dict[str, float]:
    """Device metrics -> host dict with the episode-stat contract the
    host backend keeps: per-unroll episode means appear only when
    episodes actually finished, and frames are simulator frames
    (agent steps x num_action_repeats).  Applied to BOTH the logged
    rows and train_ingraph's return value so they can never disagree."""
    host_metrics = {k: _host_scalar(v) for k, v in metrics.items()}
    if host_metrics.pop("episodes_completed", 0) < 1:
        host_metrics.pop("episode_return", None)
        host_metrics.pop("episode_frames", None)
    elif "episode_frames" in host_metrics:
        host_metrics["episode_frames"] *= config.num_action_repeats
    return host_metrics


def _eval_loop(envs, config: Config, agent: ImpalaAgent, params, step_fn,
               num_episodes: int) -> List[float]:
    """Drive any MultiEnv-protocol fleet (initial/step_send/step_recv)
    under one jitted [B] inference call until ``num_episodes`` episodes
    complete.

    Fixed per-slot episode quota: taking the global first-N completions
    would overrepresent short episodes (fast finishers complete more
    often), biasing mean returns vs the reference's one-env sequential
    protocol.  Each slot contributes at most ceil(N / B) episodes."""
    batch = envs.num_envs
    quota = -(-num_episodes // batch)
    counts = np.zeros((batch,), np.int64)
    returns: List[float] = []
    try:
        output = envs.initial()
        core_state = initial_state(batch, agent.core_size)
        action = np.asarray(agent.zero_actions(batch))
        rng = jax.random.key(config.seed)
        step_index = 0
        while len(returns) < num_episodes:
            step_index += 1
            agent_out, core_state = step_fn(
                params, jax.random.fold_in(rng, step_index), action,
                output, core_state)
            action = np.asarray(agent_out.action)
            envs.step_send(action)
            output = envs.step_recv()
            for i in np.nonzero(np.asarray(output.done))[0]:
                if (int(output.info.episode_step[i]) > 0
                        and counts[i] < quota):
                    counts[i] += 1
                    returns.append(float(output.info.episode_return[i]))
    finally:
        envs.close()
    return returns[:num_episodes]


def _eval_level(config: Config, agent: ImpalaAgent, params, step_fn,
                level_name: str, frame_spec: TensorSpec,
                num_episodes: int) -> List[float]:
    """Collect ``num_episodes`` returns with a BATCHED eval fleet: a
    MultiEnv of ``test_batch_size`` envs stepped under one jitted [B]
    inference call (the reference evaluates batch-1 synchronously,
    experiment.py:691-701 — this is the same protocol at fleet width)."""
    batch = max(1, min(num_episodes, config.test_batch_size))
    fns = [
        functools.partial(
            make_impala_stream, level_name,
            seed=config.seed * 977 + 131 * i,
            num_action_repeats=config.num_action_repeats,
            # One directory per (level, env slot): parallel recorders
            # must never interleave episode indices in one dir.
            record_to=(os.path.join(config.record_to, level_name,
                                    f"env_{i:02d}")
                       if config.record_to else ""),
            **env_kwargs(config, level_name))
        for i in range(batch)
    ]
    envs = MultiEnv(fns, frame_spec,
                    num_workers=min(batch, config.test_num_workers))
    return _eval_loop(envs, config, agent, params, step_fn, num_episodes)


def _eval_multi_agent(config: Config, agent: ImpalaAgent, params, step_fn,
                      num_agents: int, num_episodes: int) -> List[float]:
    """Self-play eval for lockstep multi-agent levels: K matches of A
    agents, every slot driven by the SAME policy under one jitted [K*A]
    call; per-slot episode returns pool into the result (the reference
    has no multi-agent eval at all — this goes beyond parity).
    """
    from scalable_agent_tpu.envs.doom.multiplayer import (
        DEFAULT_UDP_PORT,
        MultiAgentVectorEnv,
    )

    matches = max(1, config.test_batch_size // num_agents)
    if matches * num_agents != config.test_batch_size:
        # Eval batch is throughput sizing, not a correctness property
        # (unlike the training batch, where make_env_groups raises) —
        # round down to whole matches, loudly.
        log.info(
            "test_batch_size %d is not a multiple of num_agents %d; "
            "evaluating %d matches (%d agent slots)",
            config.test_batch_size, num_agents, matches,
            matches * num_agents)
    # Globally-unique port residue classes across a multi-process job
    # (same invariant make_env_groups enforces for training), and eval
    # seeds DECORRELATED from training's seed formula (977/131 mixing,
    # like _eval_level) so eval matches never replay trained env seeds.
    proc = jax.process_index()
    total = matches * jax.process_count()
    stride = match_port_scheme(total)
    envs = MultiAgentVectorEnv([
        functools.partial(
            create_env, config.level_name,
            num_action_repeats=config.num_action_repeats,
            seed=config.seed * 977 + 131 * (proc * matches + m),
            port_base=DEFAULT_UDP_PORT + stride * (proc * matches + m),
            port_increment=stride * total,
            # One directory per (level, match); the multiplayer factory
            # adds per-player subdirs beneath it, so parallel matches
            # and players never interleave episode streams (role of
            # the reference's record path, env_wrappers.py:433-497).
            record_to=(os.path.join(
                config.record_to, config.level_name,
                f"match_{proc * matches + m:02d}")
                if config.record_to else None),
            **env_kwargs(config))
        for m in range(matches)
    ])
    return _eval_loop(envs, config, agent, params, step_fn, num_episodes)


def test(config: Config) -> Dict[str, List[float]]:
    """Evaluate a checkpoint: test_num_episodes per level, batched.

    ``--level_name=dmlab30`` evaluates the FULL suite (every DMLab-30
    test variant) and emits capped/uncapped human-normalized suite
    scores to the log and ``<logdir>/eval_scores.json``
    (reference: experiment.py:675-708 + :716-717).
    """
    config = apply_env_overrides(config)
    # The network architecture is a property of the CHECKPOINT, not of
    # the eval-time level: adopt the trained run's architecture fields
    # from its persisted config so e.g. a no-instruction checkpoint
    # evaluates under --level_name=dmlab30 (whose env override would
    # otherwise grow an instruction tower the restore can't match).
    # ONLY param-tree-shaping fields are adopted — execution knobs
    # (core_impl/dtypes) restore fine either way and must stay CLI-
    # controllable, e.g. evaluating a pallas-trained checkpoint with
    # --core_impl=xla on a CPU-only host.
    saved_path = os.path.join(config.logdir, "config.json")
    if os.path.exists(saved_path):
        saved = Config.load(saved_path)
        config = dataclasses.replace(
            config, torso_type=saved.torso_type,
            use_instruction=saved.use_instruction,
            # The loss shapes the TrainState (--loss=impact carries a
            # target network): the restore TEMPLATE must match the
            # checkpoint's generation so the structure retry in
            # runtime/checkpoint.py stays the exception, not the rule.
            loss=saved.loss)
    suite = config.level_name == "dmlab30"
    level_names = ([f"dmlab_{name}" for name in dmlab30.TEST_LEVELS]
                   if suite else [config.level_name])

    probe_config = (dataclasses.replace(config, level_name=level_names[0])
                    if suite else config)
    observation_spec, action_space, num_agents = probe_env(probe_config)
    agent = build_agent(config, action_space)

    # Restore against a structure template so optimizer-state NamedTuples
    # come back typed (only params are used here, but the checkpoint holds
    # the full TrainState).
    mesh = make_mesh(MeshSpec(data=len(jax.devices()), model=1))
    hp = LearnerHyperparams()
    learner = Learner(agent, hp, mesh, config.frames_per_update())
    template = learner.init(
        jax.random.key(0),
        zero_trajectory(probe_config, observation_spec, agent))
    ckpt = CheckpointManager(config.logdir)
    restored = ckpt.restore(target=template)
    if restored is None:
        raise FileNotFoundError(
            f"no checkpoint under {config.logdir}/checkpoints")
    _, host_state = restored
    params = jax.device_put(host_state.params)

    step_fn = jax.jit(
        lambda params, rng, action, env_output, state: actor_step(
            agent, params, rng, action, env_output, state))

    level_returns: Dict[str, List[float]] = {}
    if num_agents > 1:
        # Self-play multi-agent eval (suite levels are never
        # multi-agent, so this is always the single-level path).
        returns = _eval_multi_agent(
            config, agent, params, step_fn, num_agents,
            config.test_num_episodes)
        level_returns[config.level_name] = returns
        log.info("multi-agent level %s: mean self-play return %.2f "
                 "over %d agent-episodes",
                 config.level_name, float(np.mean(returns)),
                 len(returns))
        return level_returns
    for level_name in level_names:
        returns = _eval_level(
            config, agent, params, step_fn, level_name,
            observation_spec.frame, config.test_num_episodes)
        level_returns[level_name] = returns
        log.info("level %s: mean return %.2f over %d episodes",
                 level_name, float(np.mean(returns)), len(returns))

    if suite:
        # Scoring keys are bare test-level names (reference:
        # dmlab30.py:186-218).
        by_level = {name[len("dmlab_"):]: r
                    for name, r in level_returns.items()}
        no_cap = dmlab30.compute_human_normalized_score(
            by_level, per_level_cap=None)
        cap_100 = dmlab30.compute_human_normalized_score(
            by_level, per_level_cap=100.0)
        log.info("suite score — no cap: %.2f  cap 100: %.2f",
                 no_cap, cap_100)
        scores_path = os.path.join(config.logdir, "eval_scores.json")
        os.makedirs(config.logdir, exist_ok=True)
        with open(scores_path, "w") as f:
            json.dump({
                "human_normalized_no_cap": no_cap,
                "human_normalized_cap_100": cap_100,
                "episodes_per_level": config.test_num_episodes,
                "mean_returns": {k: float(np.mean(v))
                                 for k, v in by_level.items()},
            }, f, indent=2)
        log.info("suite scores written to %s", scores_path)
    else:
        # Single-level runs can't produce the full-suite score; log the
        # per-level normalized value (reference computes the suite mean,
        # experiment.py:703-708).  Registry names carry the dmlab_
        # prefix; the score tables hold bare level names.
        bare = (config.level_name[len("dmlab_"):]
                if config.level_name.startswith("dmlab_")
                else config.level_name)
        if bare in dmlab30.ALL_LEVELS:
            returns = level_returns[config.level_name]
            record = dmlab30.LEVELS.get(
                bare, dmlab30._BY_TEST_NAME.get(bare))
            if record:
                normalized = (np.mean(returns) - record.random) / (
                    record.human - record.random) * 100.0
                log.info("human-normalized: %.2f%%", normalized)
    return level_returns


def main(argv: Optional[Sequence[str]] = None):
    # Some interpreters pin jax to a platform via sitecustomize's
    # jax.config, which silently overrides the standard JAX_PLATFORMS
    # env var; restore the env var's contract for the CLI (a user
    # setting JAX_PLATFORMS=cpu must get CPU, not a hung remote claim).
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    config = Config.from_argv(argv, description=__doc__)
    if config.mode == "train":
        if config.elastic:
            # Elastic supervisor mode (runtime/elastic.py): this
            # process owns N worker fleets across membership epochs
            # instead of training itself — it must never initialize a
            # jax backend (on TPU that would lock the chips its
            # workers need).
            from scalable_agent_tpu.runtime.elastic import (
                run_supervised,
            )

            code = run_supervised(config)
            if code:
                raise SystemExit(code)
            return
        train(config)
    elif config.mode == "test":
        test(config)
    else:
        raise ValueError(f"unknown mode {config.mode!r}")


if __name__ == "__main__":
    main()
