"""Device mesh construction and sharding rules.

The reference has NO collectives — its "distribution" is a TF1 gRPC
parameter-server pattern with a single learner (reference:
experiment.py:506-512; SURVEY §2.5).  The TPU-native framework replaces
that with an SPMD mesh:

- axis ``data``: learner data parallelism.  Trajectory batches are sharded
  over it; gradients are all-reduced over ICI by XLA (the jit partitioner
  inserts the psum — we only annotate shardings).
- axis ``model``: tensor parallelism for the network.  Degenerate (=1) for
  the IMPALA-size net but wired through from day one so larger torsos can
  shard without interface changes.

Multi-host: the same mesh spans hosts via ``jax.distributed.initialize``;
data-parallel gradient traffic then rides ICI within a slice and DCN
across slices, chosen by XLA from the device topology.
"""

import math
from typing import NamedTuple, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


class MeshSpec(NamedTuple):
    """Logical mesh shape: data x seq x model.

    ``seq`` is the sequence/context-parallel axis (SURVEY §5.7): batches
    shard over (data x seq) for the model compute, and the V-trace
    recurrence's TIME dimension shards over ``seq``
    (parallel/sequence.py) when the Learner runs
    ``scan_impl="time_sharded"``.  Degenerate (=1) everywhere else."""

    data: int
    seq: int = 1
    model: int = 1


def auto_data_axis(batch_size: int, num_devices: int,
                   seq: int = 1, model: int = 1) -> int:
    """The largest data-axis size a single-process mesh can take: the
    batch shards over (data x seq), so ``data * seq`` must divide the
    batch, out of the devices left after seq/model take theirs (a
    4-batch debug run on an 8-device host uses 4 devices rather than
    failing).  Pure math, shared by the driver's mesh sizing and every
    "auto" kernel-choice estimate — and the reason an ELASTIC restart
    at a different device count resizes its mesh without operator
    input: the same batch re-shards over whatever devices the new
    membership epoch has (tests/test_elastic.py pins the adaptation
    table)."""
    non_data = seq * model
    return math.gcd(
        max(1, batch_size // seq),
        max(1, num_devices // non_data))


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a 3-axis ('data', 'seq', 'model') mesh over ``devices``.

    Defaults: all devices on the data axis, seq=model=1.
    """
    devices = list(devices if devices is not None else jax.devices())
    if spec is None:
        spec = MeshSpec(data=len(devices))
    if spec.data * spec.seq * spec.model != len(devices):
        raise ValueError(
            f"mesh {spec} needs {spec.data * spec.seq * spec.model} "
            f"devices, got {len(devices)}")
    array = np.asarray(devices).reshape(spec.data, spec.seq, spec.model)
    return Mesh(array, axis_names=("data", "seq", "model"))


def batch_sharding(mesh: Mesh, batch_axis_index: int = 1) -> NamedSharding:
    """Shard the batch dimension over the (data, seq) axes.

    Trajectories are time-major [T, B, ...]; B is ``batch_axis_index`` 1.
    The seq axis joins the batch sharding so its devices carry real
    model compute too — time-resharding happens only around the V-trace
    recurrence (parallel/sequence.py).
    """
    pspec = [None] * (batch_axis_index + 1)
    pspec[batch_axis_index] = (("data", "seq")
                               if "seq" in mesh.shape else "data")
    return NamedSharding(mesh, PartitionSpec(*pspec))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (params, optimizer state, scalars)."""
    return NamedSharding(mesh, PartitionSpec())


def model_parallel_shardings(mesh: Mesh, tree):
    """Tensor-parallel shardings for a params-shaped pytree.

    Output-channel partitioning: every rank>=2 leaf whose LAST axis
    divides the ``model`` axis size shards that axis over ``model``
    (conv kernels [kh, kw, cin, cout] and dense/LSTM kernels [in, out]
    split their output features; XLA inserts the all-gathers/psums the
    dataflow needs).  Biases, scalars, and indivisible leaves (e.g. a
    9-logit head on model=2) replicate.  With model=1 every leaf
    replicates, so this is always safe to use.

    Works for optimizer state too: rmsprop/momentum accumulators are
    params-shaped, so the same rule aligns them with their params.
    """
    model_size = mesh.shape["model"]

    def shard(leaf):
        shape = getattr(leaf, "shape", ())
        if (model_size > 1 and len(shape) >= 2
                and shape[-1] % model_size == 0):
            spec = [None] * (len(shape) - 1) + ["model"]
            return NamedSharding(mesh, PartitionSpec(*spec))
        return NamedSharding(mesh, PartitionSpec())

    return jax.tree_util.tree_map(shard, tree)


def fused_kernels_profitable(mesh: Optional[Mesh] = None,
                             num_devices: Optional[int] = None) -> bool:
    """THE policy behind the ``"auto"`` LSTM-core choice (Config/driver
    core_impl, bench): the fused Pallas LSTM core (ops/lstm_pallas.py,
    1.6-2.2x over nn.scan on-chip — BENCH_NOTES r4) wins only on a
    single-device TPU mesh — ``pallas_call`` has no SPMD partitioning
    rule, so a multi-device mesh would replicate the call (correct but
    wasteful), and non-TPU backends only have the interpreter.  (The
    V-trace scan_impl="auto" no longer consults this: at production
    shapes both V-trace impls are ~2-5 us, and the associative scan is
    the shardable one, so auto always picks it.)

    Pass the actual ``mesh`` when one exists; ``num_devices`` when only
    the intended mesh size is known (e.g. from Config before the mesh is
    built); neither to ask about the whole process.
    """
    if jax.default_backend() != "tpu":
        return False
    if mesh is not None and getattr(mesh, "devices", None) is not None:
        return mesh.devices.size == 1
    if num_devices is None:
        num_devices = len(jax.devices())
    return num_devices == 1
