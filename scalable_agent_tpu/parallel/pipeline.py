"""GPipe-style pipeline parallelism over a mesh axis (prototype).

Design rationale and scoping: docs/pipeline_parallelism.md (SURVEY
§2.5 scopes PP to a design note — the reference has none, and the
IMPALA-size net never needs it; this module makes the design concrete
and testable rather than prose).

The scheme is the classic synchronous GPipe schedule expressed as pure
SPMD — no runtime, no scheduler threads, no new concepts beyond what
the rest of `parallel/` already uses:

- every device holds ONE stage's params (leading-axis sharding over the
  pipeline axis);
- a `lax.scan` over S + M - 1 ticks drives all stages every tick;
  stage-boundary activations hop to the next device with ONE
  `lax.ppermute` (a neighbor transfer — the cheapest ICI collective);
- stage s computes microbatch m at tick t = s + m; ticks outside that
  window are pipeline bubble (the compute runs on stale data and is
  masked out at collection), giving the textbook M/(M+S-1) utilization;
- the backward pass is `jax.grad` through the program: XLA
  differentiates `ppermute` into the inverse permutation, yielding the
  reverse pipeline schedule automatically.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec


def gpipe_spmd(mesh, stage_fn, stage_params, microbatches,
               axis: str = "stage"):
    """Run ``x -> stage_fn(p_{S-1}, ... stage_fn(p_0, x))`` as a
    microbatched pipeline over ``mesh[axis]``.

    stage_fn: (params_one_stage, x [mb, ...]) -> y [mb, ...] — stages
      must be shape-preserving (equal boundary widths), the usual GPipe
      contract.
    stage_params: pytree whose leaves carry a leading [S] stage axis.
    microbatches: [M, mb, ...] array, replicated.

    Returns [M, mb, ...]: the last stage's outputs per microbatch,
    replicated over the mesh.  Differentiable in ``stage_params`` and
    ``microbatches``.
    """
    from scalable_agent_tpu.parallel._compat import mark_varying, shard_map

    num_stages = mesh.shape[axis]
    num_micro = microbatches.shape[0]
    for path, leaf in jax.tree_util.tree_flatten_with_path(
            stage_params)[0]:
        if leaf.ndim == 0 or leaf.shape[0] != num_stages:
            raise ValueError(
                f"stage_params leaf {jax.tree_util.keystr(path)} has "
                f"shape {getattr(leaf, 'shape', ())} but every leaf "
                f"needs a leading (stage) dim of {num_stages} (one "
                f"stage per device on mesh axis {axis!r}, exactly)")

    def spmd(params_local, xs):
        # params_local leaves arrive as [1, ...] (their stage's slice).
        params_one = jax.tree_util.tree_map(lambda p: p[0], params_local)
        stage = lax.axis_index(axis)
        perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

        def tick(carry, t):
            # ``carry`` is the activation handed over by the previous
            # stage at the previous tick; stage 0 instead injects
            # microbatch t (clipped — out-of-window ticks are bubble).
            inbound = carry
            m = jnp.clip(t, 0, num_micro - 1)
            x = jnp.where(stage == 0, xs[m], inbound)
            y = stage_fn(params_one, x)
            handoff = lax.ppermute(y, axis, perm)
            return handoff, y

        # The carry must be typed as device-varying over the pipeline
        # axis (ppermute's output is), or the scan carry types mismatch.
        zero = mark_varying(jnp.zeros_like(xs[0]), axis)
        _, ys = lax.scan(tick, zero, jnp.arange(num_stages + num_micro - 1))

        # The last stage emits microbatch m at tick t = (S-1) + m; mask
        # everything else and psum-broadcast so the result is replicated
        # (every other stage contributes zeros).
        ticks = num_stages - 1 + jnp.arange(num_micro)
        outs = ys[ticks]  # [M, mb, ...] (only valid on the last stage)
        # SELECT rather than multiply-by-mask: bubble-tick activations
        # may be non-finite for some stage_fns, and 0 * inf would
        # poison the psum with NaN.
        contribution = jnp.where(stage == num_stages - 1, outs,
                                 jnp.zeros_like(outs))
        return lax.psum(contribution, axis)

    stage_sharded = jax.tree_util.tree_map(
        lambda p: PartitionSpec(axis, *([None] * (p.ndim - 1))),
        stage_params)
    fn = shard_map(
        spmd, mesh=mesh,
        in_specs=(stage_sharded, PartitionSpec()),
        out_specs=PartitionSpec(),
    )
    constrained = jax.tree_util.tree_map(
        lambda p, s: lax.with_sharding_constraint(
            p, NamedSharding(mesh, s)),
        stage_params, stage_sharded)
    return fn(constrained, microbatches)


def sequential_reference(stage_fn, stage_params, microbatches):
    """The pipeline's ground truth: compose all S stages sequentially
    per microbatch (what gpipe_spmd must reproduce exactly)."""
    num_stages = jax.tree_util.tree_leaves(stage_params)[0].shape[0]

    def apply_all(x):
        for s in range(num_stages):
            params_s = jax.tree_util.tree_map(
                lambda p, s=s: p[s], stage_params)
            x = stage_fn(params_s, x)
        return x

    return jax.vmap(apply_all)(microbatches)


def pipeline_utilization(num_stages: int, num_micro: int) -> float:
    """The GPipe bubble bound: fraction of device-ticks doing real
    work, M / (M + S - 1)."""
    return num_micro / (num_micro + num_stages - 1)
