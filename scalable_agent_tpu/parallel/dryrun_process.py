"""Runnable multi-process dry run: 2+ CPU processes, one SPMD learner.

Each process (launched with identical commands, differing only in
--process_id / JAX_PROCESS_ID) contributes its local half of every
batch; the update runs over a mesh spanning both processes' virtual CPU
devices, exercising the exact multi-host path of driver.train —
jax.distributed init, global mesh, make_array_from_process_local_data
batch assembly, collective update, replicated metric readback.

Usage (what __graft_entry__.dryrun_multiprocess and
tests/test_distributed.py run):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \
    python -m scalable_agent_tpu.parallel.dryrun_process \
        --coordinator=localhost:PORT --num_processes=2 --process_id=I
"""

import argparse


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--coordinator", required=True)
    parser.add_argument("--num_processes", type=int, required=True)
    parser.add_argument("--process_id", type=int, required=True)
    parser.add_argument("--updates", type=int, default=2)
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from scalable_agent_tpu.parallel.distributed import (
        initialize_distributed,
    )

    initialize_distributed(args.coordinator, args.num_processes,
                           args.process_id)
    assert jax.process_count() == args.num_processes

    import numpy as np

    from __graft_entry__ import _example_trajectory
    from scalable_agent_tpu.models import ImpalaAgent
    from scalable_agent_tpu.parallel import MeshSpec, make_mesh
    from scalable_agent_tpu.runtime import Learner, LearnerHyperparams

    unroll_len, height, width, num_actions = 4, 16, 16, 6
    global_batch = 2 * jax.device_count()
    local_batch = global_batch // jax.process_count()
    agent = ImpalaAgent(num_actions=num_actions)
    mesh = make_mesh(MeshSpec(data=jax.device_count(), model=1))
    learner = Learner(agent, LearnerHyperparams(), mesh,
                      frames_per_update=global_batch * unroll_len * 4)
    # Identical seeds on every process -> identical initial params.
    state = learner.init(
        jax.random.key(0),
        _example_trajectory(unroll_len, 1, height, width, num_actions))
    for update in range(args.updates):
        local = _example_trajectory(
            unroll_len, local_batch, height, width, num_actions)
        traj = learner.put_trajectory(local)
        state, metrics = learner.update(state, traj)
    loss = float(np.asarray(
        metrics["total_loss"].addressable_shards[0].data))
    frames = float(np.asarray(
        metrics["env_frames"].addressable_shards[0].data))
    assert np.isfinite(loss), loss
    expected = args.updates * global_batch * unroll_len * 4
    assert frames == expected, (frames, expected)
    print(f"DRYRUN-MP-OK process={jax.process_index()} "
          f"loss={loss:.4f} frames={frames:.0f}", flush=True)


if __name__ == "__main__":
    main()
