from scalable_agent_tpu.parallel.mesh import (
    MeshSpec,
    batch_sharding,
    make_mesh,
    model_parallel_shardings,
    replicated_sharding,
)
from scalable_agent_tpu.parallel.distributed import (
    initialize_distributed,
    is_coordinator,
    local_batch_size,
)
