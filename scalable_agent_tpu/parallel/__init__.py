from scalable_agent_tpu.parallel.mesh import (
    MeshSpec,
    batch_sharding,
    fused_kernels_profitable,
    make_mesh,
    model_parallel_shardings,
    replicated_sharding,
)
from scalable_agent_tpu.parallel.sequence import (
    from_importance_weights_sharded,
)
from scalable_agent_tpu.parallel.distributed import (
    initialize_distributed,
    is_coordinator,
    local_batch_size,
)
from scalable_agent_tpu.parallel.pipeline import (
    gpipe_spmd,
    pipeline_utilization,
)
