from scalable_agent_tpu.parallel.mesh import (
    MeshSpec,
    batch_sharding,
    make_mesh,
    replicated_sharding,
)
