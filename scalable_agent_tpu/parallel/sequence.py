"""Sequence-parallel V-trace: the linear recurrence sharded over time.

Long-context handling (SURVEY §5.7): the reference's only treatment of
the time dimension is a sequential in-graph LSTM unroll and a
CPU-pinned sequential V-trace scan (reference: experiment.py:228-237,
387-397; vtrace.py:250-262).  Here the V-trace recurrence

    acc_s = delta_s + a_s * acc_{s+1},   acc_T = 0

is distributed over a mesh axis carrying the TIME dimension, the same
decomposition ring-attention-style context parallelism uses for
attention: each shard owns a contiguous time chunk, computes its local
affine composition, exchanges ONE composed (A, B) pair per shard over
the axis (all_gather — S pairs of [B]-vectors, a few KB), derives its
boundary accumulator from the suffix composition, and finishes locally.
Cross-shard traffic is O(S * B) floats regardless of T — the recurrence
itself never leaves the chip.

The heavy elementwise work (rhos, clipping, deltas) happens OUTSIDE the
shard_map in plain jnp, so XLA shards it over the same time axis with
zero communication; only the recurrence needs the hand-written
decomposition.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from scalable_agent_tpu.ops.vtrace import (
    VTraceReturns,
    compose_affine,
    elementwise_epilogue,
    elementwise_prologue,
)


def _chunk_recurrence(a, b, axis_name):
    """shard_map body: solve the reverse recurrence over time chunks.

    a, b: the LOCAL [T/S, B...] chunk.  Returns (acc, acc_next) where
    acc_next[s] = acc[s+1] globally (the next chunk's first accumulator
    at the chunk boundary).
    """
    # Composed suffix maps within the chunk: (A_s, B_s) such that
    # acc_s = B_s + A_s * x where x is the accumulator just past the
    # chunk end.
    comp_a, comp_b = lax.associative_scan(compose_affine, (a, b), reverse=True)

    # One composed pair per shard (its first element composes the whole
    # chunk); gather S of them and fold the suffix on every shard.
    all_a = lax.all_gather(comp_a[0], axis_name)    # [S, B...]
    all_b = lax.all_gather(comp_b[0], axis_name)

    # suffix[j] = (f_j o f_{j+1} o ... o f_{S-1})(0): reverse scan over
    # the shard axis (S is tiny — this is S log S work on [B] vectors).
    _, suffix = lax.associative_scan(
        compose_affine, (all_a, all_b), reverse=True, axis=0)
    # boundary for shard j = acc at the first element of shard j+1
    # = suffix[j+1], with suffix[S] = 0.
    suffix_padded = jnp.concatenate(
        [suffix[1:], jnp.zeros_like(suffix[:1])], axis=0)
    my = lax.axis_index(axis_name)
    boundary = jnp.take(suffix_padded, my, axis=0)  # [B...]

    acc = comp_b + comp_a * boundary[None]
    # acc_next: shift within the chunk; the last position's successor is
    # exactly the boundary accumulator.
    acc_next = jnp.concatenate([acc[1:], boundary[None]], axis=0)
    return acc, acc_next


def from_importance_weights_sharded(
    mesh: Mesh,
    log_rhos,
    discounts,
    rewards,
    values,
    bootstrap_value,
    clip_rho_threshold: Optional[float] = 1.0,
    clip_pg_rho_threshold: Optional[float] = 1.0,
    seq_axis: str = "data",
) -> VTraceReturns:
    """V-trace with the time dimension sharded over ``mesh[seq_axis]``.

    Inputs as ops/vtrace.from_importance_weights ([T, B...] etc.); T
    must divide evenly by the axis size.  Numerics match the
    single-device associative path (same composition order).
    """
    from scalable_agent_tpu.parallel._compat import shard_map

    log_rhos = jnp.asarray(log_rhos, jnp.float32)
    discounts = jnp.asarray(discounts, jnp.float32)
    rewards = jnp.asarray(rewards, jnp.float32)
    values = jnp.asarray(values, jnp.float32)
    bootstrap_value = jnp.asarray(bootstrap_value, jnp.float32)

    seq_size = mesh.shape[seq_axis]
    if log_rhos.shape[0] % seq_size:
        raise ValueError(
            f"unroll length {log_rhos.shape[0]} must divide evenly over "
            f"sequence axis {seq_axis!r} of size {seq_size}")

    a, deltas, rhos, values_t_plus_1 = elementwise_prologue(
        log_rhos, discounts, rewards, values, bootstrap_value,
        clip_rho_threshold)

    ndim = log_rhos.ndim
    # Keep the batch dimension sharded over 'data' while time shards
    # over the seq axis: on a dp x sp mesh the inputs then move WITHOUT
    # any batch all-gather (each device holds its [T/S, B/D] tile and
    # computes only its shard's recurrence).  When the caller uses the
    # data axis itself as the time axis (standalone/demo usage), the
    # batch stays unsharded — an axis can appear only once in a spec.
    batch_axis = ("data" if ndim >= 2 and seq_axis != "data"
                  and "data" in mesh.axis_names else None)
    trailing = [None] * max(0, ndim - 2)
    if ndim >= 2:
        time_sharded = PartitionSpec(seq_axis, batch_axis, *trailing)
    else:
        time_sharded = PartitionSpec(seq_axis)
    fn = shard_map(
        functools.partial(_chunk_recurrence, axis_name=seq_axis),
        mesh=mesh,
        in_specs=(time_sharded, time_sharded),
        out_specs=(time_sharded, time_sharded),
    )
    constrain = lambda x: lax.with_sharding_constraint(
        x, NamedSharding(mesh, time_sharded))
    acc, acc_next = fn(constrain(a), constrain(deltas))

    vs = acc + values
    vs_t_plus_1 = acc_next + values_t_plus_1
    pg_advantages = elementwise_epilogue(
        rhos, discounts, rewards, values, vs_t_plus_1,
        clip_pg_rho_threshold)
    return VTraceReturns(
        vs=lax.stop_gradient(vs),
        pg_advantages=lax.stop_gradient(pg_advantages))
