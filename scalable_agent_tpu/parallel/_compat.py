"""Small jax version shims shared by the parallel package."""

try:
    from jax import shard_map
except ImportError:  # jax < 0.8
    from jax.experimental.shard_map import shard_map  # noqa: F401

from jax import lax


def mark_varying(x, axis_name):
    """Type ``x`` as device-varying over ``axis_name`` inside shard_map
    (needed e.g. for a scan carry that meets a ppermute output)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, (axis_name,))  # pre-pcast jax
    # Pre-varying-types jax (< 0.4.52): there is no device-variance type
    # system at all — every value inside shard_map is implicitly
    # varying, so the marker is a no-op.
    return x
