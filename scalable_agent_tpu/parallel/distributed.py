"""Multi-host distribution over DCN.

The reference distributes with the TF1 gRPC runtime: one learner process
hosting a FIFOQueue, N actor processes enqueueing trajectories and
reading parameters over gRPC (reference: experiment.py:497-512,531,
556-562).  The TPU-native replacement is SPMD: every process calls
``jax.distributed.initialize``; the mesh spans all processes' devices;
the learner update is ONE jitted program whose data-axis collectives
ride ICI within a host and DCN across hosts (XLA picks the transport
from the topology); each host's actor pool contributes its local shard
of every global batch via ``jax.make_array_from_process_local_data``
(runtime/learner.py put_trajectory).

Process roles collapse: there is no separate "learner job" — every
process runs actors AND its slice of the learner, the standard JAX
multi-host pattern.  Host-side artifacts (metrics, logs) are written by
process 0 only; checkpoints are written collectively (Orbax handles
multi-host save/restore of global arrays).
"""

import os
from typing import Optional

import jax

from scalable_agent_tpu.utils import log


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed when configured; returns True if the
    job is multi-process.

    Explicit args win; otherwise standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID) or a
    TPU-pod auto-detecting environment apply.  A no-config single
    process is left untouched.
    """
    coordinator = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        env = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("JAX_PROCESS_ID")
        process_id = int(env) if env else None
    if coordinator is None and num_processes is None:
        return jax.process_count() > 1
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info("jax.distributed up: process %d/%d, %d local / %d global "
             "devices", jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())
    return jax.process_count() > 1


def is_coordinator() -> bool:
    return jax.process_index() == 0


def local_batch_size(global_batch: int) -> int:
    """Per-process share of a batch sharded over all processes."""
    processes = jax.process_count()
    if global_batch % processes:
        raise ValueError(
            f"global batch {global_batch} not divisible by "
            f"{processes} processes")
    return global_batch // processes
