"""Multi-host distribution over DCN.

The reference distributes with the TF1 gRPC runtime: one learner process
hosting a FIFOQueue, N actor processes enqueueing trajectories and
reading parameters over gRPC (reference: experiment.py:497-512,531,
556-562).  The TPU-native replacement is SPMD: every process calls
``jax.distributed.initialize``; the mesh spans all processes' devices;
the learner update is ONE jitted program whose data-axis collectives
ride ICI within a host and DCN across hosts (XLA picks the transport
from the topology); each host's actor pool contributes its local shard
of every global batch via ``jax.make_array_from_process_local_data``
(runtime/learner.py put_trajectory).

Process roles collapse: there is no separate "learner job" — every
process runs actors AND its slice of the learner, the standard JAX
multi-host pattern.  Host-side artifacts (metrics, logs) are written by
process 0 only; checkpoints are written collectively (Orbax handles
multi-host save/restore of global arrays).
"""

import os
import socket
import time
from typing import Optional

import jax

from scalable_agent_tpu.utils import log


def pick_unused_port(host: str = "localhost") -> int:
    """An OS-assigned free TCP port — the coordinator-port allocator
    for launchers that stand fleets up on one machine (the elastic
    supervisor, the multi-process test harness).  The usual bind(0)
    race applies: the port is only *probably* free by the time the
    coordinator binds it, which is why ``initialize_distributed``'s
    retry loop — not this helper — owns robustness."""
    with socket.socket() as sock:
        sock.bind((host, 0))
        return sock.getsockname()[1]

# Backoff shape for the coordinator-connect retry: first retry after
# 0.5s, doubling to a 10s cap — a fleet scheduler routinely starts
# process N seconds before the coordinator's container is reachable.
_INIT_BACKOFF_INITIAL_S = 0.5
_INIT_BACKOFF_CAP_S = 10.0


def _reset_distributed_state():
    """Undo a half-done ``jax.distributed.initialize`` so the retry
    loop can call it again.  jax assigns ``global_state.client`` (and
    process 0's service) BEFORE the blocking ``connect()``, so a failed
    connect leaves state behind and every later initialize raises
    'should only be called once' — without this reset the backoff loop
    could never actually retry."""
    try:
        jax.distributed.shutdown()
        return
    except Exception:
        pass
    try:  # client.shutdown() on a never-connected client may itself
        from jax._src import distributed  # raise: force-clear the state

        distributed.global_state.client = None
        distributed.global_state.service = None
        distributed.global_state.preemption_sync_manager = None
    except Exception:  # pragma: no cover - jax internals moved
        log.warning("could not reset jax.distributed state; the next "
                    "initialize attempt may refuse to run")


def _enable_cpu_gloo_collectives():
    """Point the (not-yet-initialized) CPU backend's cross-process
    collectives at gloo, returning a restore callable.  Restoring
    matters on the init-failed path: gloo demands the distributed
    client that never came up, so a leaked flag would poison every
    later backend init in this process with an unrelated-looking
    ``make_gloo_tcp_collectives`` error."""
    flag, value = "jax_cpu_collectives_implementation", "gloo"
    try:
        prev = getattr(jax.config, flag)
    except AttributeError:  # pre-rename jax spelling
        flag, value = "jax_cpu_enable_gloo_collectives", True
        prev = getattr(jax.config, flag, False)
    try:
        jax.config.update(flag, value)
    except Exception:
        log.warning("could not enable gloo CPU collectives; "
                    "multi-process CPU collectives may fail")
        return lambda: None

    def restore():
        try:
            jax.config.update(flag, prev)
        except Exception:  # pragma: no cover
            pass

    return restore


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    init_timeout_s: float = 60.0,
) -> bool:
    """Initialize jax.distributed when configured; returns True if the
    job is multi-process.

    Explicit args win; otherwise standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID) or a
    TPU-pod auto-detecting environment apply.  A no-config single
    process is left untouched.

    The coordinator is routinely NOT up yet when a scheduler launches
    the fleet: ``jax.distributed.initialize`` is retried with capped
    exponential backoff for up to ``init_timeout_s``
    (``--coordinator_init_timeout_s``), each retry counted in
    ``fleet/init_retries_total``, before the failure is re-raised with
    the attempt history attached.
    """
    coordinator = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        env = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("JAX_PROCESS_ID")
        process_id = int(env) if env else None
    if coordinator is None and num_processes is None:
        return jax.process_count() > 1
    platform = (os.environ.get("JAX_PLATFORMS", "")
                or str(getattr(jax.config, "jax_platforms", None) or ""))
    restore_collectives = lambda: None
    if platform.startswith("cpu"):
        # Cross-process collectives on the CPU backend need the gloo
        # transport; without it every multi-process CPU run (the
        # localhost test rig, a CPU smoke of a TPU job) dies at its
        # first psum with "Multiprocess computations aren't
        # implemented".  Checked via config/env, never jax.devices():
        # backend init must stay AFTER jax.distributed.initialize.
        restore_collectives = _enable_cpu_gloo_collectives()
    from scalable_agent_tpu.obs import get_registry

    retries = get_registry().counter(
        "fleet/init_retries_total",
        "jax.distributed.initialize attempts retried while waiting "
        "for the coordinator to come up")
    deadline = time.monotonic() + max(0.0, init_timeout_s)
    delay = _INIT_BACKOFF_INITIAL_S
    attempt = 0
    while True:
        attempt += 1
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
                # Bound jax's own blocking connect so OUR deadline (not
                # its multi-minute default) paces the retry loop.
                initialization_timeout=max(
                    5, int(deadline - time.monotonic()) or 5),
            )
            break
        except Exception as exc:
            _reset_distributed_state()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                restore_collectives()
                raise RuntimeError(
                    f"coordinator {coordinator} unreachable after "
                    f"{attempt} attempt(s) over "
                    f"{init_timeout_s:.0f}s "
                    f"(--coordinator_init_timeout_s)") from exc
            retries.inc()
            sleep_s = min(delay, remaining)
            log.warning(
                "jax.distributed.initialize attempt %d failed (%s: "
                "%s) — coordinator %s not up yet? retrying in %.1fs "
                "(%.0fs left)", attempt, type(exc).__name__, exc,
                coordinator, sleep_s, remaining)
            time.sleep(sleep_s)
            delay = min(delay * 2, _INIT_BACKOFF_CAP_S)
    log.info("jax.distributed up: process %d/%d, %d local / %d global "
             "devices", jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())
    return jax.process_count() > 1


def is_coordinator() -> bool:
    return jax.process_index() == 0


def local_batch_size(global_batch: int) -> int:
    """Per-process share of a batch sharded over all processes."""
    processes = jax.process_count()
    if global_batch % processes:
        raise ValueError(
            f"global batch {global_batch} not divisible by "
            f"{processes} processes")
    return global_batch // processes
