"""V-trace off-policy actor-critic targets, TPU-native.

Functional parity with the reference's ``vtrace.py`` (reference:
vtrace.py:71-161 ``from_logits``, vtrace.py:164-280
``from_importance_weights``), re-designed for TPU:

- The reference computes the v_s recurrence with a strictly sequential
  reverse ``tf.scan`` (``parallel_iterations=1``) deliberately placed on CPU
  because it was slow on GPU (reference: experiment.py:387-389,
  vtrace.py:250-262).  The recurrence

      acc_s = delta_s + (discount_s * c_s) * acc_{s+1}

  is a first-order *linear* recurrence, so here it is reformulated as a
  parallel ``jax.lax.associative_scan`` over composed affine maps — O(log T)
  depth on-device, fully fusable by XLA, and shardable over a mesh axis for
  sequence parallelism.  A sequential ``lax.scan`` path is kept for
  cross-checking (``scan_impl='sequential'``), and ``scan_impl='pallas'``
  runs the whole computation as ONE fused VMEM-resident Pallas kernel
  (ops/vtrace_pallas.py) — possible precisely because the outputs are
  stop-gradient'ed, so no VJP is ever needed through it.

- Like the reference, extra trailing dimensions are supported: ``rewards``
  may be [T, B, C...], ``bootstrap_value`` [B, C...] (reference:
  vtrace.py:176-180).

All math is float32; outputs are wrapped in ``stop_gradient`` exactly as the
reference does (reference: vtrace.py:279-280).
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


class VTraceDiagnostics(NamedTuple):
    """Scalar off-policyness diagnostics of one V-trace batch (ISSUE 17:
    the learning-dynamics plane).  All f32 scalars, stop-gradient'ed —
    pure telemetry, never part of the loss tape:

    - ``rho_clip_fraction`` / ``cs_clip_fraction`` /
      ``pg_rho_clip_fraction``: fraction of cells whose rho exceeded
      the rho-bar / 1.0 (the c-bar) / pg-rho-bar threshold — how much
      of the correction V-trace actually truncated.
    - ``log_rho_mean`` / ``log_rho_p95``: location and tail of the log
      importance ratio (0 when on-policy).
    - ``ess_frac``: effective sample size of the UNclipped importance
      weights, (Σρ)²/(N·Σρ²), as a fraction of N — 1.0 on-policy,
      → 1/N when one cell dominates.
    """

    rho_clip_fraction: jax.Array
    cs_clip_fraction: jax.Array
    pg_rho_clip_fraction: jax.Array
    log_rho_mean: jax.Array
    log_rho_p95: jax.Array
    ess_frac: jax.Array


class VTraceReturns(NamedTuple):
    vs: jax.Array
    pg_advantages: jax.Array
    # Trailing default keeps positional unpacking (vs, pg) working for
    # every pre-ISSUE-17 caller.
    diagnostics: Optional[VTraceDiagnostics] = None


class VTraceFromLogitsReturns(NamedTuple):
    vs: jax.Array
    pg_advantages: jax.Array
    log_rhos: jax.Array
    behaviour_action_log_probs: jax.Array
    target_action_log_probs: jax.Array
    diagnostics: Optional[VTraceDiagnostics] = None


def importance_diagnostics(log_rhos,
                           clip_rho_threshold: Optional[float] = 1.0,
                           clip_pg_rho_threshold: Optional[float] = 1.0
                           ) -> VTraceDiagnostics:
    """Off-policyness diagnostics from log importance ratios.

    Strict ``>`` comparisons: a rho exactly AT a threshold is returned
    unchanged by ``minimum``, so only values the clip actually altered
    count (an exactly-on-policy batch reports 0 clipped everywhere).
    A ``None`` threshold disables that clip, so its fraction is 0.
    """
    log_rhos = lax.stop_gradient(jnp.asarray(log_rhos, jnp.float32))
    rhos = jnp.exp(log_rhos)
    zero = jnp.zeros((), jnp.float32)
    rho_clip_fraction = (
        jnp.mean((rhos > jnp.float32(clip_rho_threshold))
                 .astype(jnp.float32))
        if clip_rho_threshold is not None else zero)
    pg_rho_clip_fraction = (
        jnp.mean((rhos > jnp.float32(clip_pg_rho_threshold))
                 .astype(jnp.float32))
        if clip_pg_rho_threshold is not None else zero)
    cs_clip_fraction = jnp.mean(
        (rhos > jnp.float32(1.0)).astype(jnp.float32))
    # ESS is scale-invariant in the weights, so shift by the max log
    # ratio before exponentiating — exp(2*log_rho) overflows f32 from
    # log_rho ~ 44, and one rogue trajectory would NaN the gauge.
    shifted = jnp.exp(log_rhos - jnp.max(log_rhos))
    sum_rho = jnp.sum(shifted)
    sum_rho_sq = jnp.sum(jnp.square(shifted))
    n = jnp.float32(log_rhos.size)
    ess_frac = jnp.square(sum_rho) / jnp.maximum(
        n * sum_rho_sq, jnp.float32(1e-30))
    return VTraceDiagnostics(
        rho_clip_fraction=rho_clip_fraction,
        cs_clip_fraction=cs_clip_fraction,
        pg_rho_clip_fraction=pg_rho_clip_fraction,
        log_rho_mean=jnp.mean(log_rhos),
        log_rho_p95=jnp.quantile(log_rhos, 0.95),
        ess_frac=ess_frac)


def log_probs_from_logits_and_actions(policy_logits, actions):
    """Sampling log-probability of ``actions`` under softmax ``policy_logits``.

    policy_logits: [T, B, NUM_ACTIONS] float; actions: [T, B] int.
    Returns [T, B] float32.  (reference: vtrace.py:45-68)
    """
    policy_logits = jnp.asarray(policy_logits, jnp.float32)
    actions = jnp.asarray(actions, jnp.int32)
    log_pi = jax.nn.log_softmax(policy_logits, axis=-1)
    return jnp.take_along_axis(log_pi, actions[..., None], axis=-1).squeeze(-1)


def compose_affine(later, earlier):
    """Affine-map composition for the reverse recurrence, shared by the
    single-device associative scan and the time-sharded path
    (parallel/sequence.py).  With reverse=True, associative_scan folds
    later timesteps into the left operand; composing
    f_earlier ∘ f_later gives (a_e * a_l, b_e + a_e * b_l)."""
    a_l, b_l = later
    a_e, b_e = earlier
    return a_e * a_l, b_e + a_e * b_l


def elementwise_prologue(log_rhos, discounts, rewards, values,
                         bootstrap_value, clip_rho_threshold):
    """The V-trace elementwise pre-computation shared by every
    recurrence implementation (single-device scans here, the Pallas
    kernel's host-side wrapper, and the time-sharded path in
    parallel/sequence.py): returns (a, deltas, rhos, values_t_plus_1)
    where acc solves acc_s = deltas_s + a_s * acc_{s+1}."""
    rhos = jnp.exp(log_rhos)
    if clip_rho_threshold is not None:
        clipped_rhos = jnp.minimum(jnp.float32(clip_rho_threshold), rhos)
    else:
        clipped_rhos = rhos
    cs = jnp.minimum(jnp.float32(1.0), rhos)
    values_t_plus_1 = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * values_t_plus_1 - values)
    return discounts * cs, deltas, rhos, values_t_plus_1


def elementwise_epilogue(rhos, discounts, rewards, values, vs_t_plus_1,
                         clip_pg_rho_threshold):
    """The shared pg-advantage computation given vs_{t+1}."""
    if clip_pg_rho_threshold is not None:
        clipped_pg_rhos = jnp.minimum(
            jnp.float32(clip_pg_rho_threshold), rhos)
    else:
        clipped_pg_rhos = rhos
    return clipped_pg_rhos * (rewards + discounts * vs_t_plus_1 - values)


def _linear_recurrence_reverse(a, b, scan_impl: str):
    """Solve acc_s = b_s + a_s * acc_{s+1} with acc_T = 0, over axis 0.

    Each timestep is the affine map f_s(x) = b_s + a_s * x; the answer at s is
    (f_s ∘ f_{s+1} ∘ ... ∘ f_{T-1})(0).  Affine-map composition is
    associative, so the whole solve is one ``associative_scan``.
    """
    if scan_impl == "sequential":
        def step(acc, ab):
            a_t, b_t = ab
            acc = b_t + a_t * acc
            return acc, acc

        _, out = lax.scan(step, jnp.zeros_like(b[0]), (a, b), reverse=True)
        return out

    if scan_impl != "associative":
        raise ValueError(f"unknown scan_impl: {scan_impl!r}")

    _, acc = lax.associative_scan(compose_affine, (a, b), reverse=True)
    return acc


def from_importance_weights(
    log_rhos,
    discounts,
    rewards,
    values,
    bootstrap_value,
    clip_rho_threshold: Optional[float] = 1.0,
    clip_pg_rho_threshold: Optional[float] = 1.0,
    scan_impl: str = "associative",
    mesh=None,
    seq_axis: str = "seq",
) -> VTraceReturns:
    """V-trace targets from log importance weights.

    Shapes: log_rhos/discounts/rewards/values [T, B, C...],
    bootstrap_value [B, C...].  (reference: vtrace.py:164-280)

    ``scan_impl="time_sharded"``: the recurrence's time dimension shards
    over ``mesh[seq_axis]`` (sequence/context parallelism,
    parallel/sequence.py) — the distributed replacement for the
    reference's CPU-pinned sequential scan (vtrace.py:250-262).
    """
    if scan_impl == "time_sharded":
        if mesh is None:
            raise ValueError(
                "scan_impl='time_sharded' needs the mesh argument")
        from scalable_agent_tpu.parallel import sequence

        sharded = sequence.from_importance_weights_sharded(
            mesh, log_rhos, discounts, rewards, values, bootstrap_value,
            clip_rho_threshold=clip_rho_threshold,
            clip_pg_rho_threshold=clip_pg_rho_threshold,
            seq_axis=seq_axis)
        # The diagnostics are elementwise reductions with no time
        # recurrence, so they need none of the sequence sharding —
        # compute them here and attach them to the delegated result.
        return sharded._replace(diagnostics=importance_diagnostics(
            log_rhos, clip_rho_threshold, clip_pg_rho_threshold))
    log_rhos = jnp.asarray(log_rhos, jnp.float32)
    discounts = jnp.asarray(discounts, jnp.float32)
    rewards = jnp.asarray(rewards, jnp.float32)
    values = jnp.asarray(values, jnp.float32)
    bootstrap_value = jnp.asarray(bootstrap_value, jnp.float32)

    if values.ndim != log_rhos.ndim:
        raise ValueError(
            f"values rank {values.ndim} != log_rhos rank {log_rhos.ndim}")
    if bootstrap_value.ndim != log_rhos.ndim - 1:
        raise ValueError(
            f"bootstrap_value rank {bootstrap_value.ndim} != "
            f"log_rhos rank {log_rhos.ndim} - 1")
    if discounts.ndim != log_rhos.ndim or rewards.ndim != log_rhos.ndim:
        raise ValueError("discounts/rewards rank must match log_rhos rank")

    diagnostics = importance_diagnostics(
        log_rhos, clip_rho_threshold, clip_pg_rho_threshold)

    if scan_impl == "pallas":
        # Fused single-kernel path (ops/vtrace_pallas.py).  The kernel is
        # rank-2 [T, B]; extra trailing value dims are flattened into the
        # batch (lane) axis — the recurrence is independent per column.
        from scalable_agent_tpu.ops import vtrace_pallas

        shape = log_rhos.shape
        # Stop gradients at the kernel INPUTS: the outputs are
        # stop-gradient'ed anyway, and pallas_call has no JVP rule, so the
        # tape must be severed before the call, not after.
        flat = lambda x: lax.stop_gradient(x).reshape(shape[0], -1)
        bootstrap_value = lax.stop_gradient(bootstrap_value)
        vs, pg = vtrace_pallas.vtrace_fused(
            flat(log_rhos), flat(discounts), flat(rewards), flat(values),
            bootstrap_value.reshape(-1),
            clip_rho_threshold=clip_rho_threshold,
            clip_pg_rho_threshold=clip_pg_rho_threshold,
            interpret=jax.default_backend() != "tpu")
        return VTraceReturns(
            vs=lax.stop_gradient(vs.reshape(shape)),
            pg_advantages=lax.stop_gradient(pg.reshape(shape)),
            diagnostics=diagnostics)

    a, deltas, rhos, _ = elementwise_prologue(
        log_rhos, discounts, rewards, values, bootstrap_value,
        clip_rho_threshold)
    vs_minus_v_xs = _linear_recurrence_reverse(a, deltas, scan_impl)
    vs = vs_minus_v_xs + values

    vs_t_plus_1 = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_advantages = elementwise_epilogue(
        rhos, discounts, rewards, values, vs_t_plus_1,
        clip_pg_rho_threshold)

    return VTraceReturns(
        vs=lax.stop_gradient(vs),
        pg_advantages=lax.stop_gradient(pg_advantages),
        diagnostics=diagnostics)


def from_logits(
    behaviour_policy_logits,
    target_policy_logits,
    actions,
    discounts,
    rewards,
    values,
    bootstrap_value,
    clip_rho_threshold: Optional[float] = 1.0,
    clip_pg_rho_threshold: Optional[float] = 1.0,
    scan_impl: str = "associative",
    dist_spec=None,
    mesh=None,
    seq_axis: str = "seq",
) -> VTraceFromLogitsReturns:
    """V-trace for softmax policies.  (reference: vtrace.py:71-161)

    behaviour/target logits: [T, B, NUM_LOGITS]; actions: [T, B] int
    ([T, B, K] for composite policies with ``dist_spec``);
    discounts/rewards/values: [T, B]; bootstrap_value: [B].

    ``dist_spec`` (ops/distributions.DistributionSpec): composite
    tuple-categorical policies — log-rhos become joint (summed) component
    log-prob ratios, the natural generalization the reference never built
    (its V-trace is single-categorical only, vtrace.py:45-68).
    """
    behaviour_policy_logits = jnp.asarray(behaviour_policy_logits, jnp.float32)
    target_policy_logits = jnp.asarray(target_policy_logits, jnp.float32)
    actions = jnp.asarray(actions, jnp.int32)

    if behaviour_policy_logits.ndim != 3 or target_policy_logits.ndim != 3:
        raise ValueError("policy logits must be rank 3 [T, B, NUM_LOGITS]")
    if dist_spec is None or dist_spec.num_components == 1:
        if actions.ndim != 2:
            raise ValueError("actions must be rank 2 [T, B]")
        behaviour_action_log_probs = log_probs_from_logits_and_actions(
            behaviour_policy_logits, actions)
        target_action_log_probs = log_probs_from_logits_and_actions(
            target_policy_logits, actions)
    else:
        from scalable_agent_tpu.ops import distributions

        if actions.ndim != 3:
            raise ValueError(
                "composite actions must be rank 3 [T, B, K]")
        behaviour_action_log_probs = distributions.log_prob(
            behaviour_policy_logits, actions, dist_spec)
        target_action_log_probs = distributions.log_prob(
            target_policy_logits, actions, dist_spec)
    log_rhos = target_action_log_probs - behaviour_action_log_probs

    vtrace_returns = from_importance_weights(
        log_rhos=log_rhos,
        discounts=discounts,
        rewards=rewards,
        values=values,
        bootstrap_value=bootstrap_value,
        clip_rho_threshold=clip_rho_threshold,
        clip_pg_rho_threshold=clip_pg_rho_threshold,
        scan_impl=scan_impl,
        mesh=mesh,
        seq_axis=seq_axis)

    return VTraceFromLogitsReturns(
        vs=vtrace_returns.vs,
        pg_advantages=vtrace_returns.pg_advantages,
        log_rhos=log_rhos,
        behaviour_action_log_probs=behaviour_action_log_probs,
        target_action_log_probs=target_action_log_probs,
        diagnostics=vtrace_returns.diagnostics)
