"""Fused done-reset LSTM unroll for TPU, written in Pallas.

The agent core is a single-layer LSTM(256) scanned over T timesteps
with a per-step done-triggered state reset (reference:
experiment.py:225-237 — the reference's own comment notes the reset
rules out CuDNN, forcing a Python unroll; the XLA path here uses
``nn.scan``).  This module goes one step further than ``nn.scan``: the
whole unroll is ONE Pallas program with

- the gate weights (Wi [D,4H], Wh [H,4H], bias [4H]) resident in VMEM
  across all T steps (constant-index blocks — fetched once, not
  re-streamed from HBM per step),
- the (c, h) carry living in VMEM scratch between grid steps (the TPU
  grid executes sequentially, which is exactly what a recurrence needs),
- per-timestep inputs/outputs streamed HBM<->VMEM by the Pallas
  pipeline with double buffering.

Unlike V-trace, gradients DO flow through the core, so the op carries a
custom VJP: the forward kernel stashes the gate activations and
post-reset carries as residuals, and a second Pallas kernel runs the
standard BPTT recurrence in reverse (grid index map ``t -> T-1-t``),
accumulating the weight gradients in VMEM scratch and writing them out
on the final grid step.

Math and parameter layout exactly match
``flax.linen.OptimizedLSTMCell`` (gate order i, f, g, o; i/f/o
sigmoid, g tanh; c' = f*c + i*g; h' = o*tanh(c'); no forget-gate bias
offset), so the flax cell and this kernel are interchangeable on the
same parameter pytree — see models/agent.py, which concatenates the
cell's ii/if/ig/io and hi/hf/hg/ho kernels into Wi/Wh.

Carry/gate math is float32.  The four matmuls (the kernel's only MXU
work) run at a configurable precision: ``matmul_dtype="float32"``
(default — bit-exact parity with the flax cell, which promotes to the
f32 params' dtype regardless of a bfloat16 torso) or ``"bfloat16"``
(operands cast to bf16, accumulation still f32 via
``preferred_element_type`` — 2x the MXU rate at ~1e-2 relative gate
error, the standard mixed-precision recipe).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _mm(a, b, matmul_dtype):
    """MXU matmul at the configured operand precision, f32 accumulate."""
    return jnp.dot(a.astype(matmul_dtype), b.astype(matmul_dtype),
                   preferred_element_type=jnp.float32)


def _cell_step(x_ref, done_ref, c0_ref, h0_ref, wi_ref, wh_ref, b_ref,
               c_s, h_s, matmul_dtype):
    """Shared cell math for one grid step: reset the carry where done,
    run the gates, update the VMEM carry.  Returns the intermediates
    the residual-producing kernel stashes for BPTT."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        c_s[:] = c0_ref[:]
        h_s[:] = h0_ref[:]

    keep = 1.0 - done_ref[0]                       # [B, 1]
    c = keep * c_s[:]
    h = keep * h_s[:]

    gates = (
        _mm(x_ref[0], wi_ref[:], matmul_dtype)
        + _mm(h, wh_ref[:], matmul_dtype)
        + b_ref[0][None, :])
    hidden = c.shape[-1]
    i = jax.nn.sigmoid(gates[:, :hidden])
    f = jax.nn.sigmoid(gates[:, hidden:2 * hidden])
    g = jnp.tanh(gates[:, 2 * hidden:3 * hidden])
    o = jax.nn.sigmoid(gates[:, 3 * hidden:])

    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    c_s[:] = c_new
    h_s[:] = h_new
    return c, h, i, f, g, o, c_new, h_new


def _fwd_kernel_lean(x_ref, done_ref, c0_ref, h0_ref, wi_ref, wh_ref,
                     b_ref, ys_ref, ct_ref, ht_ref, c_s, h_s,
                     matmul_dtype=jnp.float32):
    """Inference-only forward: writes just ys and the final carry — no
    residual traffic (the primal path of lstm_unroll; XLA cannot DCE
    individual outputs of one kernel, so the residual variant would pay
    ~7x the HBM writes for nothing outside a grad context)."""
    _, _, _, _, _, _, c_new, h_new = _cell_step(
        x_ref, done_ref, c0_ref, h0_ref, wi_ref, wh_ref, b_ref, c_s, h_s,
        matmul_dtype)
    ys_ref[0] = h_new
    # Constant-index output block: the last grid step's write survives.
    ct_ref[:] = c_new
    ht_ref[:] = h_new


def _fwd_kernel(x_ref, done_ref, c0_ref, h0_ref, wi_ref, wh_ref, b_ref,
                ys_ref, ifgo_ref, cpost_ref, hpost_ref, cnew_ref,
                ct_ref, ht_ref, c_s, h_s, matmul_dtype=jnp.float32):
    """Residual-producing forward (the VJP primal): additionally stashes
    the gate activations ifgo [1,B,4H], post-reset carries cpost/hpost
    [1,B,H], and cnew [1,B,H] per timestep for the backward kernel."""
    c, h, i, f, g, o, c_new, h_new = _cell_step(
        x_ref, done_ref, c0_ref, h0_ref, wi_ref, wh_ref, b_ref, c_s, h_s,
        matmul_dtype)
    cpost_ref[0] = c
    hpost_ref[0] = h
    ifgo_ref[0] = jnp.concatenate([i, f, g, o], axis=-1)
    cnew_ref[0] = c_new
    ys_ref[0] = h_new
    ct_ref[:] = c_new
    ht_ref[:] = h_new


def _bwd_kernel(dys_ref, x_ref, done_ref, ifgo_ref, cpost_ref, hpost_ref,
                cnew_ref, wi_ref, wh_ref, dct_ref, dht_ref,
                dx_ref, dwi_ref, dwh_ref, db_ref, dc0_ref, dh0_ref,
                dc_s, dh_s, dwi_s, dwh_s, db_s,
                matmul_dtype=jnp.float32):
    """One reverse timestep of BPTT (grid step k visits t = T-1-k via the
    index maps; inside the kernel every per-t ref is already the t-th
    block)."""
    k = pl.program_id(0)

    @pl.when(k == 0)
    def _():
        dc_s[:] = dct_ref[:]
        dh_s[:] = dht_ref[:]
        dwi_s[:] = jnp.zeros_like(dwi_s)
        dwh_s[:] = jnp.zeros_like(dwh_s)
        db_s[:] = jnp.zeros_like(db_s)

    hidden = dc_s.shape[-1]
    ifgo = ifgo_ref[0]
    i = ifgo[:, :hidden]
    f = ifgo[:, hidden:2 * hidden]
    g = ifgo[:, 2 * hidden:3 * hidden]
    o = ifgo[:, 3 * hidden:]
    c_new = cnew_ref[0]
    tanh_c = jnp.tanh(c_new)

    dh = dys_ref[0] + dh_s[:]
    do = dh * tanh_c * o * (1.0 - o)
    dc = dc_s[:] + dh * o * (1.0 - tanh_c * tanh_c)
    df = dc * cpost_ref[0] * f * (1.0 - f)
    di = dc * g * i * (1.0 - i)
    dg = dc * i * (1.0 - g * g)
    dgates = jnp.concatenate([di, df, dg, do], axis=-1)   # [B, 4H]

    # dx = dgates @ Wi^T ; dh_prev = dgates @ Wh^T  (contract gate dim).
    mm = lambda a, b, dims: lax.dot_general(
        a.astype(matmul_dtype), b.astype(matmul_dtype), dims,
        preferred_element_type=jnp.float32)
    contract_last = (((1,), (1,)), ((), ()))
    dx_ref[0] = mm(dgates, wi_ref[:], contract_last)
    dh_prev = mm(dgates, wh_ref[:], contract_last)
    dc_prev = dc * f

    # Weight grads: x^T @ dgates and h_post^T @ dgates (contract batch).
    contract_batch = (((0,), (0,)), ((), ()))
    dwi_s[:] += mm(x_ref[0], dgates, contract_batch)
    dwh_s[:] += mm(hpost_ref[0], dgates, contract_batch)
    db_s[:] += jnp.sum(dgates, axis=0, keepdims=True)

    # Chain through the pre-step reset: grads vanish where done was 1.
    keep = 1.0 - done_ref[0]                       # [B, 1]
    dc_s[:] = dc_prev * keep
    dh_s[:] = dh_prev * keep

    # Constant-index output blocks: written every grid step, the final
    # (t=0) step's values survive.
    dwi_ref[:] = dwi_s[:]
    dwh_ref[:] = dwh_s[:]
    db_ref[0] = db_s[0]
    dc0_ref[:] = dc_s[:]
    dh0_ref[:] = dh_s[:]


def _fwd_call(x, done, c0, h0, wi, wh, b, *, interpret, with_residuals,
              matmul_dtype=jnp.float32):
    unroll_len, batch, in_dim = x.shape
    hidden = c0.shape[-1]
    f32 = jnp.float32
    t_spec = lambda *shape: pl.BlockSpec((1,) + shape, lambda t: (t,) + (0,) * len(shape))
    const = lambda *shape: pl.BlockSpec(shape, lambda t: (0,) * len(shape))
    tb = lambda *shape: jax.ShapeDtypeStruct((unroll_len,) + shape, f32)
    carry_spec, carry_shape = const(batch, hidden), jax.ShapeDtypeStruct(
        (batch, hidden), f32)
    if with_residuals:
        kernel = _fwd_kernel
        out_specs = (
            t_spec(batch, hidden),           # ys
            t_spec(batch, 4 * hidden),       # ifgo
            t_spec(batch, hidden),           # cpost
            t_spec(batch, hidden),           # hpost
            t_spec(batch, hidden),           # cnew
            carry_spec,                      # cT
            carry_spec,                      # hT
        )
        out_shape = (
            tb(batch, hidden), tb(batch, 4 * hidden), tb(batch, hidden),
            tb(batch, hidden), tb(batch, hidden), carry_shape, carry_shape)
    else:
        kernel = _fwd_kernel_lean
        out_specs = (t_spec(batch, hidden), carry_spec, carry_spec)
        out_shape = (tb(batch, hidden), carry_shape, carry_shape)
    return pl.pallas_call(
        functools.partial(kernel, matmul_dtype=matmul_dtype),
        grid=(unroll_len,),
        in_specs=[
            t_spec(batch, in_dim),           # x
            t_spec(batch, 1),                # done [T,B,1]
            carry_spec,                      # c0
            carry_spec,                      # h0
            const(in_dim, 4 * hidden),       # wi
            const(hidden, 4 * hidden),       # wh
            const(1, 4 * hidden),            # b
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((batch, hidden), f32),
            pltpu.VMEM((batch, hidden), f32),
        ],
        interpret=interpret,
    )(x, done[..., None], c0, h0, wi, wh, b.reshape(1, -1))


def _bwd_call(residuals, cotangents, *, interpret,
              matmul_dtype=jnp.float32):
    x, done, wi, wh, ifgo, cpost, hpost, cnew = residuals
    dys, dct, dht = cotangents
    unroll_len, batch, in_dim = x.shape
    hidden = cpost.shape[-1]
    f32 = jnp.float32
    rev = lambda *shape: pl.BlockSpec(
        (1,) + shape, lambda k: (unroll_len - 1 - k,) + (0,) * len(shape))
    const = lambda *shape: pl.BlockSpec(shape, lambda k: (0,) * len(shape))
    return pl.pallas_call(
        functools.partial(_bwd_kernel, matmul_dtype=matmul_dtype),
        grid=(unroll_len,),
        in_specs=[
            rev(batch, hidden),              # dys
            rev(batch, in_dim),              # x
            rev(batch, 1),                   # done [T,B,1]
            rev(batch, 4 * hidden),          # ifgo
            rev(batch, hidden),              # cpost
            rev(batch, hidden),              # hpost
            rev(batch, hidden),              # cnew
            const(in_dim, 4 * hidden),       # wi
            const(hidden, 4 * hidden),       # wh
            const(batch, hidden),            # dcT
            const(batch, hidden),            # dhT
        ],
        out_specs=(
            rev(batch, in_dim),              # dx
            const(in_dim, 4 * hidden),       # dwi
            const(hidden, 4 * hidden),       # dwh
            const(1, 4 * hidden),            # db
            const(batch, hidden),            # dc0
            const(batch, hidden),            # dh0
        ),
        out_shape=(
            jax.ShapeDtypeStruct((unroll_len, batch, in_dim), f32),
            jax.ShapeDtypeStruct((in_dim, 4 * hidden), f32),
            jax.ShapeDtypeStruct((hidden, 4 * hidden), f32),
            jax.ShapeDtypeStruct((1, 4 * hidden), f32),
            jax.ShapeDtypeStruct((batch, hidden), f32),
            jax.ShapeDtypeStruct((batch, hidden), f32),
        ),
        scratch_shapes=[
            pltpu.VMEM((batch, hidden), f32),       # dc carry
            pltpu.VMEM((batch, hidden), f32),       # dh carry
            pltpu.VMEM((in_dim, 4 * hidden), f32),  # dwi accum
            pltpu.VMEM((hidden, 4 * hidden), f32),  # dwh accum
            pltpu.VMEM((1, 4 * hidden), f32),       # db accum
        ],
        interpret=interpret,
    )(dys, x, done[..., None], ifgo, cpost, hpost, cnew, wi, wh, dct, dht)


def _resolve_matmul_dtype(matmul_dtype):
    dtype = jnp.dtype(matmul_dtype)
    if dtype not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        raise ValueError(
            f"matmul_dtype must be float32 or bfloat16, got {dtype}")
    return dtype


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def lstm_unroll(x, done, c0, h0, wi, wh, b, interpret=False,
                matmul_dtype="float32"):
    """Fused done-reset LSTM unroll.

    x [T,B,D] float32, done [T,B] float32 (1.0 resets the carry BEFORE
    the step), c0/h0 [B,H], wi [D,4H], wh [H,4H], b [4H] in flax
    OptimizedLSTMCell's (i,f,g,o) gate order.  Returns
    (ys [T,B,H], (cT, hT)).  Differentiable in everything but ``done``.

    ``matmul_dtype``: operand precision for the gate/BPTT matmuls —
    "float32" (bit-exact vs the flax cell) or "bfloat16" (2x MXU rate,
    f32 accumulation).
    """
    ys, ct, ht = _fwd_call(
        x, done, c0, h0, wi, wh, b, interpret=interpret,
        with_residuals=False,
        matmul_dtype=_resolve_matmul_dtype(matmul_dtype))
    return ys, (ct, ht)


def _vjp_fwd(x, done, c0, h0, wi, wh, b, interpret, matmul_dtype):
    ys, ifgo, cpost, hpost, cnew, ct, ht = _fwd_call(
        x, done, c0, h0, wi, wh, b, interpret=interpret,
        with_residuals=True,
        matmul_dtype=_resolve_matmul_dtype(matmul_dtype))
    residuals = (x, done, wi, wh, ifgo, cpost, hpost, cnew)
    return (ys, (ct, ht)), residuals


def _vjp_bwd(interpret, matmul_dtype, residuals, cotangents):
    dys, (dct, dht) = cotangents
    dx, dwi, dwh, db, dc0, dh0 = _bwd_call(
        residuals, (dys, dct, dht), interpret=interpret,
        matmul_dtype=_resolve_matmul_dtype(matmul_dtype))
    ddone = jnp.zeros_like(residuals[1])  # non-differentiable data input
    return dx, ddone, dc0, dh0, dwi, dwh, db.reshape(-1)


lstm_unroll.defvjp(_vjp_fwd, _vjp_bwd)
