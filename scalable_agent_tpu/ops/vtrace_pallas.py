"""Fused single-kernel V-trace for TPU, written in Pallas.

The associative-scan formulation in ``ops/vtrace.py`` is O(log T) depth
but materializes the composed affine-map operands ((a, b) pairs) between
scan levels, and XLA lowers it as a tree of elementwise kernels over
HBM-resident intermediates.  For IMPALA shapes (T=100, B=32..512) the
whole working set is a few hundred KB — it fits VMEM outright.  This
kernel therefore does the entire V-trace computation in ONE Pallas
program per batch tile:

    rhos -> clipped rhos / cs -> deltas -> reverse linear recurrence
    -> vs -> pg_advantages

with every intermediate living in VMEM/registers and exactly one
HBM read per input and one HBM write per output.  The reverse
recurrence is a `fori_loop` over time inside the kernel — sequential
over T like the reference's CPU `tf.scan` (reference: vtrace.py:250-262)
but running on-chip on (1, B_tile) vectors with zero kernel-launch or
HBM traffic per step.

V-trace outputs are consumed under ``stop_gradient`` (reference:
vtrace.py:279-280), so the kernel needs no custom VJP: gradients never
flow through it.

Layout: time on the sublane axis, batch on the lane axis ([T, B]
blocks, batch tiled in multiples of 128 lanes).  Extra trailing value
dimensions are flattened into the batch axis by the caller
(``ops/vtrace.py``) — the recurrence is independent per column, so
padding columns introduced by Pallas block padding stay confined to
lanes that are never written back.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128


def _vtrace_kernel(log_rhos_ref, discounts_ref, rewards_ref, values_ref,
                   bootstrap_ref, vs_ref, pg_ref, deltas_ref, a_ref, *,
                   unroll_len, clip_rho_threshold, clip_pg_rho_threshold):
    """One batch tile: full V-trace, VMEM-resident.

    Refs are [T, Bt] except bootstrap_ref [1, Bt]; deltas_ref/a_ref are
    VMEM scratch (Mosaic only lowers dynamic time indexing on *refs*, so
    the recurrence operands are staged through scratch).
    """
    rhos = jnp.exp(log_rhos_ref[:])
    if clip_rho_threshold is not None:
        clipped_rhos = jnp.minimum(jnp.float32(clip_rho_threshold), rhos)
    else:
        clipped_rhos = rhos
    cs = jnp.minimum(jnp.float32(1.0), rhos)

    values = values_ref[:]
    rewards = rewards_ref[:]
    discounts = discounts_ref[:]
    boot = bootstrap_ref[:]                       # (1, Bt)

    # Mosaic rejects zero-size vectors, so T=1 can't slice values[1:].
    if unroll_len > 1:
        values_t_plus_1 = jnp.concatenate([values[1:], boot], axis=0)
    else:
        values_t_plus_1 = boot
    deltas_ref[:] = clipped_rhos * (
        rewards + discounts * values_t_plus_1 - values)
    a_ref[:] = discounts * cs

    # acc_s = deltas_s + a_s * acc_{s+1}, acc_T = 0; write vs_s as we go.
    def step(i, acc):
        t = unroll_len - 1 - i
        acc = deltas_ref[pl.ds(t, 1), :] + a_ref[pl.ds(t, 1), :] * acc
        vs_ref[pl.ds(t, 1), :] = acc + values_ref[pl.ds(t, 1), :]
        return acc

    lax.fori_loop(0, unroll_len, step, jnp.zeros_like(boot))

    vs = vs_ref[:]
    if unroll_len > 1:
        vs_t_plus_1 = jnp.concatenate([vs[1:], boot], axis=0)
    else:
        vs_t_plus_1 = boot
    if clip_pg_rho_threshold is not None:
        clipped_pg_rhos = jnp.minimum(
            jnp.float32(clip_pg_rho_threshold), rhos)
    else:
        clipped_pg_rhos = rhos
    pg_ref[:] = clipped_pg_rhos * (
        rewards + discounts * vs_t_plus_1 - values)


@functools.partial(
    jax.jit,
    static_argnames=("clip_rho_threshold", "clip_pg_rho_threshold",
                     "interpret"))
def vtrace_fused(log_rhos, discounts, rewards, values, bootstrap_value,
                 clip_rho_threshold=1.0, clip_pg_rho_threshold=1.0,
                 interpret=False):
    """(vs, pg_advantages) for rank-2 [T, B] inputs, bootstrap [B].

    Batch is tiled over the grid in 128-lane blocks; each block runs the
    fused kernel above.  ``interpret=True`` runs the Pallas interpreter
    (the caller enables it on every non-TPU backend — the Mosaic
    lowering is TPU-only).
    """
    unroll_len, batch = log_rhos.shape
    to_f32 = lambda x: jnp.asarray(x, jnp.float32)
    log_rhos, discounts, rewards, values = map(
        to_f32, (log_rhos, discounts, rewards, values))
    boot = to_f32(bootstrap_value)[None, :]        # (1, B)

    tile = min(_LANES, batch)
    grid = (pl.cdiv(batch, tile),)
    tb_spec = pl.BlockSpec((unroll_len, tile), lambda i: (0, i))
    boot_spec = pl.BlockSpec((1, tile), lambda i: (0, i))

    kernel = functools.partial(
        _vtrace_kernel, unroll_len=unroll_len,
        clip_rho_threshold=clip_rho_threshold,
        clip_pg_rho_threshold=clip_pg_rho_threshold)
    out_shape = jax.ShapeDtypeStruct((unroll_len, batch), jnp.float32)
    vs, pg = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[tb_spec, tb_spec, tb_spec, tb_spec, boot_spec],
        out_specs=(tb_spec, tb_spec),
        out_shape=(out_shape, out_shape),
        scratch_shapes=[
            pltpu.VMEM((unroll_len, tile), jnp.float32),   # deltas
            pltpu.VMEM((unroll_len, tile), jnp.float32),   # a = discount*c
        ],
        interpret=interpret,
    )(log_rhos, discounts, rewards, values, boot)
    return vs, pg
