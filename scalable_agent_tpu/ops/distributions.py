"""Categorical and tuple-categorical action distributions (pure JAX).

The reference implements these as torch classes
(reference: algorithms/utils/action_distributions.py —
``CategoricalActionDistribution`` :49-108, ``TupleActionDistribution``
:111-201, ``calc_num_logits`` :10-17).  TPU-native re-design:

- A distribution is not an object but a static ``DistributionSpec``
  (the per-component logit widths) plus pure functions over a single
  concatenated logits tensor [..., sum(sizes)].  Static widths mean XLA
  sees fixed slices — no ragged structures, no host control flow.
- Component independence makes every quantity a sum over components:
  log_prob, entropy, and KL all reduce with one vectorized pass per
  component (K is tiny — Doom's largest composite has 6 components).
- Actions are int32 with a trailing component axis [..., K]; the K == 1
  case also accepts component-less actions so the plain-Discrete fast
  path keeps its existing [T, B] layout.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from scalable_agent_tpu.envs.spaces import (
    Discrete,
    Space,
    TupleSpace,
    calc_num_logits,
)


class DistributionSpec(NamedTuple):
    """Static shape of a (tuple-)categorical policy: logit width per
    independent component."""

    sizes: Tuple[int, ...]

    @property
    def num_logits(self) -> int:
        return sum(self.sizes)

    @property
    def num_components(self) -> int:
        return len(self.sizes)


def spec_for_space(space: Space) -> DistributionSpec:
    """Space -> DistributionSpec (reference: calc_num_logits, :10-17)."""
    if isinstance(space, Discrete):  # includes Discretized
        return DistributionSpec(sizes=(space.n,))
    if isinstance(space, TupleSpace):
        sizes = []
        for sub in space.spaces:
            sub_spec = spec_for_space(sub)
            sizes.extend(sub_spec.sizes)
        return DistributionSpec(sizes=tuple(sizes))
    raise NotImplementedError(f"no categorical policy over {space!r}")


def _offsets(spec: DistributionSpec):
    offsets = []
    start = 0
    for size in spec.sizes:
        offsets.append((start, size))
        start += size
    return offsets


def _component_logits(logits, spec: DistributionSpec):
    """Split [..., num_logits] into per-component views (static slices)."""
    if logits.shape[-1] != spec.num_logits:
        raise ValueError(
            f"logits last dim {logits.shape[-1]} != spec {spec.num_logits}")
    return [logits[..., start:start + size]
            for start, size in _offsets(spec)]


def _component_actions(actions, spec: DistributionSpec):
    """Actions [..., K] (or [...] when K == 1) -> list of [...] int32."""
    k = spec.num_components
    actions = jnp.asarray(actions)
    if k == 1:
        # Single-component policies always use the component-less layout
        # ([T, B] etc.) — never a trailing K axis, avoiding ambiguity
        # with batch dims of size 1.
        return [actions]
    if actions.shape[-1] != k:
        raise ValueError(
            f"actions last dim {actions.shape[-1]} != {k} components")
    return [actions[..., i] for i in range(k)]


def sample(rng: jax.Array, logits, spec: DistributionSpec):
    """Sample all components; returns int32 [..., K], squeezed to [...]
    for K == 1 (preserving the plain-Discrete layout)."""
    parts = []
    for i, chunk in enumerate(_component_logits(logits, spec)):
        parts.append(jax.random.categorical(
            jax.random.fold_in(rng, i), chunk, axis=-1))
    stacked = jnp.stack(parts, axis=-1).astype(jnp.int32)
    if spec.num_components == 1:
        return stacked[..., 0]
    return stacked


def log_prob(logits, actions, spec: DistributionSpec):
    """Joint log pi(a|s): sum of component log-probs (independence).

    (reference: TupleActionDistribution.log_prob, :160-165)
    """
    total = None
    for chunk, action in zip(_component_logits(logits, spec),
                             _component_actions(actions, spec)):
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(chunk, axis=-1),
            action[..., None].astype(jnp.int32), axis=-1)[..., 0]
        total = lp if total is None else total + lp
    return total


def entropy(logits, spec: DistributionSpec):
    """Joint entropy: sum of component entropies.

    (reference: TupleActionDistribution.entropy, :180-184)
    """
    total = None
    for chunk in _component_logits(logits, spec):
        log_p = jax.nn.log_softmax(chunk, axis=-1)
        ent = -jnp.sum(jnp.exp(log_p) * log_p, axis=-1)
        total = ent if total is None else total + ent
    return total


def kl_divergence(p_logits, q_logits, spec: DistributionSpec):
    """KL(p || q), summed over components.

    (reference: CategoricalActionDistribution.kl_divergence :96-100,
    TupleActionDistribution sums over the tuple :186-192)
    """
    total = None
    for p_chunk, q_chunk in zip(_component_logits(p_logits, spec),
                                _component_logits(q_logits, spec)):
        log_p = jax.nn.log_softmax(p_chunk, axis=-1)
        log_q = jax.nn.log_softmax(q_chunk, axis=-1)
        kl = jnp.sum(jnp.exp(log_p) * (log_p - log_q), axis=-1)
        total = kl if total is None else total + kl
    return total


def symmetric_kl(p_logits, q_logits, spec: DistributionSpec):
    """0.5 * (KL(p || q) + KL(q || p)), summed over components.

    The reference's ``kl_divergence`` is in fact this symmetric form
    (reference: CategoricalActionDistribution._kl_symmetric/_kl_inverse
    :84-93 and kl_divergence :100-101; TupleActionDistribution sums over
    the tuple :193-201).
    """
    return 0.5 * (kl_divergence(p_logits, q_logits, spec)
                  + kl_divergence(q_logits, p_logits, spec))


def kl_to_prior(logits, spec: DistributionSpec):
    """Symmetric KL against the uniform prior, summed over components.

    (reference: CategoricalActionDistribution.kl_prior :95-98 — the
    prior is uniform over each component's actions, log_prior_probs
    :60-63; TupleActionDistribution.kl_prior :187-191.)
    """
    total = None
    for chunk in _component_logits(logits, spec):
        prior = jnp.zeros_like(chunk)  # uniform after log_softmax
        component_spec = DistributionSpec(sizes=(chunk.shape[-1],))
        kl = symmetric_kl(chunk, prior, component_spec)
        total = kl if total is None else total + kl
    return total


def one_hot_actions(actions, spec: DistributionSpec):
    """Concatenated per-component one-hots [..., num_logits] — the
    "last action" conditioning input for composite spaces (generalizes
    the reference's single one_hot, experiment.py:196-198)."""
    parts = [
        jax.nn.one_hot(action, size, dtype=jnp.float32)
        for (_, size), action in zip(
            _offsets(spec), _component_actions(actions, spec))
    ]
    return jnp.concatenate(parts, axis=-1) if len(parts) > 1 else parts[0]
