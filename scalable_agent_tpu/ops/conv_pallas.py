"""Pallas weight-gradient kernel for the torso's strided stem conv.

The per-kernel roofline ledger names ``conv0_gradw`` as the learner's
worst kernel: XLA lowers the 8x8/stride-4 stem's weight gradient to a
kernel that runs at 0.107 MFU for ~13 ms at the B=256 merged batch
(BENCH_NOTES round-5 conv table), and the space-to-depth reformulation
made it WORSE (0.047) because it only helps the input gradient — which
the stem, fed by the gradient-free uint8 frame, never computes.  This
module attacks the weight gradient directly.

``stem_conv`` is the SAME 8x8/stride-4 convolution wrapped in a
``jax.custom_vjp``:

- **forward** and **grad-input** stay XLA's (both already run near the
  layer's output-lane ceiling; grad-input is DCE'd entirely in the
  torso, whose stem input needs no gradient),
- **grad-W** is a Pallas im2col-tiled MXU matmul.  The padded input is
  re-laid-out once (space-to-depth by the stride S, so every kernel tap
  becomes a CONTIGUOUS slice), then a sequential grid over the batch
  gathers per-tile patch matrices ``P [BN*OH*OW, K*K*Cin]`` from D*D
  static slices (D = K/S), contracts them against the output cotangent
  ``G [BN*OH*OW, Cout]`` on the MXU, and accumulates ``[K*K*Cin, Cout]``
  in float32 VMEM scratch across grid steps — one revisited
  constant-index output block, exactly the lstm_pallas.py accumulation
  idiom.

Why this beats XLA's lowering: XLA derives grad-W as a conv with the
8x8 kernel dims mapped to the *spatial output* of a big dilated
convolution — a shape (8x8 "image", 32 lanes) that strands most of the
MXU.  Here the contraction is a single [K*K*Cin, N*OH*OW] x
[N*OH*OW, Cout] matmul with the huge merged batch as the contracting
dimension, which is the shape the MXU was built for.

Requires ``K % S == 0`` (true for the 8/4 stem; D = K/S).  Any other
kernel/stride pair silently falls back to XLA's own grad-W — the
wrapper is then semantically inert, and the parity tests pin that.

Like ops/lstm_pallas.py: ``interpret=True`` runs the identical kernel
under the Pallas interpreter so CPU tier-1 exercises the same code
path, and ``matmul_dtype`` picks the MXU operand precision ("float32"
bit-parity / "bfloat16" 2x rate, f32 accumulation either way via
``preferred_element_type``).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Trace/HLO name of the grad-W kernel.  obs/kernels.py keys its
# custom-call FLOPs model on this exact string appearing in the
# instruction's op_name metadata — change them together.
GRADW_KERNEL_NAME = "pallas_conv0_gradw"

# VMEM budget for one grid tile's working set (inputs + patch matrix);
# the batch tile BN shrinks to fit.  Conservative: ~half of a v5e
# core's 16 MB, leaving room for the pipeline's double buffering.
_TILE_BYTES_BUDGET = 8 << 20
_MAX_BATCH_TILE = 32


def _resolve_matmul_dtype(matmul_dtype):
    dtype = jnp.dtype(matmul_dtype)
    if dtype not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        raise ValueError(
            f"matmul_dtype must be float32 or bfloat16, got {dtype}")
    return dtype


def _forward(x, w, stride):
    return lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _same_pads(size, k, s):
    """XLA SAME padding: out = ceil(size/s); lo gets the smaller half."""
    out = -(-size // s)
    total = max((out - 1) * s + k - size, 0)
    return out, (total // 2, total - total // 2)


def _gradw_kernel(xs_ref, g_ref, dw_ref, acc_s, *, depth, out_h, out_w,
                  matmul_dtype):
    """One batch tile of the grad-W contraction.

    xs_ref [BN, OH+D-1, OW+D-1, S*S*C] — space-to-depth input; each
    kernel tap (dh, dw) of the ORIGINAL conv is the contiguous slice
    ``xs[:, dh:dh+OH, dw:dw+OW, :]``.  g_ref [BN, OH, OW, F] is the
    output cotangent.  Accumulates [D*D*S*S*C, F] in f32 scratch; the
    constant-index dw_ref block is written every step (last survives).
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_s[...] = jnp.zeros_like(acc_s)

    bn = xs_ref.shape[0]
    s2c = xs_ref.shape[-1]
    f = g_ref.shape[-1]
    rows = bn * out_h * out_w
    patches = [
        xs_ref[:, dh:dh + out_h, dw:dw + out_w, :].reshape(rows, s2c)
        for dh in range(depth) for dw in range(depth)
    ]
    p = jnp.concatenate(patches, axis=-1).astype(matmul_dtype)
    g = g_ref[...].reshape(rows, f).astype(matmul_dtype)
    # [D*D*S*S*C, BN*OH*OW] x [BN*OH*OW, F]: the merged batch is the
    # contracting dim — the MXU-shaped form of grad-W.
    acc_s[...] += lax.dot_general(
        p, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dw_ref[...] = acc_s[...]


def _batch_tile(n, per_image_floats):
    bn = max(1, _TILE_BYTES_BUDGET // max(1, per_image_floats * 4))
    return max(1, min(n, _MAX_BATCH_TILE, bn))


def conv_gradw(x, g, kernel_size, stride, interpret=False,
               matmul_dtype="float32"):
    """Weight gradient of the SAME-padded ``kernel_size``/``stride``
    conv: x [N,H,W,C], g [N,OH,OW,F] -> dW [K,K,C,F] float32.  Pallas
    when ``kernel_size % stride == 0``, XLA's own grad-W otherwise."""
    matmul_dtype = _resolve_matmul_dtype(matmul_dtype)
    n, h, w_in, c = x.shape
    _, out_h, out_w, f = g.shape
    k, s = int(kernel_size), int(stride)
    if k % s != 0:
        # The D-slice gather needs every tap on the s2d lattice; other
        # geometries take XLA's derivative (already fine off the stem).
        w_shape = (k, k, c, f)
        _, vjp_w = jax.vjp(
            lambda ww: _forward(x, ww, s),
            jnp.zeros(w_shape, x.dtype))
        return vjp_w(g)[0].astype(jnp.float32)

    depth = k // s
    _, (ph_lo, ph_hi) = _same_pads(h, k, s)
    _, (pw_lo, pw_hi) = _same_pads(w_in, k, s)
    xp = jnp.pad(x, ((0, 0), (ph_lo, ph_hi), (pw_lo, pw_hi), (0, 0)))
    hp, wp = xp.shape[1], xp.shape[2]
    # Space-to-depth by the stride: [N, HP/S, WP/S, S*S*C], depth rows
    # ordered (sh, sw, c).  HP = (OH-1)*S + K = (OH+D-1)*S exactly, so
    # the lattice always divides.
    xs = xp.reshape(n, hp // s, s, wp // s, s, c)
    xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(
        n, hp // s, wp // s, s * s * c)
    tile_h, tile_w = out_h + depth - 1, out_w + depth - 1
    s2c = s * s * c
    per_image = (tile_h * tile_w * s2c + out_h * out_w * f
                 + out_h * out_w * depth * depth * s2c)
    bn = _batch_tile(n, per_image)
    n_pad = -(-n // bn) * bn
    if n_pad != n:
        # Zero-padded images contribute zero cotangent rows — exact.
        xs = jnp.pad(xs, ((0, n_pad - n), (0, 0), (0, 0), (0, 0)))
        g = jnp.pad(g, ((0, n_pad - n), (0, 0), (0, 0), (0, 0)))
    rows_out = depth * depth * s2c
    with jax.named_scope(GRADW_KERNEL_NAME):
        dw = pl.pallas_call(
            functools.partial(
                _gradw_kernel, depth=depth, out_h=out_h, out_w=out_w,
                matmul_dtype=matmul_dtype),
            grid=(n_pad // bn,),
            in_specs=[
                pl.BlockSpec((bn, tile_h, tile_w, s2c),
                             lambda i: (i, 0, 0, 0)),
                pl.BlockSpec((bn, out_h, out_w, f),
                             lambda i: (i, 0, 0, 0)),
            ],
            out_specs=pl.BlockSpec((rows_out, f), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((rows_out, f), jnp.float32),
            scratch_shapes=[pltpu.VMEM((rows_out, f), jnp.float32)],
            interpret=interpret,
            name=GRADW_KERNEL_NAME,
        )(xs, g)
    # Rows are ordered (dh, dw, sh, sw, c); kh = dh*S + sh.
    dw = dw.reshape(depth, depth, s, s, c, f).transpose(0, 2, 1, 3, 4, 5)
    return dw.reshape(k, k, c, f)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def stem_conv(x, w, stride=4, interpret=False, matmul_dtype="float32"):
    """SAME-padded NHWC conv (x [N,H,W,C], w [K,K,C,F], square stride)
    whose weight gradient is the Pallas im2col kernel above.  Forward
    and input gradient are XLA's — numerically this op IS
    ``lax.conv_general_dilated(..., "SAME")``; only d/dW's lowering
    differs.  ``interpret`` and ``matmul_dtype`` follow
    ops/lstm_pallas.py's contract."""
    return _forward(x, w, stride)


def _vjp_fwd(x, w, stride, interpret, matmul_dtype):
    return _forward(x, w, stride), (x, w)


def _vjp_bwd(stride, interpret, matmul_dtype, residuals, g):
    x, w = residuals
    # Input gradient: XLA's transposed conv.  In the torso the stem's
    # input is the gradient-free normalized frame, so this whole branch
    # is dead code XLA eliminates; it exists for standalone parity.
    _, vjp_x = jax.vjp(lambda xx: _forward(xx, w, stride), x)
    dx = vjp_x(g)[0]
    dw = conv_gradw(x, g, w.shape[0], stride, interpret=interpret,
                    matmul_dtype=matmul_dtype)
    return dx, dw.astype(w.dtype)


stem_conv.defvjp(_vjp_fwd, _vjp_bwd)
