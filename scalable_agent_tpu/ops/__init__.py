from scalable_agent_tpu.ops import vtrace
from scalable_agent_tpu.ops import losses
