# NOTE: the Pallas modules (lstm_pallas, vtrace_pallas) are deliberately
# NOT imported here — their consumers import them lazily at the use site
# so the XLA-only paths never pay (or depend on) the Pallas TPU imports.
from scalable_agent_tpu.ops import impact
from scalable_agent_tpu.ops import losses
from scalable_agent_tpu.ops import vtrace
