"""IMPACT clipped-target surrogate (Luo et al., arXiv:1912.00167).

The off-policy dial ROADMAP item 2 needs: raw V-trace degrades as the
behaviour policy ages (the importance ratio π_θ/μ drifts and the clip
throws the sample away), so a learner fed from replay — where frame age
is a *throughput choice*, not an accident — needs a surrogate built to
tolerate staleness.  IMPACT's construction:

- a **target network** π_tgt (a periodic hard copy of the online
  params, riding in ``TrainState.target_params``) anchors the
  surrogate.  The behaviour→target correction ``β = min(c̄, π_tgt/μ)``
  is exactly V-trace's clipped pg-rho with the TARGET network as the
  "target policy" — so the advantage the learner sees is already
  β-weighted by ``vtrace.from_logits(target_policy_logits=π_tgt, ...)``
  (ops/vtrace.py), and this module only adds the clipped ratio term.
- the **clipped-target surrogate** itself is PPO-shaped but measured
  against the *target* network rather than the behaviour policy::

      r_t(θ) = π_θ(a_t|s_t) / π_tgt(a_t|s_t)
      L = -Σ min( r_t · Â_t, clip(r_t, 1-ε, 1+ε) · Â_t )

  Because π_tgt moves only every ``target_update_interval`` updates,
  r_t stays near 1 no matter how stale the *behaviour* data is — the
  property that turns ``replay_ratio`` into a throughput dial instead
  of a divergence dial.

Loss terms are SUMS over time and batch, matching ops/losses.py (so
entropy_cost/baseline_cost transfer unchanged between ``--loss=vtrace``
and ``--loss=impact``).
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from scalable_agent_tpu.ops import distributions

__all__ = ["ImpactSurrogate", "surrogate_from_logits"]


class ImpactSurrogate(NamedTuple):
    """The clipped-target policy loss plus its diagnostics.

    loss: scalar (negated summed surrogate — minimize it).
    ratio_mean: mean of r_t = π_θ/π_tgt over the batch (≈1 when the
        online net hugs the target; drift here is the staleness
        instrument the obs plane reads).
    clip_fraction: fraction of (t, b) cells where the clip bound was
        the active side of the min — the surrogate's own "how stale is
        my data" gauge.
    log_ratio_mean / log_ratio_p95: location and tail of
        log(π_θ/π_tgt) — the online→target drift the
        ``target_update_interval`` dial controls (ISSUE 17).
    ess_frac: effective sample size of the online→target importance
        weights, (Σr)²/(N·Σr²) as a fraction of N.

    The ISSUE-17 diagnostics are trailing fields with None defaults so
    positional construction/unpacking of the original triple keeps
    working.
    """

    loss: jax.Array
    ratio_mean: jax.Array
    clip_fraction: jax.Array
    log_ratio_mean: Optional[jax.Array] = None
    log_ratio_p95: Optional[jax.Array] = None
    ess_frac: Optional[jax.Array] = None


def surrogate_from_logits(
    online_logits,
    target_logits,
    actions,
    advantages,
    clip_epsilon: float = 0.3,
    dist_spec: Optional[distributions.DistributionSpec] = None,
) -> ImpactSurrogate:
    """IMPACT surrogate from logits.

    online_logits/target_logits: [T, B, NUM_LOGITS]; actions [T, B]
    ([T, B, K] composite with ``dist_spec``); ``advantages`` [T, B] are
    the β-weighted V-trace pg-advantages (computed with the TARGET
    network as V-trace's target policy — the β = min(c̄, π_tgt/μ)
    correction is V-trace's clipped pg-rho, not re-applied here).
    """
    if clip_epsilon <= 0.0:
        raise ValueError(
            f"impact clip_epsilon must be > 0, got {clip_epsilon}")
    online_logits = jnp.asarray(online_logits, jnp.float32)
    target_logits = jnp.asarray(target_logits, jnp.float32)
    actions = jnp.asarray(actions, jnp.int32)
    if dist_spec is None:
        dist_spec = distributions.DistributionSpec(
            sizes=(online_logits.shape[-1],))
    lp_online = distributions.log_prob(online_logits, actions, dist_spec)
    # No gradient flows into the target net anyway (its params are a
    # separate TrainState field), but the stop_gradient documents the
    # anchor role and keeps the tape minimal.
    lp_target = lax.stop_gradient(
        distributions.log_prob(target_logits, actions, dist_spec))
    ratio = jnp.exp(lp_online - lp_target)
    adv = lax.stop_gradient(jnp.asarray(advantages, jnp.float32))
    clipped = jnp.clip(ratio, 1.0 - clip_epsilon, 1.0 + clip_epsilon)
    objective = jnp.minimum(ratio * adv, clipped * adv)
    loss = -jnp.sum(objective)
    clip_active = (clipped * adv < ratio * adv)
    log_ratio = lax.stop_gradient(lp_online - lp_target)
    # ESS is scale-invariant in the weights: shift by the max log
    # ratio before exponentiating so exp(2*log_ratio) can't overflow
    # f32 and NaN the gauge on a badly drifted batch.
    shifted = jnp.exp(log_ratio - jnp.max(log_ratio))
    ess_frac = jnp.square(jnp.sum(shifted)) / jnp.maximum(
        jnp.float32(log_ratio.size) * jnp.sum(jnp.square(shifted)),
        jnp.float32(1e-30))
    return ImpactSurrogate(
        loss=loss,
        ratio_mean=jnp.mean(ratio),
        clip_fraction=jnp.mean(clip_active.astype(jnp.float32)),
        log_ratio_mean=jnp.mean(log_ratio),
        log_ratio_p95=jnp.quantile(log_ratio, 0.95),
        ess_frac=ess_frac,
    )
