"""IMPALA loss terms and reward transforms.

Parity with the reference's loss helpers (reference: experiment.py:324-343)
and reward clipping modes (reference: experiment.py:377-382).  All terms are
*sums* over time and batch (not means) — matching the reference exactly so
hyperparameters like entropy_cost transfer unchanged.
"""

import jax
import jax.numpy as jnp
from jax import lax


def compute_baseline_loss(advantages) -> jax.Array:
    """0.5 * sum(advantages^2).  (reference: experiment.py:324-329)"""
    return 0.5 * jnp.sum(jnp.square(jnp.asarray(advantages, jnp.float32)))


def compute_entropy_loss(logits) -> jax.Array:
    """Negative total policy entropy.  (reference: experiment.py:332-336)"""
    log_policy = jax.nn.log_softmax(jnp.asarray(logits, jnp.float32), axis=-1)
    policy = jnp.exp(log_policy)
    entropy_per_timestep = jnp.sum(-policy * log_policy, axis=-1)
    return -jnp.sum(entropy_per_timestep)


def compute_policy_gradient_loss(logits, actions, advantages) -> jax.Array:
    """sum(cross_entropy(actions) * stop_grad(advantages)).

    (reference: experiment.py:339-343)
    """
    log_pi = jax.nn.log_softmax(jnp.asarray(logits, jnp.float32), axis=-1)
    cross_entropy = -jnp.take_along_axis(
        log_pi, jnp.asarray(actions, jnp.int32)[..., None], axis=-1
    ).squeeze(-1)
    return jnp.sum(cross_entropy * lax.stop_gradient(advantages))


def clip_rewards(rewards, mode: str) -> jax.Array:
    """Reward clipping modes.  (reference: experiment.py:377-382)

    - 'abs_one': clip to [-1, 1].
    - 'soft_asymmetric': tanh squashing on a +/-5 scale with negative rewards
      down-weighted by 0.3.
    - 'none': pass-through.
    """
    rewards = jnp.asarray(rewards, jnp.float32)
    if mode == "abs_one":
        return jnp.clip(rewards, -1.0, 1.0)
    if mode == "soft_asymmetric":
        squeezed = jnp.tanh(rewards / 5.0)
        return jnp.where(rewards < 0, 0.3 * squeezed, squeezed) * 5.0
    if mode == "none":
        return rewards
    raise ValueError(f"unknown reward clipping mode: {mode!r}")
