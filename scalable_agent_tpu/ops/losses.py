"""IMPALA loss terms and reward transforms.

Parity with the reference's loss helpers (reference: experiment.py:324-343)
and reward clipping modes (reference: experiment.py:377-382).  All terms are
*sums* over time and batch (not means) — matching the reference exactly so
hyperparameters like entropy_cost transfer unchanged.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from scalable_agent_tpu.ops import distributions


def _default_spec(logits, dist_spec):
    if dist_spec is not None:
        return dist_spec
    return distributions.DistributionSpec(sizes=(logits.shape[-1],))


def compute_baseline_loss(advantages) -> jax.Array:
    """0.5 * sum(advantages^2).  (reference: experiment.py:324-329)"""
    return 0.5 * jnp.sum(jnp.square(jnp.asarray(advantages, jnp.float32)))


def compute_entropy_loss(
        logits,
        dist_spec: Optional[distributions.DistributionSpec] = None,
) -> jax.Array:
    """Negative total policy entropy; for composite policies the joint
    entropy is the sum over components.  (reference: experiment.py:332-336;
    TupleActionDistribution.entropy, action_distributions.py:180-184)"""
    logits = jnp.asarray(logits, jnp.float32)
    entropy_per_timestep = distributions.entropy(
        logits, _default_spec(logits, dist_spec))
    return -jnp.sum(entropy_per_timestep)


def compute_policy_gradient_loss(
        logits, actions, advantages,
        dist_spec: Optional[distributions.DistributionSpec] = None,
) -> jax.Array:
    """sum(cross_entropy(actions) * stop_grad(advantages)); composite
    policies sum component cross-entropies (independent heads).

    (reference: experiment.py:339-343)
    """
    logits = jnp.asarray(logits, jnp.float32)
    cross_entropy = -distributions.log_prob(
        logits, jnp.asarray(actions, jnp.int32),
        _default_spec(logits, dist_spec))
    return jnp.sum(cross_entropy * lax.stop_gradient(advantages))


def clip_rewards(rewards, mode: str) -> jax.Array:
    """Reward clipping modes.  (reference: experiment.py:377-382)

    - 'abs_one': clip to [-1, 1].
    - 'soft_asymmetric': tanh squashing on a +/-5 scale with negative rewards
      down-weighted by 0.3.
    - 'none': pass-through.
    """
    rewards = jnp.asarray(rewards, jnp.float32)
    if mode == "abs_one":
        return jnp.clip(rewards, -1.0, 1.0)
    if mode == "soft_asymmetric":
        squeezed = jnp.tanh(rewards / 5.0)
        return jnp.where(rewards < 0, 0.3 * squeezed, squeezed) * 5.0
    if mode == "none":
        return rewards
    raise ValueError(f"unknown reward clipping mode: {mode!r}")
