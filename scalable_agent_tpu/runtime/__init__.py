from scalable_agent_tpu.runtime.actor import ActorPool, VectorActor
from scalable_agent_tpu.runtime.accum_actor import (
    AccumPrograms,
    AccumVectorActor,
)
from scalable_agent_tpu.runtime.ingraph import InGraphTrainer
from scalable_agent_tpu.runtime.batcher import (
    BatcherClosedError,
    DynamicBatcher,
    bucket_ladder,
    pad_to_bucket,
)
from scalable_agent_tpu.runtime.service import (
    ActorService,
    TrajectoryPacker,
)
from scalable_agent_tpu.runtime.faults import (
    FaultInjector,
    InjectedFault,
    configure_faults,
    get_fault_injector,
)
from scalable_agent_tpu.runtime.elastic import (
    DriverLauncher,
    ElasticSupervisor,
    classify_exit,
    run_supervised,
)
from scalable_agent_tpu.runtime.fleet import (
    FleetMonitor,
    GraceWindow,
    PeerTracker,
    configure_fleet,
    get_fleet,
)
from scalable_agent_tpu.runtime.learner import (
    Learner,
    LearnerHyperparams,
    NonFiniteTracker,
    TrainState,
    Trajectory,
)
from scalable_agent_tpu.runtime.replay import DeviceReplayBuffer
from scalable_agent_tpu.runtime.transport import (
    InflightWindow,
    PackedTransport,
    PerLeafTransport,
    make_transport,
)
