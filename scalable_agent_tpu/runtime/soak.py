"""Chaos soak engine: seeded fault schedules graded against SLOs.

Every recovery mechanism in the robustness layer exists in isolation —
non-finite rollback, fleet fault domains, elastic reshard, the
numerics sentinel — and each chaos point (runtime/faults.py) is proven
one-at-a-time in tests.  This module is the layer that turns them into
ONE graded, repeatable claim (ROADMAP item 3; the availability story
of Espeholt et al. 1802.01561): a **seeded randomized fault schedule**
sampled from the chaos registry with per-point weights, injected into
an **already-running** fleet through the runtime channel
(``<logdir>/chaos_inject.jsonl`` under ``--chaos_channel``), graded by
a continuous **invariant checker** and written atomically as a
schema'd ``soak_report.json``.

The invariants (each graded independently; the soak passes only when
every one holds):

- ``throughput_floor`` — every healthy-window throughput reading stays
  >= ``floor`` (default 0.8) of the run's OWN healthy-window baseline
  (median fps over rows whose measurement interval touches no injected
  fault's declared recovery window; the first row — startup compile —
  is always excluded).
- ``mttr_ceiling`` — every reshard's epochs-log ``mttr`` event
  (runtime/elastic.py) stays under the ceiling.
- ``frame_exactness`` — the final verified checkpoint's
  ``env_frames == updates * frames_per_update`` exactly: no fault may
  double-count or drop a frame.
- ``final_checkpoint`` — the walk-back restore
  (runtime/checkpoint.py) finds a checkpoint that verifies against its
  per-leaf CRC manifest.
- ``quiet_outside_windows`` — zero health-plane anomaly records
  (obs/health.py) outside the injected windows, and no more sentinel
  trips than injected sentinel-class faults: recovery noise must be
  attributable to the schedule, never spontaneous.

CLI::

    python -m scalable_agent_tpu.runtime.soak run \
        --soak_seed=1 --soak_faults=6 --soak_budget_s=120 \
        --logdir=/tmp/soak --mode=train --level_name=fake_small ...
    python -m scalable_agent_tpu.runtime.soak report --logdir=/tmp/soak

``run`` takes the driver's full flag surface after its own ``--soak_*``
flags, forces ``--chaos_channel``, launches the elastic supervisor
(``--distributed_num_processes`` > 1 or ``--elastic``) or the
single-process driver, appends the schedule's channel lines at their
sampled times, SIGTERMs the run at the wall budget (the preemption
grace protocol drains to one final verified checkpoint), then grades.
Pair it with ``--compile_cache_dir`` so mid-soak relaunches compile
from disk — the MTTR engineering half of the story
(docs/robustness.md, "Running a chaos soak").

The schedule is deterministic in (seed, faults, budget, points):
``sample_schedule`` drives one ``random.Random(seed)``, so a soak
failure replays with the same flags.  Faults are sampled only inside
the middle of the budget (after ``SCHEDULE_WARMUP_FRAC``, before
``SCHEDULE_COOLDOWN_FRAC`` from the end) so startup compile and the
final drain checkpoint stay clean.

``bench.py bench_soak`` runs a short seeded single-process soak and
publishes ``soak_pass`` / ``soak_throughput_floor_frac`` /
``soak_mttr_worst_s`` into the round artifact, where
``soak_regression_guard`` and the ``rounds report`` scoreboard's
``chaos_soak`` target (item 3) grade it per round.
"""

import argparse
import dataclasses
import json
import os
import random
import re
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from scalable_agent_tpu.runtime.faults import CHANNEL_NAME, CHAOS_POINTS
from scalable_agent_tpu.utils import log

__all__ = [
    "DEFAULT_WEIGHTS",
    "FLEET_ONLY_POINTS",
    "SOAK_REPORT_NAME",
    "check_invariants",
    "grade_soak",
    "main",
    "read_soak_report",
    "run_soak",
    "sample_schedule",
]

SOAK_REPORT_NAME = "soak_report.json"
SOAK_SCHEMA_VERSION = 1

# Schedule sampling weights over the chaos registry.  Weight 0 points
# exist in the registry but are excluded from random schedules:
# service_stall needs --actor=service, replay_corrupt needs
# --replay_ratio>0, the sentinel-class points need --sentinel_interval
# — a schedule is sampled against the CONFIG the soak runs, and
# run_soak enables exactly the points the config can consume (callers
# can pass their own points/weights).
DEFAULT_WEIGHTS: Dict[str, float] = {
    "nan_grad": 3.0,
    "throughput_sag": 3.0,
    "actor_raise": 2.0,
    "worker_kill": 2.0,
    "ckpt_torn": 1.0,
    "ckpt_save_fail": 1.0,
    "peer_exit": 2.0,
    "preempt_sigterm": 0.0,   # ends the run — opt-in only
    "peer_hang": 0.0,         # wedges a peer until peer_timeout_s
    "service_stall": 0.0,
    "replay_corrupt": 0.0,
    "param_bitflip": 0.0,
    "kernel_miscompute": 0.0,
    "replica_diverge": 0.0,
}

# Points that only make sense with a multi-process fleet under the
# elastic supervisor (they kill/wedge a peer and expect a reshard).
FLEET_ONLY_POINTS = ("peer_exit", "peer_hang", "preempt_sigterm",
                     "replica_diverge")

# Declared recovery window per point (seconds after injection during
# which throughput readings and anomaly records are expected and
# excluded from the healthy-window grading).  Fleet deaths cover a
# full relaunch; everything else is absorbed in-process.
DEFAULT_RECOVERY_S: Dict[str, float] = {
    "peer_exit": 120.0,
    "peer_hang": 150.0,
    "preempt_sigterm": 120.0,
    "worker_kill": 30.0,
    "actor_raise": 20.0,
    "ckpt_torn": 10.0,
    "ckpt_save_fail": 10.0,
    "service_stall": 30.0,
    "throughput_sag": 15.0,
    "nan_grad": 15.0,
    "replay_corrupt": 15.0,
    "param_bitflip": 30.0,
    "kernel_miscompute": 30.0,
    "replica_diverge": 60.0,
}
_FALLBACK_RECOVERY_S = 30.0

# The fraction of the budget kept clean at each end: startup compile
# (and its fps row) at the front, the drain's final verified
# checkpoint at the back.
SCHEDULE_WARMUP_FRAC = 0.25
SCHEDULE_COOLDOWN_FRAC = 0.25

# Sentinel-class points: a sentinel trip during the soak is only
# "quiet" if the schedule injected at least that many of these.
SENTINEL_POINTS = ("param_bitflip", "kernel_miscompute",
                   "replica_diverge")


def sample_schedule(seed: int, num_faults: int, budget_s: float,
                    points: Optional[Sequence[str]] = None,
                    weights: Optional[Dict[str, float]] = None,
                    num_processes: int = 1,
                    recovery_s: Optional[Dict[str, float]] = None,
                    ) -> List[dict]:
    """A deterministic fault schedule: ``num_faults`` events sampled
    from ``points`` by weight, at times uniform over the middle of the
    budget, sorted.  Each event is
    ``{"t_s", "point", "proc", "recovery_s"}`` (``proc`` is None
    single-process, else a sampled target process id)."""
    weights = dict(DEFAULT_WEIGHTS if weights is None else weights)
    if points is None:
        points = [p for p, w in weights.items() if w > 0]
        if num_processes <= 1:
            points = [p for p in points if p not in FLEET_ONLY_POINTS]
    unknown = sorted(set(points) - set(CHAOS_POINTS))
    if unknown:
        raise ValueError(
            f"unknown chaos point(s) {unknown} — the registry is "
            f"runtime/faults.py CHAOS_POINTS")
    if not points:
        raise ValueError("no chaos points to sample from")
    recovery_s = dict(DEFAULT_RECOVERY_S if recovery_s is None
                      else recovery_s)
    rng = random.Random(seed)
    lo = budget_s * SCHEDULE_WARMUP_FRAC
    hi = budget_s * (1.0 - SCHEDULE_COOLDOWN_FRAC)
    point_weights = [max(weights.get(p, 1.0), 1e-9) for p in points]
    events = []
    for _ in range(max(0, int(num_faults))):
        point = rng.choices(list(points), weights=point_weights)[0]
        events.append({
            "t_s": round(rng.uniform(lo, hi), 3),
            "point": point,
            "proc": (rng.randrange(num_processes)
                     if num_processes > 1 else None),
            "recovery_s": float(recovery_s.get(point,
                                               _FALLBACK_RECOVERY_S)),
        })
    events.sort(key=lambda e: (e["t_s"], e["point"]))
    return events


# ---------------------------------------------------------------------------
# The invariant checker (pure — unit-tested against synthetic streams)
# ---------------------------------------------------------------------------


def _windows(injected: Sequence[dict]) -> List[tuple]:
    """[(start_unix, end_unix)] recovery windows of the injected
    events (events that never landed carry no ``t_unix`` and declare
    no window)."""
    out = []
    for event in injected:
        t = event.get("t_unix")
        if t is None:
            continue
        out.append((float(t),
                    float(t) + float(event.get("recovery_s",
                                               _FALLBACK_RECOVERY_S))))
    return out


def _in_windows(t: float, windows: Sequence[tuple]) -> bool:
    return any(lo <= t <= hi for lo, hi in windows)


def _overlaps(lo: float, hi: float, windows: Sequence[tuple]) -> bool:
    return any(lo <= whi and wlo <= hi for wlo, whi in windows)


def check_invariants(*, metrics_rows: Sequence[dict],
                     mttr_events: Sequence[dict],
                     anomalies: Sequence[dict],
                     injected: Sequence[dict],
                     ckpt: dict,
                     frames_per_update: int,
                     throughput_floor: float = 0.8,
                     mttr_ceiling_s: float = 180.0,
                     sentinel_trips: int = 0,
                     warmup_until_unix: Optional[float] = None,
                     ) -> Dict[str, dict]:
    """Grade every soak invariant against the run's streams.  Pure:
    callers (and tests/test_soak.py) hand in parsed rows.  Returns
    ``{invariant: {"ok": bool, ...evidence...}}`` — every invariant is
    always present and always graded.

    ``warmup_until_unix``: throughput rows whose measurement interval
    starts before this are excluded — the schedule keeps its warmup
    fraction fault-free precisely because startup compile and actor
    ramp-up are not steady state."""
    windows = _windows(injected)

    # -- throughput_floor --------------------------------------------------
    fps_rows = [r for r in metrics_rows
                if isinstance(r.get("fps"), (int, float))
                and isinstance(r.get("time"), (int, float))]
    graded, excluded = [], 0
    for i, row in enumerate(fps_rows):
        if i == 0:
            excluded += 1  # startup: the first interval is compile
            continue
        interval = (float(fps_rows[i - 1]["time"]), float(row["time"]))
        if warmup_until_unix is not None \
                and interval[0] < warmup_until_unix:
            excluded += 1
            continue
        if _overlaps(interval[0], interval[1], windows):
            excluded += 1
            continue
        graded.append(float(row["fps"]))
    if graded:
        ordered = sorted(graded)
        baseline = ordered[len(ordered) // 2]
        worst = min(graded)
        frac = (worst / baseline) if baseline > 0 else 0.0
        throughput = {
            "ok": bool(baseline > 0 and frac >= throughput_floor),
            "floor": throughput_floor,
            "baseline_fps": round(baseline, 3),
            "worst_fps": round(worst, 3),
            "worst_frac": round(frac, 4),
            "rows_graded": len(graded),
            "rows_excluded": excluded,
        }
    else:
        throughput = {
            "ok": False,
            "floor": throughput_floor,
            "rows_graded": 0,
            "rows_excluded": excluded,
            "detail": "no healthy-window throughput rows to grade",
        }

    # -- mttr_ceiling ------------------------------------------------------
    mttrs = [float(e["mttr_s"]) for e in mttr_events
             if isinstance(e.get("mttr_s"), (int, float))]
    mttr = {
        "ok": bool(all(m <= mttr_ceiling_s for m in mttrs)),
        "ceiling_s": mttr_ceiling_s,
        "events": len(mttrs),
        "worst_s": round(max(mttrs), 3) if mttrs else None,
    }

    # -- frame_exactness ---------------------------------------------------
    step = ckpt.get("step")
    env_frames = ckpt.get("env_frames")
    if step is None or env_frames is None:
        exactness = {"ok": False,
                     "detail": "no verified checkpoint to account "
                               "against"}
    else:
        expected = float(step) * float(frames_per_update)
        exactness = {
            "ok": bool(abs(float(env_frames) - expected) < 0.5),
            "updates": int(step),
            "frames_per_update": int(frames_per_update),
            "env_frames": float(env_frames),
            "expected": expected,
        }

    # -- final_checkpoint --------------------------------------------------
    final = {"ok": bool(ckpt.get("verified")), "step": step}
    if ckpt.get("error"):
        final["error"] = ckpt["error"]

    # -- quiet_outside_windows ---------------------------------------------
    stray = [a for a in anomalies
             if isinstance(a.get("ts_unix"), (int, float))
             and not _in_windows(float(a["ts_unix"]), windows)]
    sentinel_budget = sum(1 for e in injected
                          if e.get("t_unix") is not None
                          and e.get("point") in SENTINEL_POINTS)
    quiet = {
        "ok": bool(not stray and sentinel_trips <= sentinel_budget),
        "stray_anomalies": [
            {"id": a.get("id"), "detector": a.get("detector"),
             "ts_unix": a.get("ts_unix")} for a in stray],
        "anomalies_total": len(anomalies),
        "sentinel_trips": sentinel_trips,
        "sentinel_trip_budget": sentinel_budget,
    }

    return {
        "throughput_floor": throughput,
        "mttr_ceiling": mttr,
        "frame_exactness": exactness,
        "final_checkpoint": final,
        "quiet_outside_windows": quiet,
    }


# ---------------------------------------------------------------------------
# Artifact readers (torn-line tolerant, jax-free)
# ---------------------------------------------------------------------------


def _read_jsonl(path: str) -> List[dict]:
    try:
        lines = open(path).read().splitlines()
    except OSError:
        return []
    rows = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(row, dict):
            rows.append(row)
    return rows


def _read_anomalies(logdir: str) -> List[dict]:
    """Last record per anomaly id (the obs/health.py event-sourced
    read, reimplemented jax-free)."""
    by_id: Dict[str, dict] = {}
    for row in _read_jsonl(os.path.join(logdir, "anomalies.jsonl")):
        anomaly_id = row.get("id")
        if isinstance(anomaly_id, str):
            by_id[anomaly_id] = row
    return list(by_id.values())


_PROM_LINE = re.compile(
    r"^impala_([A-Za-z0-9_]+?)(?:\{[^}]*\})?\s+([0-9eE+.\-]+)\s*$")


def _read_prom_counters(logdir: str) -> Dict[str, float]:
    """{bare_metric_name: max value across label variants} from the
    run's final metrics.prom snapshot."""
    out: Dict[str, float] = {}
    try:
        lines = open(os.path.join(logdir, "metrics.prom")).read(
        ).splitlines()
    except OSError:
        return out
    for line in lines:
        match = _PROM_LINE.match(line.strip())
        if not match:
            continue
        try:
            value = float(match.group(2))
        except ValueError:
            continue
        name = match.group(1)
        out[name] = max(out.get(name, value), value)
    return out


def _inspect_final_checkpoint(logdir: str) -> dict:
    """Walk-back restore + CRC verify of the run's newest checkpoint
    (imports jax — grading runs in the engine process, not the hot
    path).  Returns {"verified", "step", "env_frames", "error"}."""
    from scalable_agent_tpu.runtime.checkpoint import (
        CheckpointIntegrityError,
        CheckpointManager,
    )

    info = {"verified": False, "step": None, "env_frames": None,
            "error": None}
    try:
        restored = CheckpointManager(logdir).restore(target=None)
    except CheckpointIntegrityError as exc:
        info["error"] = str(exc)
        return info
    except Exception as exc:  # unexpected — grade, don't crash
        info["error"] = f"{type(exc).__name__}: {exc}"
        return info
    if restored is None:
        info["error"] = "no checkpoint on disk"
        return info
    step, state = restored
    info["verified"] = True
    info["step"] = int(step)
    env_frames = (state or {}).get("env_frames")
    if env_frames is not None:
        try:
            import numpy as np

            info["env_frames"] = float(np.asarray(env_frames))
        except Exception:
            info["env_frames"] = None
    return info


# ---------------------------------------------------------------------------
# Grading + report
# ---------------------------------------------------------------------------


def grade_soak(logdir: str, *, injected: Sequence[dict],
               planned: Sequence[dict], frames_per_update: int,
               throughput_floor: float = 0.8,
               mttr_ceiling_s: float = 180.0,
               warmup_until_unix: Optional[float] = None,
               meta: Optional[dict] = None) -> dict:
    """Read the run's artifacts (metrics.jsonl, fleet_epochs.jsonl,
    anomalies.jsonl, metrics.prom, the checkpoint directory), grade
    every invariant, and return the schema'd report dict."""
    metrics_rows = _read_jsonl(os.path.join(logdir, "metrics.jsonl"))
    epoch_events = _read_jsonl(os.path.join(logdir,
                                            "fleet_epochs.jsonl"))
    mttr_events = [e for e in epoch_events if e.get("event") == "mttr"]
    anomalies = _read_anomalies(logdir)
    counters = _read_prom_counters(logdir)
    ckpt = _inspect_final_checkpoint(logdir)
    invariants = check_invariants(
        metrics_rows=metrics_rows,
        mttr_events=mttr_events,
        anomalies=anomalies,
        injected=injected,
        ckpt=ckpt,
        frames_per_update=frames_per_update,
        throughput_floor=throughput_floor,
        mttr_ceiling_s=mttr_ceiling_s,
        sentinel_trips=int(counters.get("sentinel_trips_total", 0)),
        warmup_until_unix=warmup_until_unix)
    report = {
        "schema_version": SOAK_SCHEMA_VERSION,
        "logdir": os.path.abspath(logdir),
        "pass": bool(all(v["ok"] for v in invariants.values())),
        "invariants": invariants,
        "injected": list(injected),
        "planned_not_injected": [e for e in planned
                                 if e.get("t_unix") is None],
        "points": sorted({e["point"] for e in injected
                          if e.get("t_unix") is not None}),
        "counters": {
            "faults_injected_total": counters.get(
                "faults_injected_total", 0.0),
            "sentinel_trips_total": counters.get(
                "sentinel_trips_total", 0.0),
            "watchdog_stalls_total": counters.get(
                "watchdog_stalls_total", 0.0),
        },
        "mttr_events": mttr_events,
        "checkpoint": ckpt,
    }
    report.update(meta or {})
    return report


def write_report(logdir: str, report: dict,
                 path: Optional[str] = None) -> str:
    """Atomic (tmp + rename) ``soak_report.json`` write — a killed
    grader must never leave a torn report for `rounds` to parse."""
    path = path or os.path.join(logdir, SOAK_REPORT_NAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return path


def read_soak_report(logdir: str) -> Optional[dict]:
    try:
        report = json.load(open(os.path.join(logdir,
                                             SOAK_REPORT_NAME)))
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    return report if isinstance(report, dict) else None


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------


def _worker_command(config) -> List[str]:
    """The subprocess the soak drives: the elastic supervisor for a
    fleet (or when --elastic is set), the plain driver otherwise."""
    fleet = (config.distributed_num_processes or 0) > 1 \
        or getattr(config, "elastic", False)
    module = ("scalable_agent_tpu.runtime.elastic" if fleet
              else "scalable_agent_tpu.driver")
    return [sys.executable, "-m", module] + config.to_argv()


def _append_channel_line(logdir: str, event: dict) -> float:
    """Arm one injection in the running fleet.  Returns the stamped
    ``t_unix`` (the injector skips lines predating its own arm time,
    so relaunched epochs never replay consumed lines)."""
    t_unix = time.time()
    line = {"point": event["point"], "t_unix": t_unix}
    if event.get("proc") is not None:
        line["proc"] = int(event["proc"])
    with open(os.path.join(logdir, CHANNEL_NAME), "a") as f:
        f.write(json.dumps(line) + "\n")
        f.flush()
    return t_unix


def run_soak(config, *, seed: int = 0, num_faults: int = 6,
             budget_s: float = 120.0,
             points: Optional[Sequence[str]] = None,
             weights: Optional[Dict[str, float]] = None,
             throughput_floor: float = 0.8,
             mttr_ceiling_s: float = 180.0,
             recovery_s: Optional[Dict[str, float]] = None,
             drain_grace_s: float = 60.0,
             poll_s: float = 0.2,
             env: Optional[Dict[str, str]] = None,
             report_path: Optional[str] = None) -> dict:
    """Run one seeded soak against ``config`` and return the graded
    report (also written to ``<logdir>/soak_report.json``).

    The run ends at whichever comes first: the config's
    ``total_environment_frames``, or ``budget_s`` of wall clock — at
    the budget the engine SIGTERMs the fleet and the preemption grace
    protocol drains it to one final verified checkpoint.  Events still
    pending at exit are reported under ``planned_not_injected``."""
    config = dataclasses.replace(config, chaos_channel=True)
    num_processes = config.distributed_num_processes or 1
    schedule = sample_schedule(
        seed, num_faults, budget_s, points=points, weights=weights,
        num_processes=num_processes, recovery_s=recovery_s)
    os.makedirs(config.logdir, exist_ok=True)
    cmd = _worker_command(config)
    run_env = dict(os.environ)
    run_env.update(env or {})
    log.info("soak: launching %s (seed=%d, %d scheduled fault(s), "
             "budget %.0fs)", " ".join(cmd[:3]), seed, len(schedule),
             budget_s)
    started_unix = time.time()
    start = time.monotonic()
    proc = subprocess.Popen(cmd, env=run_env)
    pending = list(schedule)
    injected: List[dict] = []
    drain_sent = False
    try:
        while proc.poll() is None:
            elapsed = time.monotonic() - start
            while pending and pending[0]["t_s"] <= elapsed:
                # Stamp the SCHEDULE entry itself (not a copy):
                # grade_soak tells planned-but-never-injected events
                # apart by the missing t_unix.
                event = pending.pop(0)
                event["t_unix"] = _append_channel_line(config.logdir,
                                                       event)
                injected.append(event)
                log.info("soak: t=%.1fs injected %r%s", elapsed,
                         event["point"],
                         "" if event.get("proc") is None
                         else f" (proc {event['proc']})")
            if not drain_sent and elapsed >= budget_s:
                drain_sent = True
                log.info("soak: budget reached — draining the run to "
                         "its final checkpoint")
                try:
                    proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
            if drain_sent and elapsed >= budget_s + drain_grace_s:
                log.error("soak: drain grace exhausted — killing")
                proc.kill()
                break
            time.sleep(poll_s)
        rc = proc.wait()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    finished_unix = time.time()
    report = grade_soak(
        config.logdir, injected=injected,
        planned=schedule, frames_per_update=config.frames_per_update(),
        throughput_floor=throughput_floor,
        mttr_ceiling_s=mttr_ceiling_s,
        warmup_until_unix=started_unix
        + budget_s * SCHEDULE_WARMUP_FRAC,
        meta={
            "seed": seed,
            "num_faults": num_faults,
            "budget_s": budget_s,
            "num_processes": num_processes,
            "mode": "fleet" if num_processes > 1 else "single",
            "worker_rc": rc,
            "drained": drain_sent,
            "started_unix": round(started_unix, 3),
            "wall_s": round(finished_unix - started_unix, 3),
        })
    path = write_report(config.logdir, report, path=report_path)
    log.info("soak: %s — report at %s",
             "PASS" if report["pass"] else "FAIL", path)
    return report


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _format_report(report: dict) -> str:
    lines = [
        f"chaos soak: {'PASS' if report.get('pass') else 'FAIL'} "
        f"(seed={report.get('seed')}, mode={report.get('mode')}, "
        f"wall {report.get('wall_s')}s, worker rc "
        f"{report.get('worker_rc')})",
        f"  injected: {len(report.get('injected', []))} event(s) "
        f"across points {report.get('points')}",
    ]
    for name, verdict in sorted(report.get("invariants", {}).items()):
        evidence = {k: v for k, v in verdict.items() if k != "ok"}
        lines.append(
            f"  [{'ok' if verdict.get('ok') else 'FAIL'}] {name}: "
            f"{json.dumps(evidence, sort_keys=True)}")
    skipped = report.get("planned_not_injected") or []
    if skipped:
        lines.append(f"  note: {len(skipped)} scheduled event(s) "
                     f"never injected (run ended first)")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m scalable_agent_tpu.runtime.soak run|report``."""
    from scalable_agent_tpu.config import Config

    argv = list(sys.argv[1:] if argv is None else argv)
    parser = argparse.ArgumentParser(
        prog="python -m scalable_agent_tpu.runtime.soak",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("command", choices=("run", "report"))
    parser.add_argument("--soak_seed", type=int, default=0)
    parser.add_argument("--soak_faults", type=int, default=6)
    parser.add_argument("--soak_budget_s", type=float, default=120.0)
    parser.add_argument(
        "--soak_points", type=str, default="",
        help="comma-separated chaos points to sample (default: every "
             "positive-weight point valid for the fleet size)")
    parser.add_argument("--soak_floor", type=float, default=0.8)
    parser.add_argument("--soak_mttr_ceiling_s", type=float,
                        default=180.0)
    parser.add_argument("--soak_report", type=str, default="",
                        help="report path (default "
                             "<logdir>/soak_report.json)")
    parser.add_argument("--logdir", type=str, default="",
                        help="(report) the soaked run's logdir")
    args, rest = parser.parse_known_args(argv)

    if args.command == "report":
        logdir = args.logdir or (rest[0] if rest else "")
        if not logdir:
            parser.error("report needs --logdir")
        report = read_soak_report(logdir)
        if report is None:
            print(f"no {SOAK_REPORT_NAME} under {logdir}")
            return 1
        print(_format_report(report))
        return 0 if report.get("pass") else 1

    if args.logdir:
        rest = [f"--logdir={args.logdir}"] + rest
    config = Config.from_argv(
        rest,
        description="chaos soak worker config (the driver's flag "
                    "surface)")
    if config.mode != "train":
        raise ValueError("the soak engine drives --mode=train runs")
    points = ([p.strip() for p in args.soak_points.split(",")
               if p.strip()] or None)
    report = run_soak(
        config, seed=args.soak_seed, num_faults=args.soak_faults,
        budget_s=args.soak_budget_s, points=points,
        throughput_floor=args.soak_floor,
        mttr_ceiling_s=args.soak_mttr_ceiling_s,
        report_path=args.soak_report or None)
    print(_format_report(report))
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
