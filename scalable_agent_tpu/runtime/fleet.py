"""Fleet fault domains: peer health, collective timeouts, preemption grace.

The multi-host SPMD replacement for IMPALA's gRPC actor-learner split
(parallel/distributed.py) has one failure mode PRs 1-4 never touched:
when a PEER dies — host preempted, process OOM-killed, coordinator gone
— every survivor hangs forever inside the next collective (the update's
gradient all-reduce, the checkpoint decision broadcast, the Orbax
allgather) with no detection, no forensics, and no exit.  On a
preemptible TPU fleet that is the COMMON failure, not the rare one.
This module converts "infinite hang" into "bounded, checkpointed,
restartable failure":

- **Peer heartbeats** ride the ``jax.distributed`` key-value store the
  job already stands up: each process publishes a monotonic sequence
  number under ``fleet/hb/<proc>``; a monitor thread watches every
  OTHER peer's sequence and declares a peer lost when it stops
  advancing for ``--peer_timeout_s`` of LOCAL monotonic time (remote
  wall clocks are never trusted).  A lost peer — or an unreachable KV
  service, which is how a dead coordinator looks — triggers a forensic
  flight-recorder dump and a bounded exit **72**
  (``FLEET_EXIT_CODE``, joining the watchdog's 70 and the non-finite
  guard's 71 in runtime/exit_codes.py) instead of a hang.

- **Collective timeouts**: the driver/checkpoint/transport layers wrap
  their blocking cross-process points in ``fleet.collective(name)``;
  the monitor flags any armed collective older than
  ``--collective_timeout_s`` and, on ANY fatal, dumps the set of
  in-flight collectives with their ages — so a peer lost mid-allgather
  is *attributed*, not just detected.

- **Preemption grace**: SIGTERM no longer just dumps and dies.  The
  handler raises a preemption flag (``fleet/hb/preempt`` — under the
  heartbeat prefix, so the monitor's one per-poll dir-get serves both
  reads) through the KV store — pushed by the publisher thread, never
  by gRPC from signal context — so EVERY process observes it; the driver consumes the
  coordinator's broadcast verdict at its fixed per-iteration decision
  point, drains the in-flight window, takes ONE coordinated final
  verified checkpoint, and exits 0 for frame-exact resume.  The grace
  window (``--preemption_grace_s``) is a hard deadline: a drain or
  save that outlives it gets the forensic dump + exit 72 instead of
  stretching the preemption SLA.  A second SIGTERM escalates to the
  legacy dump-and-exit immediately.

Chaos points (runtime/faults.py; per-process ``--chaos_spec``, so a
multi-process soak arms them on ONE peer): ``peer_exit``
(``os._exit(1)`` — sudden peer death), ``peer_hang`` (the heartbeat
publisher falls silent forever — a wedged-but-alive peer), and
``preempt_sigterm`` (the process SIGTERMs itself — deterministic
preemption).  Occurrence indices count monitor cycles.

Known bound on this jax/jaxlib, now MITIGATED (ISSUE 6): if the
COORDINATOR process is SIGKILL'd, peers die on jax's own client
fatal (SIGABRT 134) before the ``kv_unreachable`` deadline can
convert it to 72 — the client's ``PollForError`` long-poll notices
the closed socket in ~2s, faster than any KV-poll cadence, and there
is no Python hook to run ring-dump code inside ``abort()`` (injecting
``missed_heartbeat_callback`` fails with ``std::bad_cast`` on this
jaxlib).  Two layers make the path forensic anyway: (1) the crash
handlers (obs/flightrec.py) enable the C-level ``faulthandler`` on
the fatal signals, which synchronously writes every thread's stack to
``stacks.sigabrt.<pid>.txt`` as the process dies — the GUARANTEED
artifact on the abort path; (2) the monitor's first failed KV poll
fires an early ``kv_suspect`` ring dump on a helper thread, covering
the shapes where the KV plane degrades WITHOUT a client fatal (a
wedged-but-alive coordinator, a partitioned KV service) and any rig
where the abort loses the race.  Exit 134 (signal 6) is documented in
docs/robustness.md; a supervisor treats it like 72 (restart and
resume).  The kv_unreachable path still owns the
host-alive-but-service-wedged shape, and exit-72 ordering is arranged
so OUR fatals never trigger the abort: the service-hosting process
lingers and exits last.

Elastic membership (ISSUE 6, runtime/elastic.py): every fatal verdict,
preemption decision, and exception unwinding the training loop
(``note_fatal_error`` — the driver's finally calls it first, before
any teardown step jax's client fatal could abort) also lands a
machine-readable membership verdict at ``<logdir>/fleet_epoch.json``
(epoch, kind, lost peers, last verified checkpoint step) — the
artifact the elastic supervisor consumes to decide between a reshard
relaunch, a rejoin scale-up, and "the run actually finished".  The driver feeds
``note_checkpoint(step)`` after every verified save so the verdict
names the newest resumable step, and the ``fleet/epoch`` gauge puts
the membership epoch on the metrics plane (obs/aggregate.py folds it
max across processes).

Everything here is testable without a real fleet: ``PeerTracker`` and
``GraceWindow`` are pure deadline math over injected timestamps, and
``FleetMonitor`` takes an injectable KV client, clock, and fatal hook
(tests/test_fleet.py).  Disabled (the default outside driver.train),
``get_fleet()`` is a null object whose hot-path calls are single no-op
method lookups, the same discipline as the watchdog.
"""

import contextlib
import itertools
import json
import os
import signal
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from scalable_agent_tpu.obs import get_flight_recorder, get_registry
from scalable_agent_tpu.runtime.exit_codes import FLEET_EXIT_CODE
from scalable_agent_tpu.runtime.faults import get_fault_injector
from scalable_agent_tpu.utils import log

__all__ = [
    "EPOCH_VERDICT_NAME",
    "FleetMonitor",
    "GraceWindow",
    "PeerTracker",
    "configure_fleet",
    "get_fleet",
    "install_preemption_handler",
]

_HB_PREFIX = "fleet/hb/"
# The preemption flag lives UNDER the heartbeat prefix so the monitor's
# single per-poll ``key_value_dir_get`` serves both (a second dir-get
# per process per poll would double the coordinator's steady-state KV
# load for a once-per-run event); the peer-parse loop skips it by name.
_PREEMPT_LEAF = "preempt"
_PREEMPT_KEY = _HB_PREFIX + _PREEMPT_LEAF
# Fatal-path dump budget: the forensic helper waits up to _DUMP_BLOCK_S
# for the dump lock (an unwinding exception's own dump may hold it) and
# is joined for at most _DUMP_JOIN_S before the process exits.
_DUMP_BLOCK_S = 10.0
_DUMP_JOIN_S = 15.0
# Membership verdict the elastic supervisor consumes (ISSUE 6).
EPOCH_VERDICT_NAME = "fleet_epoch.json"
_EPOCH_VERDICT_SCHEMA = 1


def _kv_client():
    """The live ``jax.distributed`` KV-store client, or None outside an
    initialized multi-process job.  Internal jax surface, so failures
    degrade to "no KV" rather than raising."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # pragma: no cover - jax internals moved
        return None


class PeerTracker:
    """Pure heartbeat-staleness math over caller-supplied timestamps.

    A peer is judged by whether its published sequence number ADVANCES,
    timed on the OBSERVER's monotonic clock — never by comparing remote
    timestamps, which preemptible fleets skew freely.  A peer that has
    not published at all is measured from ``start_time``, so a process
    that dies before its first heartbeat is still detected.
    """

    def __init__(self, expected_peers, start_time: float):
        self._last_seq: Dict[int, Optional[int]] = {
            int(p): None for p in expected_peers}
        self._last_change: Dict[int, float] = {
            int(p): float(start_time) for p in expected_peers}

    def note(self, peer: int, seq: int, now: float):
        """Fold one observed (peer, sequence) sample in.  Unknown peers
        (a re-run sharing the KV namespace) are tracked from first
        sight."""
        peer = int(peer)
        if peer not in self._last_seq:
            self._last_seq[peer] = None
            self._last_change[peer] = float(now)
        if seq != self._last_seq[peer]:
            self._last_seq[peer] = seq
            self._last_change[peer] = float(now)

    def stale_peers(self, now: float, timeout_s: float
                    ) -> List[Tuple[int, float]]:
        """[(peer, seconds-since-last-advance)] beyond the deadline,
        most-stale first."""
        stale = [(peer, now - last)
                 for peer, last in self._last_change.items()
                 if now - last > timeout_s]
        stale.sort(key=lambda item: -item[1])
        return stale

    def alive_count(self, now: float, timeout_s: float) -> int:
        return sum(1 for last in self._last_change.values()
                   if now - last <= timeout_s)

    def last_seq(self, peer: int) -> Optional[int]:
        return self._last_seq.get(int(peer))


class GraceWindow:
    """Preemption-grace deadline accounting, injectable clock.

    ``open()`` is idempotent — the deadline is anchored at the FIRST
    observation of the preemption (local SIGTERM, KV flag, or broadcast
    verdict), whichever a process sees first, so re-observing through a
    second channel can never extend the window.
    """

    def __init__(self, grace_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.grace_s = float(grace_s)
        self._clock = clock
        self._opened_at: Optional[float] = None
        self.reason = ""

    @property
    def opened(self) -> bool:
        return self._opened_at is not None

    def open(self, reason: str = "") -> bool:
        """Anchor the window now (first call only).  Returns True when
        this call newly opened it."""
        if self._opened_at is not None:
            return False
        self._opened_at = self._clock()
        self.reason = reason
        return True

    def remaining(self) -> float:
        """Seconds left before the hard deadline (inf while closed,
        clamped at 0 once blown)."""
        if self._opened_at is None:
            return float("inf")
        return max(0.0, self._opened_at + self.grace_s - self._clock())

    def expired(self) -> bool:
        return (self._opened_at is not None
                and self._clock() - self._opened_at > self.grace_s)


class FleetMonitor:
    """Peer heartbeats + collective deadlines + the preemption flag.

    Two daemon threads: ``fleet-publish`` (heartbeat + preempt-flag
    pushes to the KV store; also the chaos points' host) and
    ``fleet-monitor`` (peer staleness, KV reachability, collective
    deadlines, grace enforcement).  Every fatal verdict funnels through
    ``_fatal``: peers/collectives snapshot into the flight recorder, a
    bounded forensic dump, then ``on_fatal(72)`` — ``os._exit`` in
    production, injectable for tests.
    """

    enabled = True

    def __init__(self, peer_timeout_s: float,
                 preemption_grace_s: float = 0.0,
                 collective_timeout_s: float = 0.0,
                 registry=None,
                 recorder=None,
                 process_index: Optional[int] = None,
                 num_processes: Optional[int] = None,
                 kv=None,
                 clock: Callable[[], float] = time.monotonic,
                 on_fatal: Optional[Callable[[int], None]] = None,
                 publish_interval_s: Optional[float] = None,
                 poll_interval_s: Optional[float] = None,
                 host_exit_linger_s: Optional[float] = None,
                 epoch: int = 0,
                 logdir: Optional[str] = None):
        if process_index is None or num_processes is None:
            import jax

            process_index = (jax.process_index() if process_index is None
                             else process_index)
            num_processes = (jax.process_count() if num_processes is None
                             else num_processes)
        self.process_index = int(process_index)
        self.num_processes = int(num_processes)
        self.peer_timeout_s = float(peer_timeout_s)
        self.preemption_grace_s = float(preemption_grace_s)
        # 0 = auto: collectives legitimately block for minutes on a
        # first-update compile or a big Orbax read, so the guard's
        # deadline sits far above the heartbeat deadline — the
        # heartbeat path is the fast detector, this one catches a peer
        # that still heartbeats but stopped entering collectives.
        self.collective_timeout_s = float(collective_timeout_s) or max(
            600.0, 4.0 * self.peer_timeout_s)
        self._kv = kv if kv is not None else _kv_client()
        self._clock = clock
        self._on_fatal = on_fatal or (lambda code: os._exit(code))
        self._recorder = recorder or get_flight_recorder()
        registry = registry or get_registry()
        self._peers_alive = registry.gauge(
            "fleet/peers_alive",
            "processes whose heartbeat advanced within the deadline "
            "(incl. this one)")
        self._peers_alive.set(float(self.num_processes))
        self._peer_lost = registry.counter(
            "fleet/peer_lost_total",
            "peer processes declared lost (stale heartbeat or "
            "unreachable KV service)")
        self._collective_timeouts = registry.counter(
            "fleet/collective_timeouts_total",
            "blocking cross-process points that outlived the "
            "collective deadline")
        self._preemptions = registry.counter(
            "fleet/preemptions_total",
            "preemption flags raised or observed by this process")
        registry.gauge(
            "fleet/peer_timeout_s",
            "configured peer heartbeat deadline").set(self.peer_timeout_s)
        # Elastic membership (runtime/elastic.py): the epoch this
        # process was launched into, and where the membership verdict
        # file lands.  The supervisor bumps the epoch on every
        # relaunch, so the aggregated (fold=max) gauge IS the fleet's
        # membership-history cursor.
        self.epoch = int(epoch)
        self._logdir = logdir
        self._last_verified_step = -1
        registry.gauge(
            "fleet/epoch",
            "elastic membership epoch this process was launched into "
            "(bumped by the supervisor on every reshard/rejoin "
            "relaunch)").set(float(self.epoch))
        self._kv_suspect_dumped = False

        beat = self.peer_timeout_s if self.peer_timeout_s > 0 else 4.0
        self._publish_s = publish_interval_s or max(0.2, min(2.0, beat / 5))
        self._poll_s = poll_interval_s or max(0.1, min(1.0, beat / 5))
        # Process 0 HOSTS the jax coordination service: the instant it
        # exits, every peer's error-poll RPC fails and jax's C++ client
        # LOG(FATAL)s them (SIGABRT 134) before their own monitors can
        # reach the bounded exit-72 verdict — this jaxlib exposes no
        # hook to soften that.  So on a fatal, the host lingers and
        # exits LAST.  The budget must cover a peer's WHOLE exit path,
        # not just heartbeat phase skew: its verdict can land up to
        # ~two polls after ours, and its forensic dump is bounded by
        # the _DUMP_JOIN_S join (the dump lock may be held up to
        # _DUMP_BLOCK_S by an unwinding exception's own dump — the
        # load-dependent race reason_pin exists for).
        self._host_linger_s = (host_exit_linger_s
                               if host_exit_linger_s is not None
                               else _DUMP_JOIN_S + 2.0 * self._poll_s
                               + 1.0)
        start = self._clock()
        self._tracker = PeerTracker(
            [p for p in range(self.num_processes)
             if p != self.process_index], start)
        self._grace = GraceWindow(self.preemption_grace_s, clock=clock)
        # Hot-path flag: one attribute read per driver iteration.
        self._preempt = False
        self._preempt_reason = ""
        self._preempt_push_needed = False
        self._preempt_counted = False
        self._announce_needed = False
        self._hb_seq = 0
        self._hung = False  # peer_hang chaos: publisher falls silent
        self._last_publish_ok: Optional[float] = None
        self._defer_noted = False
        self._kv_down_since: Optional[float] = None
        self._fatal_fired = False
        # token -> (name, armed_at, deadline); plain dict + lock, the
        # collective() hot path is two dict ops under a short lock.
        self._collectives: Dict[int, Tuple[str, float, float]] = {}
        self._coll_lock = threading.Lock()
        self._coll_tokens = itertools.count()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._uninstall_signal: Optional[Callable[[], None]] = None

    # -- hot path ----------------------------------------------------------

    def preemption_requested(self) -> bool:
        """One attribute read — the driver checks this every iteration."""
        return self._preempt

    @contextlib.contextmanager
    def collective(self, name: str, timeout_s: Optional[float] = None):
        """Arm a deadline around one blocking cross-process point.  The
        monitor attributes (and bounds) a hang inside the body; exiting
        disarms.  Single-process jobs arm nothing — their "collectives"
        are local."""
        if self.num_processes <= 1:
            yield
            return
        now = self._clock()
        deadline = now + (timeout_s if timeout_s is not None
                          else self.collective_timeout_s)
        token = next(self._coll_tokens)
        with self._coll_lock:
            self._collectives[token] = (name, now, deadline)
        try:
            yield
        finally:
            with self._coll_lock:
                self._collectives.pop(token, None)

    def note_checkpoint(self, step: int):
        """The driver landed (or restored) a VERIFIED checkpoint at
        ``step`` — remember it so a later membership verdict names the
        newest resumable step.  One int store; called at checkpoint
        cadence, not per update."""
        self._last_verified_step = max(self._last_verified_step,
                                       int(step))

    def note_fatal_error(self, error: BaseException):
        """An exception is unwinding the training loop.  In a
        multi-process fleet that is usually someone ELSE's death
        arriving early: the aborted collective's XlaRuntimeError (gloo
        fails fast on a reset connection) can beat the heartbeat
        deadline, and jax's own client fatal (SIGABRT) can then end
        the process mid-teardown — before the monitor ever judges the
        peer.  Land the membership verdict NOW (kind
        ``collective_error``), so the elastic supervisor always finds
        an epoch-stamped verdict no matter which exit path wins; the
        monitor's own fatal (richer — it names the stale peer) keeps
        precedence when it got there first, and may still overwrite
        this one later (last writer wins, both epoch-stamped)."""
        if self.num_processes <= 1 or self._fatal_fired:
            return
        detail = {"error_type": type(error).__name__,
                  "error": str(error)[:200]}
        self._recorder.record(
            "fleet_error", type(error).__name__,
            dict(detail,
                 in_flight_collectives=dict(
                     self.in_flight_collectives())))
        self._write_epoch_verdict("collective_error", detail)

    def in_flight_collectives(self) -> List[Tuple[str, float]]:
        """[(name, age_s)] of currently-armed collectives — the fatal
        dump's attribution payload."""
        now = self._clock()
        with self._coll_lock:
            return [(name, round(now - armed_at, 3))
                    for name, armed_at, _ in self._collectives.values()]

    # -- preemption --------------------------------------------------------

    def request_preemption(self, reason: str):
        """Raise the preemption flag from THIS process (the SIGTERM
        handler's path).  Signal-context safe: flag stores, the
        lock-free ring append, and a clock read — the KV push, the
        counter, and the log line all happen on the publisher/monitor
        threads (a handler taking the logging or instrument locks the
        interrupted frame may hold would self-deadlock, the same hazard
        install_crash_handlers dodges with its helper thread)."""
        newly = self._grace.open(reason)
        self._preempt = True
        self._preempt_reason = self._preempt_reason or reason
        self._preempt_push_needed = self._kv is not None
        if newly:
            self._recorder.record(
                "preempt", "requested",
                {"reason": reason, "grace_s": self.preemption_grace_s})
            self._announce_needed = True

    def _count_preemption(self):
        """Tick ``fleet/preemptions_total`` exactly once per run, from
        whichever non-signal-context path observes the preemption first
        (monitor announce, KV observation, or the driver's decision
        point)."""
        if not self._preempt_counted:
            self._preempt_counted = True
            self._preemptions.inc()

    def note_preempt_decision(self, update: int):
        """The driver committed to the coordinated drain at a known
        iteration (the broadcast verdict) — anchor the grace window on
        processes that learned of the preemption this way.  Counting
        here (driver thread) rather than waiting for the next monitor
        poll keeps ``fleet/preemptions_total`` ahead of a drain fast
        enough to write the final metrics snapshot within one poll
        interval."""
        self._grace.open("decision")
        self._count_preemption()
        self._preempt = True
        self._recorder.record(
            "preempt", "decision",
            {"update": int(update),
             "remaining_s": round(self._grace.remaining(), 3)})
        # A drained preemption exits 0 on EVERY process — exactly like
        # a completed run.  The verdict file is how the elastic
        # supervisor tells them apart (epoch-stamped, so a stale file
        # from a previous epoch can't read as this one's preemption).
        self._write_epoch_verdict(
            "preempt", {"update": int(update),
                        "reason": self._preempt_reason or "decision"})
        log.warning(
            "fleet: coordinated preemption drain at update %d "
            "(%.1fs of grace left)", update, self._grace.remaining())

    # -- publisher thread --------------------------------------------------

    def publish_once(self):
        """One heartbeat cycle: sequence bump + preempt-flag push.  KV
        errors are counted by the monitor's reachability check, not
        raised — a dead coordinator must not kill the publisher before
        the monitor can attribute it."""
        if self._hung or self._kv is None:
            return
        self._hb_seq += 1
        try:
            self._kv.key_value_set(
                f"{_HB_PREFIX}{self.process_index}",
                str(self._hb_seq), allow_overwrite=True)
            self._last_publish_ok = self._clock()
            if self._preempt_push_needed:
                self._kv.key_value_set(
                    _PREEMPT_KEY,
                    f"{self.process_index}:{self._preempt_reason}",
                    allow_overwrite=True)
                self._preempt_push_needed = False
        except Exception as exc:
            log.debug("fleet: heartbeat publish failed: %s", exc)

    def _own_publish_fresh(self, now: float) -> bool:
        """Whether THIS process's heartbeat went out on schedule
        recently.  False before the first successful publish and
        whenever the last one is older than a few publish intervals —
        the monitor's gate for the peer-lost verdict, so a starved or
        KV-stalled process never declares healthy peers dead."""
        if self._last_publish_ok is None:
            return False
        return (now - self._last_publish_ok
                <= max(3.0 * self._publish_s, 2.0))

    def _publish_loop(self):
        while not self._stop.wait(self._publish_s):
            try:
                self.publish_once()
            except Exception:  # must never die silently
                log.exception("fleet publisher cycle failed")

    # -- monitor thread ----------------------------------------------------

    def monitor_once(self, now: Optional[float] = None):
        """One monitor pass (the thread calls this every poll interval;
        tests call it directly with a mocked clock behind ``clock=``)."""
        now = self._clock() if now is None else now
        if self._fatal_fired:
            return
        # Chaos points (runtime/faults.py) ride the monitor cycle —
        # the one fleet thread that exists in BOTH single- and
        # multi-process runs, so `preempt_sigterm@N` drives the grace
        # protocol deterministically everywhere.  Occurrence indices
        # count monitor cycles.
        injector = get_fault_injector()
        if injector.active:
            if injector.should_fire("peer_exit"):
                log.error("chaos: peer_exit — dying without warning")
                os._exit(1)
            if injector.should_fire("preempt_sigterm"):
                log.warning("chaos: preempt_sigterm — SIGTERMing self")
                os.kill(os.getpid(), signal.SIGTERM)
            if injector.should_fire("peer_hang"):
                log.error("chaos: peer_hang — heartbeat falls silent")
                self._hung = True
        if self._announce_needed:
            # Deferred from the signal handler (see request_preemption).
            self._announce_needed = False
            self._count_preemption()
            log.warning(
                "fleet: preemption requested (%s) — raising the fleet "
                "flag, draining to a final checkpoint within %.0fs",
                self._preempt_reason, self.preemption_grace_s)
        multiprocess = self.num_processes > 1
        if multiprocess and self._kv is not None:
            # A KV read failure must NOT end the pass early: the grace
            # and collective deadlines below are exactly the
            # enforcement a dead coordinator would otherwise suspend
            # for up to peer_timeout_s.
            entries = None
            try:
                entries = self._kv.key_value_dir_get(_HB_PREFIX)
                self._kv_down_since = None
            except Exception as exc:
                # An unreachable KV service is how a dead COORDINATOR
                # looks from every other process: give it the same
                # deadline as a silent peer, then exit bounded.
                if self._kv_down_since is None:
                    self._kv_down_since = now
                    log.warning("fleet: KV store unreachable (%s) — "
                                "coordinator suspect, deadline %.0fs",
                                exc, self.peer_timeout_s)
                if not self._kv_suspect_dumped:
                    # Early forensics (once per run): a dead
                    # coordinator can SIGABRT this process through
                    # jax's own client fatal BEFORE the kv_unreachable
                    # deadline converts it to a bounded 72 — abort()
                    # runs no Python, so the ring dump must already be
                    # on disk by then.  Fire-and-forget helper thread:
                    # this is a suspicion, not a verdict, and the
                    # monitor pass must not block on the dump lock.
                    self._kv_suspect_dumped = True
                    self._recorder.record(
                        "fleet_suspect", "kv_unreachable",
                        {"error": str(exc)[:200]})
                    threading.Thread(
                        target=self._recorder.dump_all,
                        args=("fleet:kv_suspect",),
                        daemon=True, name="flightrec-dump").start()
                # Same opt-out as stale-peer detection: peer_timeout_s=0
                # disables the verdict (config.py), not "fatal on the
                # second failed poll".
                if self.peer_timeout_s > 0 and \
                        now - self._kv_down_since > self.peer_timeout_s:
                    self._fatal(
                        "kv_unreachable",
                        {"down_s": round(now - self._kv_down_since, 3),
                         "error": str(exc)[:200]},
                        lost_peers=[(-1, now - self._kv_down_since)])
                    return
        if multiprocess and self._kv is not None and entries is not None:
            for key, value in entries:
                peer = key[len(_HB_PREFIX):] if key.startswith(
                    _HB_PREFIX) else key.rsplit("/", 1)[-1]
                if peer == _PREEMPT_LEAF:
                    # The preemption flag shares the heartbeat prefix
                    # so this one dir-get serves both reads.
                    if not self._preempt:
                        origin, _, reason = str(value).partition(":")
                        if self._grace.open(f"peer:{origin}:{reason}"):
                            self._count_preemption()
                            self._recorder.record(
                                "preempt", "observed",
                                {"origin": origin, "reason": reason})
                            log.warning(
                                "fleet: preemption flag observed "
                                "(raised by process %s: %s)",
                                origin, reason)
                        self._preempt = True
                    continue
                try:
                    peer_id, seq = int(peer), int(value)
                except ValueError:
                    continue  # foreign key under the prefix
                if peer_id == self.process_index:
                    continue  # our own heartbeat is not a peer's
                self._tracker.note(peer_id, seq, now)
            alive = 1 + self._tracker.alive_count(now, self.peer_timeout_s)
            self._peers_alive.set(float(alive))
            stale = (self._tracker.stale_peers(now, self.peer_timeout_s)
                     if self.peer_timeout_s > 0 else [])
            if stale and not self._own_publish_fresh(now):
                # Self-check: OUR publisher is behind schedule, so the
                # whole heartbeat plane is suspect (host CPU crunch
                # during a fleet-wide first compile, a paused VM, a
                # slow KV service) — peers are seeing US as silent too.
                # Defer the verdict (the collective/grace deadlines
                # below still apply); peers that kept advancing clear
                # themselves on the next healthy observation, and a
                # truly dead peer still fatals once our own plane
                # recovers.
                if not self._defer_noted:
                    self._defer_noted = True
                    self._recorder.record(
                        "fleet_selfcheck", "defer_peer_lost",
                        {"peers": {str(p): round(age, 3)
                                   for p, age in stale}})
                    log.warning(
                        "fleet: own heartbeat publisher is behind "
                        "schedule — deferring peer-lost verdict on %s "
                        "until the local heartbeat plane recovers",
                        [p for p, _ in stale])
                stale = []
            elif self._defer_noted:
                self._defer_noted = False
            if stale:
                self._fatal(
                    "peer_lost",
                    {"peers": {str(p): round(age, 3)
                               for p, age in stale}},
                    lost_peers=stale)
                return
        with self._coll_lock:
            overdue = [(name, now - armed_at)
                       for name, armed_at, deadline
                       in self._collectives.values() if now > deadline]
        if overdue:
            self._collective_timeouts.inc(len(overdue))
            self._fatal(
                "collective_timeout",
                {"collectives": {name: round(age, 3)
                                 for name, age in overdue}})
            return
        if self._grace.expired():
            self._fatal(
                "preempt_grace_exceeded",
                {"grace_s": self.preemption_grace_s,
                 "reason": self._grace.reason})

    def _monitor_loop(self):
        while not self._stop.wait(self._poll_s):
            try:
                self.monitor_once()
            except Exception:  # must never die silently
                log.exception("fleet monitor pass failed")

    # -- membership verdict (elastic supervisor contract) ------------------

    def _write_epoch_verdict(self, kind: str, detail: dict,
                             lost_peers: Optional[
                                 List[Tuple[int, float]]] = None):
        """Atomic ``<logdir>/fleet_epoch.json``: the machine-readable
        membership verdict the elastic supervisor consumes.  Every
        process writes the same epoch/kind (last writer wins — the
        tmp+rename keeps the file always-parseable); ``lost_peers`` and
        ``last_verified_step`` tell the supervisor who to drop and
        where resume will land."""
        if not self._logdir:
            return
        payload = {
            "schema_version": _EPOCH_VERDICT_SCHEMA,
            "epoch": self.epoch,
            "kind": kind,
            "process_index": self.process_index,
            "num_processes": self.num_processes,
            "lost_peers": [int(p) for p, _ in (lost_peers or [])],
            "last_verified_step": self._last_verified_step,
            "detail": detail,
            "wrote_unix": time.time(),
        }
        path = os.path.join(self._logdir, EPOCH_VERDICT_NAME)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:
            log.exception(
                "fleet: could not write membership verdict %s", path)

    # -- fatal path --------------------------------------------------------

    def _fatal(self, kind: str, detail: dict,
               lost_peers: Optional[List[Tuple[int, float]]] = None):
        """Bounded exit 72 with attribution: the peers/collectives
        snapshot goes in the ring, the forensic dump runs on a bounded
        helper thread (the wedged resource may be exactly what a dump
        touches — same rationale as the watchdog), then ``on_fatal``."""
        if self._fatal_fired:
            return
        self._fatal_fired = True
        if lost_peers:
            self._peer_lost.inc(len(lost_peers))
            for peer, age in lost_peers:
                self._recorder.record(
                    "peer_lost", str(peer), {"stale_s": round(age, 3)})
            self._peers_alive.set(
                float(max(1, self.num_processes - len(lost_peers))))
        in_flight = self.in_flight_collectives()
        self._recorder.record(
            "fleet_fatal", kind,
            dict(detail, in_flight_collectives=dict(in_flight)))
        # Membership verdict BEFORE the dump: the supervisor's reshard
        # decision must never wait on (or lose a race with) the
        # forensic dump budget.
        self._write_epoch_verdict(kind, detail, lost_peers=lost_peers)
        log.error(
            "fleet: %s %s — in-flight collectives: %s — dumping "
            "forensics and exiting %d (restart resumes from the last "
            "checkpoint)", kind, detail,
            in_flight or "none", FLEET_EXIT_CODE)
        # Pin the attribution BEFORE dumping: the aborted collective's
        # XlaRuntimeError is about to unwind the main thread and its
        # exception dump may run after ours — the pin keeps this
        # verdict as the dump's reason either way (the late dump still
        # refreshes the events, its own reason demoted to
        # ``secondary_reason``).
        self._recorder.reason_pin = f"fleet:{kind}"
        dumper = threading.Thread(
            target=self._recorder.dump_all,
            # Blocking: an exception already unwinding may hold the
            # dump lock with a pre-verdict dump — ours must land, it
            # carries the peer_lost/fleet_fatal attribution.
            args=(f"fleet:{kind}",), kwargs={"blocking_s": _DUMP_BLOCK_S},
            daemon=True, name="flightrec-dump")
        dumper.start()
        dumper.join(timeout=_DUMP_JOIN_S)
        survivors = self.num_processes - 1 - len(lost_peers or [])
        if self.num_processes > 1 and self.process_index == 0 \
                and survivors > 0:
            # Coordination-service host exits last (see __init__) —
            # but only while another SURVIVOR still needs the service
            # for its own verdict + dump.  When every other peer is
            # already in the lost set (the 2-process reshard, a
            # correlated N-process failure) the linger protects nobody
            # and would sit squarely on the elastic supervisor's
            # detect segment of MTTR.
            time.sleep(self._host_linger_s)
        self._on_fatal(FLEET_EXIT_CODE)

    # -- lifecycle ---------------------------------------------------------

    def start(self, install_signal: bool = True) -> "FleetMonitor":
        """Start the publisher/monitor threads (idempotent) and, by
        default, take over SIGTERM for the grace protocol."""
        if install_signal and self.preemption_grace_s > 0 \
                and self._uninstall_signal is None:
            self._uninstall_signal = install_preemption_handler(self)
        if not self._threads:
            if self._kv is not None and self.num_processes > 1:
                publisher = threading.Thread(
                    target=self._publish_loop, daemon=True,
                    name="fleet-publish")
                publisher.start()
                self._threads.append(publisher)
            monitor = threading.Thread(
                target=self._monitor_loop, daemon=True,
                name="fleet-monitor")
            monitor.start()
            self._threads.append(monitor)
        return self

    def stop(self):
        self._stop.set()
        for thread in self._threads:
            thread.join(timeout=5)
        self._threads = []
        if self._uninstall_signal is not None:
            self._uninstall_signal()
            self._uninstall_signal = None
        # A stopped fleet must not freeze a stale aliveness reading
        # into the final metrics snapshot.
        self._peers_alive.set(float(self.num_processes))


class _DisabledFleet:
    """Null object: the driver-adjacent call sites run unconditionally
    and the disabled fleet makes each a single no-op method call."""

    enabled = False
    num_processes = 1
    peer_timeout_s = 0.0
    preemption_grace_s = 0.0

    def preemption_requested(self) -> bool:
        return False

    def collective(self, name: str, timeout_s: Optional[float] = None):
        return contextlib.nullcontext()

    def request_preemption(self, reason: str):
        pass

    def note_preempt_decision(self, update: int):
        pass

    def note_checkpoint(self, step: int):
        pass

    def note_fatal_error(self, error: BaseException):
        pass

    def in_flight_collectives(self):
        return []

    def stop(self):
        pass


_DISABLED = _DisabledFleet()
_fleet = _DISABLED
_fleet_lock = threading.Lock()


def get_fleet():
    return _fleet


def configure_fleet(peer_timeout_s: Optional[float], **kwargs):
    """Install (and return) the process-global fleet monitor.  ``None``
    stops any live monitor and restores the disabled null object;
    otherwise a monitor is started whenever either protection is
    enabled (heartbeats need a multi-process job, the preemption grace
    protocol does not).  The enablement check runs BEFORE construction:
    a run that stays disabled must not get ``fleet/*`` series
    registered into its metrics."""
    global _fleet
    with _fleet_lock:
        old, _fleet = _fleet, _DISABLED
        old.stop()
        if peer_timeout_s is None:
            return _fleet
        grace_s = float(kwargs.get("preemption_grace_s", 0.0) or 0.0)
        num_processes = kwargs.get("num_processes")
        if num_processes is None:
            import jax

            num_processes = jax.process_count()
        if (grace_s > 0
                or (float(peer_timeout_s) > 0 and int(num_processes) > 1)):
            _fleet = FleetMonitor(peer_timeout_s, **kwargs).start()
        return _fleet


def install_preemption_handler(fleet: FleetMonitor,
                               handled_signals=(signal.SIGTERM,)
                               ) -> Callable[[], None]:
    """SIGTERM -> preemption grace instead of dump-and-die.

    The first SIGTERM records the request and RETURNS — the run keeps
    control and drains to its coordinated checkpoint; the fleet
    monitor's grace deadline bounds how long that may take.  A second
    SIGTERM chains to the PREVIOUS handler (the flight recorder's
    dump + ``SystemExit(143)``) for an operator who wants out now.
    Installed over the crash handlers, uninstalled by ``stop()``.
    Signal handlers need the main thread; elsewhere this layer is
    skipped silently (same contract as install_crash_handlers).
    """
    prev: Dict[int, object] = {}
    installed: Dict[int, object] = {}
    # Escalation keys on "THIS process was already signalled", not the
    # fleet-wide preemption flag: a process that learned of the
    # preemption via the KV flag or the broadcast verdict is mid-drain,
    # and its own (first) SIGTERM — routine when a scheduler signals
    # every process with seconds of delivery skew — must join the
    # coordinated drain, not abort it with the legacy dump-and-exit.
    signalled = set()
    try:
        for sig in handled_signals:
            def _on_signal(signum, frame):
                if signum in signalled:
                    handler = prev.get(signum)
                    if callable(handler):
                        handler(signum, frame)
                        return
                    raise SystemExit(128 + signum)
                signalled.add(signum)
                fleet.request_preemption(
                    f"signal:{signal.Signals(signum).name}")

            prev[sig] = signal.signal(sig, _on_signal)
            installed[sig] = _on_signal
    except ValueError:  # not the main thread
        prev.clear()
        installed.clear()

    def uninstall():
        # Identity-checked: the driver tears obs down BEFORE the fleet
        # (the fleet must cover the whole teardown tail), and the obs
        # uninstall restores its own pre-obs handler over ours —
        # re-installing the saved (obs) handler after that would leak a
        # dead recorder's handler into the next in-process run.
        for sig, handler in prev.items():
            try:
                if signal.getsignal(sig) is installed.get(sig):
                    signal.signal(sig, handler)
            except ValueError:
                pass

    return uninstall
