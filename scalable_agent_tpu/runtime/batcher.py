"""Dynamic batching service for actor inference.

Re-design of the reference's C++ TF-op batcher + Python decorator
(reference: batcher.cc:91-204 state machine; dynamic_batching.py:65-162
``batch_fn_with_options``) as a host-side service in front of a jitted TPU
function:

- Callers (actor threads) submit single samples and block on a Future.
- A consumer thread forms batches under min_batch_size / max_batch_size /
  timeout_ms semantics: waits for ``min``; a timeout after the *first*
  pending request flushes a partial batch (reference:
  dynamic_batching.py:96-98); never exceeds ``max`` per batch
  (batcher.cc:241-258).
- Results scatter back row-by-row to each caller's Future; batches are
  correlated by id, and multiple consumers may complete out of order
  (reference: batcher.cc:316-327, dynamic_batching_test.py:334-375).
- ``close()`` cancels all pending and in-flight callers with an error
  (reference: batcher.cc:393-431).

Differences by design: callers pass *unbatched* pytrees (the reference
requires a leading batch dim of exactly 1 and validates it,
batcher.cc:282-285 — an artifact of TF ops; a host API can just take the
sample).  Padding: if a formed batch is smaller than ``pad_to_sizes``'s
smallest fit, inputs are padded so the jitted function sees a small, fixed
set of batch shapes (XLA recompiles per shape; the reference's TF graph
had the same constraint solved by static shapes,
dynamic_batching.py:125-128).
"""

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Optional, Sequence

import numpy as np

from scalable_agent_tpu.obs import (
    get_flight_recorder,
    get_ledger,
    get_registry,
    get_tracer,
    get_watchdog,
)
from scalable_agent_tpu.types import map_structure


class BatcherClosedError(RuntimeError):
    """Raised to callers whose requests were cancelled by close()."""


# -- shared batch formation ---------------------------------------------------
# The bucketing policy every batching front-end shares: DynamicBatcher,
# NativeBatcher, ActorPool's service-mode ladder, and the continuous-
# batching actor service (runtime/service.py).  One implementation so
# "how many distinct batch shapes can XLA see" has one answer.


def bucket_ladder(maximum: int, minimum: int = 1) -> list:
    """Power-of-two pad sizes ``[minimum, 2*minimum, ..., maximum]``.

    Padding formed batches up the ladder bounds the set of batch shapes
    a jitted compute function sees to ~log2(maximum) — the recompile
    bound the reference solved with static graph shapes
    (dynamic_batching.py:125-128)."""
    if maximum < 1:
        raise ValueError(f"maximum must be >= 1, got {maximum}")
    sizes = [max(1, min(int(minimum), maximum))]
    while sizes[-1] < maximum:
        sizes.append(min(sizes[-1] * 2, maximum))
    return sizes


def pad_to_bucket(n: int, sizes: Optional[Sequence[int]]) -> int:
    """Smallest bucket in ascending ``sizes`` holding ``n`` valid rows
    (``n`` itself when no bucket fits or bucketing is disabled)."""
    if sizes is None:
        return n
    for size in sizes:
        if size >= n:
            return size
    return n


class _Request:
    __slots__ = ("sample", "future", "enqueued_at")

    def __init__(self, sample):
        self.sample = sample
        self.future = Future()
        self.enqueued_at = time.monotonic()


class DynamicBatcher:
    """Batch single-sample calls onto ``compute_fn``.

    ``compute_fn(batched_sample_tree, batch_size) -> batched_result_tree``
    where every leaf of the input has a leading batch dim and the result's
    leaves must too.  ``batch_size`` is the *valid* (unpadded) row count.

    Args mirror ``batch_fn_with_options`` (reference:
    dynamic_batching.py:65-102): minimum_batch_size, maximum_batch_size,
    timeout_ms.  ``pad_to_sizes`` (ascending) quantizes batch shapes to
    bound XLA recompilation; None disables padding.
    """

    def __init__(
        self,
        compute_fn: Callable[[Any, int], Any],
        minimum_batch_size: int = 1,
        maximum_batch_size: int = 1024,
        timeout_ms: Optional[float] = 100.0,
        pad_to_sizes: Optional[Sequence[int]] = None,
        num_consumers: int = 1,
        metrics_name: str = "batcher",
        registry=None,
    ):
        if minimum_batch_size > maximum_batch_size:
            raise ValueError("minimum_batch_size > maximum_batch_size")
        if pad_to_sizes is not None:
            pad_to_sizes = sorted(pad_to_sizes)
            if pad_to_sizes[-1] < maximum_batch_size:
                raise ValueError(
                    "largest pad_to_sizes must cover maximum_batch_size")
        self._compute_fn = compute_fn
        self._min = minimum_batch_size
        self._max = maximum_batch_size
        self._timeout_s = None if timeout_ms is None else timeout_ms / 1000.0
        self._pad_to_sizes = pad_to_sizes

        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._pending = deque()
        self._closed = False
        self._batch_ids = itertools.count()

        # Observability: queue depth is sampled by callback at snapshot
        # time (zero hot-path cost); batch shape/latency histograms are
        # fed once per formed batch.  ``metrics_name`` disambiguates
        # coexisting batchers in one process.  Weak reference only: the
        # global registry must not keep a closed batcher alive.
        import weakref

        registry = registry or get_registry()
        pending_ref = weakref.ref(self._pending)
        registry.gauge(
            f"{metrics_name}/queue_depth", "requests awaiting a batch",
            fn=lambda: (len(p) if (p := pending_ref()) is not None
                        else 0.0))
        self._batch_size_hist = registry.histogram(
            f"{metrics_name}/batch_size", "valid rows per formed batch")
        self._occupancy_hist = registry.histogram(
            f"{metrics_name}/occupancy",
            "valid rows / maximum_batch_size per formed batch")
        self._latency_hist = registry.histogram(
            f"{metrics_name}/request_latency_s",
            "enqueue -> result seconds per request")
        self._batches_total = registry.counter(
            f"{metrics_name}/batches_total", "batches executed")

        self._consumers = [
            threading.Thread(target=self._consume_loop, daemon=True,
                             name=f"batcher-consumer-{i}")
            for i in range(num_consumers)
        ]
        for t in self._consumers:
            t.start()

    # -- caller side -------------------------------------------------------

    def compute(self, sample):
        """Submit one sample; block until its result row is ready."""
        return self.compute_async(sample).result()

    def compute_async(self, sample) -> Future:
        with get_tracer().span("batcher/enqueue"):
            with self._lock:
                if self._closed:
                    raise BatcherClosedError("batcher is closed")
                request = _Request(sample)
                self._pending.append(request)
                self._nonempty.notify()
        return request.future

    # -- consumer side -----------------------------------------------------

    def _take_batch(self):
        """Block until a batch is ready (min reached, or timeout after the
        first pending request), honoring max.  Returns None at close."""
        with self._lock:
            deadline = None
            while True:
                if self._closed:
                    return None
                if len(self._pending) >= self._min:
                    n = min(len(self._pending), self._max)
                    return [self._pending.popleft() for _ in range(n)]
                if not self._pending:
                    deadline = None
                    self._nonempty.wait()
                elif self._timeout_s is None:
                    self._nonempty.wait()
                else:
                    if deadline is None:
                        deadline = self._now() + self._timeout_s
                    remaining = deadline - self._now()
                    if remaining <= 0:  # flush a partial batch
                        n = min(len(self._pending), self._max)
                        return [self._pending.popleft() for _ in range(n)]
                    self._nonempty.wait(remaining)

    @staticmethod
    def _now():
        import time

        return time.monotonic()

    def _consume_loop(self):
        watchdog = get_watchdog()
        while True:
            # Disarm while blocked awaiting requests — an idle batcher
            # is not a wedge; re-arm for the batch execution, which IS
            # bounded work a stale heartbeat should flag.
            watchdog.suspend()
            batch = self._take_batch()
            if batch is None:
                return
            watchdog.touch()
            self._run_batch(batch)

    def _pad_rows(self, n: int) -> int:
        return pad_to_bucket(n, self._pad_to_sizes)

    def _run_batch(self, batch):
        n = len(batch)
        padded = self._pad_rows(n)
        self._batch_size_hist.observe(n)
        self._occupancy_hist.observe(n / self._max)
        self._batches_total.inc()
        try:
            started_at = time.monotonic()
            with get_tracer().span("batcher/run_batch",
                                   args={"n": n, "padded": padded}):
                stacked = map_structure(
                    lambda *rows: _stack_padded(rows, padded),
                    *[r.sample for r in batch])
                result = self._compute_fn(stacked, n)
                rows = _unstack(result, n)
            done_at = time.monotonic()
            # Ledger service stage (obs/ledger.py): arrivals + busy
            # seconds per executed batch feed the inference service's
            # queueing-model utilization ρ.
            get_ledger().note_service(
                "inference_service", n, done_at - started_at)
            for request, row in zip(batch, rows):
                self._latency_hist.observe(done_at - request.enqueued_at)
                request.future.set_result(row)
        except BaseException as exc:  # propagate to all callers in batch
            get_flight_recorder().record(
                "exception", type(exc).__name__,
                {"where": threading.current_thread().name})
            for request in batch:
                if not request.future.done():
                    request.future.set_exception(exc)

    # -- lifecycle ---------------------------------------------------------

    def close(self):
        """Cancel pending requests and stop consumers.

        (reference: batcher.cc:393-431 — close cascades errors to every
        waiting caller)
        """
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pending = list(self._pending)
            self._pending.clear()
            self._nonempty.notify_all()
        for request in pending:
            request.future.set_exception(
                BatcherClosedError("batcher closed while request pending"))
        for t in self._consumers:
            t.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.close()


def _stack_padded(rows, padded: int):
    arr = np.stack([np.asarray(r) for r in rows])
    if padded > arr.shape[0]:
        pad_widths = [(0, padded - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
        arr = np.pad(arr, pad_widths)
    return arr


def _unstack(tree, n: int):
    """Split a batched result pytree into n per-row pytrees."""
    # The transport module owns the shared flatten/unflatten helpers
    # (None treated as a leaf) used at every pytree<->rows boundary.
    from scalable_agent_tpu.runtime.transport import (
        tree_flatten_with_none,
        tree_unflatten,
    )

    leaves, treedef = tree_flatten_with_none(tree)
    rows = []
    for i in range(n):
        rows.append(tree_unflatten(treedef, [np.asarray(l)[i]
                                             for l in leaves]))
    return rows
