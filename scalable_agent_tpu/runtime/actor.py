"""Host-side actor runtime: experience generation feeding the learner.

Re-design of the reference's in-graph actor machinery (reference:
experiment.py:240-321 ``build_actor`` + QueueRunner threads :559-562) for
a host-runtime world:

- A ``VectorActor`` drives one vectorized env group: ONE jitted
  ``actor_step`` evaluates the whole group's policies as a single [B]
  batch on the TPU (the role of the reference's dynamic batcher — but
  batching is structural here, not opportunistic; the ``DynamicBatcher``
  service remains for irregular callers).
- Trajectory layout matches the reference exactly: each unroll emits T+1
  entries whose first entry is the last entry of the previous unroll, plus
  the LSTM state at the unroll boundary (reference: experiment.py:311-321).
  The learner drops the first behaviour entry and bootstraps from the last
  (runtime/learner.py).
- An ``ActorPool`` runs several groups in Python threads; while one group
  waits on env subprocess pipes, another's inference runs on device (the
  overlap the reference gets from async TF ops).  Trajectories flow
  through a bounded queue (capacity 1 per group — the policy-lag semantics
  of the reference's FIFOQueue(1), experiment.py:531).
- Weights: actors read a versioned host-side snapshot published by the
  learner loop (replacing implicit parameter-server variable reads,
  reference: experiment.py:503-505).
"""

import functools
import queue as queue_lib
import threading
import time
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from scalable_agent_tpu.models.agent import ImpalaAgent, actor_step, initial_state
from scalable_agent_tpu.envs.vector import MultiEnv
from scalable_agent_tpu.obs import (
    get_flight_recorder,
    get_ledger,
    get_registry,
    get_tracer,
    get_watchdog,
)
from scalable_agent_tpu.obs.ledger import now_us as ledger_now_us
from scalable_agent_tpu.types import (
    ActorOutput,
    AgentOutput,
    AgentState,
    map_structure,
)


def actor_stage_histograms(registry=None):
    """The shared per-step stage histograms every actor flavour feeds
    (and the stall attributor reads): (env_step_s, inference_s).  One
    registration point so the metric names can't drift apart across
    VectorActor / AccumVectorActor / GroupedAccumActor."""
    registry = registry or get_registry()
    return (
        registry.histogram(
            "actor/env_step_s",
            "seconds per vectorized env step (send+recv)"),
        registry.histogram(
            "actor/inference_s",
            "seconds per batched inference step (dispatch+fetch)"),
    )


def _to_numpy(tree):
    return map_structure(
        lambda x: None if x is None else np.asarray(x), tree)


def _stack_time(entries):
    """List of [B, ...] pytrees -> one [T, B, ...] pytree."""
    return map_structure(
        lambda *xs: None if xs[0] is None else np.stack(xs), *entries)


def snapshot_params_for_inference(params, device):
    """Re-place learner params as a private single-device snapshot.

    Shared by ActorPool.set_params and ActorService.set_params: the
    snapshot must be a real COPY — ``device_put`` aliases any existing
    copy the target device already holds (single-device meshes
    trivially; multi-device replicated params via their local shard),
    and the learner's donated update would free the aliased buffer out
    from under the actors ("Array has been deleted").  Params are
    small; the on-device copy is cheap."""

    def local_view(leaf):
        # Multi-host: a global array isn't fully addressable here.
        # Replicated leaves carry the full value in every local shard —
        # take this process's copy.  (Cross-host tensor-sharded params
        # would need a DCN gather; actors don't support that layout.)
        if (hasattr(leaf, "is_fully_addressable")
                and not leaf.is_fully_addressable):
            shard = leaf.addressable_shards[0].data
            if shard.shape != leaf.shape:
                raise NotImplementedError(
                    "actor inference needs replicated (or host-local) "
                    "params; got a cross-host-sharded leaf of shape "
                    f"{leaf.shape} with local shard {shard.shape}")
            return shard
        return leaf

    params = jax.tree_util.tree_map(local_view, params)
    params = jax.device_put(params, device)
    return jax.tree_util.tree_map(jnp.copy, params)


def publish_trajectory(queue, trajectory, stop, *, actor_name: str,
                       level_name: str, birth_us=None, frames: float = 0.0,
                       frames_counter=None, trajectories_counter=None
                       ) -> bool:
    """Hand one trajectory to the learner queue with full provenance.

    Opens the ledger record at the unroll's birth, binds it to the
    trajectory OBJECT (so the consumer recovers the id regardless of
    producer interleaving), blocks on the bounded queue re-touching the
    watchdog (a full queue is backpressure, not a wedge), and — when
    shutdown catches the hand-off — closes the record as ``abandoned``
    instead of leaking it open.  Returns True when delivered.  Shared
    by ActorPool's unroll loop and the ActorService trajectory packer
    (runtime/service.py)."""
    ledger = get_ledger()
    watchdog = get_watchdog()
    tid = ledger.open(actor_name, level_name or "actor",
                      birth_us=birth_us)
    ledger.stamp(tid, "unroll_done")
    ledger.bind(id(trajectory), tid)
    delivered = False
    with get_tracer().span("batcher/queue_put", cat="queue"):
        while not stop.is_set():
            watchdog.touch()
            try:
                queue.put(trajectory, timeout=0.1)
                delivered = True
                break
            except queue_lib.Full:
                continue
    if delivered:
        ledger.stamp(tid, "queue_put")
        get_flight_recorder().record("queue", "put")
        if trajectories_counter is not None:
            trajectories_counter.inc()
        if frames_counter is not None and frames:
            frames_counter.inc(frames)
    else:
        # Shutdown caught the hand-off: the record must not leak open
        # (and its binding must not alias a later object at the same
        # address).
        ledger.unbind(id(trajectory))
        ledger.close(tid, retired=False, fate="abandoned")
    return delivered


def consume_trajectory(queue, timeout: Optional[float] = None):
    """The learner-side half of the queue hand-off (ActorPool and
    ActorService ``get_trajectory``): pop one item, re-raise marshalled
    producer exceptions, recover the provenance record bound to the
    object and make it the consuming thread's CURRENT record so the
    transport/learner layers downstream stamp the right one."""
    with get_tracer().span("batcher/queue_get", cat="queue"):
        item = queue.get(timeout=timeout)
    get_flight_recorder().record("queue", "get")
    if isinstance(item, Exception):
        raise item
    ledger = get_ledger()
    tid = ledger.lookup(id(item))
    if tid is not None:
        ledger.stamp(tid, "queue_get")
    ledger.set_current(tid)
    return item


def merged_episode_stats(envs_iter):
    """Merged completed-episode (return, length) ring buffers across a
    fleet of MultiEnvs (ActorPool and ActorService share this)."""
    stats = []
    for envs in envs_iter:
        stats.extend(envs.episode_stats)
    return stats


def drain_level_stats(envs_iter):
    """Pop all level-attributed episodes completed since the last
    drain: {level_name: [(episode_return, episode_length), ...]}.

    Feeds multi-task per-level metrics and the DMLab-30 training suite
    score (reference: experiment.py:634-667, which clears the per-level
    lists after each score — draining gives the same
    each-episode-counted-once semantics).  popleft is atomic, so env
    threads can keep appending during the drain.  Shared by ActorPool
    and ActorService."""
    by_level = {}
    for envs in envs_iter:
        queue = getattr(envs, "level_episode_stats", None)
        if not queue:
            continue
        while True:
            try:
                level, ret, length = queue.popleft()
            except IndexError:
                break
            by_level.setdefault(level, []).append((ret, length))
    return by_level


def run_with_retry(loop_fn, *, stop: threading.Event, deliver,
                   reset=None, max_restarts: int = 3,
                   backoff_s: float = 0.5, backoff_cap_s: float = 30.0,
                   window_s: float = 600.0, restarts_counter=None):
    """Bounded-respawn shell around a producer thread's steady-state
    loop: a transient simulator/link fault must not end a multi-day run
    (docs/robustness.md).

    ``loop_fn`` runs until clean stop or an exception; a failure gets
    ``max_restarts`` respawns within a sliding ``window_s`` (crash-loop
    detection — isolated faults days apart age out) with capped
    exponential backoff, ``reset()`` called before each retry; the
    terminal exception goes to ``deliver(exc)`` (the queue hand-off
    that marshals it to the driver).  Shared by ActorPool's actor
    threads and the ActorService env-group threads."""
    from collections import deque as _deque

    from scalable_agent_tpu.utils import log

    recorder = get_flight_recorder()
    thread_name = threading.current_thread().name
    restart_times = _deque()
    try:
        while not stop.is_set():
            try:
                loop_fn()
                return  # clean stop
            except Exception as exc:
                if stop.is_set():
                    return  # shutdown cascade (e.g. batcher closed)
                recorder.record("exception", type(exc).__name__,
                                {"where": thread_name})
                now = time.monotonic()
                while (restart_times
                       and now - restart_times[0] > window_s):
                    restart_times.popleft()
                if len(restart_times) >= max_restarts:
                    # Budget spent: surface the terminal failure.  The
                    # deliver hand-off carries the exception to the
                    # driver; the flight-recorder dump preserves THIS
                    # thread's last moments (ring tail + every thread's
                    # stack) even if the driver never drains it.
                    recorder.dump_all(
                        f"exception:{type(exc).__name__}:{thread_name}")
                    deliver(exc)
                    return
                restart_times.append(now)
                in_window = len(restart_times)
                backoff = min(backoff_cap_s,
                              backoff_s * 2 ** (in_window - 1))
                if restarts_counter is not None:
                    restarts_counter.inc()
                recorder.record(
                    "actor_restart", thread_name,
                    {"restart": in_window, "max": max_restarts,
                     "backoff_s": round(backoff, 3),
                     "error": type(exc).__name__})
                log.error(
                    "actor %s failed (%s: %s) — restart %d/%d in the "
                    "%.0fs window, retrying in %.2fs",
                    thread_name, type(exc).__name__, exc, in_window,
                    max_restarts, window_s, backoff)
                # Idle backoff is not a wedge; the next loop's touch
                # re-arms the heartbeat.
                get_watchdog().suspend()
                if reset is not None:
                    try:
                        reset()
                    except Exception:
                        log.exception("actor %s reset failed before "
                                      "retry", thread_name)
                stop.wait(backoff)
    finally:
        get_watchdog().suspend()


def _service_step(agent, params, key_data, actions, env_outputs, states):
    """k co-batched group requests ([k, B, ...]) -> [k, B, ...] outputs.

    vmapped so each group keeps its own rng stream; params are shared
    across the vmap (one weight broadcast, k-fold batched compute)."""

    rngs = jax.random.wrap_key_data(key_data)  # [k] typed keys

    def one_group(rng, action, env_output, state):
        return actor_step(agent, params, rng, action, env_output, state)

    return jax.vmap(one_group)(rngs, actions, env_outputs, states)


class VectorActor:
    """One env group: batched inference + trajectory accumulation."""

    def __init__(
        self,
        agent: ImpalaAgent,
        envs: MultiEnv,
        unroll_length: int,
        level_name: str = "",
        seed: int = 0,
        step_fn: Optional[Callable] = None,
    ):
        self._agent = agent
        self._envs = envs
        self._unroll_length = unroll_length
        self.level_name = level_name
        self._rng = jax.random.key(seed)
        self._step_count = 0
        # One jitted inference step shared by everything that hands us the
        # same agent (jit caches on shapes).
        self._actor_step = step_fn or jax.jit(
            lambda params, rng, action, env_output, state: actor_step(
                agent, params, rng, action, env_output, state))
        self._last_env_output = None
        self._last_agent_output = None
        self._core_state = None
        self._h_env, self._h_infer = actor_stage_histograms()

    def _bootstrap(self, params):
        """First-ever unroll: create the initial carried entries.

        The reference initializes persistent state from a zero action and
        a zero agent output (experiment.py:243-251).
        """
        batch = self._envs.num_envs
        self._last_env_output = self._envs.initial()
        self._core_state = initial_state(batch, self._agent.core_size)
        self._last_agent_output = AgentOutput(
            action=np.asarray(self._agent.zero_actions(batch)),
            policy_logits=np.zeros(
                (batch, self._agent.num_logits), np.float32),
            baseline=np.zeros((batch,), np.float32),
        )

    def run_unroll(self, params) -> ActorOutput:
        """Generate one [T+1, B] trajectory batch under ``params``."""
        # Ledger birth stamp (obs/ledger.py): the moment this unroll's
        # first env step happens — the age every downstream staleness/
        # latency number is measured from.  The pool reads it when it
        # opens the trajectory's provenance record.
        self.unroll_birth_us = ledger_now_us()
        if self._last_env_output is None:
            self._bootstrap(params)

        env_entries = [self._last_env_output]
        agent_entries = [self._last_agent_output]
        first_state = _to_numpy(
            AgentState(c=self._core_state.c, h=self._core_state.h))

        env_output = self._last_env_output
        agent_output = self._last_agent_output
        core_state = self._core_state
        tracer = get_tracer()
        watchdog = get_watchdog()
        for _ in range(self._unroll_length):
            watchdog.touch()  # per-step heartbeat: one dict store
            self._step_count += 1
            rng = jax.random.fold_in(self._rng, self._step_count)
            t0 = time.perf_counter()
            with tracer.span("actor/inference", cat="actor"):
                out, core_state = self._actor_step(
                    params, rng, agent_output.action, env_output,
                    core_state)
                agent_output = _to_numpy(out)
            t1 = time.perf_counter()
            # Dispatch env steps, then wait — device work for other groups
            # can run while this thread blocks on the pipes.
            with tracer.span("actor/env_step", cat="actor"):
                self._envs.step_send(agent_output.action)
                env_output = self._envs.step_recv()
            self._h_infer.observe(t1 - t0)
            self._h_env.observe(time.perf_counter() - t1)
            env_entries.append(env_output)
            agent_entries.append(agent_output)

        self._last_env_output = env_output
        self._last_agent_output = agent_output
        self._core_state = core_state

        return ActorOutput(
            level_name=self.level_name,
            agent_state=first_state,
            env_outputs=_stack_time(env_entries),
            agent_outputs=_stack_time(agent_entries),
        )

    def reset(self):
        """Drop the carried unroll state after a mid-unroll failure
        (ActorPool's retry path): re-align the env pipes and force a
        fresh bootstrap — the next unroll starts from clean initial
        outputs instead of a half-stepped carry."""
        resync = getattr(self._envs, "resync", None)
        if resync is not None:
            resync()
        self._last_env_output = None
        self._last_agent_output = None
        self._core_state = None

    def close(self):
        self._envs.close()


class ActorPool:
    """N groups of vectorized actors on threads, feeding a bounded queue.

    Two inference modes:

    - ``structural`` (default): each group evaluates its own jitted
      ``actor_step`` on its full [B] batch — regular, shape-stable device
      calls.
    - ``service``: groups submit their inference requests to a
      ``NativeBatcher`` (the C++ dynamic-batching core) whose consumer
      thread co-batches however many groups arrive within ``timeout_ms``
      into ONE device call (vmapped over groups).  This is the reference's
      dynamic-batching architecture — many irregular callers amortized
      onto one accelerator (reference: dynamic_batching.py:65-102 +
      batcher.cc) — and pays off when there are many small groups.
    """

    def __init__(
        self,
        agent: ImpalaAgent,
        env_groups: Sequence[MultiEnv],
        unroll_length: int,
        level_name: str = "",
        seed: int = 0,
        queue_capacity: Optional[int] = None,
        inference_device: Optional[jax.Device] = None,
        inference_mode: str = "structural",
        service_timeout_ms: float = 5.0,
        observation_spec=None,
        fused_shards: int = 0,
        max_restarts: int = 3,
        restart_backoff_s: float = 0.5,
        restart_backoff_cap_s: float = 30.0,
        restart_window_s: float = 600.0,
    ):
        # Inference runs on ONE device (by default the first): actor
        # threads must never launch multi-device SPMD programs — concurrent
        # SPMD launches from several threads can interleave differently
        # across devices and deadlock.  set_params therefore re-places the
        # learner's (mesh-sharded) params as a single-device snapshot — the
        # explicit versioned weight publication replacing the reference's
        # parameter-server variable reads (reference: experiment.py:503-505).
        # local_devices: in a multi-host job each process's actors infer
        # on that process's own first device.
        self._inference_device = inference_device or jax.local_devices()[0]
        self._agent = agent
        if inference_mode == "structural":
            step_fn = jax.jit(
                lambda params, rng, action, env_output, state: actor_step(
                    agent, params, rng, action, env_output, state))
        elif inference_mode == "service":
            sizes = {envs.num_envs for envs in env_groups}
            if len(sizes) > 1:
                raise ValueError(
                    f"service inference needs uniform group sizes, got "
                    f"{sorted(sizes)}")
            self._service_max = len(env_groups)
            self._service_timeout_ms = service_timeout_ms
            self._batcher = None  # built lazily from the first request
            self._batcher_lock = threading.Lock()
            # One device call for k co-batched groups: vmap over the group
            # axis with per-group rng.
            self._service_jit = jax.jit(functools.partial(
                _service_step, agent))
            step_fn = self._service_request
        elif inference_mode not in ("accum", "accum_fused"):
            raise ValueError(f"unknown inference_mode {inference_mode!r}")
        self._inference_mode = inference_mode
        if inference_mode in ("accum", "accum_fused"):
            # On-device trajectory accumulation: per step only flat frame
            # bytes go up and sampled actions come down; the trajectory
            # never re-crosses the link (runtime/accum_actor.py).
            from scalable_agent_tpu.runtime.accum_actor import (
                AccumPrograms,
                AccumVectorActor,
                GroupedAccumActor,
            )

            sizes = {envs.num_envs for envs in env_groups}
            if len(sizes) > 1:
                raise ValueError(
                    f"accum inference needs uniform group sizes, got "
                    f"{sorted(sizes)}")
            # Optional observation streams (instruction token ids,
            # Doom measurement vectors) need device buffers sized from
            # the spec — the driver passes its probed observation_spec
            # so language/measurement levels work in accum mode.
            instr_spec = getattr(observation_spec, "instruction", None)
            meas_spec = getattr(observation_spec, "measurements", None)
            programs = AccumPrograms(
                agent, unroll_length, env_groups[0].num_envs,
                env_groups[0].frame_slab().shape[1:],
                instruction_shape=(tuple(instr_spec.shape)
                                   if instr_spec is not None else None),
                measurements_shape=(tuple(meas_spec.shape)
                                    if meas_spec is not None else None))
            if inference_mode == "accum_fused":
                # Cross-group co-dispatch: a lockstep driver serves its
                # groups with one vmapped device call + one fused
                # action fetch per step (~1 link RTT for its k groups;
                # see GroupedAccumActor).  ``fused_shards`` > 1 splits
                # the fleet into that many lockstep drivers on separate
                # threads, so one shard's env stepping/upload overlaps
                # another's link round trip — the middle ground between
                # fully-threaded accum (k RTTs) and one lockstep batch
                # (no overlap).  Same per-group seeds as the threaded
                # path either way, so trajectories are identical.
                # 0 = auto: probe the link at startup and pick the
                # predicted-best count (1 co-located, 2 on the
                # bandwidth-bound tunnel — runtime/linktune.py).
                from scalable_agent_tpu.runtime.linktune import (
                    resolve_fused_shards,
                )
                from scalable_agent_tpu.utils import log

                frame_shape = env_groups[0].frame_slab().shape[1:]
                shards, link = resolve_fused_shards(
                    fused_shards, len(env_groups),
                    env_groups[0].num_envs,
                    int(np.prod(frame_shape)),
                    device=self._inference_device)
                if link is not None:
                    log.info(
                        "auto accum_fused_shards=%d (probed rtt "
                        "%.1f ms, h2d %.0f MB/s, %d groups x %d envs)",
                        shards, link.rtt_s * 1e3,
                        link.h2d_bytes_per_s / 1e6, len(env_groups),
                        env_groups[0].num_envs)
                self.fused_shards = shards
                # Balanced split: exactly ``shards`` drivers (e.g. 4
                # groups over 3 shards -> [2, 1, 1]), so the config
                # value means what it says.
                base, extra = divmod(len(env_groups), shards)
                sizes = [base + (1 if s < extra else 0)
                         for s in range(shards)]
                bounds = [0]
                for size in sizes:
                    bounds.append(bounds[-1] + size)
                self._actors = [
                    GroupedAccumActor(
                        programs, env_groups[lo:hi],
                        level_name=level_name,
                        seeds=[seed + 1000 * i for i in range(lo, hi)])
                    for lo, hi in zip(bounds, bounds[1:])
                ]
            else:
                self._actors = [
                    AccumVectorActor(programs, envs,
                                     level_name=level_name,
                                     seed=seed + 1000 * i)
                    for i, envs in enumerate(env_groups)
                ]
        else:
            self._actors = [
                VectorActor(agent, envs, unroll_length,
                            level_name=level_name, seed=seed + 1000 * i,
                            step_fn=step_fn)
                for i, envs in enumerate(env_groups)
            ]
        self.queue = queue_lib.Queue(
            maxsize=queue_capacity or len(env_groups))
        self._params = None
        self._params_version = 0
        self._params_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads = []
        self._errors = []
        # Bounded respawn budget per actor thread (--actor_max_restarts):
        # a transient fault retries with capped exponential backoff; the
        # terminal exception surfaces only once the budget is spent.
        # The budget is WINDOWED (restarts within restart_window_s, the
        # same crash-loop-not-lifetime-fault semantics as MultiEnv's
        # worker respawn budget): isolated faults days apart must never
        # accumulate into a kill.  0 restores the old fail-fast
        # marshalling.
        self._max_restarts = max(0, int(max_restarts))
        self._restart_backoff_s = float(restart_backoff_s)
        self._restart_backoff_cap_s = float(restart_backoff_cap_s)
        self._restart_window_s = float(restart_window_s)

        # Observability: trajectory-queue gauges sample by callback
        # (nothing on the hot path); the frames counter gives actor-side
        # FPS independently of the learner's consumption rate.  The
        # callbacks hold only WEAK references — the process-global
        # registry must never keep a finished pool (and the trajectories
        # buffered in its queue) alive.
        import weakref

        registry = get_registry()
        queue_ref = weakref.ref(self.queue)
        registry.gauge(
            "actor_pool/queue_depth",
            "trajectories staged for the learner",
            fn=lambda: (q.qsize() if (q := queue_ref()) is not None
                        else 0.0))
        registry.gauge(
            "actor_pool/queue_capacity",
            "trajectory queue bound").set(self.queue.maxsize)
        pool_ref = weakref.ref(self)
        registry.gauge(
            "actor_pool/params_version",
            "newest published weight snapshot",
            fn=lambda: (p._params_version if (p := pool_ref()) is not None
                        else 0.0))
        self._frames_counter = registry.counter(
            "actor/agent_steps_total",
            "agent steps generated across all groups (x action repeats "
            "= env frames)")
        self._trajectories_counter = registry.counter(
            "actor/trajectories_total", "unrolls handed to the queue")
        self._restarts_counter = registry.counter(
            "actor/restarts_total",
            "actor-thread respawns after a transient failure (the "
            "per-actor detail rides the flight recorder's "
            "actor_restart events)")
        self._frames_per_trajectory = unroll_length * (
            env_groups[0].num_envs if env_groups else 0)

    # -- service-mode plumbing ---------------------------------------------

    def _service_request(self, params, rng, action, env_output, state):
        """VectorActor-facing step_fn: one group's request -> the shared
        batcher (params arg ignored; the consumer reads the newest
        snapshot at batch time, like the reference's variable reads)."""
        del params
        sample = (
            np.asarray(jax.random.key_data(rng), np.uint32),
            np.asarray(action),
            _to_numpy(env_output),
            np.asarray(state.c),
            np.asarray(state.h),
        )
        batcher = self._ensure_batcher(sample)
        out, c, h = batcher.compute(sample)
        return out, AgentState(c=c, h=h)

    def _ensure_batcher(self, example_sample):
        with self._batcher_lock:
            if self._batcher is None:
                from scalable_agent_tpu.runtime.batcher import (
                    bucket_ladder)
                from scalable_agent_tpu.runtime.native_batcher import (
                    NativeBatcher)

                example_result = self._service_compute(
                    map_structure(
                        lambda x: None if x is None else x[None],
                        example_sample), 1)
                example_result = map_structure(
                    lambda x: None if x is None else x[0], example_result)
                pad = bucket_ladder(self._service_max)
                self._batcher = NativeBatcher(
                    self._service_compute,
                    example_sample=example_sample,
                    example_result=example_result,
                    minimum_batch_size=1,
                    maximum_batch_size=self._service_max,
                    timeout_ms=self._service_timeout_ms,
                    pad_to_sizes=pad,
                )
            return self._batcher

    def _service_compute(self, batched, k):
        """Batcher consumer: k co-batched group requests -> one vmapped
        jitted device call under the newest params."""
        key_data, action, env_output, c, h = batched
        out, new_state = self._service_jit(
            self._get_params(), key_data, action, env_output,
            AgentState(c=c, h=h))
        out = _to_numpy(out)
        new_state = _to_numpy(new_state)
        return (out, new_state.c, new_state.h)

    # -- weight publication ------------------------------------------------

    def set_params(self, params, version: Optional[int] = None):
        """Publish a new weight snapshot for subsequent unrolls.

        The snapshot must be a real COPY when the learner's params already
        live solely on the inference device (a 1-device mesh): there
        ``device_put`` aliases the learner's buffers, and the learner's
        donated update (donate_argnums) would invalidate the actors'
        snapshot on the very next step ("Array has been deleted").
        ``snapshot_params_for_inference`` owns that re-placement.
        """
        params = snapshot_params_for_inference(params,
                                               self._inference_device)
        with self._params_lock:
            self._params = params
            self._params_version = (
                version if version is not None else self._params_version + 1)

    def _get_params(self):
        with self._params_lock:
            return self._params

    # -- run ---------------------------------------------------------------

    def _chaos_kill_worker(self, actor) -> None:
        """``worker_kill`` injection: SIGKILL one env worker process of
        this actor — MultiEnv's respawn machinery must absorb it."""
        envs_list = (getattr(actor, "envs_list", None)
                     or [getattr(actor, "_envs", None)])
        for envs in envs_list:
            procs = getattr(envs, "_procs", None)
            if not procs:
                continue
            proc = procs[0]
            if proc is not None and proc.is_alive():
                from scalable_agent_tpu.utils import log

                log.warning("chaos: killing env worker pid %d", proc.pid)
                proc.kill()
                return

    def _unroll_loop(self, actor: VectorActor):
        """The steady-state produce loop for one actor (runs until stop
        or an exception; the retry layer in _actor_loop owns both)."""
        from scalable_agent_tpu.runtime.faults import get_fault_injector

        recorder = get_flight_recorder()
        while not self._stop.is_set():
            # Re-read the global tracer each unroll: the driver may
            # enable tracing after this thread was born.
            tracer = get_tracer()
            watchdog = get_watchdog()
            watchdog.touch()
            injector = get_fault_injector()
            if injector.active:
                injector.maybe_raise("actor_raise")
                if injector.should_fire("worker_kill"):
                    self._chaos_kill_worker(actor)
            params = self._get_params()
            with tracer.span("actor/unroll", cat="actor"):
                result = actor.run_unroll(params)
            # Grouped (co-dispatch) actors emit one trajectory per
            # group per lockstep unroll.
            items = result if isinstance(result, list) else [result]
            recorder.record("unroll", actor.level_name or "actor",
                            {"trajectories": len(items)})
            thread_name = threading.current_thread().name
            birth_us = getattr(actor, "unroll_birth_us", None)
            for trajectory in items:
                # Provenance record born at the unroll's first env step,
                # bound to the trajectory object; shutdown can abandon
                # the put (publish_trajectory closes the record then).
                publish_trajectory(
                    self.queue, trajectory, self._stop,
                    actor_name=thread_name,
                    level_name=actor.level_name,
                    birth_us=birth_us,
                    frames=self._frames_per_trajectory,
                    frames_counter=self._frames_counter,
                    trajectories_counter=self._trajectories_counter)

    def _actor_loop(self, actor: VectorActor):
        """Retry shell around ``_unroll_loop``: the shared
        ``run_with_retry`` gives a failing actor thread
        ``max_restarts`` respawns within a sliding ``restart_window_s``
        (crash-loop detection — isolated faults days apart age out)
        with capped exponential backoff before its terminal exception
        is marshalled to the driver through the queue
        (docs/robustness.md)."""

        def deliver(exc):
            self._errors.append(exc)
            self.queue.put(exc)

        run_with_retry(
            lambda: self._unroll_loop(actor),
            stop=self._stop, deliver=deliver,
            reset=getattr(actor, "reset", None),
            max_restarts=self._max_restarts,
            backoff_s=self._restart_backoff_s,
            backoff_cap_s=self._restart_backoff_cap_s,
            window_s=self._restart_window_s,
            restarts_counter=self._restarts_counter)

    def start(self):
        if self._params is None:
            raise RuntimeError("set_params before start")
        for i, actor in enumerate(self._actors):
            # Stable names: watchdog heartbeats, flight-recorder events,
            # and trace thread tracks all key on the thread name.
            t = threading.Thread(
                target=self._actor_loop, args=(actor,), daemon=True,
                name=f"actor-{i}")
            t.start()
            self._threads.append(t)
        return self

    def get_trajectory(self, timeout: Optional[float] = None) -> ActorOutput:
        # Ledger hand-off inside: recovers the provenance record bound
        # to the object and makes it the consuming thread's CURRENT
        # record, so the transport/learner layers stamp the right one.
        return consume_trajectory(self.queue, timeout=timeout)

    def stop(self):
        self._stop.set()
        if self._inference_mode == "service":
            with self._batcher_lock:
                if self._batcher is not None:
                    # Cascades BatcherClosedError to any actor thread
                    # blocked awaiting a batch (reference: batcher.cc
                    # close semantics, :393-431).
                    self._batcher.close()
        for t in self._threads:
            t.join(timeout=10)
        for actor in self._actors:
            actor.close()

    def _all_envs(self):
        """Every MultiEnv behind every actor (grouped actors own
        several)."""
        out = []
        for actor in self._actors:
            out.extend(getattr(actor, "envs_list", None)
                       or [actor._envs])
        return out

    @property
    def num_envs(self) -> int:
        return sum(envs.num_envs for envs in self._all_envs())

    def episode_stats(self):
        """Merged completed-episode (return, length) ring buffers."""
        return merged_episode_stats(self._all_envs())

    def drain_level_stats(self):
        """Pop all level-attributed episodes completed since the last
        drain (shared implementation: ``drain_level_stats``)."""
        return drain_level_stats(self._all_envs())
