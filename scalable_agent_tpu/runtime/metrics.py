"""Scalar metrics writer: TensorBoard (if available) + JSONL.

Reference metric names are kept for comparison runs (reference:
experiment.py:423-425 learning_rate/total_loss summaries; :643-664
per-level episode_return/episode_frames and DMLab-30 human-normalized
scores; SF's tensorboardX usage, algorithms/utils/agent.py:195-238).
"""

import json
import os
import time
from typing import Dict, Optional


class MetricsWriter:
    def __init__(self, logdir: str, flush_every_s: float = 5.0):
        os.makedirs(logdir, exist_ok=True)
        self._jsonl = open(os.path.join(logdir, "metrics.jsonl"), "a")
        self._flush_every_s = flush_every_s
        self._last_flush = 0.0
        try:
            from tensorboardX import SummaryWriter

            self._tb = SummaryWriter(os.path.join(logdir, "summaries"))
        except ImportError:
            self._tb = None

    def write(self, step: int, scalars: Dict[str, float],
              wall_time: Optional[float] = None):
        wall_time = wall_time or time.time()
        record = {"step": int(step), "time": wall_time}
        for key, value in scalars.items():
            value = float(value)
            record[key] = value
            if self._tb is not None:
                self._tb.add_scalar(key, value, global_step=step,
                                    walltime=wall_time)
        self._jsonl.write(json.dumps(record) + "\n")
        now = time.monotonic()
        if now - self._last_flush > self._flush_every_s:
            self.flush()
            self._last_flush = now

    def flush(self):
        self._jsonl.flush()
        if self._tb is not None:
            self._tb.flush()

    def close(self):
        self.flush()
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()
