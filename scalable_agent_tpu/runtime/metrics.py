"""Back-compat shim: the metrics writer moved into the observability
package (obs/exporters.py), rebuilt on the metrics registry.  Existing
imports (``from scalable_agent_tpu.runtime.metrics import MetricsWriter``)
keep working."""

from scalable_agent_tpu.obs.exporters import MetricsWriter

__all__ = ["MetricsWriter"]
