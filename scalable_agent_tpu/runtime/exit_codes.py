"""One registry for every deliberate non-zero exit the runtime takes.

A production supervisor restarts failed workers by exit code; three
subsystems ending runs with three privately-defined constants is how
two of them end up sharing a number.  Every bounded-failure path
imports its code from here, and docs/robustness.md renders this table
for operators:

| Code | Name | Raised by | Meaning |
|---|---|---|---|
| 70 | watchdog | obs/watchdog.py (``--watchdog_abort``) | a pipeline thread missed its heartbeat deadline — the run was wedged, forensics dumped |
| 71 | non-finite | driver._rollback_or_exit | the non-finite tolerance was exhausted with ``--no_rollback`` or nothing restorable — numeric divergence, not a hang |
| 72 | fleet | runtime/fleet.py | a peer process was lost (stale heartbeat, dead coordinator, timed-out collective) or the preemption grace window expired — restart and resume |
| 73 | sentinel | runtime/sentinel.py via driver | the numerics sentinel detected silent corruption that survived the full degradation ladder and a rollback (or rollback was impossible) — the hardware/software combination is producing wrong arithmetic |

``128 + signum`` (e.g. 143 for SIGTERM with the grace protocol
disabled) keeps its POSIX meaning; 0 is a completed run — including a
preempted run that drained and checkpointed inside its grace window.

This module must stay import-free (pure constants): it is imported from
both the obs layer and the runtime layer, and anything heavier would
recreate the circular-import problem that scattered the codes in the
first place.
"""

# EX_SOFTWARE-adjacent block, deliberately contiguous and above the
# 64-78 sysexits range's common collisions.
WATCHDOG_EXIT_CODE = 70
NONFINITE_EXIT_CODE = 71
FLEET_EXIT_CODE = 72
SENTINEL_EXIT_CODE = 73

# name -> (code, one-line operator meaning); the docs table and the
# exit-code tests render from this.
EXIT_CODES = {
    "watchdog": (WATCHDOG_EXIT_CODE,
                 "a pipeline thread missed its heartbeat deadline "
                 "(hang; --watchdog_abort)"),
    "nonfinite": (NONFINITE_EXIT_CODE,
                  "non-finite tolerance exhausted with --no_rollback "
                  "or no restorable checkpoint"),
    "fleet": (FLEET_EXIT_CODE,
              "peer lost / collective timed out / preemption grace "
              "expired — restart resumes from the last checkpoint"),
    "sentinel": (SENTINEL_EXIT_CODE,
                 "silent numeric corruption survived the full "
                 "degradation ladder and a rollback — restart at the "
                 "same shape (the reference path is trusted; persistent "
                 "breach points at the hardware)"),
}
