"""ctypes front-end for the native (C++) dynamic batcher.

Same service contract as ``runtime.batcher.DynamicBatcher`` — min/max/
timeout batch formation, error cascade on close, out-of-order batches —
but caller blocking, batch formation, and gather/scatter memcpy happen in
``native/batcher.cc`` with the GIL released (reference: batcher.cc's role
as the C++ half of dynamic_batching.py).

Samples/results are fixed-layout pytrees of numpy arrays: the layout is
declared up front (from example pytrees) so every request packs into one
contiguous byte blob.  The Python consumer thread drives the jitted
compute function exactly as the QueueRunner thread drives the batched
subgraph in the reference (dynamic_batching.py:131-144).
"""

import ctypes
import threading
import time
from typing import Any, Callable, Optional, Sequence

import numpy as np

from scalable_agent_tpu.native import load_library
from scalable_agent_tpu.obs import (
    get_flight_recorder,
    get_ledger,
    get_registry,
    get_tracer,
    get_watchdog,
)
from scalable_agent_tpu.runtime.batcher import (
    BatcherClosedError,
    pad_to_bucket,
)
# One flat-pytree byte layout serves every host-side pytree<->bytes
# boundary (this batcher's request/result rows and the packed trajectory
# transport's segments) — runtime/transport.py is the single source of
# truth for offsets/shape/dtype bookkeeping.
from scalable_agent_tpu.runtime.transport import FlatRowLayout as _Layout
from scalable_agent_tpu.types import map_structure

_OK, _CLOSED, _TIMEOUT, _INVALID = 0, 1, 2, 3


class NativeBatcher:
    """Drop-in DynamicBatcher with the C++ core.

    ``example_sample``/``example_result``: pytrees fixing the layout.
    ``compute_fn(batched_tree, n) -> batched_result_tree``.
    """

    def __init__(
        self,
        compute_fn: Callable[[Any, int], Any],
        example_sample,
        example_result,
        minimum_batch_size: int = 1,
        maximum_batch_size: int = 1024,
        timeout_ms: Optional[float] = 100.0,
        pad_to_sizes: Optional[Sequence[int]] = None,
        num_consumers: int = 1,
        variant: str = "opt",
        metrics_name: str = "native_batcher",
        registry=None,
    ):
        if minimum_batch_size > maximum_batch_size:
            raise ValueError("minimum_batch_size > maximum_batch_size")
        if pad_to_sizes is not None:
            pad_to_sizes = sorted(pad_to_sizes)
            if pad_to_sizes[-1] < maximum_batch_size:
                raise ValueError(
                    "largest pad_to_sizes must cover maximum_batch_size")
        # The pending queue lives in C++; in-flight callers (entered
        # compute(), result not yet unpacked) are the Python-visible
        # depth proxy the gauge samples.  Weak reference only: the
        # global registry must not keep a closed batcher alive.
        import weakref

        self._inflight = 0
        self._inflight_lock = threading.Lock()
        registry = registry or get_registry()
        self_ref = weakref.ref(self)
        registry.gauge(
            f"{metrics_name}/queue_depth",
            "callers blocked in the native batcher",
            fn=lambda: (b._inflight if (b := self_ref()) is not None
                        else 0.0))
        self._batch_size_hist = registry.histogram(
            f"{metrics_name}/batch_size", "valid rows per formed batch")
        self._occupancy_hist = registry.histogram(
            f"{metrics_name}/occupancy",
            "valid rows / maximum_batch_size per formed batch")
        self._latency_hist = registry.histogram(
            f"{metrics_name}/request_latency_s",
            "enqueue -> result seconds per request")
        self._batches_total = registry.counter(
            f"{metrics_name}/batches_total", "batches executed")
        self._lib = load_library(variant)
        self._compute_fn = compute_fn
        self._sample_layout = _Layout(example_sample)
        self._result_layout = _Layout(example_result)
        self._max = maximum_batch_size
        self._pad_to_sizes = pad_to_sizes
        self._handle = ctypes.c_void_p(self._lib.batcher_create(
            self._sample_layout.nbytes, self._result_layout.nbytes,
            minimum_batch_size, maximum_batch_size,
            -1.0 if timeout_ms is None else float(timeout_ms)))
        self._closed = False
        self._compute_error = None
        self._consumers = [
            threading.Thread(target=self._consume_loop, daemon=True,
                             name=f"native-batcher-consumer-{i}")
            for i in range(num_consumers)
        ]
        for t in self._consumers:
            t.start()

    # -- caller side -------------------------------------------------------

    def compute(self, sample):
        if self._closed:
            raise BatcherClosedError("batcher is closed")
        t0 = time.monotonic()
        with self._inflight_lock:
            self._inflight += 1
        try:
            sample_buf = bytearray(self._sample_layout.nbytes)
            self._sample_layout.pack_into(memoryview(sample_buf), sample)
            result_buf = bytearray(self._result_layout.nbytes)
            sample_c = (ctypes.c_char * len(sample_buf)).from_buffer(
                sample_buf)
            result_c = (ctypes.c_char * len(result_buf)).from_buffer(
                result_buf)
            status = self._lib.batcher_compute(
                self._handle, ctypes.addressof(sample_c),
                ctypes.addressof(result_c))
        finally:
            with self._inflight_lock:
                self._inflight -= 1
        if status == _CLOSED:
            raise BatcherClosedError(
                "batcher closed while request pending")
        if status != _OK:
            error = self._compute_error or RuntimeError(
                f"native batcher error status {status}")
            raise error
        self._latency_hist.observe(time.monotonic() - t0)
        return self._result_layout.unpack_one(memoryview(result_buf))

    # -- consumer side -----------------------------------------------------

    def _pad_rows(self, n: int) -> int:
        return pad_to_bucket(n, self._pad_to_sizes)

    def _consume_loop(self):
        sample_nbytes = self._sample_layout.nbytes
        batch_buf = bytearray(self._max * sample_nbytes)
        batch_c = (ctypes.c_char * len(batch_buf)).from_buffer(batch_buf)
        n_c = ctypes.c_int(0)
        id_c = ctypes.c_int64(0)
        watchdog = get_watchdog()
        while True:
            # Disarm across the GIL-released native wait (idle is not a
            # wedge); re-arm for the bounded batch execution.
            watchdog.suspend()
            status = self._lib.batcher_get_batch(
                self._handle, ctypes.addressof(batch_c),
                ctypes.byref(n_c), ctypes.byref(id_c))
            if status == _CLOSED:
                return
            watchdog.touch()
            n = n_c.value
            try:
                self._batch_size_hist.observe(n)
                self._occupancy_hist.observe(n / self._max)
                self._batches_total.inc()
                started_at = time.monotonic()
                with get_tracer().span("batcher/native_run_batch",
                                       args={"n": n}):
                    batched = self._sample_layout.unpack_rows(
                        memoryview(batch_buf), n)
                    padded = self._pad_rows(n)
                    if padded > n:
                        batched = map_structure(
                            lambda x: None if x is None else np.pad(
                                x,
                                [(0, padded - n)] + [(0, 0)] * (x.ndim - 1)),
                            batched)
                    result = self._compute_fn(batched, n)
                    result_buf = bytearray(n * self._result_layout.nbytes)
                    self._result_layout.pack_rows(
                        memoryview(result_buf), result, n)
                # Same service-stage feed as the Python batcher: the
                # ledger's inference-service ρ covers both cores.
                get_ledger().note_service(
                    "inference_service", n,
                    time.monotonic() - started_at)
                result_c = (ctypes.c_char * len(result_buf)).from_buffer(
                    result_buf)
                self._lib.batcher_set_results(
                    self._handle, id_c.value, ctypes.addressof(result_c),
                    _OK)
            except BaseException as exc:
                # The error cascades to callers via the status code; the
                # ring keeps the native consumer's side of the story.
                get_flight_recorder().record(
                    "exception", type(exc).__name__,
                    {"where": threading.current_thread().name})
                self._compute_error = exc
                self._lib.batcher_set_results(
                    self._handle, id_c.value, None, _INVALID)

    # -- lifecycle ---------------------------------------------------------

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._lib.batcher_close(self._handle)
        for t in self._consumers:
            t.join(timeout=5)
        self._lib.batcher_destroy(self._handle)
        self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.close()
