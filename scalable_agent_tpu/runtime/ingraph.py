"""Fused in-graph training: rollout + IMPALA update as ONE device program.

With an on-device environment (envs/device/) the whole actor side —
T agent-inference steps, T env transitions, trajectory assembly — plus
the learner update compiles into a single jitted function.  A train step
involves NO host↔device data movement at all (the host only dispatches),
so chained dispatches stream to the device back-to-back; metrics are
fetched on whatever cadence the caller wants.

Per-update semantics match the host pipeline:

- Trajectory layout is the reference's T+1 overlap layout (first entry of
  unroll k+1 == last entry of unroll k, reference: experiment.py:311-321)
  via the rollout carry.
- The rollout runs under the params of the CURRENT state, i.e. zero
  policy lag.  The host pipeline has >= 1 update of lag (the reference's
  queue + staging design, experiment.py:531,587-597); V-trace corrects
  for the behaviour/target gap in both cases, so this only shifts where
  on the on/off-policy spectrum the data sits.
"""

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scalable_agent_tpu.envs.device import (
    env_telemetry_spec,
    record_episode_telemetry,
)
from scalable_agent_tpu.models.agent import (
    ImpalaAgent,
    actor_step,
    initial_state,
)
from scalable_agent_tpu.obs.device_telemetry import (
    TelemetryPublisher,
    fetch_merged,
    merge_init,
)
from scalable_agent_tpu.runtime.faults import get_fault_injector
from scalable_agent_tpu.runtime.learner import Learner, Trajectory
from scalable_agent_tpu.types import AgentOutput, AgentState


class RolloutCarry(NamedTuple):
    """Everything that flows from one unroll into the next, all [B]."""

    env_state: object
    env_output: object  # StepOutput
    agent_output: AgentOutput
    core_state: AgentState


class TrainCarry(NamedTuple):
    """The fused step's full donated carry: the rollout state plus the
    device-telemetry pytree (obs/device_telemetry.py) — env episode
    instruments and the learner's update instruments accumulate inside
    the same jitted program, in the same donated buffers, and the host
    fetches them only at log-interval cadence.  This is how the fused
    megastep keeps a live obs plane with zero per-update host sync."""

    rollout: RolloutCarry
    telemetry: Dict
    # The WORST consecutive non-finite-skip streak seen inside the
    # megaloop since the host last acted on it (f32 scalar; None — an
    # empty pytree node — when the finite guard is off).  TrainState
    # carries the streak at the LAST update of a dispatch, so with
    # K = updates_per_dispatch > 1 a streak that reaches the rollback
    # tolerance mid-dispatch and then resets (one finite update) would
    # be invisible at the dispatch boundary — up to K-1 skips past the
    # documented trigger.  The peak is monotone across scan iterations
    # AND across dispatches, surfaced as
    # ``metrics['nonfinite_streak_peak']``; the host's NonFiniteTracker
    # takes max(streak, peak), and the driver resets the peak to 0 on
    # rollback (the only action that forgives a tolerance breach).
    streak_peak: Any = None


def _stack_first(first, seq):
    """[B] entry + [T, B] sequence -> [T+1, B]."""
    return jax.tree_util.tree_map(
        lambda f, r: None if f is None else jnp.concatenate(
            [f[None], r], axis=0),
        first, seq, is_leaf=lambda x: x is None)


class InGraphTrainer:
    """Owns the fused (rollout + update) jitted step for a device env.

    ``env`` must expose ``initial(seeds) -> (env_state, StepOutput[B])``
    and ``step(env_state, action) -> (env_state, StepOutput[B])`` as pure
    jnp functions (see envs/device.DeviceFakeEnv).
    """

    def __init__(
        self,
        agent: ImpalaAgent,
        learner: Learner,
        env,
        unroll_length: int,
        batch: int,
        seed: int = 0,
        emit_trajectory: bool = False,
        updates_per_dispatch: int = 1,
    ):
        self._agent = agent
        self._learner = learner
        self._env = env
        self._unroll_length = unroll_length
        self._batch = batch
        self._seed = int(seed)
        # The multi-update megaloop: one device dispatch runs K =
        # updates_per_dispatch fused (rollout + update) iterations as a
        # lax.scan, so a cheap-env run is no longer bound by the
        # per-dispatch host overhead (the Python loop + runtime launch
        # path) — the measured fps measures the chip.  K == 1 keeps one
        # update per dispatch THROUGH THE SAME scan body, so K is a
        # pure batching knob: K updates are bit-exact with K dispatches
        # of 1 over the same total update count (tests/test_device_env
        # pins this golden property).
        self._updates_per_dispatch = int(updates_per_dispatch)
        if self._updates_per_dispatch < 1:
            raise ValueError(
                f"updates_per_dispatch must be >= 1, got "
                f"{updates_per_dispatch}")
        # Replay tap (runtime/replay.py): when set, train_step ALSO
        # returns the unroll's device-resident Trajectory so the driver
        # can insert it into the replay slab — extra HBM output, zero
        # host traffic.  Off (the default) the fused program is
        # unchanged.  Incompatible with K > 1: the replay dial samples
        # the slab BETWEEN fresh updates, which only exists between
        # dispatches.
        self._emit_trajectory = bool(emit_trajectory)
        if self._emit_trajectory and self._updates_per_dispatch > 1:
            raise ValueError(
                "emit_trajectory requires updates_per_dispatch == 1: "
                "replayed updates interleave with fresh ones on the "
                "host side, between dispatches")
        # Shard the rollout over the learner's data axis: one constraint
        # on the carry propagates through the scan, so env transitions
        # and agent inference compute on their batch shard's device
        # (PartitionSpec("data") shards axis 0 at any rank).
        from scalable_agent_tpu.parallel.mesh import batch_sharding

        self._batch_sharding = batch_sharding(
            learner.mesh, batch_axis_index=0)
        self._env_tel_spec = env_telemetry_spec()
        self._tel_specs = [self._env_tel_spec]
        # Every learner-owned spec rides the same merged carry dict:
        # the update counters AND the learning-dynamics plane
        # (devtel/learn/*), whose in-update observes accumulate across
        # all K megaloop iterations of a dispatch.
        self._tel_specs.extend(learner.devtel_specs)
        self._tel_publisher = TelemetryPublisher(self._tel_specs)
        self.train_step = jax.jit(self._fused, donate_argnums=(0, 1))
        # Replayed-batch update: the learner's fresh=False
        # specialization driven with THIS trainer's merged telemetry
        # pytree (donated, like the fused step's carry).
        self.replay_step = jax.jit(self._replay_step,
                                   donate_argnums=(0, 1))

    # -- initialization ----------------------------------------------------

    def init(self, rng: jax.Array) -> Tuple[object, TrainCarry]:
        """(TrainState, TrainCarry) ready for ``train_step``."""
        seeds = np.arange(self._batch, dtype=np.int32) + self._seed
        env_state, env_output = self._env.initial(seeds)
        agent_output = AgentOutput(
            action=jnp.asarray(self._agent.zero_actions(self._batch)),
            policy_logits=jnp.zeros(
                (self._batch, self._agent.num_logits), jnp.float32),
            baseline=jnp.zeros((self._batch,), jnp.float32),
        )
        core_state = initial_state(self._batch, self._agent.core_size)
        carry = TrainCarry(
            rollout=RolloutCarry(env_state, env_output, agent_output,
                                 core_state),
            telemetry=merge_init(self._tel_specs),
            # None (an empty pytree node, nothing allocated) when the
            # finite guard is off — the carry structure then matches
            # pre-peak checkpointed runs byte-for-byte.
            streak_peak=(jnp.float32(0.0)
                         if self._learner._finite_guard else None))
        example = Trajectory(
            agent_state=core_state,
            env_outputs=_stack_first(
                env_output,
                jax.tree_util.tree_map(
                    lambda x: None if x is None else x[None],
                    env_output, is_leaf=lambda x: x is None)),
            agent_outputs=_stack_first(
                agent_output,
                jax.tree_util.tree_map(
                    lambda x: None if x is None else x[None],
                    agent_output, is_leaf=lambda x: x is None)),
        )
        state = self._learner.init(rng, example)
        return state, carry

    # -- the fused program -------------------------------------------------

    def _rollout(self, params, carry: RolloutCarry, rng):
        agent, env = self._agent, self._env

        # The named scopes land in the compiled HLO's op_name metadata,
        # which the kernel ledger (obs/kernels.py) reads to attribute
        # device time env-vs-inference-vs-learner inside a
        # device_bound verdict.
        def scan_fn(c, t):
            with jax.named_scope("actor_inference"):
                out, core = actor_step(
                    agent, params, jax.random.fold_in(rng, t),
                    c.agent_output.action, c.env_output, c.core_state)
            with jax.named_scope("env_step"):
                env_state, env_output = env.step(c.env_state, out.action)
            return RolloutCarry(env_state, env_output, out, core), (
                env_output, out)

        new_carry, (env_seq, agent_seq) = jax.lax.scan(
            scan_fn, carry, jnp.arange(self._unroll_length))
        trajectory = Trajectory(
            agent_state=carry.core_state,
            env_outputs=_stack_first(carry.env_output, env_seq),
            agent_outputs=_stack_first(carry.agent_output, agent_seq),
        )
        return trajectory, new_carry

    def _constrain_batch(self, tree):
        return jax.tree_util.tree_map(
            lambda x: x if x is None or getattr(x, "ndim", 0) == 0
            else jax.lax.with_sharding_constraint(x, self._batch_sharding),
            tree, is_leaf=lambda x: x is None)

    def _one_update(self, state, rollout_carry, telemetry, update_index):
        """One fused (rollout + update) iteration — the megaloop's scan
        body.  ``update_index`` is the GLOBAL update counter (it keys
        the rollout rng), so K scanned iterations are the same stream
        as K separate dispatches."""
        rng = jax.random.fold_in(
            jax.random.key(self._seed), update_index)
        trajectory, new_rollout = self._rollout(
            state.params, rollout_carry, rng)
        # Chaos (trace-time): the host backend's ``nan_grad`` hook
        # lives in Learner.update, which this fused path never calls —
        # bake the armed occurrence set into the compiled program and
        # match it against the GLOBAL update index on device instead
        # (faults.occurrences: 1-based, so occurrence n poisons update
        # index n-1's batch; not counted in faults/injected_total).
        injector = get_fault_injector()
        if injector.active:
            armed = sorted(injector.occurrences("nan_grad"))
            if armed:
                fire = jnp.any(jnp.asarray(armed, jnp.int32)
                               == update_index + 1)
                poison = jnp.where(fire, jnp.float32(float("nan")),
                                   jnp.float32(1.0))
                trajectory = trajectory._replace(
                    env_outputs=trajectory.env_outputs._replace(
                        reward=trajectory.env_outputs.reward * poison))
        # The [1:] slice drops the T+1 overlap entry (it was the
        # PREVIOUS unroll's last step — counting it again would
        # double-book every episode boundary), for both the metrics
        # accounting below and the device telemetry.
        emitted = jax.tree_util.tree_map(
            lambda t: None if t is None else t[1:],
            trajectory.env_outputs, is_leaf=lambda x: x is None)
        telemetry = record_episode_telemetry(
            self._env_tel_spec, telemetry, emitted)
        with jax.named_scope("learner_update"):
            new_state, telemetry, metrics = self._learner._update_impl(
                state, trajectory, telemetry)
        # Episode accounting from the on-device env stream (the host
        # backend reads MultiEnv ring buffers; here the trajectory
        # itself carries the emitted per-done episode stats), as SUMS so
        # the megaloop can fold them across the scan.
        done = emitted.done
        steps = emitted.info.episode_step
        finished = jnp.logical_and(done, steps > 0)
        episode_sums = {
            "count": jnp.sum(finished),
            "return_sum": jnp.sum(jnp.where(
                finished, emitted.info.episode_return, 0.0)),
            "frames_sum": jnp.sum(jnp.where(
                finished, steps, 0)).astype(jnp.float32),
        }
        return new_state, new_rollout, telemetry, metrics, \
            episode_sums, trajectory

    def _fused(self, state, carry: TrainCarry, counter):
        # Only the rollout state takes the batch-sharding constraint:
        # the telemetry leaves are replicated scalars/bucket vectors
        # with no batch axis.
        rollout_carry = self._constrain_batch(carry.rollout)
        k = self._updates_per_dispatch

        def body(loop_carry, update_index):
            state, rollout_carry, telemetry, peak = loop_carry
            (state, rollout_carry, telemetry, metrics, episode_sums,
             trajectory) = self._one_update(
                state, rollout_carry, telemetry, update_index)
            if peak is not None and "nonfinite_streak" in metrics:
                # The megaloop's tolerance contract: fold the
                # post-update streak into the monotone peak each
                # iteration, so a streak that breaches mid-dispatch
                # and then resets is still visible at the boundary.
                peak = jnp.maximum(peak, metrics["nonfinite_streak"])
            ys = (metrics, episode_sums)
            if self._emit_trajectory:
                ys = ys + (trajectory,)
            return (state, rollout_carry, telemetry, peak), ys

        # K == 1 runs through the SAME scan body: lax.scan compiles the
        # body as its own while-loop computation at any length, so a
        # K-update dispatch is bit-exact with K single-update dispatches
        # (the golden property driver resume / the K knob rely on).
        (new_state, new_rollout, telemetry, peak), ys = jax.lax.scan(
            body,
            (state, rollout_carry, carry.telemetry, carry.streak_peak),
            counter + jnp.arange(k, dtype=jnp.int32))
        metrics_seq, episode_seq = ys[0], ys[1]
        # Scalar gauges (loss, lr, grad_norm, env_frames, ...) read the
        # LAST update's value — the state the dispatch hands back;
        # episode stats aggregate across all K unrolls.
        metrics = jax.tree_util.tree_map(lambda x: x[-1], metrics_seq)
        count = episode_seq["count"].sum()
        denom = jnp.maximum(count, 1).astype(jnp.float32)
        metrics["episodes_completed"] = count
        metrics["episode_return"] = episode_seq["return_sum"].sum() / denom
        metrics["episode_frames"] = episode_seq["frames_sum"].sum() / denom
        if peak is not None:
            metrics["nonfinite_streak_peak"] = peak
        out_carry = TrainCarry(new_rollout, telemetry, peak)
        if self._emit_trajectory:
            # K == 1 (enforced in __init__): drop the length-1 scan
            # axis so the replay tap sees the plain [T+1, B] pytree.
            trajectory = jax.tree_util.tree_map(
                lambda x: x[0], ys[2])
            return new_state, out_carry, metrics, trajectory
        return new_state, out_carry, metrics

    def _replay_step(self, state, telemetry, trajectory):
        """One REPLAYED update (env_frames held, target-net schedule
        held — runtime/learner.py fresh=False).  Returns
        ``(new_state, new_telemetry, metrics)``; the caller rebinds the
        carry's telemetry."""
        return self._learner._update_impl(
            state, trajectory, telemetry, fresh=False)

    # -- host loop ---------------------------------------------------------

    def run(self, state, carry, num_updates: int, counter_start: int = 0,
            on_trajectory=None):
        """Dispatch ``num_updates`` chained fused steps WITHOUT any host
        synchronization; the caller decides when to fetch metrics (e.g.
        ``float(np.asarray(metrics['total_loss']))``).

        ``on_trajectory`` is the emitted-trajectory sink for an
        ``emit_trajectory=True`` trainer (e.g. ``replay.insert``): it
        receives the device-resident Trajectory of every dispatch.  An
        emitting trainer REFUSES to run without a sink — silently
        dropping emitted trajectories here once cost replay its data
        (the insert path and run() couldn't compose)."""
        if self._emit_trajectory and on_trajectory is None:
            raise ValueError(
                "this trainer emits trajectories (emit_trajectory="
                "True) but run() was given no on_trajectory sink; "
                "pass one (e.g. replay.insert) or drive train_step "
                "directly")
        k = self._updates_per_dispatch
        if num_updates % k:
            raise ValueError(
                f"num_updates {num_updates} not divisible by "
                f"updates_per_dispatch {k}")
        metrics = None
        for i in range(0, num_updates, k):
            result = self.train_step(
                state, carry, np.int32(counter_start + i))
            state, carry, metrics = result[:3]
            if self._emit_trajectory:
                on_trajectory(result[3])
        return state, carry, metrics

    # -- telemetry (host side, log-interval cadence) -----------------------

    def fetch_telemetry(self, carry: TrainCarry) -> dict:
        """Materialize every telemetry instrument riding ``carry`` —
        the obs plane's ONE device→host sync, a few hundred bytes."""
        return fetch_merged(self._tel_specs, carry.telemetry)

    def publish_telemetry(self, carry: TrainCarry) -> dict:
        """Fetch + fold into the metrics registry (``devtel/env/*`` and
        ``devtel/learner/*`` ride the normal prom/report path)."""
        fetched = self.fetch_telemetry(carry)
        self._tel_publisher.publish(fetched)
        return fetched
