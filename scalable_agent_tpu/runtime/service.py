"""Continuous-batching actor service: no per-step group barrier.

BENCH_r04's verdict (ROADMAP item 1) is a ~200x gap between what the
learner eats (~2.55M env_frames/s/chip) and what the host pipeline
delivers (12.6k), and the grouped actor path owns most of it by
construction: ``MultiEnv.step_recv`` gathers an ENTIRE group each step
— the slowest env worker gates its whole group — and ``VectorActor``
alternates env-dispatch → wait → inference, so inference never overlaps
stepping.  This module replaces that lockstep with the async
whole-machine design of "Accelerated Methods for Deep RL" (PAPERS.md)
fused with the reference's dynamic-batcher idea (batcher.cc):

- **Per-worker completion** (envs/vector.py ``worker_send`` /
  ``worker_recv``): each env worker's observations flow out the moment
  its reply lands.  A slow worker delays only its own slice.
- **Request ring**: finished slices push ``(generation, group, worker,
  observations)`` requests into a lock-free deque (atomic append/pop —
  the flightrec ring discipline; a condition variable exists only to
  wake the idle consumer).
- **One continuous-batching inference thread**: drains WHATEVER is
  pending — no minimum, no timeout, no barrier — up to
  ``--service_max_batch`` rows, pads to the shared power-of-two bucket
  ladder (runtime/batcher.py ``bucket_ladder``/``pad_to_bucket``, the
  batch-formation core both dynamic batchers use) to bound XLA
  recompiles, and runs ONE jitted ``actor_step`` whose LSTM states live
  device-resident in a ``[num_envs + 1, core]`` slab (gathered by env
  id on the way in, scattered back on the way out; the extra row
  swallows padding writes).  Per step only observations go up and
  actions come down — the state never re-crosses the link.
- **Per-env trajectory packing** (``TrajectoryPacker``): every lane (a
  worker's env slice — envs that always step together) independently
  accumulates the reference's T+1 overlap layout and emits a full
  [T+1, B] ``ActorOutput`` into the existing ActorPool-compatible queue
  as soon as every lane of a group has an unroll ready, feeding the
  packed transport unchanged.  A straggler bounds emission cadence,
  never its siblings' stepping.

Observability: the service feeds the pipeline ledger's ``service_wait``
(Little's-law L of parked requests) and ``service_batch`` (inference
thread utilization) stages, ``service/*`` histograms mapped in
``ledger.TIMING_STAGE_MAP``, and the watchdog (the inference thread
heartbeats per batch, so a wedged service dumps forensics instead of
silently starving the learner — chaos point ``service_stall``,
runtime/faults.py).

Select with ``--actor=service`` (``--actor=grouped`` keeps the lockstep
pool); docs/performance.md, "Continuous-batching actor service".  This
is the host-env prong (b) of ROADMAP item 1 and the inference-engine
skeleton for the item-4 serving path.
"""

import functools
import os
import queue as queue_lib
import threading
import time
from collections import deque
from multiprocessing import connection as mp_connection
from typing import List, Optional, Sequence

import jax
import numpy as np

from scalable_agent_tpu.envs.vector import MultiEnv
from scalable_agent_tpu.models.agent import ImpalaAgent, actor_step
from scalable_agent_tpu.obs import (
    get_flight_recorder,
    get_ledger,
    get_registry,
    get_tracer,
    get_watchdog,
)
from scalable_agent_tpu.obs.ledger import now_us as ledger_now_us
from scalable_agent_tpu.runtime.actor import (
    _stack_time,
    _to_numpy,
    actor_stage_histograms,
    consume_trajectory,
    drain_level_stats,
    merged_episode_stats,
    publish_trajectory,
    run_with_retry,
    snapshot_params_for_inference,
)
from scalable_agent_tpu.runtime.batcher import bucket_ladder, pad_to_bucket
from scalable_agent_tpu.types import (
    ActorOutput,
    AgentOutput,
    AgentState,
    map_structure,
)

__all__ = ["ActorService", "TrajectoryPacker", "SERVICE_STALL_S"]

# How long the ``service_stall`` chaos point wedges the inference
# thread (runtime/faults.py): long enough to trip a test-sized watchdog
# deadline, short enough that the run recovers and completes.  The env
# var is read at FIRE time so tests can tune it after import.
SERVICE_STALL_S = 2.0


def _stall_seconds() -> float:
    try:
        return float(os.environ.get("SCALABLE_AGENT_SERVICE_STALL_S",
                                    SERVICE_STALL_S))
    except ValueError:
        return SERVICE_STALL_S


def _service_actor_step(agent, params, rng, ids, last_actions,
                        env_outputs, slab_c, slab_h):
    """One continuous batch: gather LSTM states by env id from the
    device-resident slab, run the shared ``actor_step``, scatter the
    new states back.  ``ids`` pads with the slab's extra dummy row, so
    padded rows gather junk (discarded) and scatter harmlessly.  The
    slabs are donated — they never leave the device."""
    state = AgentState(c=slab_c[ids], h=slab_h[ids])
    out, new_state = actor_step(agent, params, rng, last_actions,
                                env_outputs, state)
    slab_c = slab_c.at[ids].set(new_state.c)
    slab_h = slab_h.at[ids].set(new_state.h)
    return out, new_state, slab_c, slab_h


class TrajectoryPacker:
    """Per-lane T+1 overlap trajectory assembly for one env group.

    A *lane* is a contiguous slice of the group's batch whose envs
    always step together (the service uses one lane per env worker;
    tests use one env per lane).  Each lane independently accumulates
    (env_output, agent_output) entry pairs; crossing T steps completes
    an unroll, which buffers until EVERY lane has one — then ``pop``
    concatenates lanes into one [T+1, B] batch.

    Layout contract (bit-identical to ``VectorActor``,
    tests/test_service.py): entry 0 of unroll k+1 is entry T of unroll
    k; ``agent_state`` is the LSTM state captured AFTER the inference
    that produced entry T's agent half (``stage_state`` — the caller
    stages it before dispatching the env step, so the reply can never
    outrun it).

    Thread model: one producer per lane (stage_inference/stage_state
    from the inference thread, add_env from the lane's env thread) —
    per-lane calls strictly alternate because at most one step is ever
    outstanding per lane.
    """

    def __init__(self, lane_widths: Sequence[int], unroll_length: int):
        if unroll_length < 1:
            raise ValueError("unroll_length must be >= 1")
        self._T = int(unroll_length)
        self._widths = [int(w) for w in lane_widths]
        n = len(self._widths)
        self._env_entries: List[list] = [[] for _ in range(n)]
        self._agent_entries: List[list] = [[] for _ in range(n)]
        self._state = [None] * n          # current unroll boundary state
        self._staged_agent = [None] * n   # next entry's agent half
        self._staged_state = [None] * n   # next unroll's boundary state
        self._unroll_start_us = [0] * n
        self._completed = [deque() for _ in range(n)]

    @property
    def num_lanes(self) -> int:
        return len(self._widths)

    @property
    def num_envs(self) -> int:
        return sum(self._widths)

    def lane_width(self, lane: int) -> int:
        return self._widths[lane]

    def entry_count(self, lane: int) -> int:
        """Entries in the lane's CURRENT (partial) unroll."""
        return len(self._env_entries[lane])

    def completed_depth(self, lane: int) -> int:
        """Finished unrolls buffered for the lane (straggler siblings
        keep stepping; their output parks here)."""
        return len(self._completed[lane])

    def bootstrap(self, lane: int, env_tree, agent_tree, c_rows,
                  h_rows) -> None:
        """Entry 0 of the lane's first unroll: initial env outputs, a
        zero agent output, and the zero LSTM state (the reference's
        persistent-state init, experiment.py:243-251)."""
        self._env_entries[lane] = [env_tree]
        self._agent_entries[lane] = [agent_tree]
        self._state[lane] = (c_rows, h_rows)
        self._staged_agent[lane] = None
        self._staged_state[lane] = None
        self._unroll_start_us[lane] = ledger_now_us()

    def has_staged(self, lane: int) -> bool:
        """True when the lane has an inference staged and its env step
        in flight — i.e. a reply is EXPECTED.  A reply landing with
        nothing staged means the worker died idle and was respawned
        (the service re-bootstraps just that lane)."""
        return self._staged_agent[lane] is not None

    def stage_inference(self, lane: int, agent_tree) -> bool:
        """Record the agent half of the lane's next entry (the
        inference output whose action the env is about to execute).
        Returns True when that entry will COMPLETE an unroll — the
        caller must ``stage_state`` before dispatching the env step."""
        if self._staged_agent[lane] is not None:
            raise RuntimeError(
                f"lane {lane}: staging a second inference with one "
                f"already outstanding (protocol violation)")
        self._staged_agent[lane] = agent_tree
        return len(self._env_entries[lane]) == self._T

    def stage_state(self, lane: int, c_rows, h_rows) -> None:
        """The post-inference LSTM state rows that become the NEXT
        unroll's ``agent_state`` (may be lazy device arrays — ``pop``
        materializes them)."""
        self._staged_state[lane] = (c_rows, h_rows)

    def add_env(self, lane: int, env_tree) -> bool:
        """Pair the env reply with the staged agent half into one
        entry.  Returns True when the lane completed an unroll."""
        agent_tree = self._staged_agent[lane]
        if agent_tree is None:
            raise RuntimeError(
                f"lane {lane}: env reply with no staged inference "
                f"(protocol violation)")
        self._staged_agent[lane] = None
        self._env_entries[lane].append(env_tree)
        self._agent_entries[lane].append(agent_tree)
        if len(self._env_entries[lane]) <= self._T:
            return False
        staged = self._staged_state[lane]
        if staged is None:
            raise RuntimeError(
                f"lane {lane}: unroll completed without a staged "
                f"boundary state")
        self._completed[lane].append(
            (self._unroll_start_us[lane], self._state[lane],
             self._env_entries[lane], self._agent_entries[lane]))
        # T+1 overlap: the completed unroll's last entry seeds the next.
        self._env_entries[lane] = [env_tree]
        self._agent_entries[lane] = [agent_tree]
        self._state[lane] = staged
        self._staged_state[lane] = None
        self._unroll_start_us[lane] = ledger_now_us()
        return True

    def ready(self) -> bool:
        return all(self._completed)

    def pop(self):
        """One [T+1, B] batch: the oldest completed unroll of every
        lane, concatenated in lane (= batch) order.  Returns
        ``(birth_us, agent_state, env_outputs, agent_outputs)`` where
        ``birth_us`` is the OLDEST lane's unroll start — the
        conservative staleness anchor."""
        births, cs, hs, env_trees, agent_trees = [], [], [], [], []
        for lane in range(self.num_lanes):
            birth, (c, h), env_rows, agent_rows = (
                self._completed[lane].popleft())
            births.append(birth)
            cs.append(np.asarray(c))
            hs.append(np.asarray(h))
            env_trees.append(_stack_time(env_rows))
            agent_trees.append(_stack_time(agent_rows))

        def join(*xs):
            return (None if xs[0] is None
                    else np.concatenate(xs, axis=1))

        return (
            min(births),
            AgentState(c=np.concatenate(cs), h=np.concatenate(hs)),
            map_structure(join, *env_trees),
            map_structure(join, *agent_trees),
        )

    def reset(self) -> None:
        """Drop ALL lane state (partial entries, staged halves,
        buffered unrolls) after a mid-unroll failure: the retry path
        re-bootstraps from fresh initial outputs, exactly like
        ``VectorActor.reset``."""
        n = self.num_lanes
        self._env_entries = [[] for _ in range(n)]
        self._agent_entries = [[] for _ in range(n)]
        self._state = [None] * n
        self._staged_agent = [None] * n
        self._staged_state = [None] * n
        self._completed = [deque() for _ in range(n)]


class _Request:
    """One worker slice's pending inference request.  Three staleness
    stamps, all checked under the worker lock before dispatch: ``gen``
    is the group generation (bumped by a full group reset),
    ``lane_gen`` the per-lane generation (bumped when a lane alone
    re-bootstraps after an idle worker death), and ``env_gen`` the
    worker's RESPAWN generation (MultiEnv.worker_generation — a
    respawn's _INITIAL prime already has a reply in flight, so a
    request predating the respawn must be discarded, not dispatched on
    top of it)."""

    __slots__ = ("gen", "lane_gen", "env_gen", "group", "worker",
                 "env_tree", "submitted_us")

    def __init__(self, gen, lane_gen, env_gen, group, worker, env_tree,
                 submitted_us):
        self.gen = gen
        self.lane_gen = lane_gen
        self.env_gen = env_gen
        self.group = group
        self.worker = worker
        self.env_tree = env_tree
        self.submitted_us = submitted_us


class _Group:
    """Per-group bookkeeping: envs, packer, global env offset, and the
    generation counter that invalidates in-flight requests across a
    retry reset."""

    __slots__ = ("envs", "packer", "offset", "slices", "gen",
                 "lane_gen", "sent_at", "poisoned")

    def __init__(self, envs: MultiEnv, packer: TrajectoryPacker,
                 offset: int):
        self.envs = envs
        self.packer = packer
        self.offset = offset
        # Immutable after MultiEnv construction — cached so the hot
        # batch loops don't allocate a fresh list per request.
        self.slices = envs.worker_slices()
        self.gen = 0
        self.lane_gen = [0] * envs.num_workers
        self.sent_at = [0.0] * envs.num_workers
        # An exception the inference thread hit dispatching to THIS
        # group (e.g. its worker's respawn budget raising inside
        # worker_send): marshalled here so the group's OWN retry shell
        # — the layer with the reset + budget semantics — absorbs it,
        # instead of the inference thread retrying the wrong resource.
        self.poisoned: Optional[BaseException] = None


class ActorService:
    """Continuous-batching actor service (``--actor=service``).

    Drop-in for ``ActorPool`` on the driver's side: same queue/
    ``set_params``/``start``/``get_trajectory``/``stop``/stats surface,
    same [T+1, B] ``ActorOutput`` batches.  Internally there is no
    group lockstep: env worker threads stream per-worker observations
    into a request ring, one inference thread continuously batches
    whatever arrived against a device-resident LSTM state slab, and
    per-lane packers assemble trajectories (module docstring).
    """

    def __init__(
        self,
        agent: ImpalaAgent,
        env_groups: Sequence[MultiEnv],
        unroll_length: int,
        level_name: str = "",
        seed: int = 0,
        queue_capacity: Optional[int] = None,
        inference_device: Optional[jax.Device] = None,
        max_batch: int = 0,
        max_restarts: int = 3,
        restart_backoff_s: float = 0.5,
        restart_backoff_cap_s: float = 30.0,
        restart_window_s: float = 600.0,
    ):
        if not env_groups:
            raise ValueError("ActorService needs at least one env group")
        self._agent = agent
        self._unroll_length = int(unroll_length)
        self.level_name = level_name
        self._inference_device = inference_device or jax.local_devices()[0]
        self._rng = jax.random.key(seed)
        self._batch_counter = 0

        offset = 0
        self._groups: List[_Group] = []
        widest = 1
        for envs in env_groups:
            widths = [sl.stop - sl.start for sl in envs.worker_slices()]
            widest = max(widest, *widths)
            self._groups.append(_Group(
                envs, TrajectoryPacker(widths, unroll_length), offset))
            offset += envs.num_envs
        self._num_envs = offset
        # The dummy slab row padding rows gather from / scatter into.
        self._dummy_slot = self._num_envs
        if max_batch and max_batch < widest:
            raise ValueError(
                f"service_max_batch {max_batch} is smaller than the "
                f"widest worker slice ({widest} envs) — requests are "
                f"slice-granular")
        self._max_batch = int(max_batch) or self._num_envs
        self._buckets = bucket_ladder(self._max_batch)

        # Device-resident per-env LSTM state: [N + 1, core] (the +1 row
        # swallows padded scatter writes).  Donated through every
        # batch, so the state never re-crosses the link.
        zeros = np.zeros((self._num_envs + 1, agent.core_size),
                         np.float32)
        self._slab_c = jax.device_put(zeros, self._inference_device)
        self._slab_h = jax.device_put(zeros.copy(),
                                      self._inference_device)
        # Host-side last sampled action per env (the next inference's
        # ``last_action`` input).
        self._last_actions = np.asarray(
            agent.zero_actions(self._num_envs)).copy()
        self._step_fn = jax.jit(
            functools.partial(_service_actor_step, agent),
            donate_argnums=(5, 6))

        # Lock-free request ring (deque append/popleft are atomic); the
        # condition only wakes the idle inference thread.
        self._ring: deque = deque()
        self._ring_cond = threading.Condition()

        self.queue = queue_lib.Queue(
            maxsize=queue_capacity or len(env_groups))
        self._params = None
        self._params_version = 0
        self._params_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._errors: List[BaseException] = []
        self._max_restarts = max(0, int(max_restarts))
        self._restart_backoff_s = float(restart_backoff_s)
        self._restart_backoff_cap_s = float(restart_backoff_cap_s)
        self._restart_window_s = float(restart_window_s)

        # Observability: the pool-compatible gauges keep driver
        # dashboards working unchanged; the service/* instruments are
        # this path's own (ledger TIMING_STAGE_MAP maps them).  Weak
        # references only — the registry must never keep a stopped
        # service (and its queued trajectories) alive.
        import weakref

        registry = get_registry()
        queue_ref = weakref.ref(self.queue)
        registry.gauge(
            "actor_pool/queue_depth",
            "trajectories staged for the learner",
            fn=lambda: (q.qsize() if (q := queue_ref()) is not None
                        else 0.0))
        registry.gauge(
            "actor_pool/queue_capacity",
            "trajectory queue bound").set(self.queue.maxsize)
        self_ref = weakref.ref(self)
        registry.gauge(
            "actor_pool/params_version",
            "newest published weight snapshot",
            fn=lambda: (s._params_version if (s := self_ref()) is not None
                        else 0.0))
        ring_ref = weakref.ref(self._ring)
        registry.gauge(
            "service/pending_requests",
            "worker slices parked in the request ring",
            fn=lambda: (len(r) if (r := ring_ref()) is not None
                        else 0.0))
        self._frames_counter = registry.counter(
            "actor/agent_steps_total",
            "agent steps generated across all groups (x action repeats "
            "= env frames)")
        self._trajectories_counter = registry.counter(
            "actor/trajectories_total", "unrolls handed to the queue")
        self._restarts_counter = registry.counter(
            "actor/restarts_total",
            "actor-thread respawns after a transient failure (the "
            "per-actor detail rides the flight recorder's "
            "actor_restart events)")
        self._h_env, self._h_infer = actor_stage_histograms(registry)
        self._h_wait = registry.histogram(
            "service/wait_s",
            "request submission -> batch formation seconds (the "
            "ledger's service_wait stage)")
        self._h_batch = registry.histogram(
            "service/batch_s",
            "batched inference execution seconds per service batch "
            "(the ledger's service_batch stage)")
        self._h_latency = registry.histogram(
            "service/request_latency_s",
            "request submission -> action dispatched seconds")
        self._h_batch_size = registry.histogram(
            "service/batch_size", "valid rows per service batch")
        self._h_occupancy = registry.histogram(
            "service/occupancy",
            "valid rows / service_max_batch per service batch")
        self._batches_counter = registry.counter(
            "service/batches_total", "service batches executed")
        self._frames_per_trajectory = (
            unroll_length * env_groups[0].num_envs)

    # -- weight publication ------------------------------------------------

    def set_params(self, params, version: Optional[int] = None):
        """Publish a private single-device weight snapshot for
        subsequent batches (same re-placement contract as
        ActorPool.set_params — ``snapshot_params_for_inference``)."""
        params = snapshot_params_for_inference(params,
                                               self._inference_device)
        with self._params_lock:
            self._params = params
            self._params_version = (
                version if version is not None
                else self._params_version + 1)

    def _get_params(self):
        with self._params_lock:
            return self._params

    # -- env side ----------------------------------------------------------

    def _submit(self, request: _Request) -> None:
        self._ring.append(request)
        with self._ring_cond:
            self._ring_cond.notify()

    def _bootstrap_lane(self, gi: int, w: int, out) -> None:
        """Entry 0 for ONE lane from its (initial) slice outputs: zero
        agent output + zero LSTM state (VectorActor._bootstrap's
        layout), plus the lane's first inference request."""
        group = self._groups[gi]
        sl = group.slices[w]
        k = sl.stop - sl.start
        zero_agent = AgentOutput(
            action=np.asarray(self._agent.zero_actions(k)),
            policy_logits=np.zeros(
                (k, self._agent.num_logits), np.float32),
            baseline=np.zeros((k,), np.float32))
        zeros = np.zeros((k, self._agent.core_size), np.float32)
        group.packer.bootstrap(w, out, zero_agent, zeros, zeros.copy())
        self._last_actions[group.offset + sl.start:
                           group.offset + sl.stop] = zero_agent.action
        self._submit(_Request(group.gen, group.lane_gen[w],
                              group.envs.worker_generation(w), gi, w,
                              out, ledger_now_us()))

    def _bootstrap_group(self, gi: int) -> None:
        """(Re)start one group: fresh initial outputs and entry 0 per
        worker slice."""
        group = self._groups[gi]
        envs = group.envs
        group.packer.reset()
        for w in range(envs.num_workers):
            self._bootstrap_lane(gi, w, envs.worker_initial(w))

    def _reset_group(self, gi: int) -> None:
        """Retry-path reset: invalidate in-flight requests (generation
        bump), wait out any straddling send (lock cycle), drain stale
        pipe replies, drop partial trajectories.  The next loop pass
        re-bootstraps."""
        group = self._groups[gi]
        group.gen += 1
        for w in range(group.envs.num_workers):
            # A send dispatched under the OLD generation must finish
            # before the drain, or its reply arrives after and desyncs.
            with group.envs.worker_lock(w):
                pass
        group.envs.resync()
        group.packer.reset()

    def _chaos_kill_worker(self, envs: MultiEnv) -> None:
        """``worker_kill`` injection: SIGKILL one env worker process —
        the per-worker respawn machinery must absorb it."""
        procs = getattr(envs, "_procs", None)
        if not procs:
            return
        proc = procs[0]
        if proc is not None and proc.is_alive():
            from scalable_agent_tpu.utils import log

            log.warning("chaos: killing env worker pid %d", proc.pid)
            proc.kill()

    def _group_loop(self, gi: int) -> None:
        """One group's steady-state env side: bootstrap, then stream
        per-worker replies into the ring as they land (runs under the
        shared retry shell; exceptions reset + re-bootstrap)."""
        from scalable_agent_tpu.runtime.faults import get_fault_injector

        group = self._groups[gi]
        envs = group.envs
        watchdog = get_watchdog()
        self._bootstrap_group(gi)
        while not self._stop.is_set():
            # Bounded waits below re-touch, so the heartbeat only goes
            # stale when this thread truly wedges.
            watchdog.touch()
            if group.poisoned is not None:
                # The inference thread failed dispatching to this
                # group: surface it HERE so this thread's retry shell
                # resets and re-bootstraps the group.
                exc, group.poisoned = group.poisoned, None
                raise exc
            injector = get_fault_injector()
            if injector.active:
                injector.maybe_raise("actor_raise")
                if injector.should_fire("worker_kill"):
                    self._chaos_kill_worker(envs)
            # Re-read the conns each pass: a respawn replaces them.
            conns = [envs.worker_connection(w)
                     for w in range(envs.num_workers)]
            try:
                ready = mp_connection.wait(conns, timeout=0.1)
            except (OSError, ValueError):
                # A conn in the snapshot was closed mid-wait by a
                # concurrent respawn (the inference thread's
                # worker_send hit the dead pipe first) — refresh the
                # snapshot next pass instead of treating a routine
                # worker death as a group failure.
                continue
            for conn in ready:
                if self._stop.is_set():
                    return
                w = conns.index(conn)
                out = envs.worker_recv(w)
                sent_at = group.sent_at[w]
                if sent_at:
                    self._h_env.observe(time.monotonic() - sent_at)
                self._handle_reply(gi, w, out)

    def _handle_reply(self, gi: int, w: int, out) -> None:
        group = self._groups[gi]
        # The whole classify-and-consume step runs under the worker
        # lock — the same lock the inference thread stages/dispatches
        # under — so "nothing staged" is judged against a SETTLED lane:
        # either the parked request already staged (normal pairing
        # below) or the lane-gen bump here invalidates it before the
        # inference thread can dispatch it.
        with group.envs.worker_lock(w):
            if not group.packer.has_staged(w):
                # A reply with no inference staged: the worker died
                # IDLE (its request parked in the ring, no step in
                # flight) and worker_recv respawned it — ``out`` is its
                # fresh initial slice.  Recover at LANE granularity,
                # like the grouped path's respawn: invalidate the stale
                # parked request (lane generation bump) and
                # re-bootstrap just this lane, without resetting
                # siblings or burning the group restart budget.
                group.lane_gen[w] += 1
                self._bootstrap_lane(gi, w, out)
                return
            completed = group.packer.add_env(w, out)
            # The reply is BOTH trajectory entry t and inference input
            # for entry t+1 (the VectorActor loop's data flow,
            # barrier-free).
            self._submit(_Request(group.gen, group.lane_gen[w],
                                  group.envs.worker_generation(w),
                                  gi, w, out, ledger_now_us()))
        if completed:
            self._maybe_emit(gi)

    def _maybe_emit(self, gi: int) -> None:
        group = self._groups[gi]
        thread_name = threading.current_thread().name
        while group.packer.ready():
            birth_us, agent_state, env_outputs, agent_outputs = (
                group.packer.pop())
            trajectory = ActorOutput(
                level_name=self.level_name,
                agent_state=agent_state,
                env_outputs=env_outputs,
                agent_outputs=agent_outputs)
            get_flight_recorder().record(
                "unroll", self.level_name or "actor",
                {"trajectories": 1, "service": True})
            publish_trajectory(
                self.queue, trajectory, self._stop,
                actor_name=thread_name,
                level_name=self.level_name,
                birth_us=birth_us,
                frames=self._frames_per_trajectory,
                frames_counter=None,  # counted per batch row instead
                trajectories_counter=self._trajectories_counter)

    # -- inference side ----------------------------------------------------

    def _take_requests(self) -> Optional[List[_Request]]:
        """Continuous batch formation: block until at least one request
        exists, then take whatever else is already pending up to
        ``max_batch`` rows — no minimum, no flush timeout, no barrier.
        Returns None at stop."""
        watchdog = get_watchdog()
        while not self._stop.is_set():
            try:
                first = self._ring.popleft()
            except IndexError:
                # Idle is not a wedge; re-arm for the batch below.
                watchdog.suspend()
                with self._ring_cond:
                    self._ring_cond.wait(0.2)
                watchdog.touch()
                continue
            requests = [first]
            total = self._request_rows(first)
            while total < self._max_batch:
                try:
                    nxt = self._ring.popleft()
                except IndexError:
                    break
                rows = self._request_rows(nxt)
                if total + rows > self._max_batch:
                    self._ring.appendleft(nxt)
                    break
                requests.append(nxt)
                total += rows
            return requests
        return None

    def _request_rows(self, request: _Request) -> int:
        return self._groups[request.group].packer.lane_width(
            request.worker)

    def _inference_loop(self) -> None:
        """The service thread: drain → pad → one jitted step → stream
        actions back per worker slice.  Runs under the retry shell; a
        failed batch's requests are re-queued first so its envs cannot
        starve across the retry."""
        from scalable_agent_tpu.runtime.faults import get_fault_injector

        watchdog = get_watchdog()
        while not self._stop.is_set():
            requests = self._take_requests()
            if requests is None:
                return
            watchdog.touch()
            injector = get_fault_injector()
            if injector.active and injector.should_fire("service_stall"):
                from scalable_agent_tpu.utils import log

                stall = _stall_seconds()
                log.warning("chaos: service inference thread stalling "
                            "%.1fs", stall)
                time.sleep(stall)
            self._run_batch(requests)

    def _reset_inference(self) -> None:
        """Inference-retry reset: a device call that failed AFTER its
        donation invalidated the state slabs would otherwise make every
        retry fail on the deleted buffers — rebuild them as zeros (the
        done-reset restores per-env state at each episode boundary)."""
        deleted = any(
            getattr(slab, "is_deleted", lambda: False)()
            for slab in (self._slab_c, self._slab_h))
        if deleted:
            zeros = np.zeros((self._num_envs + 1, self._agent.core_size),
                             np.float32)
            self._slab_c = jax.device_put(zeros, self._inference_device)
            self._slab_h = jax.device_put(zeros.copy(),
                                          self._inference_device)

    def _run_batch(self, requests: List[_Request]) -> None:
        start_us = ledger_now_us()
        t0 = time.monotonic()
        # Drop requests a group reset or lane re-bootstrap invalidated
        # (their staging would pollute the freshly bootstrapped packer).
        # This unlocked read is a fast filter; the authoritative check
        # re-runs under the worker lock in the dispatch pass below.
        live = [r for r in requests
                if (r.gen == self._groups[r.group].gen
                    and r.lane_gen
                    == self._groups[r.group].lane_gen[r.worker]
                    and r.env_gen
                    == self._groups[r.group].envs.worker_generation(
                        r.worker))]
        if not live:
            return
        wait_sum = 0.0
        for request in live:
            wait = max(0.0, (start_us - request.submitted_us) / 1e6)
            wait_sum += wait
            self._h_wait.observe(wait)

        n = sum(self._request_rows(r) for r in live)
        padded = pad_to_bucket(n, self._buckets)
        ids = np.full((padded,), self._dummy_slot, np.int32)
        action_rows = []
        row = 0
        for request in live:
            group = self._groups[request.group]
            sl = group.slices[request.worker]
            lo = group.offset + sl.start
            hi = group.offset + sl.stop
            ids[row:row + hi - lo] = np.arange(lo, hi, dtype=np.int32)
            action_rows.append(self._last_actions[lo:hi])
            row += hi - lo

        def join(*leaves):
            if leaves[0] is None:
                return None
            arr = np.concatenate([np.asarray(x) for x in leaves])
            if padded > n:
                arr = np.pad(arr, [(0, padded - n)]
                             + [(0, 0)] * (arr.ndim - 1))
            return arr

        env_batch = map_structure(join,
                                  *[r.env_tree for r in live])
        actions = join(*action_rows)

        self._batch_counter += 1
        rng = jax.random.fold_in(self._rng, self._batch_counter)
        try:
            with get_tracer().span("service/batch", cat="actor",
                                   args={"n": n, "padded": padded}):
                out, new_state, self._slab_c, self._slab_h = (
                    self._step_fn(
                        self._get_params(), rng, ids, actions,
                        env_batch, self._slab_c, self._slab_h))
                out_np = _to_numpy(out)
        except BaseException:
            # The batch died BEFORE any action dispatched: its envs
            # have no step in flight, so park the requests for the
            # retried loop (front of the ring, oldest first).  Failures
            # past this point dispatched for some slices already — the
            # env threads' own retry resets recover those groups.
            for request in reversed(requests):
                self._ring.appendleft(request)
            raise
        exec_s = time.monotonic() - t0
        self._h_batch.observe(exec_s)
        self._h_infer.observe(exec_s)
        self._h_batch_size.observe(n)
        self._h_occupancy.observe(n / self._max_batch)
        self._batches_counter.inc()
        self._frames_counter.inc(n)
        ledger = get_ledger()
        ledger.note_service("service_batch", n, exec_s)
        ledger.note_service("service_wait", n, wait_sum)

        # Stage each slice's agent half (and, at unroll boundaries, its
        # post-inference LSTM state rows), THEN dispatch its env step —
        # all under the worker lock, gen-checked, so a reply can never
        # outrun its staged state and a group reset can never interleave
        # a stale send.
        done_us = ledger_now_us()
        row = 0
        for request in live:
            group = self._groups[request.group]
            sl = group.slices[request.worker]
            k = sl.stop - sl.start
            rows = slice(row, row + k)
            row += k
            agent_tree = AgentOutput(
                action=out_np.action[rows],
                policy_logits=out_np.policy_logits[rows],
                baseline=out_np.baseline[rows])
            try:
                with group.envs.worker_lock(request.worker):
                    if (group.gen != request.gen
                            or group.lane_gen[request.worker]
                            != request.lane_gen
                            or group.envs.worker_generation(
                                request.worker) != request.env_gen):
                        # Stale by group reset, lane re-bootstrap, or a
                        # worker respawn whose _INITIAL prime already
                        # has a reply in flight — dispatching would
                        # double-book the request/reply protocol.
                        continue
                    need_state = group.packer.stage_inference(
                        request.worker, agent_tree)
                    if need_state:
                        # Lazy device slices: materialized (np.asarray)
                        # at pop time, so the hot loop never syncs on
                        # them.
                        group.packer.stage_state(
                            request.worker,
                            new_state.c[rows], new_state.h[rows])
                    lo = group.offset + sl.start
                    self._last_actions[lo:lo + k] = agent_tree.action
                    group.sent_at[request.worker] = time.monotonic()
                    group.envs.worker_send(request.worker,
                                           agent_tree.action)
            except Exception as exc:
                # Per-request isolation: a dispatch failure (e.g. the
                # worker's respawn budget raising in worker_send) must
                # not starve the OTHER co-batched lanes — poison the
                # owning group so ITS retry shell (the layer with the
                # reset + budget semantics) absorbs the error, and keep
                # dispatching the rest of the batch.
                get_flight_recorder().record(
                    "exception", type(exc).__name__,
                    {"where": f"service-dispatch:g{request.group}"
                              f"w{request.worker}"})
                group.poisoned = exc
                continue
            self._h_latency.observe(
                max(0.0, (done_us - request.submitted_us) / 1e6))

    # -- run ---------------------------------------------------------------

    def start(self) -> "ActorService":
        if self._params is None:
            raise RuntimeError("set_params before start")
        for gi in range(len(self._groups)):

            def deliver(exc):
                self._errors.append(exc)
                self.queue.put(exc)

            def group_main(gi=gi, deliver=deliver):
                run_with_retry(
                    lambda: self._group_loop(gi),
                    stop=self._stop, deliver=deliver,
                    reset=lambda: self._reset_group(gi),
                    max_restarts=self._max_restarts,
                    backoff_s=self._restart_backoff_s,
                    backoff_cap_s=self._restart_backoff_cap_s,
                    window_s=self._restart_window_s,
                    restarts_counter=self._restarts_counter)

            thread = threading.Thread(
                target=group_main, daemon=True,
                name=f"service-env-{gi}")
            thread.start()
            self._threads.append(thread)

        def deliver_inference(exc):
            self._errors.append(exc)
            self.queue.put(exc)

        def inference_main():
            run_with_retry(
                self._inference_loop,
                stop=self._stop, deliver=deliver_inference,
                reset=self._reset_inference,
                max_restarts=self._max_restarts,
                backoff_s=self._restart_backoff_s,
                backoff_cap_s=self._restart_backoff_cap_s,
                window_s=self._restart_window_s,
                restarts_counter=self._restarts_counter)

        thread = threading.Thread(target=inference_main, daemon=True,
                                  name="service-inference")
        thread.start()
        self._threads.append(thread)
        return self

    def get_trajectory(self, timeout: Optional[float] = None
                       ) -> ActorOutput:
        return consume_trajectory(self.queue, timeout=timeout)

    def stop(self) -> None:
        self._stop.set()
        with self._ring_cond:
            self._ring_cond.notify_all()
        for thread in self._threads:
            thread.join(timeout=10)
        for group in self._groups:
            group.envs.close()

    # -- stats (the ActorPool surface the driver reads) --------------------

    @property
    def num_envs(self) -> int:
        return self._num_envs

    def episode_stats(self):
        return merged_episode_stats(g.envs for g in self._groups)

    def drain_level_stats(self):
        """Pop all level-attributed episodes completed since the last
        drain (the implementation shared with ActorPool)."""
        return drain_level_stats(g.envs for g in self._groups)
