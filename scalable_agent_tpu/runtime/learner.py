"""The learner: one jitted, mesh-sharded IMPALA update step.

Functional parity with the reference's ``build_learner`` (reference:
experiment.py:346-427), re-designed for TPU:

- The whole update — target-policy unroll, V-trace, losses, RMSProp — is
  ONE jitted function over a ``('data', 'model')`` mesh.  Trajectory
  batches are sharded over ``data``; parameters are replicated; XLA's
  partitioner inserts the gradient all-reduce (psum over ICI).  The
  reference instead runs a single-GPU learner fed by a gRPC queue and
  places V-trace on the *CPU* because its sequential scan was slow on
  device (experiment.py:387-397) — here V-trace is an associative scan and
  stays on the TPU (ops/vtrace.py).

- The learning rate decays linearly to zero as a function of the
  environment-frame count (reference: experiment.py:409-420, where the
  global step literally counts env frames).  ``env_frames`` is carried as
  a float32 scalar in TrainState: float32 integer precision (~2^24) is
  exhausted at 16M, so frames are accumulated in units of
  ``frames_per_update`` at update granularity — exact for billions of
  frames — and the authoritative count also lives host-side.

- The time dimension (unroll T=100) is handled inside the model's
  ``lax.scan`` and V-trace's ``associative_scan``; an optional sequence-
  parallel mesh axis for very long unrolls hooks in at ops/vtrace.py.
"""

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from scalable_agent_tpu.models.agent import ImpalaAgent
from scalable_agent_tpu.obs import (
    get_flight_recorder,
    get_ledger,
    get_registry,
    get_tracer,
)
from scalable_agent_tpu.obs.device_telemetry import (
    DeviceTelemetry,
    TelemetryPublisher,
    fetch_merged,
    merge_init,
)
from scalable_agent_tpu.ops import distributions
from scalable_agent_tpu.ops import impact as impact_lib
from scalable_agent_tpu.ops import losses as losses_lib
from scalable_agent_tpu.ops import vtrace
from scalable_agent_tpu.parallel.mesh import (
    batch_sharding,
    model_parallel_shardings,
    replicated_sharding,
)
from scalable_agent_tpu.runtime.faults import get_fault_injector
from scalable_agent_tpu.runtime.transport import (
    broadcast_prefix,
    make_transport,
)
from scalable_agent_tpu.types import AgentOutput, AgentState, StepOutput


class Trajectory(NamedTuple):
    """Device-side trajectory batch (ActorOutput minus the level name —
    strings stay on the host).  (reference: experiment.py:98-100)

    agent_state: AgentState [B, H]; env_outputs: StepOutput [T+1, B, ...];
    agent_outputs: AgentOutput [T+1, B, ...].
    """

    agent_state: AgentState
    env_outputs: StepOutput
    agent_outputs: AgentOutput


class LearnerHyperparams(NamedTuple):
    """Loss/optimizer knobs, reference defaults.

    (reference: experiment.py:61-95)
    """

    entropy_cost: float = 0.00025
    baseline_cost: float = 0.5
    discounting: float = 0.99
    reward_clipping: str = "abs_one"  # abs_one | soft_asymmetric | none
    learning_rate: float = 0.00048
    total_environment_frames: float = 1e9
    rmsprop_decay: float = 0.99
    rmsprop_momentum: float = 0.0
    rmsprop_epsilon: float = 0.1
    clip_rho_threshold: float = 1.0
    clip_pg_rho_threshold: float = 1.0


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    env_frames: jax.Array  # f32 scalar, counts frames in exact multiples
    # Non-finite-guard state (docs/robustness.md): cumulative skipped
    # updates and the current consecutive-skip streak, carried ON DEVICE
    # so the verdict rides whatever metrics fetch the driver already
    # pays — no extra host sync per update.  f32 scalars (exact to 2^24
    # counts); they ride the checkpoint like env_frames so a resumed
    # run keeps its skip accounting.
    nonfinite_skips: jax.Array
    nonfinite_streak: jax.Array
    # IMPACT target network (ops/impact.py): a periodic hard copy of
    # ``params`` anchoring the clipped-target surrogate, refreshed
    # in-graph every ``target_update_interval`` fresh updates.  None
    # under ``--loss=vtrace`` (a None pytree node carries zero leaves,
    # so the default path's TrainState allocates nothing new and its
    # checkpoint bytes are unchanged); populated under
    # ``--loss=impact`` and carried through the checkpoint so a resumed
    # run keeps its anchor (runtime/checkpoint.py migrates checkpoints
    # from either generation across the loss modes).
    target_params: Any = None


# Per-field batch-axis positions: agent_state leaves are [B, ...], the
# [T+1, B, ...] subtrees carry the batch at axis 1.  The transport layer
# splits/joins the data-sharding axis here.
_TRAJ_BATCH_AXES = Trajectory(agent_state=0, env_outputs=1,
                              agent_outputs=1)

# Re-exported for callers that used the private helper here.
_broadcast_prefix = broadcast_prefix


def learner_telemetry_spec() -> DeviceTelemetry:
    """The learner's device-resident instrument set (obs/
    device_telemetry.py): update/skip counters, the last loss, and a
    log-bucketed grad-norm histogram — all accumulated INSIDE the
    jitted update in donated buffers (the non-finite-counter pattern
    generalized), fetched once per log interval."""
    return (
        DeviceTelemetry("learner")
        .counter("updates", "update steps executed on device")
        .counter("skipped", "updates the fused non-finite guard no-op'd")
        .gauge("loss", "total_loss of the newest accumulated update")
        .histogram(
            "grad_norm",
            (0.01, 0.1, 1.0, 10.0, 100.0, 1000.0, 10000.0),
            "global grad norm per update, log-ish buckets")
    )


# Per-layer-group telemetry buckets: the agent's param tree divides
# into the conv torso ("convnet" + the optional instruction encoder),
# the recurrent core ("core"/lstm), and the linear heads
# ("policy_logits"/"baseline").  Keyed on flax module names so a new
# head lands in "heads" and anything else defaults to the torso.
LAYER_GROUPS = ("torso", "core", "heads")

# Shared bucket edges for fraction-valued histograms ([0, 1] series:
# clip fractions, ESS, normalized entropy).
_FRACTION_EDGES = (0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99)


def learning_telemetry_spec(loss: str = "vtrace") -> DeviceTelemetry:
    """The learning-dynamics instrument set (ISSUE 17): off-policy clip
    diagnostics, policy entropy/KL, value explained-variance, and
    per-layer-group optimizer health — all accumulated INSIDE the
    jitted update in the same donated devtel buffers as
    ``learner_telemetry_spec`` (merged via ``merge_init``), fetched in
    the one existing log-interval transfer.

    Gauges carry the newest update's value (what the health detectors
    and ``obs.watch`` read); histograms additionally aggregate across
    every update between fetches — in particular all K updates of an
    ``--updates_per_dispatch=K`` megaloop dispatch, where the metrics
    dict only surfaces the last update's scalars.
    """
    spec = DeviceTelemetry("learn")
    for name, help_text in (
        ("entropy_frac",
         "policy entropy / max entropy (1.0 = uniform; ~0 = collapsed)"),
        ("kl",
         "KL(behaviour || learner) — how far the learner has moved off "
         "the data-generating policy"),
        ("ess_frac",
         "effective sample size of the V-trace importance weights as a "
         "fraction of the batch (1.0 = on-policy)"),
        ("explained_variance",
         "1 - Var(vs - baseline)/Var(vs): how much of the value target "
         "the baseline explains (<=0 = diverging critic)"),
        ("rho_clip_fraction",
         "fraction of V-trace rhos cut by clip_rho_threshold"),
        ("cs_clip_fraction",
         "fraction of V-trace cs cut by the c-bar clip"),
        ("pg_rho_clip_fraction",
         "fraction of pg-rhos cut by clip_pg_rho_threshold"),
        ("log_rho_mean",
         "mean log importance ratio log(pi/mu) (0 = on-policy)"),
        ("log_rho_p95",
         "p95 log importance ratio — the off-policy tail"),
        ("dead_torso_frac",
         "fraction of conv-torso output units at <=0 across the whole "
         "batch (dead ReLUs)"),
    ):
        spec.gauge(name, help_text)
    for group in LAYER_GROUPS:
        spec.gauge(f"grad_norm_{group}",
                   f"gradient norm over the {group} param group")
        spec.gauge(f"param_norm_{group}",
                   f"param norm of the {group} param group")
        spec.gauge(f"update_ratio_{group}",
                   f"|lr-scaled update| / |param| for the {group} group "
                   "(healthy ~1e-4..1e-2)")
    if loss == "impact":
        # ISSUE 17 satellite: the IMPACT ratio series ride HISTOGRAMS
        # (not just the per-update metrics dict) so a megaloop dispatch
        # aggregates all K updates instead of surfacing only the last.
        spec.histogram(
            "impact_ratio",
            (0.5, 0.8, 0.9, 0.95, 1.0, 1.05, 1.1, 1.25, 2.0),
            "per-update mean IMPACT ratio pi_theta/pi_tgt (~1 = online "
            "net hugging its target anchor)")
        spec.histogram(
            "impact_clip_fraction", _FRACTION_EDGES,
            "per-update fraction of cells where the IMPACT clip bound "
            "was active")
        spec.gauge("impact_log_ratio_p95",
                   "p95 of log(pi_theta/pi_tgt) — online-to-target "
                   "drift tail")
        spec.gauge("impact_ess_frac",
                   "ESS fraction of the online-to-target importance "
                   "weights")
    return spec


def _torso_filter(mdl, _method_name) -> bool:
    """flax capture_intermediates filter: only the conv torso output."""
    return mdl.name == "convnet"


def _dead_unit_fraction(captured) -> jax.Array:
    """Fraction of torso output units that are <= 0 for EVERY element
    of the [T*B] batch — dead ReLUs the optimizer can no longer reach."""
    conv_out = captured["intermediates"]["convnet"]["__call__"][0]
    conv_out = jax.lax.stop_gradient(jnp.asarray(conv_out, jnp.float32))
    return jnp.mean(jnp.all(conv_out <= 0.0, axis=0).astype(jnp.float32))


def _layer_group(path) -> str:
    """Map a param-tree path to its LAYER_GROUPS bucket."""
    keys = {str(getattr(entry, "key", entry)) for entry in path}
    if "core" in keys:
        return "core"
    if "policy_logits" in keys or "baseline" in keys:
        return "heads"
    return "torso"


def _make_optimizer(hp: LearnerHyperparams) -> optax.GradientTransformation:
    # lr=1.0 here; the decayed lr is applied inside the update so it can be
    # keyed on env frames rather than update count (resume-exact, reference
    # experiment.py:409-415).
    #
    # initial_scale=1.0: tf.train.RMSPropOptimizer initializes the
    # mean-square accumulator to ONE (optax defaults to zero), and with
    # eps=0.1 that difference makes the first updates far larger than the
    # reference's — early training dynamics would diverge.
    #
    # Momentum-ordering note: with rmsprop_momentum != 0, the momentum
    # trace here accumulates un-lr-scaled steps (the decayed lr multiplies
    # the final update), whereas TF accumulates lr-scaled steps.  The two
    # differ only while the lr changes between steps; the reference default
    # is momentum=0, where both reduce to the same update.
    return optax.rmsprop(
        learning_rate=1.0,
        decay=hp.rmsprop_decay,
        eps=hp.rmsprop_epsilon,
        initial_scale=1.0,
        momentum=(hp.rmsprop_momentum
                  if hp.rmsprop_momentum else None),
    )


class Learner:
    """Owns the jitted sharded update.  Construct once per training run.

    ``frames_per_update`` = batch_size * unroll_length *
    num_action_repeats (reference: experiment.py:417-420).
    """

    def __init__(
        self,
        agent: ImpalaAgent,
        hp: LearnerHyperparams,
        mesh,
        frames_per_update: int,
        scan_impl: str = "auto",
        transport: str = "per_leaf",
        finite_guard: bool = True,
        device_telemetry: bool = True,
        learn_telemetry: bool = True,
        loss: str = "vtrace",
        target_update_interval: int = 100,
        impact_clip_epsilon: float = 0.3,
        fused_forward: bool = True,
    ):
        self._agent = agent
        # Fused single-forward loss (default): ONE whole-trajectory
        # unroll (Learner._forward) produces both the
        # behaviour-comparison quantities V-trace consumes (target
        # logits, values, bootstrap) and the differentiated loss
        # outputs.  ``False`` compiles the two-pass REFERENCE shape —
        # a separate stop-gradiented comparison unroll behind an
        # optimization barrier (so XLA cannot CSE it back into one) —
        # kept as bench_kernel_war's measurable baseline, not for
        # production.  Both compile to the same loss value and
        # gradient: V-trace stop-gradients every input (ops/vtrace.py).
        self._fused_forward = bool(fused_forward)
        self._hp = hp
        self._mesh = mesh
        self._frames_per_update = float(frames_per_update)
        # Loss surrogate: "vtrace" (the seed path, bit-for-bit) or
        # "impact" (clipped-target surrogate, ops/impact.py — the
        # replay-tolerant objective ROADMAP item 2 calls for).
        if loss not in ("vtrace", "impact"):
            raise ValueError(
                f"unknown loss {loss!r} (vtrace | impact)")
        if target_update_interval < 1:
            raise ValueError(
                f"target_update_interval must be >= 1, got "
                f"{target_update_interval}")
        self._loss_name = loss
        self._target_update_interval = float(target_update_interval)
        self._impact_clip_epsilon = float(impact_clip_epsilon)
        # The non-finite guard is fused into the jitted update (a
        # tree-wide isfinite reduction + per-leaf selects); ``False``
        # exists for bench_resilience's baseline measurement, not for
        # production runs.
        self._finite_guard = bool(finite_guard)
        if scan_impl == "auto":
            # The associative scan is the auto choice everywhere: at
            # production shapes V-trace is ~2-5 us on-chip either way
            # (BENCH_NOTES r4 — earlier "1.23x pallas win" numbers were
            # dispatch artifacts of the remote-TPU link), and only the
            # associative form shards over data/seq axes.  Explicit
            # "pallas" still forces the fused kernel (ops/
            # vtrace_pallas.py).  A seq axis > 1 auto-selects the
            # time-sharded recurrence (parallel/sequence.py — SURVEY
            # §5.7 sequence parallelism).
            if mesh.shape.get("seq", 1) > 1:
                scan_impl = "time_sharded"
            else:
                scan_impl = "associative"
        if scan_impl == "time_sharded" and mesh.shape.get("seq", 1) == 1:
            # Degenerate seq axis: the shard_map would be pure overhead.
            scan_impl = "associative"
        self._scan_impl = scan_impl
        if hp.rmsprop_momentum:
            import warnings

            warnings.warn(
                "rmsprop_momentum != 0: the momentum trace accumulates "
                "un-lr-scaled steps (TF accumulates lr-scaled steps), so "
                "updates diverge from the reference while the decayed lr "
                "changes between steps (see _make_optimizer note)",
                stacklevel=2)
        self._tx = _make_optimizer(hp)

        replicated = replicated_sharding(mesh)
        batch_b = batch_sharding(mesh, batch_axis_index=0)  # [B, ...]
        batch_tb = batch_sharding(mesh, batch_axis_index=1)  # [T+1, B, ...]
        # Prefix pytree: one sharding per Trajectory field covers the whole
        # subtree beneath it.
        traj_shardings = Trajectory(
            agent_state=batch_b,
            env_outputs=batch_tb,
            agent_outputs=batch_tb,
        )
        # Computation follows data: ``init``/``place_state`` and
        # ``put_trajectory`` commit arguments to their mesh shardings
        # (params/optimizer tensor-parallel over 'model', batch over
        # 'data'), and jit compiles the SPMD program from the argument
        # placements — no in_shardings pinning, so the same Learner
        # serves any (data, model) mesh shape.  The device-telemetry
        # pytree (obs/device_telemetry.py) rides as a third DONATED
        # argument: accumulation is in-place on device, and the host
        # only touches it at the log-interval fetch.
        self._update = jax.jit(self._update_impl, donate_argnums=(0, 2))
        # Replayed-batch variant: ``fresh=False`` is a PYTHON branch in
        # _update_impl (env_frames held, no target-net sync), so the
        # two jits are two specializations; the fresh one's jaxpr is
        # byte-identical to the pre-replay program.
        import functools

        self._update_replayed = jax.jit(
            functools.partial(self._update_impl, fresh=False),
            donate_argnums=(0, 2))
        self._replicated = replicated
        self._devtel_enabled = bool(device_telemetry)
        self._devtel_spec = (learner_telemetry_spec()
                             if self._devtel_enabled
                             else DeviceTelemetry("learner"))
        # Learning-dynamics plane (ISSUE 17): a second spec in its own
        # "learn" namespace, merged into the SAME donated pytree —
        # same buffers, same single log-interval fetch, zero new syncs.
        self._learn_enabled = bool(learn_telemetry) and self._devtel_enabled
        self._learn_spec = (learning_telemetry_spec(loss)
                            if self._learn_enabled
                            else DeviceTelemetry("learn"))
        # Normalizer for entropy_frac: the distribution's max entropy
        # (sum of log cell sizes — the joint entropy of the uniform
        # policy).
        self._max_entropy = max(
            float(sum(np.log(s) for s in agent.dist_spec.sizes)), 1e-6)
        self._devtel = self._place_replicated(
            merge_init(self.devtel_specs))
        self._devtel_publisher = (
            TelemetryPublisher(self.devtel_specs)
            if self._devtel_enabled else None)
        self._traj_shardings = traj_shardings
        # Host->device trajectory placement strategy: "per_leaf" (one
        # device_put per leaf — the seed path, bit-for-bit preserved) or
        # "packed" (single-copy H2D + jitted on-device unpack,
        # runtime/transport.py).
        self._transport = make_transport(
            transport, mesh, traj_shardings, _TRAJ_BATCH_AXES)
        registry = get_registry()
        self._h_put = registry.histogram(
            "learner/put_trajectory_s",
            "host->device trajectory placement seconds")
        self._updates_counter = registry.counter(
            "learner/updates_total", "update steps dispatched")
        self._frames_counter = registry.counter(
            "learner/env_frames_total",
            "env frames consumed by dispatched updates")
        self._replayed_counter = registry.counter(
            "learner/replayed_updates_total",
            "update steps dispatched on REPLAYED batches (their frames "
            "were already counted at fresh consumption)")
        if self._loss_name == "impact":
            # The anchor cadence, published so obs.report can convert
            # it into a staleness budget (interval / update rate) and
            # judge the replayed-staleness p95 against the clip's
            # useful range.
            registry.gauge(
                "replay/target_update_interval",
                "fresh updates between IMPACT target-network hard "
                "copies (the clipped-target surrogate's anchor "
                "cadence)").set(self._target_update_interval)

    @property
    def loss_name(self) -> str:
        """"vtrace" or "impact" — which surrogate the update compiles."""
        return self._loss_name

    @property
    def mesh(self):
        """The device mesh this learner's update is sharded over."""
        return self._mesh

    # -- device telemetry --------------------------------------------------

    def _place_replicated(self, tree):
        """Commit a small host pytree replicated onto the mesh — the
        multi-process path builds from local data (the place_state
        discipline: device_put onto a non-addressable sharding runs a
        hidden value-dependent collective)."""
        if jax.process_count() <= 1:
            return jax.device_put(tree, self._replicated)

        def _place(x):
            host = np.asarray(x)
            return jax.make_array_from_callback(
                host.shape, self._replicated,
                lambda idx, _h=host: _h[idx])

        return jax.tree_util.tree_map(_place, tree)

    @property
    def devtel_spec(self) -> DeviceTelemetry:
        """The learner's device-telemetry spec (empty when disabled)."""
        return self._devtel_spec

    @property
    def learn_spec(self) -> DeviceTelemetry:
        """The learning-dynamics spec (``devtel/learn/*``; empty when
        disabled)."""
        return self._learn_spec

    @property
    def devtel_specs(self):
        """Every non-empty spec riding this learner's donated telemetry
        pytree (learner counters + the learning-dynamics plane)."""
        return [spec for spec in (self._devtel_spec, self._learn_spec)
                if not spec.empty]

    @property
    def device_telemetry(self):
        """The CURRENT device-resident telemetry buffers.  Callers
        driving ``_update`` directly (bench AOT path, in-graph trainer)
        thread this pytree themselves; everyone else just calls
        ``update()``/``publish_device_telemetry()``."""
        return self._devtel

    def adopt_device_telemetry(self, devtel) -> None:
        """Rebind the telemetry buffers.  Callers driving the RAW
        jitted/AOT update themselves (bench's compiled wrapper, the
        in-graph trainer) receive the donated-and-returned pytree from
        each call; handing it back here keeps ``fetch_device_
        telemetry`` reading live buffers instead of donated husks."""
        self._devtel = devtel

    def lower_update(self, state: "TrainState", trajectory: "Trajectory"):
        """``jax.jit(...).lower`` of the update at these shapes — the
        one sanctioned way to lower it (cost analysis for the MFU
        gauge, HLO text for the kernel ledger) now that the jitted
        signature carries the telemetry buffers."""
        return self._update.lower(state, trajectory, self._devtel)

    def fetch_device_telemetry(self) -> Optional[Dict[str, np.ndarray]]:
        """Materialize the telemetry on the host — the ONE device→host
        sync the telemetry ever causes, sized a few hundred bytes; the
        driver calls it at log-interval cadence.  None when disabled."""
        if not self._devtel_enabled:
            return None
        return fetch_merged(self.devtel_specs, self._devtel)

    def publish_device_telemetry(self) -> Optional[Dict[str, np.ndarray]]:
        """Fetch + fold into the metrics registry (``devtel/learner/*``
        names ride the normal prom/report/aggregate path)."""
        fetched = self.fetch_device_telemetry()
        if fetched is not None:
            self._devtel_publisher.publish(fetched)
        return fetched

    # -- state ------------------------------------------------------------

    def init(self, rng: jax.Array, example_trajectory: Trajectory,
             env_frames: float = 0.0) -> TrainState:
        """Initialize params/optimizer, replicated over the mesh."""
        example = jax.tree_util.tree_map(
            lambda x: x if x is None else jnp.asarray(x),
            example_trajectory, is_leaf=lambda x: x is None)
        params = self._agent.init(
            rng,
            example.agent_outputs.action,
            example.env_outputs,
            example.agent_state,
        )
        opt_state = self._tx.init(params)
        state = TrainState(
            params=params,
            opt_state=opt_state,
            env_frames=jnp.float32(env_frames),
            nonfinite_skips=jnp.float32(0.0),
            nonfinite_streak=jnp.float32(0.0),
            # IMPACT: the target net starts as a DISTINCT copy of the
            # online params (jnp.array copies) — aliased buffers would
            # make the update's pytree donation try to donate the same
            # buffer twice.
            target_params=(jax.tree_util.tree_map(jnp.array, params)
                           if self._loss_name == "impact" else None),
        )
        return self.place_state(state)

    def state_shardings(self, state: TrainState) -> TrainState:
        """Sharding pytree for a TrainState: params + optimizer state
        tensor-parallel over 'model' (replicated when model=1), frame
        counter replicated."""
        return TrainState(
            params=model_parallel_shardings(self._mesh, state.params),
            opt_state=model_parallel_shardings(
                self._mesh, state.opt_state),
            env_frames=self._replicated,
            nonfinite_skips=self._replicated,
            nonfinite_streak=self._replicated,
            target_params=(
                None if state.target_params is None
                else model_parallel_shardings(
                    self._mesh, state.target_params)),
        )

    def place_state(self, state: TrainState) -> TrainState:
        """Commit a (host or device) TrainState onto the mesh — also the
        restore path after checkpoint load.

        Multi-process placement builds each global array from
        process-local data (``make_array_from_callback``) instead of
        ``jax.device_put``: device_put onto a non-addressable sharding
        runs a hidden per-leaf ``multihost_utils.assert_equal``
        collective inside jax whose fire-or-skip decision depends on
        each leaf's commitment state — the one value-dependent
        collective sequence in the whole setup path, and gloo (the CPU
        rig's transport) aborts the entire fleet on any cross-process
        divergence (pair.cc "op.preamble.length <= op.nbytes").  The
        callers already guarantee process-identical values (init: same
        seed; restore/rollback: the primary's state arrives by explicit
        broadcast), so the local build is also strictly cheaper: no
        params-sized network broadcast per init/restore."""
        if self._loss_name == "impact" and state.target_params is None:
            # Checkpoint migration (docs/robustness.md): a pre-IMPACT
            # (or --loss=vtrace) checkpoint restored into an impact run
            # initializes the target net FROM the online params — the
            # host-level copy below lands as distinct device buffers,
            # keeping the update's donation aliasing-free.  Runs AFTER
            # restore()'s manifest verification, which checked the
            # un-widened tree.
            host_params = jax.tree_util.tree_map(
                np.asarray, state.params)
            state = state._replace(
                target_params=jax.tree_util.tree_map(
                    np.array, host_params))
        shardings = self.state_shardings(state)
        if jax.process_count() <= 1:
            return jax.device_put(state, shardings)

        def _place(x, s):
            host = np.asarray(x)
            return jax.make_array_from_callback(
                host.shape, s, lambda idx, _h=host: _h[idx])

        return jax.tree_util.tree_map(_place, state, shardings)

    def put_trajectory(self, trajectory: Trajectory) -> Trajectory:
        """Host batch -> device, sharded over the data axis.

        Multi-process (multi-host): each process holds its LOCAL batch
        shard; the global array is assembled from per-process data so
        the data axis spans hosts (DCN) exactly like the reference's
        actors feeding one learner queue over gRPC
        (reference: experiment.py:531,556-562).  The fleet guard
        (runtime/fleet.py) bounds + attributes the assembly when a peer
        is lost under it — disabled/single-process it is one no-op
        call."""
        from scalable_agent_tpu.runtime.fleet import get_fleet

        with get_tracer().span("learner/put_trajectory", cat="h2d"), \
                self._h_put.time(), \
                get_fleet().collective("put_trajectory"):
            result = self._transport.put(trajectory)
        # Ledger stage boundary: device placement complete for the
        # calling thread's current trajectory record (the packed path
        # additionally stamped pack/upload/unpack inside put()).
        get_ledger().stamp_current("put_done")
        get_flight_recorder().record("queue", "put_trajectory")
        return result

    # -- update -----------------------------------------------------------

    def _forward(self, params, trajectory: Trajectory, capture=False):
        """The ONE whole-trajectory unroll of the update (reference:
        experiment.py:358-365).  Every loss quantity — the
        behaviour-comparison logits V-trace consumes AND the
        differentiated policy/value outputs — derives from this single
        apply; tests/test_learner_fused.py counts the lowered convs to
        pin it.  ``capture=True`` additionally captures the torso
        output (flax capture_intermediates) for the dead-unit gauge —
        still no second forward.  Returns ``((logits [T+1,B,L] f32,
        baselines [T+1,B] f32), dead_torso_frac | None)``."""
        if capture:
            (out, _), captured = self._agent.apply(
                params,
                trajectory.agent_outputs.action,
                trajectory.env_outputs,
                trajectory.agent_state,
                capture_intermediates=_torso_filter,
                mutable=["intermediates"],
            )
            return out, _dead_unit_fraction(captured)
        out, _ = self._agent.apply(
            params,
            trajectory.agent_outputs.action,
            trajectory.env_outputs,
            trajectory.agent_state,
        )
        return out, None

    def _comparison_forward(self, params, trajectory: Trajectory):
        """The UNFUSED (``fused_forward=False``) reference: a separate
        stop-gradiented unroll for the comparison quantities V-trace
        reads.  The optimization barrier keeps XLA from CSE-ing this
        pass back into the differentiated one (the two forwards are
        value-identical by construction, so without the barrier the
        'double forward' baseline would silently measure the fused
        program).  Exists to keep the single-vs-double-forward delta
        measurable (bench_kernel_war); production always fuses.
        ``stop_gradient`` BEFORE the barrier: optimization_barrier has
        no differentiation rule, and the comparison pass never needs
        one (its outputs are stop-gradiented anyway); stop_gradient is
        identity in lowered HLO, so the anti-CSE barrier survives."""
        barrier_params = jax.lax.optimization_barrier(
            jax.lax.stop_gradient(params))
        (logits, baselines), _ = self._forward(barrier_params, trajectory)
        return (jax.lax.stop_gradient(logits),
                jax.lax.stop_gradient(baselines))

    def _loss(self, params, trajectory: Trajectory, target_params=None):
        """Dispatch on the construction-time surrogate choice (a Python
        branch: each jit specialization compiles exactly one)."""
        if self._loss_name == "impact":
            return self._loss_impact(params, trajectory, target_params)
        return self._loss_vtrace(params, trajectory)

    def _loss_vtrace(self, params, trajectory: Trajectory):
        hp = self._hp
        (target_logits, baselines), dead_torso = self._forward(
            params, trajectory, capture=self._learn_enabled)
        if self._fused_forward:
            comparison_logits, comparison_baselines = (
                target_logits, baselines)
        else:
            comparison_logits, comparison_baselines = (
                self._comparison_forward(params, trajectory))
        # The last baseline is the bootstrap; then drop the last target
        # output and the first behaviour/env entry (reference:
        # experiment.py:368-375 — "use last baseline value for
        # bootstrapping").
        bootstrap_value = comparison_baselines[-1]
        behaviour = jax.tree_util.tree_map(
            lambda t: t[1:], trajectory.agent_outputs)
        env_outputs = jax.tree_util.tree_map(
            lambda t: t[1:], trajectory.env_outputs)
        target_logits = target_logits[:-1]
        baselines = baselines[:-1]
        comparison_logits = comparison_logits[:-1]
        comparison_baselines = comparison_baselines[:-1]

        rewards = losses_lib.clip_rewards(
            env_outputs.reward, hp.reward_clipping)
        discounts = jnp.where(
            env_outputs.done, 0.0, hp.discounting).astype(jnp.float32)

        dist_spec = self._agent.dist_spec
        # V-trace reads the COMPARISON quantities (identical tensors in
        # the fused path; V-trace stop-gradients internally, so the
        # unfused reference matches it bit-for-bit)...
        vt = vtrace.from_logits(
            behaviour_policy_logits=behaviour.policy_logits,
            target_policy_logits=comparison_logits,
            actions=behaviour.action,
            discounts=discounts,
            rewards=rewards,
            values=comparison_baselines,
            bootstrap_value=bootstrap_value,
            clip_rho_threshold=hp.clip_rho_threshold,
            clip_pg_rho_threshold=hp.clip_pg_rho_threshold,
            scan_impl=self._scan_impl,
            dist_spec=dist_spec,
            mesh=self._mesh if self._scan_impl == "time_sharded" else None,
        )

        # ...while the DIFFERENTIATED outputs feed the loss terms.
        pg_loss = losses_lib.compute_policy_gradient_loss(
            target_logits, behaviour.action, vt.pg_advantages,
            dist_spec=dist_spec)
        baseline_loss = losses_lib.compute_baseline_loss(
            vt.vs - baselines)
        entropy_loss = losses_lib.compute_entropy_loss(
            target_logits, dist_spec=dist_spec)
        total = (pg_loss + hp.baseline_cost * baseline_loss
                 + hp.entropy_cost * entropy_loss)
        metrics = {
            "total_loss": total,
            "policy_gradient_loss": pg_loss,
            "baseline_loss": baseline_loss,
            "entropy_loss": entropy_loss,
        }
        if self._learn_enabled:
            metrics.update(self._learning_metrics(
                vt, behaviour.policy_logits, target_logits, baselines,
                dist_spec, dead_torso))
        return total, metrics

    def _loss_impact(self, params, trajectory: Trajectory, target_params):
        """IMPACT clipped-target surrogate (ops/impact.py): V-trace
        advantages computed with the TARGET network as the target
        policy (so the β = min(c̄, π_tgt/μ) behaviour→target correction
        is V-trace's clipped pg-rho), then the PPO-shaped ratio clip of
        π_θ against π_tgt.  Baseline/entropy terms keep the vtrace
        branch's shape so the cost hyperparameters transfer."""
        hp = self._hp
        # ONE online unroll (capture feeds the dead-unit gauge — the
        # params being optimized).
        (online_logits, baselines), dead_torso = self._forward(
            params, trajectory, capture=self._learn_enabled)
        if self._fused_forward:
            comparison_baselines = baselines
        else:
            _, comparison_baselines = self._comparison_forward(
                params, trajectory)
        # Second (TARGET-net) unroll: the staleness anchor.  This one
        # is irreducible — different params — and is the price of
        # tolerating arbitrarily stale behaviour data; the fused-
        # forward contract is about the ONLINE net only.
        (anchor_logits, _), _ = self._forward(target_params, trajectory)
        bootstrap_value = comparison_baselines[-1]
        behaviour = jax.tree_util.tree_map(
            lambda t: t[1:], trajectory.agent_outputs)
        env_outputs = jax.tree_util.tree_map(
            lambda t: t[1:], trajectory.env_outputs)
        online_logits = online_logits[:-1]
        anchor_logits = anchor_logits[:-1]
        baselines = baselines[:-1]
        comparison_baselines = comparison_baselines[:-1]

        rewards = losses_lib.clip_rewards(
            env_outputs.reward, hp.reward_clipping)
        discounts = jnp.where(
            env_outputs.done, 0.0, hp.discounting).astype(jnp.float32)

        dist_spec = self._agent.dist_spec
        vt = vtrace.from_logits(
            behaviour_policy_logits=behaviour.policy_logits,
            target_policy_logits=anchor_logits,
            actions=behaviour.action,
            discounts=discounts,
            rewards=rewards,
            values=comparison_baselines,
            bootstrap_value=bootstrap_value,
            clip_rho_threshold=hp.clip_rho_threshold,
            clip_pg_rho_threshold=hp.clip_pg_rho_threshold,
            scan_impl=self._scan_impl,
            dist_spec=dist_spec,
            mesh=self._mesh if self._scan_impl == "time_sharded" else None,
        )

        surrogate = impact_lib.surrogate_from_logits(
            online_logits, anchor_logits, behaviour.action,
            vt.pg_advantages,
            clip_epsilon=self._impact_clip_epsilon,
            dist_spec=dist_spec)
        baseline_loss = losses_lib.compute_baseline_loss(
            vt.vs - baselines)
        entropy_loss = losses_lib.compute_entropy_loss(
            online_logits, dist_spec=dist_spec)
        total = (surrogate.loss + hp.baseline_cost * baseline_loss
                 + hp.entropy_cost * entropy_loss)
        metrics = {
            "total_loss": total,
            "policy_gradient_loss": surrogate.loss,
            "baseline_loss": baseline_loss,
            "entropy_loss": entropy_loss,
            "impact_ratio_mean": surrogate.ratio_mean,
            "impact_clip_fraction": surrogate.clip_fraction,
        }
        if self._learn_enabled:
            metrics.update(self._learning_metrics(
                vt, behaviour.policy_logits, online_logits, baselines,
                dist_spec, dead_torso))
            metrics["impact_log_ratio_mean"] = surrogate.log_ratio_mean
            metrics["impact_log_ratio_p95"] = surrogate.log_ratio_p95
            metrics["impact_ess_frac"] = surrogate.ess_frac
        return total, metrics

    def _learning_metrics(self, vt, behaviour_logits, online_logits,
                          baselines, dist_spec, dead_torso
                          ) -> Dict[str, jax.Array]:
        """The learning-dynamics scalars (ISSUE 17): V-trace clip/ESS
        diagnostics, policy entropy (absolute + normalized),
        behaviour→learner KL, value explained-variance, dead torso
        units.  All stop-gradiented — pure observation, the loss value
        and its gradient are bit-identical with the plane on or off."""
        sg = jax.lax.stop_gradient
        diag = vt.diagnostics
        online = sg(online_logits)
        entropy = jnp.mean(distributions.entropy(online, dist_spec))
        kl = jnp.mean(distributions.kl_divergence(
            sg(behaviour_logits), online, dist_spec))
        vs = sg(vt.vs)
        explained_variance = 1.0 - (
            jnp.var(vs - sg(baselines))
            / jnp.maximum(jnp.var(vs), jnp.float32(1e-8)))
        return {
            "policy_entropy": entropy,
            "entropy_frac": entropy / jnp.float32(self._max_entropy),
            "behaviour_kl": kl,
            "explained_variance": explained_variance,
            "rho_clip_fraction": diag.rho_clip_fraction,
            "cs_clip_fraction": diag.cs_clip_fraction,
            "pg_rho_clip_fraction": diag.pg_rho_clip_fraction,
            "log_rho_mean": diag.log_rho_mean,
            "log_rho_p95": diag.log_rho_p95,
            "ess_frac": diag.ess_frac,
            "dead_torso_frac": dead_torso,
        }

    def _update_impl(self, state: TrainState, trajectory: Trajectory,
                     devtel: Dict, fresh: bool = True
                     ) -> Tuple[TrainState, Dict, Dict[str, jax.Array]]:
        """One update.  ``devtel`` is the device-telemetry pytree
        (donated; may carry other specs' leaves — e.g. the in-graph
        trainer's env instruments — which pass through untouched).
        ``fresh`` is a PYTHON (specialization-time) flag: a replayed
        batch's update holds env_frames (the frames were counted at
        fresh consumption) and skips the target-net sync schedule.
        Returns ``(new_state, new_devtel, metrics)``."""
        (_, metrics), grads = jax.value_and_grad(
            self._loss, has_aux=True)(
                state.params, trajectory, state.target_params)

        # Linear decay to 0 over total frames (reference:
        # experiment.py:409-412 polynomial_decay power=1).
        frames = state.env_frames
        lr = self._hp.learning_rate * jnp.maximum(
            0.0, 1.0 - frames / self._hp.total_environment_frames)

        updates, opt_state = self._tx.update(
            grads, state.opt_state, state.params)
        updates = jax.tree_util.tree_map(lambda u: u * lr, updates)
        params = optax.apply_updates(state.params, updates)

        metrics = dict(metrics)
        metrics["learning_rate"] = lr
        metrics["grad_norm"] = optax.global_norm(grads)

        skips, streak = state.nonfinite_skips, state.nonfinite_streak
        if self._finite_guard:
            # All-finite verdict over loss + every gradient leaf, fused
            # into the update program (no host sync; the select below
            # makes a non-finite step a no-op on params/opt_state while
            # env_frames still advances — the batch WAS consumed, and
            # the driver's host-side frame accounting increments
            # unconditionally, so the two counts stay exact).
            finite = jnp.isfinite(metrics["total_loss"])
            for leaf in jax.tree_util.tree_leaves(grads):
                finite = jnp.logical_and(
                    finite, jnp.all(jnp.isfinite(leaf)))

            def keep(new, old):
                return jnp.where(finite, new, old)

            params = jax.tree_util.tree_map(keep, params, state.params)
            opt_state = jax.tree_util.tree_map(
                keep, opt_state, state.opt_state)
            skipped = 1.0 - finite.astype(jnp.float32)
            skips = skips + skipped
            streak = jnp.where(finite, 0.0, streak + 1.0)
            # The verdict rides the existing metrics dict: cumulative +
            # streak counters mean NO skip is lost even when the driver
            # only materializes metrics every few updates (in-flight
            # window) and only fetches them at log time.
            metrics["update_skipped"] = skipped
            metrics["nonfinite_skips"] = skips
            metrics["nonfinite_streak"] = streak

        target_params = state.target_params
        if self._loss_name == "impact" and fresh:
            # Periodic hard copy, fused into the update program (no
            # host sync): the UPDATED params overwrite the target every
            # ``target_update_interval`` fresh updates.  The schedule
            # keys on the frame counter (exact multiples of
            # frames_per_update, resume-exact like the LR schedule);
            # replayed updates hold the counter, so they never advance
            # the schedule.  The guard's `keep` select above already
            # chose params vs state.params, so a skipped (non-finite)
            # update syncs the HELD params — the target can never
            # absorb a poisoned step.
            k_next = (frames + self._frames_per_update) \
                / self._frames_per_update
            sync = jnp.mod(jnp.round(k_next),
                           self._target_update_interval) == 0.0
            target_params = jax.tree_util.tree_map(
                lambda t, p: jnp.where(sync, p, t),
                state.target_params, params)
        new_state = TrainState(
            params=params,
            opt_state=opt_state,
            env_frames=(frames + self._frames_per_update
                        if fresh else frames),
            nonfinite_skips=skips,
            nonfinite_streak=streak,
            target_params=target_params,
        )
        metrics["env_frames"] = new_state.env_frames
        if self._devtel_enabled:
            # Device telemetry: the same zero-host-sync contract as the
            # non-finite counters — a few scalar adds and one bucketed
            # observe fused into the update program.
            spec = self._devtel_spec
            devtel = spec.inc(devtel, "updates")
            devtel = spec.set(devtel, "loss", metrics["total_loss"])
            # A non-finite grad norm (the event the finite guard
            # absorbs) must not reach the histogram: its ":sum" buffer
            # is CUMULATIVE, so one NaN would poison every subsequent
            # fetch of the run.
            devtel = spec.observe(
                devtel, "grad_norm", metrics["grad_norm"],
                where=jnp.isfinite(metrics["grad_norm"]))
            if self._finite_guard:
                devtel = spec.inc(devtel, "skipped",
                                  metrics["update_skipped"])
        if self._learn_enabled:
            devtel = self._accumulate_learning_telemetry(
                devtel, metrics, grads, updates, params)
        return new_state, devtel, metrics

    def _accumulate_learning_telemetry(self, devtel, metrics, grads,
                                       updates, params):
        """Fold the learning-dynamics scalars into the donated devtel
        pytree inside the update program — gauge sets, histogram
        observes, and three tree reductions per layer group; no host
        sync (the same contract as the non-finite counters, proven by
        the transfer-guard tests)."""
        lspec = self._learn_spec
        for name in ("entropy_frac", "ess_frac", "explained_variance",
                     "rho_clip_fraction", "cs_clip_fraction",
                     "pg_rho_clip_fraction", "log_rho_mean",
                     "log_rho_p95", "dead_torso_frac"):
            devtel = lspec.set(devtel, name, metrics[name])
        devtel = lspec.set(devtel, "kl", metrics["behaviour_kl"])
        if self._loss_name == "impact":
            # Satellite fix: histograms aggregate EVERY update between
            # fetches — under --updates_per_dispatch=K the metrics dict
            # only surfaces the last of the K scan iterations, but
            # these observes run inside each iteration on the carried
            # devtel dict, so count/sum/mean cover all K.
            for hist, key in (("impact_ratio", "impact_ratio_mean"),
                              ("impact_clip_fraction",
                               "impact_clip_fraction")):
                value = metrics[key]
                devtel = lspec.observe(devtel, hist, value,
                                       where=jnp.isfinite(value))
            devtel = lspec.set(devtel, "impact_log_ratio_p95",
                               metrics["impact_log_ratio_p95"])
            devtel = lspec.set(devtel, "impact_ess_frac",
                               metrics["impact_ess_frac"])
        # Per-layer-group optimizer health: grads/updates/params share
        # one treedef, so a single flatten-with-path keys all three.
        zero = jnp.zeros((), jnp.float32)
        acc = {group: [zero, zero, zero] for group in LAYER_GROUPS}
        flat_grads, _ = jax.tree_util.tree_flatten_with_path(grads)
        flat_updates = jax.tree_util.tree_leaves(updates)
        flat_params = jax.tree_util.tree_leaves(params)
        for (path, g), u, p in zip(flat_grads, flat_updates, flat_params):
            group = acc[_layer_group(path)]
            group[0] = group[0] + jnp.sum(
                jnp.square(jnp.asarray(g, jnp.float32)))
            group[1] = group[1] + jnp.sum(
                jnp.square(jnp.asarray(u, jnp.float32)))
            group[2] = group[2] + jnp.sum(
                jnp.square(jnp.asarray(p, jnp.float32)))
        for name, (g_sq, u_sq, p_sq) in acc.items():
            param_norm = jnp.sqrt(p_sq)
            devtel = lspec.set(devtel, f"grad_norm_{name}",
                               jnp.sqrt(g_sq))
            devtel = lspec.set(devtel, f"param_norm_{name}", param_norm)
            # ``updates`` is already lr-scaled, so this is the actual
            # step taken relative to the weights it moved.
            devtel = lspec.set(
                devtel, f"update_ratio_{name}",
                jnp.sqrt(u_sq) / (param_norm + jnp.float32(1e-8)))
        return devtel

    def update(self, state: TrainState, trajectory: Trajectory,
               fresh: bool = True
               ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        """One training step.  ``trajectory`` should already be on device
        (``put_trajectory``) for best overlap; host batches also work.
        ``fresh=False`` marks a REPLAYED batch (runtime/replay.py): the
        update holds env_frames and the target-net schedule — the
        frames were counted when the batch was consumed fresh."""
        injector = get_fault_injector()
        if injector.active and injector.should_fire("nan_grad"):
            # Chaos: poison this batch's rewards so the loss (and every
            # gradient) goes NaN — the guard must absorb it as a skip.
            trajectory = trajectory._replace(
                env_outputs=trajectory.env_outputs._replace(
                    reward=trajectory.env_outputs.reward
                    * jnp.float32(float("nan"))))
        with get_tracer().span("learner/update", cat="learner"):
            update = self._update if fresh else self._update_replayed
            new_state, self._devtel, metrics = update(
                state, trajectory, self._devtel)
            out = (new_state, metrics)
        self._updates_counter.inc()
        if fresh:
            self._frames_counter.inc(self._frames_per_update)
        else:
            self._replayed_counter.inc()
        # Step-number breadcrumb: a crash dump's ring then pins exactly
        # how far training got, independent of any metrics flush.
        get_flight_recorder().record(
            "update", "learner", {"update": int(self._updates_counter.value)})
        return out


class NonFiniteTracker:
    """Host-side observer for the fused non-finite guard.

    The jitted update carries cumulative/consecutive skip counters in
    TrainState and mirrors them into its metrics dict; this tracker
    reads them whenever the driver fetches metrics anyway (log time),
    keeps the process-wide ``learner/nonfinite_skips_total`` counter and
    flight-recorder breadcrumbs in step, and answers the one policy
    question: has the consecutive-skip streak exhausted
    ``--nonfinite_tolerance``?  (``tolerance=0`` disables the policy;
    skips are still counted.)
    """

    def __init__(self, tolerance: int, registry=None):
        from scalable_agent_tpu.obs import get_registry as _get_registry

        self.tolerance = int(tolerance)
        registry = registry or _get_registry()
        self._counter = registry.counter(
            "learner/nonfinite_skips_total",
            "updates skipped by the non-finite guard (params/opt_state "
            "held, env frames still retired)")
        self._last_total = 0.0

    def observe(self, host_metrics: Dict[str, float]) -> bool:
        """Fold one fetched metrics dict in; True when the consecutive
        streak has reached the tolerance (caller rolls back / exits)."""
        total = float(host_metrics.get("nonfinite_skips", 0.0))
        streak = float(host_metrics.get("nonfinite_streak", 0.0))
        # Megaloop contract (runtime/ingraph.py TrainCarry.streak_peak):
        # the end-of-dispatch streak can have RESET mid-dispatch after
        # breaching the tolerance; the carried peak is the worst streak
        # since the last rollback, so the boundary check honors the
        # documented trigger at any updates_per_dispatch.
        streak = max(streak, float(
            host_metrics.get("nonfinite_streak_peak", 0.0)))
        delta = total - self._last_total
        if delta > 0:
            self._counter.inc(delta)
            get_flight_recorder().record(
                "nonfinite_skip", "learner",
                {"skips_total": total, "streak": streak})
        self._last_total = max(self._last_total, total)
        return bool(self.tolerance > 0 and streak >= self.tolerance)

    def rebase(self, total: float):
        """Re-anchor after a rollback: the restored state's cumulative
        counter is older than what we already counted — without this,
        the next observe() would double-count the gap."""
        self._last_total = float(total)
