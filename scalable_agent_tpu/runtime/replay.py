"""Device-resident trajectory replay: the actor/learner decoupling dial.

BENCH_r04's verdict is that the learner can consume ~2.75M env_frames/s
while the pipeline delivers 12.6k — and ROADMAP item 2's conclusion is
that no fixed actor fleet will ever close that gap with FRESH data, so
the architecture should stop requiring it.  This module is that
admission: a circular trajectory store that lives ON the device mesh
(the "In-Network Experience Sampling" placement argument, PAPERS.md —
the sample path's location dominates replay cost, and here it never
leaves the chips), fed by the transport layer's existing single H2D
upload, sampled by a jitted on-device gather.  With the IMPACT
clipped-target surrogate (ops/impact.py) tolerating the extra staleness,
``--replay_ratio=R`` turns into a throughput dial: R replayed updates
ride behind every fresh batch, and actor fps and learner fps become
independent knobs.

Design:

- **Storage** is a pytree of slabs, one per stored-tree leaf:
  ``[capacity, *leaf_shape]``, sharded ``PartitionSpec(None, *leaf
  spec)`` — slot-major over the SAME mesh axes the live batch uses, so
  slot k of every slab holds shard-aligned rows and a gather never
  moves bytes across devices.  Two producers use the same store:

  * the host backend inserts the packed transport's UPLOADED buffer
    (``PackedTransport.set_upload_sink`` — the slab write is a
    device-side ``dynamic_update_slice`` of bytes that already paid
    their one H2D copy; no second upload, no host-side buffer), and
    samples are restored to Trajectories by the transport's existing
    jitted unpack (``postprocess``);
  * the in-graph backend inserts device-born Trajectory pytrees
    directly.

- **Sampling** is uniform over valid slots with a DEVICE-resident
  counter-folded PRNG (``fold_in(key(seed), sample_counter)``) — the
  same key math on every process, so all data shards gather the same
  slot.  Insert and sample are jitted programs over device-resident
  operands only: zero host→device transfers beyond the transport
  upload that already existed, zero device→host syncs
  (tests/test_replay.py proves both the PR 12 way —
  ``jax.transfer_guard("disallow")`` + materialization spies).

- **Staleness accounting without a sync**: the host cannot read the
  sampled slot index without a fetch, so it doesn't — it REPLAYS the
  same deterministic PRNG on the CPU backend (threefry is
  backend-independent) against its mirrored counter/filled values,
  recovers the identical slot, and feeds the slot's recorded birth
  stamp into ``ledger/staleness_replayed_s``.  The fresh/replayed
  split keeps the staleness histogram honest when R > 0
  (obs/ledger.py).

Buffer contents are deliberately NOT checkpointed: a restored run
warms the buffer back up from its first fresh batches
(docs/robustness.md, "Replay warm-up after restore").
"""

import threading
import time
from typing import Any, Callable, List, Optional

from scalable_agent_tpu.obs import get_ledger, get_registry
from scalable_agent_tpu.obs.ledger import now_us
from scalable_agent_tpu.runtime.faults import get_fault_injector
from scalable_agent_tpu.runtime.transport import (
    tree_flatten_with_none,
    tree_unflatten,
)

__all__ = ["DeviceReplayBuffer"]


def _slab_sharding(leaf):
    """The slab sharding for one stored leaf: the leaf's own mesh spec
    with a replicated slot axis in front (slot k's shard layout ==
    the live leaf's).  None when the leaf's sharding isn't a
    NamedSharding (the constraint is then skipped — correctness is
    unaffected, XLA just chooses the layout)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = getattr(leaf, "sharding", None)
    if isinstance(sharding, NamedSharding):
        return NamedSharding(
            sharding.mesh, PartitionSpec(None, *sharding.spec))
    return None


class DeviceReplayBuffer:
    """Circular device-resident trajectory store, sharded over the mesh.

    ``capacity`` counts whole stored trees (one learner batch each).
    ``postprocess`` maps a sampled stored tree to the Trajectory the
    learner eats (the packed path passes the transport's jitted unpack;
    the in-graph path stores Trajectories directly and passes None).
    Thread model: one lock serializes ``insert``/``sample`` host
    dispatch (the prefetch thread inserts while the update loop
    samples); the device programs themselves are ordered by the jax
    runtime.
    """

    def __init__(self, capacity: int, seed: int = 0,
                 postprocess: Optional[Callable[[Any], Any]] = None,
                 registry=None):
        if capacity < 1:
            raise ValueError(
                f"replay capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._seed = int(seed)
        self._postprocess = postprocess
        self._lock = threading.Lock()
        # Lazily built from the first inserted tree (shapes/dtypes are
        # a runtime property of the env/transport).
        self._slabs: Optional[List] = None
        self._treedef = None
        self._shardings: Optional[List] = None
        self._insert_jit = None
        self._sample_jit = None
        # Device-resident ring state (i32 scalars; donated through the
        # jitted insert/sample so the ring advances with no host sync).
        self._cursor = None
        self._filled = None
        self._counter = None
        # Host mirrors: exact copies of the device ring state, advanced
        # by the same +1 arithmetic at dispatch time — they fund the
        # occupancy gauge and the staleness mirror without ever reading
        # the device.
        self._host_filled = 0
        self._host_cursor = 0
        self._host_counter = 0
        self._slot_birth_us: List[int] = [0] * self.capacity
        registry = registry or get_registry()
        self._c_inserts = registry.counter(
            "replay/insert_total",
            "trajectory batches inserted into the device replay slab")
        self._c_samples = registry.counter(
            "replay/sampled_total",
            "trajectory batches sampled from the device replay slab")
        self._c_flushes = registry.counter(
            "replay/rollback_flushes_total",
            "slab flushes dropping an abandoned timeline's trajectories "
            "(rollback or sentinel demotion)")
        import weakref

        self_ref = weakref.ref(self)
        registry.gauge(
            "replay/occupancy",
            "filled fraction of the device replay slab",
            fn=lambda: ((buf._host_filled / buf.capacity)
                        if (buf := self_ref()) is not None else 0.0))
        self._h_insert = registry.histogram(
            "replay/insert_s",
            "host dispatch seconds of the jitted slab insert")
        self._h_sample = registry.histogram(
            "replay/sample_s",
            "host dispatch seconds of the jitted slab sample (+unpack)")

    # -- introspection -----------------------------------------------------

    @property
    def size(self) -> int:
        """Valid slots (host mirror; exact — inserts are host-dispatched)."""
        return self._host_filled

    def flush(self) -> None:
        """Empty the ring WITHOUT freeing the slabs: occupancy -> 0, so
        every stored trajectory becomes unreachable (sample() gates on
        ``filled``; the stale slot bytes are dead until overwritten).

        This is the rollback/demotion hygiene hook (driver.py): a
        restored timeline (or a sentinel-demoted hot path) must not
        train on the abandoned lineage's trajectories — the off-policy
        dial re-warms from fresh batches, paced by the driver's
        ``size >= 1`` sample gate.  The PRNG counter deliberately keeps
        advancing (not reset): the sampling stream stays unique across
        the flush, and a resumed run can't replay the pre-flush slot
        choices against different slab contents."""
        import jax.numpy as jnp

        with self._lock:
            if self._slabs is not None:
                self._cursor = jnp.zeros((), jnp.int32)
                self._filled = jnp.zeros((), jnp.int32)
            self._host_cursor = 0
            self._host_filled = 0
            self._slot_birth_us = [0] * self.capacity
        self._c_flushes.inc()

    # -- lazy construction -------------------------------------------------

    def _ensure(self, tree) -> None:
        import jax
        import jax.numpy as jnp
        from jax import lax

        if self._slabs is not None:
            return
        leaves, self._treedef = tree_flatten_with_none(tree)
        self._shardings = [None if leaf is None else _slab_sharding(leaf)
                           for leaf in leaves]
        slabs = []
        for leaf, sharding in zip(leaves, self._shardings):
            if leaf is None:
                slabs.append(None)
                continue
            slab = jnp.zeros((self.capacity,) + tuple(leaf.shape),
                             leaf.dtype)
            if sharding is not None:
                slab = jax.device_put(slab, sharding)
            slabs.append(slab)
        self._slabs = slabs
        self._cursor = jnp.zeros((), jnp.int32)
        self._filled = jnp.zeros((), jnp.int32)
        self._counter = jnp.zeros((), jnp.int32)
        shardings = self._shardings
        capacity = self.capacity
        seed = self._seed

        def insert(slabs, cursor, filled, leaves):
            out = []
            for slab, leaf, sharding in zip(slabs, leaves, shardings):
                if slab is None:
                    out.append(None)
                    continue
                updated = lax.dynamic_update_slice(
                    slab, leaf[None], (cursor,) + (0,) * leaf.ndim)
                if sharding is not None:
                    updated = lax.with_sharding_constraint(
                        updated, sharding)
                out.append(updated)
            return (out, (cursor + 1) % capacity,
                    jnp.minimum(filled + 1, capacity))

        def sample(slabs, filled, counter):
            slot = _slot_index(seed, counter, filled)
            out = []
            for slab, sharding in zip(slabs, shardings):
                if slab is None:
                    out.append(None)
                    continue
                row = lax.dynamic_slice(
                    slab, (slot,) + (0,) * (slab.ndim - 1),
                    (1,) + slab.shape[1:])
                row = row.reshape(slab.shape[1:])
                if sharding is not None:
                    row = lax.with_sharding_constraint(
                        row, _row_sharding(sharding))
                out.append(row)
            return out, counter + 1

        # Slabs and ring scalars are DONATED: the store advances in
        # place on device, holding exactly one slab's worth of HBM.
        self._insert_jit = jax.jit(insert, donate_argnums=(0, 1, 2))
        self._sample_jit = jax.jit(sample, donate_argnums=(2,))

    # -- the two operations ------------------------------------------------

    def insert(self, tree, birth_us: Optional[int] = None) -> None:
        """Store one device-resident tree (a packed upload buffer or a
        Trajectory pytree) into the next ring slot.  ``birth_us`` is
        the batch's unroll-birth stamp (ledger clock) for staleness
        attribution; defaults to now."""
        t0 = time.perf_counter()
        with self._lock:
            self._ensure(tree)
            leaves, treedef = tree_flatten_with_none(tree)
            if treedef != self._treedef:
                raise ValueError(
                    "inserted tree structure does not match the replay "
                    "slab layout")
            self._slabs, self._cursor, self._filled = self._insert_jit(
                self._slabs, self._cursor, self._filled, leaves)
            self._slot_birth_us[self._host_cursor] = (
                int(birth_us) if birth_us is not None else now_us())
            self._host_cursor = (self._host_cursor + 1) % self.capacity
            self._host_filled = min(self._host_filled + 1, self.capacity)
        dt = time.perf_counter() - t0
        self._c_inserts.inc()
        self._h_insert.observe(dt)
        get_ledger().note_service("replay_insert", 1, dt)

    def sample(self):
        """One uniformly sampled stored tree, postprocessed to a
        Trajectory — dispatch only, zero host sync.  Raises when the
        buffer is empty (the driver's insert-before-sample ordering
        makes that unreachable in the training loop)."""
        t0 = time.perf_counter()
        with self._lock:
            if self._host_filled < 1:
                raise RuntimeError(
                    "replay sample from an empty buffer (insert at "
                    "least one batch first)")
            leaves, self._counter = self._sample_jit(
                self._slabs, self._filled, self._counter)
            counter, filled = self._host_counter, self._host_filled
            self._host_counter += 1
            # Snapshot the birth stamps INSIDE the lock: the device
            # gather was dispatched under this lock, so the stamps as
            # of now are the ones its slots held — a concurrent insert
            # landing after release must not relabel the sampled
            # slot's age with the NEW batch's birth.
            births = tuple(self._slot_birth_us)
        tree = tree_unflatten(self._treedef, leaves)
        if self._postprocess is not None:
            tree = self._postprocess(tree)
        injector = get_fault_injector()
        if injector.active and injector.should_fire("replay_corrupt"):
            # Chaos (runtime/faults.py): poison the sampled batch's
            # rewards with NaN — the learner's non-finite guard must
            # absorb the replayed update as a bit-exact no-op and the
            # skip counter must attribute it.
            import jax.numpy as jnp

            tree = tree._replace(
                env_outputs=tree.env_outputs._replace(
                    reward=tree.env_outputs.reward
                    * jnp.float32(float("nan"))))
        dt = time.perf_counter() - t0
        self._c_samples.inc()
        self._h_sample.observe(dt)
        ledger = get_ledger()
        ledger.note_service("replay_sample", 1, dt)
        slot = self._mirror_slot(counter, filled)
        if slot is not None:
            age_s = max(0.0, (now_us() - births[slot]) / 1e6)
            ledger.observe_replay_staleness(age_s)
        return tree

    # -- staleness mirror --------------------------------------------------

    def _mirror_slot(self, counter: int, filled: int) -> Optional[int]:
        """Replay the device's slot draw on the CPU backend: threefry
        is backend-independent, so the same (seed, counter, filled)
        yields the SAME slot the device gathered — staleness
        attribution without touching the accelerator.  Best-effort:
        None (skip the observation) if the CPU backend is unavailable."""
        import jax

        try:
            # The mirror is host-local CPU work by construction; exempt
            # it from a caller's transfer guard (the guard exists to
            # catch ACCELERATOR transfers).
            with jax.transfer_guard("allow"):
                cpu = jax.local_devices(backend="cpu")[0]
                with jax.default_device(cpu):
                    return int(_slot_index(self._seed, counter, filled))
        except Exception:
            return None


def _slot_index(seed: int, counter, filled):
    """THE slot draw — one definition shared by the jitted device
    sample and the host-side CPU mirror, so the two can never diverge:
    uniform over [0, filled) keyed on fold_in(key(seed), counter)."""
    import jax
    import jax.numpy as jnp

    key = jax.random.fold_in(jax.random.key(seed), counter)
    return jax.random.randint(key, (), 0, jnp.maximum(filled, 1))


def _row_sharding(slab_sharding):
    """A sampled row's sharding: the slab spec minus the slot axis."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(slab_sharding.mesh,
                         PartitionSpec(*slab_sharding.spec[1:]))
