"""Elastic fleet membership: reshard-and-continue on peer loss.

PR 5 (runtime/fleet.py) turned "one peer dies -> every survivor hangs
forever" into a bounded, attributed exit 72 — but the whole fleet still
died with the peer: the survivors' only recovery was a full external
restart at the SAME size, impossible while the lost host is gone.  This
module is that external restart, made a first-class, membership-aware
part of the system (ROADMAP item 3; the availability story behind
IMPALA's decoupled design and the preemption-tolerant fleet schedulers
in PAPERS.md):

- **The supervisor** (``--elastic`` on the driver, or
  ``python -m scalable_agent_tpu.runtime.elastic`` with the same
  flags) owns the N worker processes.  It never trains — and never
  initializes a jax backend (on TPU that would lock the chips its
  workers need).  It watches worker EXIT CODES through the registry in
  runtime/exit_codes.py and the machine-readable membership verdict
  the fleet monitor writes (``<logdir>/fleet_epoch.json``: epoch,
  kind, lost peers, last verified checkpoint step).

- **Membership epochs.**  Every (re)launch is one epoch.  A
  fleet-fatal (exit 72 on the survivors, the lost worker SIGKILLed)
  becomes a RESHARD event: the lost slot is marked out, and the
  survivors relaunch as an (N-1)-process fleet — within a restart
  budget with capped exponential backoff — resuming frame-exact from
  the newest verified checkpoint (the walk-back restore owns which
  step that is; ``verify_after_reshard`` in runtime/checkpoint.py
  re-proves the per-leaf CRCs after the state reshards over the new,
  smaller mesh).  ``fleet/resize_total`` counts membership-size
  changes; MTTR (first observed worker death -> first post-reshard
  metrics row) lands in ``fleet/mttr_s`` and ``fleet_epochs.jsonl``,
  decomposed into detect/relaunch/compile/restore segments via the
  driver's ``mttr_breakdown.json`` startup beacon (the compile
  segment also lands in ``fleet/mttr_compile_s``; arm
  ``--compile_cache_dir`` to flatten it).

- **Rejoin.**  When the lost host comes back (locally:
  ``--elastic_rejoin_delay_s`` elapsed, or an operator touched
  ``<logdir>/rejoin.<slot>``), the supervisor schedules a scale-up at
  the next checkpoint boundary: it SIGTERMs the running fleet, whose
  preemption-grace protocol (PR 5) drains to ONE coordinated verified
  checkpoint and exits 0, then relaunches at the full size — so the
  fleet returns to N without losing a single verified frame.

- **Exit-code policy** (docs/robustness.md renders this): 72 and
  SIGABRT (134 / signal 6 — jax's own client fatal when the
  coordinator dies, see runtime/fleet.py) are *reshardable*; SIGKILL
  marks the slot *lost*; 70 (watchdog wedge) and 73 (the numerics
  sentinel's silent-corruption verdict, runtime/sentinel.py) restart
  at the same shape — a wedge clears on relaunch, and a sentinel trip
  that survived the ladder + rollback points at transient hardware
  state a fresh process may not share (the resumed run re-audits from
  its first interval); 71 (non-finite) is *fatal* — something
  poisoned the regime
  and a supervisor restarting blindly would just replay it; 0 is done
  — unless the epoch's verdict file says "preempt", in which case the
  drain was a checkpoint, not a finish line, and the fleet relaunches.

The membership history is one timeline: ``fleet_epochs.jsonl`` (one
JSON line per launch/exit/mttr event), the workers' ``fleet/epoch``
gauge (obs/aggregate.py folds it max), and the supervisor's own
``metrics.supervisor.prom`` snapshot (``fleet/resize_total``,
``fleet/mttr_s``) that the aggregator merges under the ``supervisor``
process label.

Everything is testable without real fleets: the launcher is
injectable (tests/test_elastic.py drives the whole state machine with
scripted fake workers and a virtual clock), and the real soak
(tests/test_elastic_multiproc.py, markers ``multiproc slow``) proves a
3-process fleet losing a peer via SIGKILL continues as 2 within the
MTTR budget and scales back to 3, frame-exact throughout.
"""

import json
import os
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence

from scalable_agent_tpu.runtime.exit_codes import (
    FLEET_EXIT_CODE,
    NONFINITE_EXIT_CODE,
    SENTINEL_EXIT_CODE,
    WATCHDOG_EXIT_CODE,
)
from scalable_agent_tpu.runtime.fleet import EPOCH_VERDICT_NAME
from scalable_agent_tpu.utils import log

__all__ = [
    "DriverLauncher",
    "ElasticSupervisor",
    "classify_exit",
    "compatible_fleet_size",
    "main",
    "run_supervised",
]

EPOCHS_LOG_NAME = "fleet_epochs.jsonl"
SUPERVISOR_PROM_NAME = "metrics.supervisor.prom"
# The driver's startup-cost beacon (driver._write_mttr_breakdown):
# {"epoch": E, "restore_s": ..., "compile_s": ...} written atomically
# by the relaunched coordinator after its first dispatch.  The
# supervisor joins it (epoch-matched) into the epochs-log ``mttr``
# record so the recovery time decomposes into detect / relaunch /
# compile / restore segments — the evidence behind the
# --compile_cache_dir MTTR engineering (docs/robustness.md).
MTTR_BREAKDOWN_NAME = "mttr_breakdown.json"

# Exit-code policy (the supervisor side of runtime/exit_codes.py).
RESHARDABLE = "reshardable"   # relaunch; the slot survives
LOST = "lost"                 # the slot's host is gone: reshard N-1
RESTART_SAME = "restart"      # wedge (watchdog 70): relaunch as-is
FATAL = "fatal"               # inspect before restarting (non-finite)
OK = "ok"

# jax's C++ coordination client aborts (signal 6) when the coordinator
# dies under it — a SURVIVOR of someone else's death, not a lost host
# (runtime/fleet.py module docstring; subprocess reports it as -6,
# a shell as 134).
_SIGABRT_CODES = (-signal.SIGABRT, 128 + signal.SIGABRT)
_SIGKILL_CODES = (-signal.SIGKILL, 128 + signal.SIGKILL)


def compatible_fleet_size(batch_size: Optional[int], max_n: int) -> int:
    """The largest fleet size <= ``max_n`` that divides the global
    batch (the driver shards the batch evenly over processes).  An
    elastic reshard cannot pick its survivor count — hosts die where
    they die — so incompatible intermediate sizes are SKIPPED: a
    batch-256 fleet that drops from 4 hosts to 3 runs as 2 (the third
    stays idle until the lost host rejoins) rather than failing at
    relaunch.  ``batch_size=None`` disables the constraint."""
    if batch_size is None:
        return max_n
    for n in range(max_n, 1, -1):
        if batch_size % n == 0:
            return n
    return 1


def _exit_status(code: int) -> int:
    """``Popen``'s killed-by-signal ``-N`` -> the POSIX ``128+N``
    status an outer scheduler actually sees; non-negative codes pass
    through.  Without this, propagating ``max(codes)`` of a
    segfaulting fleet would exit the supervisor with a raw negative
    (rendered as a meaningless 2xx status) instead of 139."""
    return 128 - code if code < 0 else code


def classify_exit(code: int) -> str:
    """One worker exit code -> supervisor policy bucket."""
    if code == 0:
        return OK
    if code == NONFINITE_EXIT_CODE:
        return FATAL
    if code in (WATCHDOG_EXIT_CODE, SENTINEL_EXIT_CODE):
        # 73: the sentinel exhausted its ladder + rollback — the shape
        # is fine, the arithmetic wasn't; relaunch as-is and let the
        # fresh process's audits re-judge the hardware.
        return RESTART_SAME
    if code in _SIGKILL_CODES:
        return LOST
    if code == FLEET_EXIT_CODE or code in _SIGABRT_CODES:
        return RESHARDABLE
    # Any other death (tracebacked exception, segfault, OOM-kill shows
    # as SIGKILL above): the host is fine, the process crashed —
    # relaunch against the restart budget.
    return RESHARDABLE


class DriverLauncher:
    """Spawn one epoch's worker fleet: N copies of the driver CLI on
    this machine, sharing a fresh coordinator port.  Workers inherit
    the supervisor's stdout/stderr (nothing buffers, nothing
    deadlocks) and environment — the CPU test rig sets JAX_PLATFORMS
    / XLA_FLAGS there.  Real multi-host deployments replace this class
    (one worker per host via the cluster scheduler); the supervisor's
    state machine doesn't change."""

    # Supervisor-owned fields the workers must not inherit verbatim.
    EXCLUDE = ("elastic", "fleet_epoch", "distributed_coordinator",
               "distributed_num_processes", "distributed_process_id")

    def __init__(self, config, env: Optional[Dict[str, str]] = None):
        self._config = config
        self._env = env

    def launch(self, epoch: int, num_processes: int,
               port: int) -> List[subprocess.Popen]:
        base = self._config.to_argv(exclude=self.EXCLUDE)
        workers = []
        for proc_id in range(num_processes):
            args = [
                sys.executable, "-m", "scalable_agent_tpu.driver",
                *base,
                f"--fleet_epoch={epoch}",
                f"--distributed_coordinator=localhost:{port}",
                f"--distributed_num_processes={num_processes}",
                f"--distributed_process_id={proc_id}",
            ]
            workers.append(subprocess.Popen(args, env=self._env))
        return workers


class ElasticSupervisor:
    """The membership state machine.  Injectable launcher/clock/sleep/
    port factory so every transition is unit-testable; the defaults
    run real fleets."""

    def __init__(self, n_target: int, logdir: str,
                 launcher,
                 restart_budget: int = 8,
                 stable_s: float = 300.0,
                 rejoin_delay_s: float = 60.0,
                 backoff_initial_s: float = 1.0,
                 backoff_cap_s: float = 30.0,
                 poll_s: float = 0.5,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 port_factory: Optional[Callable[[], int]] = None,
                 registry=None,
                 batch_size: Optional[int] = None):
        if n_target < 1:
            raise ValueError(f"n_target must be >= 1, got {n_target}")
        self.n_target = int(n_target)
        self._batch_size = batch_size
        self.logdir = os.path.abspath(logdir)
        self._launcher = launcher
        self._restart_budget = int(restart_budget)
        self._stable_s = float(stable_s)
        self._rejoin_delay_s = float(rejoin_delay_s)
        self._backoff_initial_s = float(backoff_initial_s)
        self._backoff_cap_s = float(backoff_cap_s)
        self._poll_s = float(poll_s)
        self._clock = clock
        self._sleep = sleep
        if port_factory is None:
            from scalable_agent_tpu.parallel.distributed import (
                pick_unused_port,
            )

            port_factory = pick_unused_port
        self._port_factory = port_factory

        # Slot model: slot i is a host seat.  available -> may run a
        # worker; lost_at timestamps when its worker was killed out
        # from under us (SIGKILL = the host is gone).
        self._available = [True] * self.n_target
        self._lost_at: Dict[int, float] = {}
        self.epoch = -1
        self._prev_n: Optional[int] = None
        self._consecutive_failures = 0
        self._shutdown_requested = False
        self._last_mttr_s: Optional[float] = None

        if registry is None:
            from scalable_agent_tpu.obs import get_registry

            registry = get_registry()
        self._epoch_gauge = registry.gauge(
            "fleet/epoch",
            "membership epoch of the currently-running fleet")
        self._size_gauge = registry.gauge(
            "fleet/size", "worker processes in the current epoch")
        self._resizes = registry.counter(
            "fleet/resize_total",
            "membership-size changes (reshard down + rejoin up)")
        self._mttr_gauge = registry.gauge(
            "fleet/mttr_s",
            "last reshard's mean-time-to-recover: first observed "
            "worker death to the first post-reshard metrics row")
        self._mttr_compile_gauge = registry.gauge(
            "fleet/mttr_compile_s",
            "compile segment of the last reshard's MTTR (the relaunched "
            "coordinator's first dispatch) — near-zero when "
            "--compile_cache_dir turns it into a disk read")
        self._restarts = registry.counter(
            "fleet/supervisor_restarts_total",
            "fleet relaunches after a non-clean epoch exit")
        from scalable_agent_tpu.obs import PrometheusExporter

        os.makedirs(self.logdir, exist_ok=True)
        self._prom = PrometheusExporter(
            registry, os.path.join(self.logdir, SUPERVISOR_PROM_NAME))

    # -- small pure helpers (unit-tested) ----------------------------------

    def available_slots(self) -> List[int]:
        return [i for i, up in enumerate(self._available) if up]

    def mark_lost(self, slot: int, now: Optional[float] = None):
        if self._available[slot]:
            self._available[slot] = False
            self._lost_at[slot] = (self._clock() if now is None
                                   else now)

    def rejoinable_slots(self, now: Optional[float] = None) -> List[int]:
        """Lost slots whose hosts count as back: the rejoin delay
        elapsed, or an operator touched ``<logdir>/rejoin.<slot>``."""
        now = self._clock() if now is None else now
        back = []
        for slot, lost_at in self._lost_at.items():
            marker = os.path.join(self.logdir, f"rejoin.{slot}")
            if (now - lost_at >= self._rejoin_delay_s
                    or os.path.exists(marker)):
                back.append(slot)
        return sorted(back)

    def _rejoin(self, slots: Sequence[int]):
        for slot in slots:
            self._available[slot] = True
            self._lost_at.pop(slot, None)
            marker = os.path.join(self.logdir, f"rejoin.{slot}")
            try:
                os.remove(marker)
            except OSError:
                pass

    def backoff_s(self) -> float:
        """Capped exponential backoff keyed on consecutive failures."""
        if self._consecutive_failures <= 0:
            return 0.0
        return min(self._backoff_cap_s,
                   self._backoff_initial_s
                   * 2 ** (self._consecutive_failures - 1))

    def read_verdict(self) -> Optional[dict]:
        """The fleet's membership verdict file (fleet_epoch.json), or
        None when absent/unparseable.  ``_run`` deletes the file
        before every launch, so what's here was written by a CURRENT
        incarnation's epoch — callers still compare
        ``verdict["epoch"]`` against the epoch that just exited (an
        older epoch of THIS incarnation could have raced its exit)."""
        try:
            return json.load(open(
                os.path.join(self.logdir, EPOCH_VERDICT_NAME)))
        except (OSError, json.JSONDecodeError, ValueError):
            return None

    def _record(self, event: str, **fields):
        """One line of membership history (fleet_epochs.jsonl) + a
        fresh supervisor prom snapshot."""
        payload = dict(event=event, epoch=self.epoch,
                       t_unix=time.time(), **fields)
        path = os.path.join(self.logdir, EPOCHS_LOG_NAME)
        try:
            with open(path, "a") as f:
                f.write(json.dumps(payload) + "\n")
        except OSError:
            log.exception("elastic: could not append %s", path)
        try:
            self._prom.dump()
        except Exception:
            log.exception("elastic: supervisor prom dump failed")

    # -- steady-state cycle (bench-timed) ----------------------------------

    def watch_cycle(self, workers, jsonl_baseline: Optional[int],
                    mttr_anchor: Optional[float]):
        """One supervisor poll: worker exit codes, the post-reshard
        MTTR beacon, and the rejoin probe.  This is the WHOLE
        steady-state cost of being supervised (bench.py bench_elastic
        amortizes it at the poll cadence against the update stage);
        everything heavier happens only on membership transitions.

        Returns ``(codes, mttr_s)`` — per-worker exit codes (None =
        running) and the measured MTTR if the beacon fired this
        cycle."""
        codes = [w.poll() for w in workers]
        mttr_s = None
        if mttr_anchor is not None:
            path = os.path.join(self.logdir, "metrics.jsonl")
            try:
                size = os.path.getsize(path)
            except OSError:
                size = 0
            if size > (jsonl_baseline or 0):
                mttr_s = self._clock() - mttr_anchor
        return codes, mttr_s

    # -- the run -----------------------------------------------------------

    def _install_signals(self):
        """Supervisor SIGTERM/SIGINT = drain the fleet gracefully and
        exit — the workers' own grace protocol turns that into one
        coordinated verified checkpoint.  Returns an uninstall
        callable (run() restores the handlers on the way out so an
        in-process caller — a test — keeps its own)."""

        def _on_signal(signum, frame):
            self._shutdown_requested = True
            log.warning("elastic: %s — draining the fleet to a final "
                        "checkpoint and exiting",
                        signal.Signals(signum).name)

        prev = {}
        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                prev[sig] = signal.signal(sig, _on_signal)
        except ValueError:  # not the main thread (tests)
            prev.clear()

        def uninstall():
            for sig, handler in prev.items():
                try:
                    signal.signal(sig, handler)
                except ValueError:
                    pass

        return uninstall

    def _read_mttr_breakdown(self) -> dict:
        """The current epoch's startup-cost beacon
        (``MTTR_BREAKDOWN_NAME``), or {} when absent, unparseable, or
        written by a different epoch (an old driver, or a beacon the
        relaunch hasn't reached yet)."""
        try:
            payload = json.load(open(
                os.path.join(self.logdir, MTTR_BREAKDOWN_NAME)))
        except (OSError, json.JSONDecodeError, ValueError):
            return {}
        if not isinstance(payload, dict) \
                or payload.get("epoch") != self.epoch:
            return {}
        return payload

    def _mttr_segments(self, mttr_s: float,
                       mttr_anchor: Optional[float],
                       launched_at: Optional[float]) -> dict:
        """Decompose a measured MTTR into detect / relaunch / compile /
        restore segments: detect = death -> relaunch (supervisor
        detection, epoch drain, backoff), restore/compile from the
        driver's startup beacon, relaunch = the remainder (spawn, jax
        and env construction, first-row wait).  Segments that cannot
        be attributed are omitted — the record stays honest when the
        relaunched driver predates the beacon."""
        segments = {}
        if mttr_anchor is not None and launched_at is not None:
            segments["detect_s"] = round(
                max(0.0, launched_at - mttr_anchor), 3)
        breakdown = self._read_mttr_breakdown()
        for key in ("restore_s", "compile_s"):
            value = breakdown.get(key)
            if isinstance(value, (int, float)):
                segments[key] = round(float(value), 3)
        if "compile_s" in segments:
            self._mttr_compile_gauge.set(segments["compile_s"])
        if {"detect_s", "restore_s", "compile_s"} <= set(segments):
            segments["relaunch_s"] = round(
                max(0.0, mttr_s - segments["detect_s"]
                    - segments["restore_s"] - segments["compile_s"]), 3)
        return segments

    def _watch(self, workers, mttr_anchor: Optional[float],
               launched_at: Optional[float] = None):
        """Poll one epoch's fleet to completion.  Returns
        ``(codes, drained_for_scale_up, first_death_at)``."""
        jsonl_path = os.path.join(self.logdir, "metrics.jsonl")
        try:
            jsonl_baseline = os.path.getsize(jsonl_path)
        except OSError:
            jsonl_baseline = 0
        drain_sent = False
        scale_up = False
        first_death_at: Optional[float] = None
        n = len(workers)
        while True:
            codes, mttr_s = self.watch_cycle(
                workers, jsonl_baseline, mttr_anchor)
            now = self._clock()
            if mttr_s is not None:
                self._last_mttr_s = mttr_s
                self._mttr_gauge.set(mttr_s)
                segments = self._mttr_segments(mttr_s, mttr_anchor,
                                               launched_at)
                self._record("mttr", mttr_s=round(mttr_s, 3),
                             **segments)
                log.info("elastic: reshard MTTR %.1fs (kill -> first "
                         "post-reshard metrics row) %s", mttr_s,
                         {k: v for k, v in segments.items()})
                mttr_anchor = None
            if first_death_at is None and any(
                    c is not None for c in codes):
                first_death_at = now
            if all(c is not None for c in codes):
                return codes, scale_up, first_death_at
            if not drain_sent and self._shutdown_requested:
                drain_sent = True
                self._terminate_all(workers)
            if (not drain_sent and first_death_at is None
                    and n < self.n_target and self.rejoinable_slots(now)):
                # Scale-up at the next checkpoint boundary: the grace
                # drain IS that boundary — one coordinated verified
                # checkpoint, every worker exits 0, and the relaunch
                # below resumes the larger fleet from it.
                drain_sent = True
                scale_up = True
                log.info(
                    "elastic: slot(s) %s rejoinable — draining the "
                    "%d-process fleet at the next checkpoint boundary "
                    "to scale back up", self.rejoinable_slots(now), n)
                self._record("scale_up_drain",
                             slots=self.rejoinable_slots(now))
                self._terminate_all(workers)
            self._sleep(self._poll_s)

    @staticmethod
    def _terminate_all(workers):
        for worker in workers:
            if worker.poll() is None:
                try:
                    worker.terminate()
                except OSError:
                    pass

    def run(self) -> int:
        """Supervise until the training run completes (0), the restart
        budget is exhausted (the dominant worker code), or a fatal
        verdict lands (71)."""
        uninstall = self._install_signals()
        try:
            return self._run()
        finally:
            uninstall()

    def _run(self) -> int:
        mttr_anchor: Optional[float] = None
        while True:
            if self._shutdown_requested:
                # A SIGTERM that lands between epochs (e.g. during the
                # backoff sleep) must not launch one more fleet.
                return 0
            slots = self.available_slots()
            if not slots:
                log.error("elastic: no available slots left")
                return FLEET_EXIT_CODE
            # The batch must shard evenly over the fleet: skip
            # incompatible intermediate sizes (the extra healthy slots
            # idle until the lost host rejoins).
            n = compatible_fleet_size(self._batch_size, len(slots))
            if n < len(slots):
                log.warning(
                    "elastic: batch %s does not divide over %d "
                    "processes — launching %d, slot(s) %s idle this "
                    "epoch", self._batch_size, len(slots), n,
                    slots[n:])
            slots = slots[:n]
            self.epoch += 1
            self._epoch_gauge.set(float(self.epoch))
            self._size_gauge.set(float(n))
            if self._prev_n is not None and n != self._prev_n:
                self._resizes.inc()
            self._prev_n = n
            port = self._port_factory()
            epoch_started = self._clock()
            # A membership verdict can only belong to the epoch that
            # writes it: clear any stale file (a previous epoch's, or a
            # previous supervisor INCARNATION's whose epoch numbering
            # restarted at 0 and would pass the epoch-match check).
            try:
                os.remove(os.path.join(self.logdir, EPOCH_VERDICT_NAME))
            except OSError:
                pass
            workers = self._launcher.launch(self.epoch, n, port)
            self._record(
                "launch", num_processes=n, slots=slots, port=port,
                pids=[getattr(w, "pid", None) for w in workers])
            log.info("elastic: epoch %d up — %d worker(s) on slots %s",
                     self.epoch, n, slots)

            codes, scale_up, first_death_at = self._watch(
                workers, mttr_anchor, launched_at=epoch_started)
            mttr_anchor = None
            ran_s = self._clock() - epoch_started
            if ran_s >= self._stable_s:
                self._consecutive_failures = 0
            kinds = [classify_exit(c) for c in codes]
            verdict = self.read_verdict()
            stale = not verdict or verdict.get("epoch") != self.epoch
            outcome, ret = self._classify_epoch(
                codes, kinds, scale_up,
                None if stale else verdict)
            self._record(
                "exit", codes=codes, outcome=outcome,
                lost_slots=[slots[i] for i, k in enumerate(kinds)
                            if k == LOST],
                verdict_kind=(None if stale else verdict.get("kind")),
                ran_s=round(ran_s, 3))
            log.info("elastic: epoch %d down (%s) — codes %s",
                     self.epoch, outcome, codes)

            if outcome == "done":
                return 0
            if outcome == "fatal":
                return NONFINITE_EXIT_CODE
            if outcome == "shutdown":
                return ret
            if outcome == "scale_up":
                self._rejoin(self.rejoinable_slots())
                continue
            if outcome == "preempt":
                # External preemption drained cleanly: not a failure.
                continue
            # reshard / restart: mark SIGKILLed slots lost, charge the
            # budget, back off, relaunch the survivors.
            now = self._clock()
            for i, kind in enumerate(kinds):
                if kind == LOST:
                    self.mark_lost(slots[i], now)
            self._consecutive_failures += 1
            self._restarts.inc()
            if self._consecutive_failures > self._restart_budget:
                log.error(
                    "elastic: restart budget exhausted (%d consecutive "
                    "failed epochs) — giving up with code %d",
                    self._consecutive_failures - 1, ret)
                self._record("budget_exhausted",
                             failures=self._consecutive_failures - 1)
                return ret
            mttr_anchor = first_death_at if first_death_at is not None \
                else now
            delay = self.backoff_s()
            if delay:
                log.warning(
                    "elastic: relaunching in %.1fs (failure %d/%d)",
                    delay, self._consecutive_failures,
                    self._restart_budget)
                self._sleep(delay)

    def _classify_epoch(self, codes, kinds, scale_up, verdict):
        """(outcome, exit_code) for one finished epoch.  ``verdict`` is
        the epoch-matched fleet_epoch.json payload or None."""
        if self._shutdown_requested:
            return "shutdown", max(
                (_exit_status(c) for c in codes if c), default=0)
        if FATAL in kinds:
            return "fatal", NONFINITE_EXIT_CODE
        if all(k == OK for k in kinds):
            if scale_up:
                return "scale_up", 0
            if verdict and verdict.get("kind") == "preempt":
                return "preempt", 0
            return "done", 0
        if LOST in kinds:
            return "reshard", FLEET_EXIT_CODE
        return "restart", max(
            (_exit_status(c) for c in codes if c),
            default=FLEET_EXIT_CODE)


def run_supervised(config) -> int:
    """Driver ``--elastic`` entry: supervise
    ``--distributed_num_processes`` (or 1) workers running this exact
    config."""
    n_target = config.distributed_num_processes or 1
    # The FULL fleet must be able to shard the batch (fail at launch,
    # not at first dispatch); intermediate reshard sizes need not —
    # compatible_fleet_size skips them, idling the extra slots.
    if config.batch_size % n_target:
        raise ValueError(
            f"batch_size {config.batch_size} is not divisible by the "
            f"fleet size {n_target} (--distributed_num_processes)")
    supervisor = ElasticSupervisor(
        n_target, config.logdir, DriverLauncher(config),
        restart_budget=config.elastic_restart_budget,
        stable_s=config.elastic_stable_s,
        rejoin_delay_s=config.elastic_rejoin_delay_s,
        batch_size=config.batch_size)
    return supervisor.run()


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m scalable_agent_tpu.runtime.elastic`` — the same
    flag surface as the driver (config.py), supervisor mode forced."""
    from scalable_agent_tpu.config import Config

    config = Config.from_argv(
        argv,
        description=(
            "Elastic fleet supervisor: owns "
            "--distributed_num_processes worker processes, reshards "
            "the survivors on peer loss, and scales back up on "
            "rejoin.  Takes the driver's full flag surface — see "
            "python -m scalable_agent_tpu.driver --help for the "
            "curated flag reference."))
    if config.mode != "train":
        raise ValueError("the elastic supervisor only supervises "
                         "--mode=train runs")
    return run_supervised(config)


if __name__ == "__main__":
    raise SystemExit(main())
