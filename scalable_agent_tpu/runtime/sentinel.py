"""Numerics sentinel: silent-corruption defense for the optimized path.

PR 18 put three fast-but-risky arms in the hot loop — the Pallas grad-W
stem kernel (``--conv_backend``), bf16 compute end-to-end
(``--compute_dtype``), and the fused single-forward loss
(``--fused_forward``).  The non-finite guard (runtime/learner.py)
catches *loud* wrongness; nothing catches a kernel that is silently
off by 2x, bf16 drift past its modeled envelope, or an SDC bit-flip in
the param slab.  This module is that defense, in three arms:

1. **Shadow audits.**  Every ``--sentinel_interval`` updates the driver
   snapshots the pre-update TrainState and the batch, runs the normal
   hot update, then hands both to :meth:`NumericsSentinel.audit`, which
   recomputes the same batch's gradients and the resulting param delta
   through the *reference* path (XLA stem, f32 compute, two-pass loss —
   the PR 18 bench baselines, still flag-reachable) entirely on device
   and compares leaves against a calibrated relative tolerance
   (``--sentinel_rtol``).  One D2H bool + a max-deviation gauge per
   audit, surfaced through the devtel plane (``devtel/sentinel/...``).

2. **Param fingerprints.**  A uint32 wrap-around checksum over the raw
   bits of the param tree, computed on device at the existing
   ``updates % 8`` decision-broadcast cadence.  Data-parallel replicas
   must agree *bit-exact*; any disagreement across processes is SDC or
   a divergent replica, and every process votes to roll back.  (The
   reduction over a replicated array compiles to a local per-device
   reduction — no collective — so each process's fetched value
   reflects its OWN replica, which is exactly what makes the
   cross-process compare meaningful.  Model-sharded leaves would fold
   in a psum and hide per-replica bits; the shadow audits cover that
   axis, see docs/robustness.md.)

3. **Degradation ladder.**  On an audit breach the sentinel demotes the
   hot path one rung at a time — ``conv_backend pallas→xla``, then
   ``compute_dtype bf16→f32``, then ``fused→two-pass`` — rebuilding the
   learner (one re-jit per rung) and counting
   ``sentinel/demotions_total``.  A breach that survives the full
   ladder requests a rollback to the newest verified checkpoint (the
   PR 4 machinery); a breach after that rollback means the trusted
   reference path itself can't be reproduced on this hardware, and the
   run exits ``SENTINEL_EXIT_CODE`` (73) — elastic policy: restart at
   the same shape.

Default path invariant: with ``--sentinel_interval=0`` (the default)
the driver never constructs this class and no jitted program changes —
the PR 13 golden losses stay bit-exact.

Chaos points (runtime/faults.py): ``param_bitflip`` flips a mantissa
bit in a param leaf right after an audited update (the delta arm must
catch it), ``kernel_miscompute`` scales the audited hot grads 2x (the
gradient arm must breach; rung 1 clears it), ``replica_diverge``
corrupts this process's fingerprint before the compare.
"""

import dataclasses
import logging
from typing import Any, Callable, Optional, Tuple

from scalable_agent_tpu.obs import get_flight_recorder, get_registry
from scalable_agent_tpu.obs.device_telemetry import (
    DeviceTelemetry,
    TelemetryPublisher,
    fetch_merged,
    merge_init,
)
from scalable_agent_tpu.runtime.exit_codes import SENTINEL_EXIT_CODE
from scalable_agent_tpu.runtime.faults import get_fault_injector

log = logging.getLogger(__name__)

__all__ = [
    "LADDER",
    "NumericsSentinel",
    "sentinel_telemetry_spec",
]

# The degradation ladder, least-drastic first.  Rung r applies the
# cumulative union of the first r entries to the run's config and
# rebuilds the learner; the *reference* path is the union of all three
# (what the shadow audit always computes against).  Ordering follows
# blast radius: the Pallas stem kernel is the newest/riskiest arm, the
# precision demotion costs the most throughput, and dropping the fused
# loss only de-optimizes scheduling.
LADDER = (
    {"conv_backend": "xla"},
    {"compute_dtype": "float32", "core_matmul_dtype": "float32"},
    {"fused_forward": False},
)

# XOR'd into this process's fingerprint by the ``replica_diverge``
# chaos point — any nonzero constant proves the compare.
_DIVERGE_MASK = 0xDEADBEEF


def sentinel_telemetry_spec() -> DeviceTelemetry:
    """Device-side sentinel telemetry: audit count, breach count, and
    the last audit's max relative deviation (the calibration signal —
    watch it approach ``--sentinel_rtol`` before a trip)."""
    return (
        DeviceTelemetry("sentinel")
        .counter("audits", "shadow audits run on device")
        .counter("breaches", "audits whose max deviation exceeded rtol")
        .gauge("max_deviation",
               "last audit's max relative grad/delta deviation")
    )


def _reference_config(config):
    """The run config with every ladder rung applied: XLA stem, f32
    compute, two-pass loss — the trusted arm audits compare against."""
    overrides = {}
    for rung in LADDER:
        overrides.update(rung)
    return dataclasses.replace(config, **overrides)


class NumericsSentinel:
    """Shadow audits + fingerprints + the degradation ladder.

    ``rebuild(config) -> (agent, learner)`` is the driver's factory
    closure; the sentinel uses it once (lazily) for the reference
    learner and once per demotion rung for the replacement hot learner.
    The driver polls :meth:`consume_swap` after each audit and, when it
    returns True, adopts :attr:`learner`/:attr:`agent` (one re-jit on
    the next update), republishes params to actors, and flushes the
    replay slab (suspect lineage).
    """

    def __init__(self, config, agent, learner,
                 rebuild: Callable[[Any], Tuple[Any, Any]],
                 registry=None):
        if config.sentinel_interval <= 0:
            raise ValueError(
                "NumericsSentinel requires --sentinel_interval > 0; "
                "the driver must not construct it for sentinel-off runs")
        registry = registry or get_registry()
        self._base_config = config
        self._rebuild = rebuild
        self._hot_agent = agent
        self._hot = learner
        self._interval = int(config.sentinel_interval)
        self._rtol = float(config.sentinel_rtol)
        # Lazily built: the reference learner re-jits its own loss the
        # first time an audit runs, not at startup.
        self._ref: Optional[Any] = None
        self._rung = 0
        self._audit_fn = None       # re-jitted per rung
        self._checksum_fn = None
        self._flip_fn = None
        self._swapped = False       # one-shot: driver consumes
        self._rolled_back = False   # a sentinel rollback already spent
        self.rollback_pending = False

        self._spec = sentinel_telemetry_spec()
        self._devtel = learner._place_replicated(merge_init([self._spec]))
        self._publisher = TelemetryPublisher([self._spec],
                                             registry=registry)

        self._c_trips = registry.counter(
            "sentinel/trips_total",
            "sentinel breaches (audit or fingerprint mismatch)")
        self._c_demotions = registry.counter(
            "sentinel/demotions_total",
            "degradation-ladder rungs taken (re-jits of the hot path)")
        self._c_fp_mismatch = registry.counter(
            "sentinel/fingerprint_mismatch_total",
            "cross-process param-fingerprint disagreements")
        self._g_rung = registry.gauge(
            "sentinel/rung",
            "current degradation-ladder rung (0 = full hot path)")
        self._g_fingerprint = registry.gauge(
            "sentinel/param_fingerprint",
            "this process's uint32 param-tree checksum")
        self._g_rung.set(0)

    # ------------------------------------------------------------------
    # Introspection the driver wires against.

    @property
    def learner(self):
        """The current hot learner (changes after a demotion)."""
        return self._hot

    @property
    def agent(self):
        """The agent paired with :attr:`learner`."""
        return self._hot_agent

    @property
    def rung(self) -> int:
        return self._rung

    def audit_due(self, updates: int) -> bool:
        """True when the update about to run (0-based counter) should
        be audited: the driver snapshots state+batch before it."""
        return (updates + 1) % self._interval == 0

    def consume_swap(self) -> bool:
        """True exactly once after a demotion — the driver adopts the
        new learner/agent and re-places state."""
        swapped, self._swapped = self._swapped, False
        return swapped

    def note_rollback(self):
        """The driver completed a sentinel-requested rollback; the next
        surviving breach exits 73 instead of rolling back again."""
        self.rollback_pending = False
        self._rolled_back = True

    # ------------------------------------------------------------------
    # Snapshots.

    def snapshot(self, state):
        """Copy the TrainState into distinct device buffers — the hot
        update donates its input, so the audit needs its own copy."""
        import jax
        import jax.numpy as jnp

        return jax.tree_util.tree_map(jnp.copy, state)

    # ------------------------------------------------------------------
    # Shadow audit.

    def _ensure_ref(self):
        if self._ref is None:
            ref_config = _reference_config(self._base_config)
            _, self._ref = self._rebuild(ref_config)
        return self._ref

    def _build_audit(self):
        import jax
        import jax.numpy as jnp
        import optax

        hot, ref = self._hot, self._ensure_ref()
        spec, rtol = self._spec, self._rtol

        def leaf_dev(h, r):
            # Per-leaf L2-relative deviation, NOT max-element: a single
            # near-cancelled element can read 45% off in clean bf16
            # (measured, bench_sentinel at production shapes) while the
            # leaf as a whole agrees to ~1%.  The faults this arm
            # exists for move the whole leaf (a 2x-scaled kernel reads
            # exactly 1.0 here) or one LARGE element (a bit-flip dwarfs
            # the reference delta's norm), so the norm keeps them loud
            # and the rounding noise quiet.
            h32 = jnp.asarray(h, jnp.float32)
            r32 = jnp.asarray(r, jnp.float32)
            dev = (jnp.linalg.norm(h32.ravel() - r32.ravel())
                   / (jnp.linalg.norm(r32.ravel()) + 1e-6))
            # NaN batches are the non-finite guard's domain; the
            # sentinel must stay quiet on them, not double-report.
            return jnp.where(jnp.isfinite(dev), dev, 0.0)

        def tree_max(tree_a, tree_b):
            devs = jax.tree_util.tree_leaves(
                jax.tree_util.tree_map(leaf_dev, tree_a, tree_b))
            return jnp.max(jnp.stack(devs)) if devs else jnp.float32(0.0)

        def audit(snap, trajectory, params_after, miscompute, devtel):
            # Arm 1: gradients, hot vs reference, same batch and params.
            (_, _), hot_grads = jax.value_and_grad(
                hot._loss, has_aux=True)(
                    snap.params, trajectory, snap.target_params)
            hot_grads = jax.tree_util.tree_map(
                lambda g: g * (1.0 + miscompute), hot_grads)
            (_, _), ref_grads = jax.value_and_grad(
                ref._loss, has_aux=True)(
                    snap.params, trajectory, snap.target_params)
            grad_dev = tree_max(hot_grads, ref_grads)

            # Arm 2: the applied param delta vs the reference delta from
            # the same optimizer state.  Catches corruption downstream
            # of the gradients (optimizer math, the apply, SDC in the
            # written slab).
            lr = ref._hp.learning_rate * jnp.maximum(
                0.0, 1.0 - snap.env_frames
                / ref._hp.total_environment_frames)
            updates, _ = ref._tx.update(
                ref_grads, snap.opt_state, snap.params)
            updates = jax.tree_util.tree_map(lambda u: u * lr, updates)
            ref_after = optax.apply_updates(snap.params, updates)

            def delta(a, b):
                return (jnp.asarray(a, jnp.float32)
                        - jnp.asarray(b, jnp.float32))

            hot_delta = jax.tree_util.tree_map(
                delta, params_after, snap.params)
            ref_delta = jax.tree_util.tree_map(
                delta, ref_after, snap.params)
            # The finite guard may have skipped the hot update (params
            # unchanged); a zero delta against a nonzero reference
            # delta is the guard doing its job, not corruption.
            applied = jnp.any(jnp.stack([
                jnp.any(jnp.abs(d) > 0)
                for d in jax.tree_util.tree_leaves(hot_delta)]))
            delta_dev = jnp.where(
                applied, tree_max(hot_delta, ref_delta), 0.0)

            max_dev = jnp.maximum(grad_dev, delta_dev)
            breach = max_dev > rtol
            devtel = spec.inc(devtel, "audits")
            devtel = spec.inc(devtel, "breaches",
                              breach.astype(jnp.float32))
            devtel = spec.set(devtel, "max_deviation", max_dev)
            return devtel, breach, max_dev

        return jax.jit(audit, donate_argnums=(4,))

    def _build_flip(self):
        import jax
        import jax.numpy as jnp

        def flip(params):
            leaves, treedef = jax.tree_util.tree_flatten(params)
            flat = jnp.concatenate([
                jnp.asarray(leaf, jnp.float32).ravel()
                for leaf in leaves])
            # Flip bit 20 of the f32 mantissa (~2^-3 = 12.5% relative)
            # in the LARGEST-magnitude element: guaranteed nonzero (a
            # zero-initialized bias bit-flips to a denormal — invisible
            # to any tolerance), far outside rtol, far inside overflow.
            idx = jnp.argmax(jnp.abs(flat))
            bits = jax.lax.bitcast_convert_type(
                flat[idx], jnp.uint32) ^ jnp.uint32(1 << 20)
            flat = flat.at[idx].set(
                jax.lax.bitcast_convert_type(bits, jnp.float32))
            out, offset = [], 0
            for leaf in leaves:
                segment = flat[offset:offset + leaf.size]
                out.append(segment.reshape(leaf.shape).astype(leaf.dtype))
                offset += leaf.size
            return jax.tree_util.tree_unflatten(treedef, out)

        return jax.jit(flip)

    def _fetch_scalar(self, x):
        import numpy as np

        if getattr(x, "is_fully_addressable", True):
            return np.asarray(x)
        return np.asarray(x.addressable_shards[0].data)

    def audit(self, snap, trajectory, state, updates: int):
        """Run one shadow audit.  ``snap`` is the pre-update snapshot,
        ``state`` the post-update TrainState.  Returns the (possibly
        chaos-corrupted) state; breach handling may demote the ladder,
        set :attr:`rollback_pending`, or exit 73."""
        import numpy as np

        injector = get_fault_injector()
        miscompute = 0.0
        if injector.active:
            # The miscomputing-kernel stand-in only exists while the
            # suspect kernel is still in the hot path (rung 0): the
            # first demotion replaces it, so post-demotion audits run
            # clean and the chaos run finishes — detect→demote→finish.
            if self._rung == 0 and injector.should_fire(
                    "kernel_miscompute"):
                miscompute = 1.0
            if injector.should_fire("param_bitflip"):
                if self._flip_fn is None:
                    self._flip_fn = self._build_flip()
                state = state._replace(
                    params=self._flip_fn(state.params))

        if self._audit_fn is None:
            self._audit_fn = self._build_audit()
        self._devtel, breach, max_dev = self._audit_fn(
            snap, trajectory, state.params,
            np.float32(miscompute), self._devtel)
        # The one D2H of the audit: a bool and a float, at audit
        # cadence only.
        if bool(self._fetch_scalar(breach)):
            self._on_breach(float(self._fetch_scalar(max_dev)), updates)
        return state

    def _on_breach(self, max_dev: float, updates: int):
        recorder = get_flight_recorder()
        self._c_trips.inc()
        recorder.record("sentinel_trip", "audit", {
            "rung": self._rung, "max_deviation": max_dev,
            "update": updates})
        if recorder.reason_pin is None:
            recorder.reason_pin = "sentinel_trip:audit"
        if self._rung < len(LADDER):
            self._demote(max_dev, updates)
        elif not self._rolled_back:
            log.error(
                "sentinel: breach (max_dev=%.3g) survived the full "
                "degradation ladder at update %d — rolling back to the "
                "newest verified checkpoint", max_dev, updates)
            self.rollback_pending = True
        else:
            recorder.record("sentinel_trip", "exhausted", {
                "max_deviation": max_dev, "update": updates})
            recorder.dump_all("sentinel:exhausted")
            log.error(
                "sentinel: breach persists after full demotion AND a "
                "rollback — the reference path cannot be reproduced on "
                "this hardware; exiting %d", SENTINEL_EXIT_CODE)
            raise SystemExit(SENTINEL_EXIT_CODE)

    def _demote(self, max_dev: float, updates: int):
        self._rung += 1
        overrides = {}
        for rung_overrides in LADDER[:self._rung]:
            overrides.update(rung_overrides)
        demoted = dataclasses.replace(self._base_config, **overrides)
        self._hot_agent, self._hot = self._rebuild(demoted)
        self._audit_fn = None  # re-jit against the new hot path
        self._swapped = True
        self._c_demotions.inc()
        self._g_rung.set(self._rung)
        get_flight_recorder().record("sentinel_trip", "demote", {
            "rung": self._rung, "overrides": dict(overrides)})
        log.warning(
            "sentinel: audit breach (max_dev=%.3g > rtol=%.3g) at "
            "update %d — demoting to rung %d (%s)",
            max_dev, self._rtol, updates, self._rung,
            ", ".join(f"{k}={v}" for k, v in overrides.items()))

    # ------------------------------------------------------------------
    # Param fingerprints.

    def _build_checksum(self):
        import jax
        import jax.numpy as jnp

        def checksum(params):
            total = jnp.zeros((), jnp.uint32)
            for leaf in jax.tree_util.tree_leaves(params):
                bits = jax.lax.bitcast_convert_type(
                    jnp.asarray(leaf, jnp.float32).ravel(), jnp.uint32)
                # uint32 wrap-around sum: order-stable, collision odds
                # irrelevant here (we compare replicas of the SAME
                # tree, not arbitrary trees).
                total = total + jnp.sum(bits, dtype=jnp.uint32)
            return total

        return jax.jit(checksum)

    def local_fingerprint(self, params) -> int:
        """This process's uint32 checksum of the param tree (one small
        D2H).  Published as ``sentinel/param_fingerprint``."""
        if self._checksum_fn is None:
            self._checksum_fn = self._build_checksum()
        fp = int(self._fetch_scalar(self._checksum_fn(params)))
        injector = get_fault_injector()
        if injector.active and injector.should_fire("replica_diverge"):
            fp ^= _DIVERGE_MASK
        self._g_fingerprint.set(fp)
        return fp

    def check_fingerprints(self, fingerprints) -> bool:
        """True when the gathered per-process fingerprints disagree —
        SDC or a divergent replica.  Every process sees the same
        gathered set, so every process reaches the same verdict (the
        rollback stays SPMD-consistent without another broadcast)."""
        import numpy as np

        distinct = {int(f) for f in np.asarray(fingerprints).ravel()}
        if len(distinct) <= 1:
            return False
        self._c_fp_mismatch.inc()
        self._c_trips.inc()
        recorder = get_flight_recorder()
        recorder.record("sentinel_trip", "fingerprint", {
            "fingerprints": sorted(distinct)})
        if recorder.reason_pin is None:
            recorder.reason_pin = "sentinel_trip:fingerprint"
        log.error(
            "sentinel: param fingerprints disagree across processes "
            "(%s) — a replica diverged; rolling back",
            sorted(distinct))
        return True

    # ------------------------------------------------------------------
    # Telemetry.

    def publish(self):
        """Fetch+publish the devtel block (driver log cadence)."""
        fetched = fetch_merged([self._spec], self._devtel)
        if fetched is not None:
            self._publisher.publish(fetched)
        return fetched
