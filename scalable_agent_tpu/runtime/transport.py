"""Asynchronous trajectory transport: host batches onto the mesh.

The host training pipeline's binding constraint is the actor→learner
hand-off, not the chip (BENCH_r05: the learner alone sustains ~2.7M
env-frames/s while the host pipeline delivers 8.6-16.4k).  Three layers
in this module attack it:

- **Packed single-copy H2D** (``PackedTransport``): every Trajectory
  leaf is flattened into ONE contiguous host buffer per batch —
  dtype-segmented, 128-byte-aligned offsets — so a batch costs a single
  H2D copy instead of a per-leaf ``device_put`` storm (flat-bytes upload
  is an order of magnitude cheaper over some transports; see
  runtime/accum_actor.py's per-step frame upload).  A jitted on-device
  unpack (bitcast + slice + reshape) restores the pytree, sharded over
  the mesh's batch axes; multi-host runs assemble the global buffer from
  per-process rows via ``make_array_from_process_local_data``.
- **Double-buffered staging**: two preallocated staging buffers rotate,
  so packing batch k+1 can overwrite host memory while batch k's
  (asynchronous) upload is still in flight.
- **Bounded in-flight dispatch** (``InflightWindow``): the driver keeps
  up to W updates in flight and blocks only when the window is full —
  metrics are materialized when their update falls out of the window —
  turning the update loop from lock-step into a pipeline with explicit
  backpressure.

``PerLeafTransport`` preserves the original per-leaf placement path
bit-for-bit (``--transport=per_leaf``); ``make_transport`` dispatches on
the config string.  The module also hosts ``FlatRowLayout``, the shared
flat-pytree byte layout the native batcher packs requests with (one
layout implementation for every host-side pytree<->bytes boundary).
"""

import threading
from collections import deque
from typing import Any, List, NamedTuple, Optional, Tuple

import numpy as np

from scalable_agent_tpu.obs import get_ledger, get_registry, get_tracer

__all__ = [
    "FlatRowLayout",
    "InflightWindow",
    "PackedTransport",
    "PerLeafTransport",
    "broadcast_prefix",
    "h2d_bytes_counter",
    "make_transport",
    "tree_flatten_with_none",
    "tree_unflatten",
]


def h2d_bytes_counter():
    """The transport layer's shared upload-byte counter: the packed
    trajectory staging here and the accum actors' per-step uploads
    (runtime/accum_actor.py) both feed it, so ``transport/
    h2d_bytes_total`` is the host->device byte rate of the whole
    pipeline."""
    return get_registry().counter(
        "transport/h2d_bytes_total",
        "host->device bytes staged by the transport layer (packed "
        "trajectory batches + accum per-step uploads)")

# Leaf offsets inside a packed shard segment are rounded up to this many
# bytes: wide enough for any dtype's alignment and for efficient DMA
# engines, small enough that padding stays negligible next to the frame
# leaf (the alignment loss is < num_leaves * 128 bytes per shard).
_ALIGN = 128


def tree_flatten_with_none(tree):
    """``tree_flatten`` with None treated as a leaf — the convention at
    every pytree<->rows boundary in the runtime (absent optional
    observations round-trip as None)."""
    import jax

    return jax.tree_util.tree_flatten(tree, is_leaf=lambda x: x is None)


def _tree_leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree, is_leaf=lambda x: x is None)


def tree_unflatten(treedef, leaves):
    import jax

    return jax.tree_util.tree_unflatten(treedef, leaves)


# Internal aliases (the public names are the API).
_tree_flatten = tree_flatten_with_none
_tree_unflatten = tree_unflatten


def broadcast_prefix(prefix, full) -> List[Any]:
    """Expand a per-field prefix tree (one entry per top-level field of
    ``full``) into a flat list aligned with ``full``'s leaves (None
    leaves included)."""
    out = []
    for entry, subtree in zip(prefix, full):
        out.extend([entry] * len(_tree_leaves(subtree)))
    return out


# ---------------------------------------------------------------------------
# FlatRowLayout: unaligned flat pytree <-> bytes (the native batcher's
# request/result rows; alignment there is fixed by the C++ core's
# byte-blob contract, so offsets pack densely).
# ---------------------------------------------------------------------------


class FlatRowLayout:
    """Flattened pytree layout: per-leaf (offset, shape, dtype).

    A None leaf (e.g. an absent optional observation) contributes zero
    bytes and round-trips as None.
    """

    def __init__(self, example):
        leaves, self.treedef = _tree_flatten(example)
        self.fields: List[Optional[
            Tuple[int, Tuple[int, ...], np.dtype]]] = []
        offset = 0
        for leaf in leaves:
            if leaf is None:
                self.fields.append(None)
                continue
            arr = np.asarray(leaf)
            self.fields.append((offset, arr.shape, arr.dtype))
            offset += arr.nbytes
        self.nbytes = offset

    def pack_into(self, buf: memoryview, tree) -> None:
        leaves = _tree_leaves(tree)
        for field, leaf in zip(self.fields, leaves):
            if field is None:
                continue
            offset, shape, dtype = field
            # No ascontiguousarray here: it would promote 0-d leaves to
            # 1-d, and tobytes() already emits C-order bytes.
            arr = np.asarray(leaf, dtype=dtype)
            if arr.shape != shape:
                raise ValueError(
                    f"leaf shape {arr.shape} != declared {shape}")
            buf[offset:offset + arr.nbytes] = arr.tobytes()

    def unpack_rows(self, buf: memoryview, n: int):
        """[n, nbytes] packed rows -> pytree of [n, ...] arrays."""
        flat = np.frombuffer(buf, np.uint8,
                             count=n * self.nbytes).reshape(n, self.nbytes)
        leaves = []
        for field in self.fields:
            if field is None:
                leaves.append(None)
                continue
            offset, shape, dtype = field
            nbytes = int(np.prod(shape)) * dtype.itemsize
            chunk = np.ascontiguousarray(flat[:, offset:offset + nbytes])
            leaves.append(chunk.view(dtype).reshape((n,) + shape))
        return _tree_unflatten(self.treedef, leaves)

    def pack_rows(self, buf: memoryview, tree, n: int) -> None:
        """pytree of [>=n, ...] arrays -> [n, nbytes] packed rows."""
        leaves = _tree_leaves(tree)
        flat = np.frombuffer(buf, np.uint8,
                             count=n * self.nbytes).reshape(n, self.nbytes)
        # frombuffer on a writable memoryview yields a writable view.
        for field, leaf in zip(self.fields, leaves):
            if field is None:
                continue
            offset, shape, dtype = field
            arr = np.ascontiguousarray(np.asarray(leaf, dtype=dtype)[:n])
            nbytes = int(np.prod(shape)) * dtype.itemsize
            # View as bytes BEFORE reshaping: reshape counts elements, so
            # reshaping the typed array to byte-count columns blows up for
            # any leaf with >1 element per row.
            flat[:, offset:offset + nbytes] = (
                arr.view(np.uint8).reshape(n, nbytes))

    def unpack_one(self, buf: memoryview):
        leaves = []
        for field in self.fields:
            if field is None:
                leaves.append(None)
                continue
            offset, shape, dtype = field
            nbytes = int(np.prod(shape)) * dtype.itemsize
            arr = np.frombuffer(buf, np.uint8, count=nbytes,
                                offset=offset).view(dtype).reshape(shape)
            leaves.append(arr.copy())
        return _tree_unflatten(self.treedef, leaves)


# ---------------------------------------------------------------------------
# Per-leaf transport: the original placement path, preserved verbatim.
# ---------------------------------------------------------------------------


class PerLeafTransport:
    """Place every trajectory leaf with its own ``device_put`` (or
    ``make_array_from_process_local_data`` in multi-host runs).  This is
    the seed behavior, kept bit-for-bit for ``--transport=per_leaf`` and
    as the fallback for trajectories whose leaves already live on device
    (the accum actor paths, where re-placement is a cheap device-side
    reshard, not an upload)."""

    def __init__(self, mesh, shardings_prefix):
        self._mesh = mesh
        self._shardings_prefix = shardings_prefix

    def put(self, trajectory):
        import jax

        if jax.process_count() > 1:
            def build(sharding, local):
                return jax.make_array_from_process_local_data(
                    sharding, np.asarray(local))

            shardings_flat = broadcast_prefix(
                self._shardings_prefix, trajectory)
            leaves, treedef = _tree_flatten(trajectory)
            placed = [
                None if leaf is None else build(sh, leaf)
                for sh, leaf in zip(shardings_flat, leaves)
            ]
            return _tree_unflatten(treedef, placed)
        return jax.device_put(trajectory, self._shardings_prefix)


# ---------------------------------------------------------------------------
# Packed transport.
# ---------------------------------------------------------------------------


class _LeafSpec(NamedTuple):
    """One leaf's slot inside a packed shard segment."""

    offset: int  # byte offset within a shard segment (128-aligned)
    nbytes: int  # bytes of ONE shard's chunk of this leaf
    shape: Tuple[int, ...]  # GLOBAL leaf shape (what unpack emits)
    local_shape: Tuple[int, ...]  # this process's leaf shape (pack input)
    chunk_shape: Tuple[int, ...]  # shape with the batch axis / num_shards
    dtype: np.dtype
    batch_axis: int


def _round_up(n: int, align: int) -> int:
    return (n + align - 1) // align * align


class PackedSpec:
    """The byte layout of one packed trajectory batch.

    Leaves are ordered dtype-segmented (stable within a dtype) and each
    gets a 128-byte-aligned offset inside the per-shard segment; the
    host buffer is ``[num_shards, shard_nbytes]`` uint8, where shard d
    holds batch slice ``[d*b:(d+1)*b]`` of every leaf — so uploading the
    buffer sharded over its leading axis lands each device's batch
    shard directly on that device.  In multi-host runs the example is
    the process-LOCAL batch (1/P of the global batch, matching the
    per-leaf path's ``make_array_from_process_local_data`` contract)
    and each process packs its own ``local_shards`` rows.
    """

    def __init__(self, example, batch_axes_prefix, num_shards: int,
                 local_shards: Optional[int] = None):
        leaves, self.treedef = _tree_flatten(example)
        batch_axes = broadcast_prefix(batch_axes_prefix, example)
        self.num_shards = int(num_shards)
        self.local_shards = int(local_shards or num_shards)
        self.specs: List[Optional[_LeafSpec]] = [None] * len(leaves)
        # dtype-segmented: leaves of one dtype pack adjacently, so the
        # alignment padding between same-dtype leaves is bounded by the
        # 128-byte rounding alone (and the unpack's bitcasts cluster).
        order = sorted(
            (i for i, leaf in enumerate(leaves) if leaf is not None),
            key=lambda i: (np.asarray(leaves[i]).dtype.str, i))
        offset = 0
        for i in order:
            arr = np.asarray(leaves[i])
            axis = batch_axes[i]
            local_batch = arr.shape[axis]
            if local_batch % self.local_shards:
                raise ValueError(
                    f"batch axis {axis} of leaf shape {arr.shape} "
                    f"({local_batch}) not divisible by "
                    f"{self.local_shards} local data shards")
            chunk = local_batch // self.local_shards
            chunk_shape = (arr.shape[:axis] + (chunk,)
                           + arr.shape[axis + 1:])
            global_shape = (arr.shape[:axis]
                            + (chunk * self.num_shards,)
                            + arr.shape[axis + 1:])
            nbytes = int(np.prod(chunk_shape)) * arr.dtype.itemsize
            offset = _round_up(offset, _ALIGN)
            self.specs[i] = _LeafSpec(
                offset=offset, nbytes=nbytes, shape=global_shape,
                local_shape=arr.shape, chunk_shape=chunk_shape,
                dtype=arr.dtype, batch_axis=axis)
            offset += nbytes
        self.shard_nbytes = _round_up(offset, _ALIGN)

    def pack_into(self, buf: np.ndarray, trajectory) -> None:
        """Write the local trajectory's leaves into ``buf``
        ([local_shards, shard_nbytes] uint8): row d holds batch chunk d
        of every leaf, leaf bytes at their aligned offsets."""
        leaves = _tree_leaves(trajectory)
        if len(leaves) != len(self.specs):
            raise ValueError(
                f"trajectory has {len(leaves)} leaves, layout declares "
                f"{len(self.specs)}")
        for spec, leaf in zip(self.specs, leaves):
            if spec is None:
                if leaf is not None:
                    raise ValueError(
                        "trajectory leaf present where the layout "
                        "declares None")
                continue
            arr = np.asarray(leaf)
            if arr.dtype != spec.dtype:
                raise ValueError(
                    f"leaf dtype {arr.dtype} != declared {spec.dtype}")
            if arr.shape != spec.local_shape:
                raise ValueError(
                    f"leaf shape {arr.shape} != declared "
                    f"{spec.local_shape}")
            axis = spec.batch_axis
            pre, post = arr.shape[:axis], arr.shape[axis + 1:]
            b = arr.shape[axis] // self.local_shards
            split = arr.reshape(pre + (self.local_shards, b) + post)
            moved = np.moveaxis(split, axis, 0)  # [shards, *pre, b, *post]
            dest = buf[:, spec.offset:spec.offset + spec.nbytes]
            dest = dest.view(spec.dtype).reshape(moved.shape)
            np.copyto(dest, moved)


class PackedTransport:
    """Single-copy H2D trajectory placement with double-buffered staging.

    ``put(trajectory)`` returns the same device-resident, mesh-sharded
    Trajectory the per-leaf path produces — bit-for-bit identical leaf
    values — but pays one contiguous upload per batch.  The layout is
    derived lazily from the first trajectory (shapes/dtypes are a
    runtime property of the env).  Trajectories whose leaves already
    live on device (accum actor paths) fall through to the per-leaf
    re-shard: packing them would FETCH device memory back to the host.
    """

    def __init__(self, mesh, shardings_prefix, batch_axes_prefix):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        self._mesh = mesh
        self._shardings_prefix = shardings_prefix
        self._batch_axes_prefix = batch_axes_prefix
        self._per_leaf = PerLeafTransport(mesh, shardings_prefix)
        # The batch dimension shards over (data, seq) — parallel/mesh.py
        # batch_sharding — so the packed buffer's shard axis must too.
        batch_axes = (("data", "seq") if "seq" in mesh.shape
                      else ("data",))
        self._num_shards = 1
        for name in batch_axes:
            self._num_shards *= mesh.shape.get(name, 1)
        self._buf_sharding = NamedSharding(
            mesh, PartitionSpec(batch_axes, None))
        self._spec: Optional[PackedSpec] = None
        self._unpack_jit = None
        # Double-buffered staging: pack k+1 while k's async upload is in
        # flight.  ``_upload_done[slot]`` holds the device buffer of the
        # LAST upload out of that slot: ``device_put`` from a numpy
        # array may read the host memory until the transfer completes
        # (PJRT immutable-until-transfer semantics), so a pack reusing a
        # slot first blocks on that slot's previous upload — with two
        # buffers that wait targets upload k-1 and is normally already
        # satisfied, making the common case wait-free.  The lock covers
        # only slot rotation and the completion bookkeeping — pack_into
        # runs outside it — so the transport supports ONE packing
        # caller at a time (the driver's single prefetch thread); a
        # third concurrent put() could reclaim a slot another caller
        # is still packing.
        self._staging: List[Optional[np.ndarray]] = [None, None]
        self._upload_done: List[Optional[object]] = [None, None]
        self._slot = 0
        self._lock = threading.Lock()
        # Replay tap (runtime/replay.py): called with each batch's
        # UPLOADED device buffer — the replay slab's insert rides the
        # one H2D copy the transport already paid, so feeding replay
        # costs a device-side slab write and nothing on the wire.
        self._upload_sink = None
        self._local_shards = self._num_shards // jax.process_count()
        if self._num_shards % jax.process_count():
            raise ValueError(
                f"{self._num_shards} batch shards not divisible by "
                f"{jax.process_count()} processes")
        registry = get_registry()
        self._h_pack = registry.histogram(
            "transport/pack_s", "host pack into the staging buffer")
        self._h_upload = registry.histogram(
            "transport/upload_s", "single-copy H2D dispatch seconds")
        self._h_unpack = registry.histogram(
            "transport/unpack_s", "on-device unpack dispatch seconds")
        self._bytes_counter = h2d_bytes_counter()

    # -- layout ------------------------------------------------------------

    def _ensure_spec(self, trajectory):
        if self._spec is None:
            # The example is the LOCAL batch; the global layout scales
            # its batch axes by the process count.
            self._spec = PackedSpec(
                trajectory, self._batch_axes_prefix,
                num_shards=self._num_shards,
                local_shards=self._local_shards)
            self._unpack_jit = self._build_unpack()
        return self._spec

    def _build_unpack(self):
        import jax
        import jax.numpy as jnp

        spec = self._spec
        shardings_flat = broadcast_prefix(
            self._shardings_prefix,
            _tree_unflatten(spec.treedef,
                            [None if s is None else 0
                             for s in spec.specs]))
        d = spec.num_shards

        def unpack(buf):
            leaves = []
            for leaf_spec, sharding in zip(spec.specs, shardings_flat):
                if leaf_spec is None:
                    leaves.append(None)
                    continue
                itemsize = leaf_spec.dtype.itemsize
                count = leaf_spec.nbytes // itemsize
                seg = jax.lax.slice_in_dim(
                    buf, leaf_spec.offset,
                    leaf_spec.offset + leaf_spec.nbytes, axis=1)
                if leaf_spec.dtype == np.bool_:
                    flat = seg != 0  # bitcast to bool is unsupported
                elif itemsize == 1:
                    flat = (seg if leaf_spec.dtype == np.uint8
                            else jax.lax.bitcast_convert_type(
                                seg, jnp.dtype(leaf_spec.dtype)))
                else:
                    flat = jax.lax.bitcast_convert_type(
                        seg.reshape(d, count, itemsize),
                        jnp.dtype(leaf_spec.dtype))
                arr = flat.reshape((d,) + leaf_spec.chunk_shape)
                # Undo the host-side moveaxis, then merge (shards, b)
                # back into the batch axis — with the input sharded over
                # its leading axis and the output constrained to the
                # leaf's batch sharding this stays a local relabeling.
                arr = jnp.moveaxis(arr, 0, leaf_spec.batch_axis)
                arr = arr.reshape(leaf_spec.shape)
                leaves.append(
                    jax.lax.with_sharding_constraint(arr, sharding))
            return _tree_unflatten(spec.treedef, leaves)

        return jax.jit(unpack)

    # -- the three stages (separable so bench_transport can decompose) -----

    def pack(self, trajectory) -> np.ndarray:
        """Trajectory -> this process's staging buffer (rotating between
        two buffers so the previous upload may still be reading the
        other one)."""
        import jax

        spec = self._ensure_spec(trajectory)
        with self._lock:
            slot = self._slot
            self._slot = 1 - slot
            if self._staging[slot] is None:
                self._staging[slot] = np.zeros(
                    (self._local_shards, spec.shard_nbytes), np.uint8)
            buf = self._staging[slot]
            pending = self._upload_done[slot]
        if pending is not None:
            # The slot's previous upload may still be streaming this
            # host buffer to the device — overwriting it mid-transfer
            # would silently corrupt that batch.  Two buffers deep this
            # waits on upload k-1, which the intervening update has
            # almost always outlived.
            jax.block_until_ready(pending)
        spec.pack_into(buf, trajectory)
        return buf

    def upload(self, buf: np.ndarray):
        """ONE H2D copy: the packed buffer, sharded over its row axis."""
        import jax

        self._bytes_counter.inc(buf.nbytes)
        if jax.process_count() > 1:
            placed = jax.make_array_from_process_local_data(
                self._buf_sharding, buf)
        else:
            placed = jax.device_put(buf, self._buf_sharding)
        with self._lock:
            # Remember which upload last read each staging buffer so the
            # next pack into that slot can wait for it (see pack()).
            for slot, staged in enumerate(self._staging):
                if staged is buf:
                    self._upload_done[slot] = placed
        return placed

    def unpack(self, device_buf):
        """Jitted bitcast+slice+reshape back to the Trajectory pytree."""
        return self._unpack_jit(device_buf)

    def set_upload_sink(self, sink) -> None:
        """Tap every uploaded device buffer (the replay insert path).
        ``sink(device_buf)`` runs on the putting thread right after the
        upload dispatch; None disconnects."""
        self._upload_sink = sink

    # -- public API --------------------------------------------------------

    def put(self, trajectory):
        import jax

        leaves = _tree_leaves(trajectory)
        if any(isinstance(leaf, jax.Array) for leaf in leaves):
            # Already on device (accum paths): re-shard, don't fetch.
            return self._per_leaf.put(trajectory)
        tracer = get_tracer()
        # Provenance stamps on the calling thread's CURRENT record
        # (set at the pool-queue hand-off) — no-ops when no record is
        # bound (bench/eval callers).
        ledger = get_ledger()
        with tracer.span("transport/pack", cat="h2d"), \
                self._h_pack.time():
            buf = self.pack(trajectory)
        ledger.stamp_current("transport_pack")
        with tracer.span("transport/upload", cat="h2d",
                         args={"bytes": int(buf.nbytes)}), \
                self._h_upload.time():
            device_buf = self.upload(buf)
        ledger.stamp_current("transport_upload")
        if self._upload_sink is not None:
            # The batch's bytes are on device now; the replay slab
            # insert is a jitted device-side write of THIS buffer — no
            # second copy ever crosses the link.
            self._upload_sink(device_buf)
        with tracer.span("transport/unpack", cat="h2d"), \
                self._h_unpack.time():
            result = self.unpack(device_buf)
        ledger.stamp_current("transport_unpack")
        return result


def make_transport(name: str, mesh, shardings_prefix, batch_axes_prefix):
    """Config string -> transport.  ``per_leaf`` is the seed path;
    ``packed`` is the single-copy pipeline."""
    if name == "per_leaf":
        return PerLeafTransport(mesh, shardings_prefix)
    if name == "packed":
        return PackedTransport(mesh, shardings_prefix, batch_axes_prefix)
    raise ValueError(
        f"unknown transport {name!r} (per_leaf | packed)")


# ---------------------------------------------------------------------------
# Bounded in-flight update window.
# ---------------------------------------------------------------------------


class InflightWindow:
    """At most W dispatched-but-unmaterialized updates.

    The driver pushes each update's metrics right after dispatch; once
    ``depth`` reaches the window it retires the oldest — blocking until
    that update's outputs exist — so the loop runs W-deep pipelined with
    hard backpressure, and every retired metrics dict belongs to a known
    update (FIFO: metrics are observed in dispatch order, so per-update
    ``env_frames`` accounting stays exact).  W=1 is lock-step.

    The window also owns the END of each trajectory's ledger record
    (obs/ledger.py): ``push`` carries the trajectory's provenance id,
    ``retire`` stamps/closes it ``retired=True``, and ``discard`` — the
    non-finite-rollback path — closes every pending record
    ``retired=False`` (counted into ``ledger/frames_discarded_total``)
    instead of letting discarded frames vanish from all accounting.
    """

    def __init__(self, window: int, registry=None):
        import weakref

        if window < 1:
            raise ValueError(f"inflight window must be >= 1, got {window}")
        self.window = int(window)
        self._pending = deque()
        registry = registry or get_registry()
        pending_ref = weakref.ref(self._pending)
        registry.gauge(
            "learner/inflight_depth",
            "dispatched updates whose outputs are not yet materialized",
            fn=lambda: (len(p) if (p := pending_ref()) is not None
                        else 0.0))
        self._h_retire = registry.histogram(
            "learner/retire_s",
            "seconds blocked materializing the oldest in-flight update")

    @property
    def depth(self) -> int:
        return len(self._pending)

    @property
    def full(self) -> bool:
        return len(self._pending) >= self.window

    def push(self, metrics, ledger_id: Optional[int] = None) -> None:
        self._pending.append((metrics, ledger_id))

    def retire(self):
        """Block until the OLDEST in-flight update's outputs exist and
        return its metrics (device arrays, ready to fetch for free)."""
        import jax

        metrics, tid = self._pending.popleft()
        with get_tracer().span("learner/retire", cat="learner"), \
                self._h_retire.time():
            jax.block_until_ready(metrics)
        if tid is not None:
            ledger = get_ledger()
            ledger.stamp(tid, "retire")
            ledger.close(tid, retired=True)
        return metrics

    def drain(self):
        """Retire everything; returns the NEWEST metrics (or None when
        nothing was in flight) — the loop-exit value the driver returns."""
        metrics = None
        while self._pending:
            metrics = self.retire()
        return metrics

    def discard(self) -> int:
        """Drop every in-flight metrics dict WITHOUT materializing it
        (the rollback path: pending updates belong to the abandoned
        timeline, blocking on them would only stretch the outage).
        Returns how many were dropped.  Their ledger records close as
        ``retired=False`` — the frames are DISCARDED, and the ledger's
        ``frames_discarded_total`` counter says so."""
        dropped = len(self._pending)
        ledger = get_ledger()
        for _, tid in self._pending:
            if tid is not None:
                ledger.close(tid, retired=False, fate="discarded")
        self._pending.clear()
        return dropped
