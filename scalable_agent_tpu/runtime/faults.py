"""Deterministic fault injection: prove the self-healing layer works.

A recovery path that only fires on real production faults is a recovery
path that has never been tested.  This module is the chaos harness the
robustness layer (docs/robustness.md) is validated against: a seedable,
deterministic registry of *named injection points* compiled into the
runtime's failure-prone seams —

- ``nan_grad``   (runtime/learner.py): poison one update's rewards with
  NaN so the non-finite guard must skip it.
- ``replay_corrupt`` (runtime/replay.py): poison one SAMPLED replay
  batch's rewards with NaN — the same non-finite guard must absorb the
  replayed update as a bit-exact no-op and the skip counter must
  attribute it (occurrences count replay samples).
- ``actor_raise`` (runtime/actor.py): raise ``InjectedFault`` from an
  actor thread's unroll loop, exercising the bounded-respawn retry.
- ``worker_kill`` (runtime/actor.py): SIGKILL one env worker process,
  exercising MultiEnv's respawn (tests/test_fault_tolerance.py).
- ``ckpt_torn``  (runtime/checkpoint.py): corrupt the just-written
  checkpoint on disk — a crash-mid-save stand-in — exercising the
  integrity manifest + walk-back restore.
- ``ckpt_save_fail`` (runtime/checkpoint.py): raise inside a cadenced
  save, exercising the log-and-continue degrade path.
- ``service_stall`` (runtime/service.py): wedge the continuous-batching
  inference thread for ``SERVICE_STALL_S`` seconds (occurrences count
  formed batches) — the service's watchdog heartbeat must go stale and
  dump forensics instead of silently starving the learner.
- ``throughput_sag`` (driver.py, both backends): sleep
  ``THROUGHPUT_SAG_S`` seconds inside the update loop (occurrences
  count update dispatches) — a deterministic stand-in for a mid-run
  slowdown (thermal throttle, noisy neighbor, input stall) that the
  run-health plane (obs/health.py) must detect, attribute, and
  auto-profile end-to-end.
- ``peer_exit``  (runtime/fleet.py): ``os._exit(1)`` from the fleet
  monitor cycle — sudden peer death; SURVIVORS must detect the stale
  heartbeat and exit 72.  Occurrences count monitor cycles.
- ``peer_hang``  (runtime/fleet.py): the heartbeat publisher falls
  silent forever — a wedged-but-alive peer, same survivor contract.
- ``preempt_sigterm`` (runtime/fleet.py): the process SIGTERMs itself,
  driving the preemption-grace protocol (coordinated final checkpoint,
  clean exit) deterministically.
- ``param_bitflip`` (runtime/sentinel.py): flip one mantissa bit in
  the param tree's largest-magnitude element right after an audited
  update — a deterministic SDC stand-in; the sentinel's param-delta
  arm must catch it within the same audit and walk the degradation
  ladder (occurrences count audits).
- ``kernel_miscompute`` (runtime/sentinel.py): scale the hot path's
  audited gradients by 2x — a silently-wrong custom kernel stand-in;
  the sentinel's gradient arm must breach and the first ladder rung
  (``conv_backend pallas→xla``) must clear it (occurrences count
  audits; only effective while the ladder is at rung 0).
- ``replica_diverge`` (runtime/sentinel.py): XOR a constant into this
  process's param fingerprint before the cross-process compare — a
  divergent-replica stand-in; every process must see the mismatch at
  the ``updates%8`` broadcast and agree to roll back (occurrences
  count fingerprint computations).

The three fleet points are armed per-process (each process parses its
OWN ``--chaos_spec``), so a multi-process soak arms them on exactly one
peer and asserts the OTHERS' behavior.

The ``--chaos_spec`` grammar is ``point@i[:j:k...]`` entries joined by
``;``: each integer is a 1-based *occurrence index* of that injection
point (its Nth evaluation fires).  Example::

    --chaos_spec='nan_grad@7;actor_raise@3:12;ckpt_torn@1;worker_kill@20'

fires a NaN gradient on the 7th update, raises from an actor unroll on
its 3rd and 12th evaluations, tears the 1st checkpoint save, and kills
an env worker at the 20th unroll.  Occurrence counting is per-point and
process-global (thread-safe), so a given spec replays the same faults
at the same points every run — the property the chaos soak test
(tests/test_chaos.py) is built on.  With no spec configured the
injector is inert: every hot-path call is one attribute check.

Every fired fault is breadcrumbed in the flight recorder (kind
``fault``) and counted in ``faults/injected_total`` so a chaos run's
artifacts show exactly which faults the recovery metrics answered.
"""

import os
import re
import threading
from typing import Dict, FrozenSet

from scalable_agent_tpu.obs import get_flight_recorder, get_registry

__all__ = [
    "CHAOS_POINTS",
    "FaultInjector",
    "InjectedFault",
    "THROUGHPUT_SAG_S",
    "configure_faults",
    "get_fault_injector",
    "parse_chaos_spec",
    "throughput_sag_s",
]

# Every injection point compiled into the runtime, name -> what firing
# it simulates.  tests/test_chaos_lint.py holds this registry to the
# coverage contract: each point must have a fault-matrix row in
# docs/robustness.md and at least one exercising test, so a point can't
# be added (or orphaned) without its recovery story.
CHAOS_POINTS = {
    "nan_grad": "poison one update's rewards with NaN",
    "replay_corrupt": "poison one sampled replay batch's rewards",
    "actor_raise": "raise from an actor thread's unroll loop",
    "worker_kill": "SIGKILL one env worker process",
    "ckpt_torn": "corrupt the just-written checkpoint on disk",
    "ckpt_save_fail": "raise inside a cadenced checkpoint save",
    "service_stall": "wedge the continuous-batching inference thread",
    "throughput_sag": "sleep inside the update loop (mid-run slowdown)",
    "peer_exit": "sudden peer process death (os._exit from monitor)",
    "peer_hang": "heartbeat publisher falls silent (wedged peer)",
    "preempt_sigterm": "self-SIGTERM driving the preemption protocol",
    "param_bitflip": "flip a mantissa bit in a param leaf (SDC)",
    "kernel_miscompute": "scale audited hot-path grads 2x (bad kernel)",
    "replica_diverge": "corrupt this process's param fingerprint",
}

_ENTRY_RE = re.compile(r"([A-Za-z_][\w.]*)@(\d+(?::\d+)*)\Z")

# How long the ``throughput_sag`` point sleeps in the driver's update
# loop when it fires.  Long enough that a log interval containing the
# sag shows a decisive fps drop even on a fast CPU test config (the
# health detectors' rel_threshold path), short enough that a chaos run
# stays inside tier-1 time budgets.
THROUGHPUT_SAG_S = 0.45


def throughput_sag_s() -> float:
    """The sag duration, env-overridable for tests (the
    ``SCALABLE_AGENT_SERVICE_STALL_S`` pattern from
    runtime/service.py)."""
    try:
        return float(os.environ.get("SCALABLE_AGENT_THROUGHPUT_SAG_S",
                                    THROUGHPUT_SAG_S))
    except ValueError:
        return THROUGHPUT_SAG_S


class InjectedFault(RuntimeError):
    """An intentionally injected fault (chaos testing only).

    Recovery code must treat it like any other transient failure — the
    whole point is that the generic paths, not a special case, absorb
    it."""


def parse_chaos_spec(spec: str) -> Dict[str, FrozenSet[int]]:
    """``'nan_grad@7;actor_raise@3:12'`` -> {point: {occurrences}}.

    Raises ``ValueError`` (with the grammar) on malformed entries —
    a silently-ignored typo would make a chaos run vacuously green.
    """
    points: Dict[str, FrozenSet[int]] = {}
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        match = _ENTRY_RE.match(entry)
        if match is None:
            raise ValueError(
                f"malformed chaos_spec entry {entry!r}: expected "
                f"'point@i[:j...]' with 1-based occurrence indices, "
                f"e.g. 'nan_grad@7;actor_raise@3:12;ckpt_torn@1'")
        name, occurrences = match.group(1), {
            int(x) for x in match.group(2).split(":")}
        if 0 in occurrences:
            raise ValueError(
                f"chaos_spec entry {entry!r}: occurrence indices are "
                f"1-based")
        points[name] = frozenset(occurrences) | points.get(
            name, frozenset())
    return points


class FaultInjector:
    """Occurrence-counting injection registry.  Deterministic: the Nth
    evaluation of a point fires iff N is in the spec's list for it."""

    def __init__(self, spec: str = ""):
        self._points = parse_chaos_spec(spec)
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    @property
    def active(self) -> bool:
        """False for the inert injector — hot paths gate on this so an
        unconfigured run pays one attribute read per injection point."""
        return bool(self._points)

    def should_fire(self, point: str) -> bool:
        """Count one evaluation of ``point``; True when this occurrence
        is armed in the spec."""
        if not self._points:
            return False
        with self._lock:
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
        if n not in self._points.get(point, ()):
            return False
        get_flight_recorder().record("fault", point, {"occurrence": n})
        get_registry().counter(
            "faults/injected_total",
            "faults fired by the chaos injection registry").inc()
        return True

    def maybe_raise(self, point: str):
        """Raise ``InjectedFault`` when this occurrence of ``point`` is
        armed; otherwise just count it."""
        if self.should_fire(point):
            raise InjectedFault(
                f"injected fault at {point!r} "
                f"(occurrence {self._counts[point]})")

    def occurrences(self, point: str) -> FrozenSet[int]:
        """The armed 1-based occurrence set for ``point`` WITHOUT
        counting an evaluation.  For trace-time injection: in-graph
        consumers (runtime/ingraph.py's megaloop) bake the set into the
        compiled program and match it against the global update index
        on device, so firings there are deterministic per update index
        rather than per host evaluation — and are NOT counted in
        ``faults/injected_total`` (the device can't call back out)."""
        return self._points.get(point, frozenset())

    def counts(self) -> Dict[str, int]:
        """Evaluations seen per point (tests/diagnostics)."""
        with self._lock:
            return dict(self._counts)


_DISABLED = FaultInjector("")
_injector = _DISABLED
_injector_lock = threading.Lock()


def get_fault_injector() -> FaultInjector:
    return _injector


def configure_faults(spec: str = "") -> FaultInjector:
    """Install (and return) the process-global injector.  Empty spec
    restores the inert injector — the driver calls that in teardown so
    one chaos run can't leak faults into the next."""
    global _injector
    with _injector_lock:
        _injector = FaultInjector(spec) if spec else _DISABLED
        return _injector
