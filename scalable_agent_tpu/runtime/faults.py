"""Deterministic fault injection: prove the self-healing layer works.

A recovery path that only fires on real production faults is a recovery
path that has never been tested.  This module is the chaos harness the
robustness layer (docs/robustness.md) is validated against: a seedable,
deterministic registry of *named injection points* compiled into the
runtime's failure-prone seams —

- ``nan_grad``   (runtime/learner.py): poison one update's rewards with
  NaN so the non-finite guard must skip it.
- ``replay_corrupt`` (runtime/replay.py): poison one SAMPLED replay
  batch's rewards with NaN — the same non-finite guard must absorb the
  replayed update as a bit-exact no-op and the skip counter must
  attribute it (occurrences count replay samples).
- ``actor_raise`` (runtime/actor.py): raise ``InjectedFault`` from an
  actor thread's unroll loop, exercising the bounded-respawn retry.
- ``worker_kill`` (runtime/actor.py): SIGKILL one env worker process,
  exercising MultiEnv's respawn (tests/test_fault_tolerance.py).
- ``ckpt_torn``  (runtime/checkpoint.py): corrupt the just-written
  checkpoint on disk — a crash-mid-save stand-in — exercising the
  integrity manifest + walk-back restore.
- ``ckpt_save_fail`` (runtime/checkpoint.py): raise inside a cadenced
  save, exercising the log-and-continue degrade path.
- ``service_stall`` (runtime/service.py): wedge the continuous-batching
  inference thread for ``SERVICE_STALL_S`` seconds (occurrences count
  formed batches) — the service's watchdog heartbeat must go stale and
  dump forensics instead of silently starving the learner.
- ``throughput_sag`` (driver.py, both backends): sleep
  ``THROUGHPUT_SAG_S`` seconds inside the update loop (occurrences
  count update dispatches) — a deterministic stand-in for a mid-run
  slowdown (thermal throttle, noisy neighbor, input stall) that the
  run-health plane (obs/health.py) must detect, attribute, and
  auto-profile end-to-end.
- ``peer_exit``  (runtime/fleet.py): ``os._exit(1)`` from the fleet
  monitor cycle — sudden peer death; SURVIVORS must detect the stale
  heartbeat and exit 72.  Occurrences count monitor cycles.
- ``peer_hang``  (runtime/fleet.py): the heartbeat publisher falls
  silent forever — a wedged-but-alive peer, same survivor contract.
- ``preempt_sigterm`` (runtime/fleet.py): the process SIGTERMs itself,
  driving the preemption-grace protocol (coordinated final checkpoint,
  clean exit) deterministically.
- ``param_bitflip`` (runtime/sentinel.py): flip one mantissa bit in
  the param tree's largest-magnitude element right after an audited
  update — a deterministic SDC stand-in; the sentinel's param-delta
  arm must catch it within the same audit and walk the degradation
  ladder (occurrences count audits).
- ``kernel_miscompute`` (runtime/sentinel.py): scale the hot path's
  audited gradients by 2x — a silently-wrong custom kernel stand-in;
  the sentinel's gradient arm must breach and the first ladder rung
  (``conv_backend pallas→xla``) must clear it (occurrences count
  audits; only effective while the ladder is at rung 0).
- ``replica_diverge`` (runtime/sentinel.py): XOR a constant into this
  process's param fingerprint before the cross-process compare — a
  divergent-replica stand-in; every process must see the mismatch at
  the ``updates%8`` broadcast and agree to roll back (occurrences
  count fingerprint computations).

The three fleet points are armed per-process (each process parses its
OWN ``--chaos_spec``), so a multi-process soak arms them on exactly one
peer and asserts the OTHERS' behavior.

The ``--chaos_spec`` grammar is ``;``-joined entries, each one of
three trigger forms on a registered point:

- ``point@i[:j:k...]`` — 1-based *occurrence indices*: the Nth
  evaluation of that injection point fires.  Example::

      --chaos_spec='nan_grad@7;actor_raise@3:12;ckpt_torn@1'

  fires a NaN gradient on the 7th update, raises from an actor unroll
  on its 3rd and 12th evaluations, and tears the 1st checkpoint save.
- ``point@t=30s`` — *time trigger*: the first evaluation of the point
  at or after 30 seconds of injector lifetime fires (the ``s`` suffix
  is optional, floats are accepted).  Each time trigger fires at most
  once.
- ``point@p=0.01`` — *probability trigger*: every evaluation fires
  with probability 0.01, drawn from a per-point RNG seeded from the
  injector's ``seed`` — so a given (spec, seed) replays the same
  decision sequence every run.

Occurrence counting is per-point and process-global (thread-safe), so
a given spec replays the same faults at the same points every run —
the property the chaos soak test (tests/test_chaos.py) is built on.
With no spec configured the injector is inert: every hot-path call is
one attribute check.

Beyond the arm-time spec there is a *runtime injection channel*: when
the injector is built with ``channel_path`` (the driver wires
``<logdir>/chaos_inject.jsonl`` under ``--chaos_channel``), each
appended JSON line ``{"point": ..., "t_unix": ...}`` arms ONE firing
of that point, consumed at the point's next evaluation — faults land
in an already-running fleet, which is what the chaos soak engine
(runtime/soak.py) drives.  Lines whose ``t_unix`` predates this
injector's arm time are skipped, so a relaunched fleet epoch does not
re-fire injections a dead epoch already consumed; an optional
``"proc"`` field targets a single fleet process (matched against
``process_id``).  The channel file is polled from ``should_fire`` at
most every ``CHANNEL_POLL_S`` seconds.

Every fired fault is breadcrumbed in the flight recorder (kind
``fault``, with the trigger form) and counted in
``faults/injected_total`` so a chaos run's artifacts show exactly
which faults the recovery metrics answered.
"""

import json
import os
import random
import re
import threading
import time
from typing import Dict, FrozenSet, List, NamedTuple, Tuple

from scalable_agent_tpu.obs import get_flight_recorder, get_registry

__all__ = [
    "CHANNEL_NAME",
    "CHAOS_POINTS",
    "ChaosSpec",
    "FaultInjector",
    "InjectedFault",
    "THROUGHPUT_SAG_S",
    "configure_faults",
    "get_fault_injector",
    "parse_chaos_spec",
    "parse_chaos_spec_full",
    "throughput_sag_s",
]

# Every injection point compiled into the runtime, name -> what firing
# it simulates.  tests/test_chaos_lint.py holds this registry to the
# coverage contract: each point must have a fault-matrix row in
# docs/robustness.md and at least one exercising test, so a point can't
# be added (or orphaned) without its recovery story.
CHAOS_POINTS = {
    "nan_grad": "poison one update's rewards with NaN",
    "replay_corrupt": "poison one sampled replay batch's rewards",
    "actor_raise": "raise from an actor thread's unroll loop",
    "worker_kill": "SIGKILL one env worker process",
    "ckpt_torn": "corrupt the just-written checkpoint on disk",
    "ckpt_save_fail": "raise inside a cadenced checkpoint save",
    "service_stall": "wedge the continuous-batching inference thread",
    "throughput_sag": "sleep inside the update loop (mid-run slowdown)",
    "peer_exit": "sudden peer process death (os._exit from monitor)",
    "peer_hang": "heartbeat publisher falls silent (wedged peer)",
    "preempt_sigterm": "self-SIGTERM driving the preemption protocol",
    "param_bitflip": "flip a mantissa bit in a param leaf (SDC)",
    "kernel_miscompute": "scale audited hot-path grads 2x (bad kernel)",
    "replica_diverge": "corrupt this process's param fingerprint",
}

_ENTRY_RE = re.compile(r"([A-Za-z_][\w.]*)@(\d+(?::\d+)*)\Z")
_TIME_RE = re.compile(r"([A-Za-z_][\w.]*)@t=(\d+(?:\.\d+)?)s?\Z")
_PROB_RE = re.compile(r"([A-Za-z_][\w.]*)@p=(\d+(?:\.\d+)?)\Z")

# The runtime injection channel: JSON lines appended to
# ``<logdir>/CHANNEL_NAME`` arm one-shot firings in an already-running
# process (see module docstring).  Polled at most this often.
CHANNEL_NAME = "chaos_inject.jsonl"
CHANNEL_POLL_S = 0.25

# How long the ``throughput_sag`` point sleeps in the driver's update
# loop when it fires.  Long enough that a log interval containing the
# sag shows a decisive fps drop even on a fast CPU test config (the
# health detectors' rel_threshold path), short enough that a chaos run
# stays inside tier-1 time budgets.
THROUGHPUT_SAG_S = 0.45


def throughput_sag_s() -> float:
    """The sag duration, env-overridable for tests (the
    ``SCALABLE_AGENT_SERVICE_STALL_S`` pattern from
    runtime/service.py)."""
    try:
        return float(os.environ.get("SCALABLE_AGENT_THROUGHPUT_SAG_S",
                                    THROUGHPUT_SAG_S))
    except ValueError:
        return THROUGHPUT_SAG_S


class InjectedFault(RuntimeError):
    """An intentionally injected fault (chaos testing only).

    Recovery code must treat it like any other transient failure — the
    whole point is that the generic paths, not a special case, absorb
    it."""


class ChaosSpec(NamedTuple):
    """A fully parsed ``--chaos_spec``: occurrence sets, time triggers
    (seconds of injector lifetime, each fires once), and per-evaluation
    firing probabilities."""
    occurrences: Dict[str, FrozenSet[int]]
    at_times: Dict[str, Tuple[float, ...]]
    probs: Dict[str, float]


def parse_chaos_spec_full(spec: str) -> ChaosSpec:
    """Parse every trigger form of the grammar (module docstring):
    ``point@i[:j...]``, ``point@t=30s``, ``point@p=0.01``.

    Raises ``ValueError`` (with the grammar) on malformed entries —
    a silently-ignored typo would make a chaos run vacuously green.
    """
    occurrences: Dict[str, FrozenSet[int]] = {}
    at_times: Dict[str, Tuple[float, ...]] = {}
    probs: Dict[str, float] = {}
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        match = _ENTRY_RE.match(entry)
        if match is not None:
            name, occs = match.group(1), {
                int(x) for x in match.group(2).split(":")}
            if 0 in occs:
                raise ValueError(
                    f"chaos_spec entry {entry!r}: occurrence indices "
                    f"are 1-based")
            occurrences[name] = frozenset(occs) | occurrences.get(
                name, frozenset())
            continue
        match = _TIME_RE.match(entry)
        if match is not None:
            name = match.group(1)
            at_times[name] = tuple(sorted(
                at_times.get(name, ()) + (float(match.group(2)),)))
            continue
        match = _PROB_RE.match(entry)
        if match is not None:
            name, p = match.group(1), float(match.group(2))
            if not 0.0 < p <= 1.0:
                raise ValueError(
                    f"chaos_spec entry {entry!r}: probability must be "
                    f"in (0, 1]")
            probs[name] = p
            continue
        raise ValueError(
            f"malformed chaos_spec entry {entry!r}: expected "
            f"'point@i[:j...]' (1-based occurrence indices), "
            f"'point@t=30s' (time trigger), or 'point@p=0.01' "
            f"(per-evaluation probability), e.g. "
            f"'nan_grad@7;actor_raise@3:12;ckpt_torn@t=5s'")
    return ChaosSpec(occurrences, at_times, probs)


def parse_chaos_spec(spec: str) -> Dict[str, FrozenSet[int]]:
    """``'nan_grad@7;actor_raise@3:12'`` -> {point: {occurrences}}.

    The occurrence-trigger view of the grammar: time and probability
    entries parse (and validate) but do not contribute occurrence
    indices — in-graph consumers (``occurrences()``) bake occurrence
    sets into compiled programs, where the other trigger forms cannot
    apply.  Raises ``ValueError`` on malformed entries.
    """
    return parse_chaos_spec_full(spec).occurrences


class FaultInjector:
    """Trigger-evaluating injection registry.  Deterministic: the Nth
    evaluation of a point fires iff N is in the spec's occurrence list,
    a not-yet-consumed time trigger is due, a seeded per-point RNG draw
    lands under the point's probability, or the runtime channel has a
    pending arm for it (module docstring)."""

    def __init__(self, spec: str = "", channel_path: str = None,
                 seed: int = 0, process_id: int = 0):
        parsed = parse_chaos_spec_full(spec)
        self._points = parsed.occurrences
        self._at_times: Dict[str, List[float]] = {
            point: sorted(times)
            for point, times in parsed.at_times.items()}
        self._probs = parsed.probs
        self._rngs = {point: random.Random(f"{seed}:{point}")
                      for point in parsed.probs}
        self._armed_monotonic = time.monotonic()
        self._armed_unix = time.time()
        self._process_id = process_id
        self._channel_path = channel_path
        self._channel_offset = 0
        self._channel_next_poll = 0.0
        self._pending: Dict[str, int] = {}
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    @property
    def active(self) -> bool:
        """False for the inert injector — hot paths gate on this so an
        unconfigured run pays one attribute read per injection point."""
        return bool(self._points or self._at_times or self._probs
                    or self._channel_path)

    def _poll_channel_locked(self):
        """Consume newly appended channel lines into ``_pending``.
        Byte-offset tailing; a torn final line (no trailing newline yet)
        is left for the next poll."""
        now = time.monotonic()
        if now < self._channel_next_poll:
            return
        self._channel_next_poll = now + CHANNEL_POLL_S
        try:
            with open(self._channel_path, "rb") as f:
                f.seek(self._channel_offset)
                data = f.read()
        except OSError:
            return
        if not data:
            return
        if not data.endswith(b"\n"):
            cut = data.rfind(b"\n") + 1
            if cut == 0:
                return
            data = data[:cut]
        self._channel_offset += len(data)
        for line in data.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                payload = json.loads(line.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                continue
            point = payload.get("point") if isinstance(
                payload, dict) else None
            if not point:
                continue
            t_unix = payload.get("t_unix")
            if t_unix is not None and t_unix < self._armed_unix:
                continue  # consumed by a previous fleet epoch
            proc = payload.get("proc")
            if proc is not None and int(proc) != self._process_id:
                continue
            self._pending[point] = (
                self._pending.get(point, 0)
                + max(1, int(payload.get("count", 1))))

    def should_fire(self, point: str) -> bool:
        """Count one evaluation of ``point``; True when any trigger is
        armed for this evaluation."""
        if not self.active:
            return False
        with self._lock:
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
            fired = None
            if n in self._points.get(point, ()):
                fired = "occurrence"
            if fired is None:
                due = self._at_times.get(point)
                if due and due[0] <= (time.monotonic()
                                      - self._armed_monotonic):
                    self._at_times[point] = due[1:]
                    fired = "time"
            if fired is None and point in self._probs:
                if self._rngs[point].random() < self._probs[point]:
                    fired = "probability"
            if fired is None and self._channel_path is not None:
                self._poll_channel_locked()
                if self._pending.get(point, 0) > 0:
                    self._pending[point] -= 1
                    fired = "channel"
        if fired is None:
            return False
        get_flight_recorder().record(
            "fault", point, {"occurrence": n, "trigger": fired})
        get_registry().counter(
            "faults/injected_total",
            "faults fired by the chaos injection registry").inc()
        return True

    def maybe_raise(self, point: str):
        """Raise ``InjectedFault`` when this occurrence of ``point`` is
        armed; otherwise just count it."""
        if self.should_fire(point):
            raise InjectedFault(
                f"injected fault at {point!r} "
                f"(occurrence {self._counts[point]})")

    def occurrences(self, point: str) -> FrozenSet[int]:
        """The armed 1-based occurrence set for ``point`` WITHOUT
        counting an evaluation.  For trace-time injection: in-graph
        consumers (runtime/ingraph.py's megaloop) bake the set into the
        compiled program and match it against the global update index
        on device, so firings there are deterministic per update index
        rather than per host evaluation — and are NOT counted in
        ``faults/injected_total`` (the device can't call back out)."""
        return self._points.get(point, frozenset())

    def counts(self) -> Dict[str, int]:
        """Evaluations seen per point (tests/diagnostics)."""
        with self._lock:
            return dict(self._counts)


_DISABLED = FaultInjector("")
_injector = _DISABLED
_injector_lock = threading.Lock()


def get_fault_injector() -> FaultInjector:
    return _injector


def configure_faults(spec: str = "", channel_path: str = None,
                     seed: int = 0,
                     process_id: int = 0) -> FaultInjector:
    """Install (and return) the process-global injector.  Empty spec
    with no channel restores the inert injector — the driver calls
    that in teardown so one chaos run can't leak faults into the
    next."""
    global _injector
    with _injector_lock:
        _injector = (FaultInjector(spec, channel_path=channel_path,
                                   seed=seed, process_id=process_id)
                     if (spec or channel_path) else _DISABLED)
        return _injector
