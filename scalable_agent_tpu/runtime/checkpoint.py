"""Checkpoint/resume via Orbax, with integrity verification + fallback.

Replaces the reference's MonitoredTrainingSession auto-checkpointing
(reference: experiment.py:608-616 — all global variables incl. the
env-frame global step, every 600s) and the SF explicit rotation
(reference: algorithms/utils/agent.py:129-193):

- Saves (params, opt_state, env_frames, guard counters) on a wall-clock
  cadence with keep-last-N rotation.
- env_frames rides in the checkpoint so the frame-keyed LR schedule
  resumes exactly (SURVEY §5.4).
- The config JSON snapshot is written separately by Config.save.

Robustness layer (docs/robustness.md):

- Every save also writes a per-leaf crc32 **integrity manifest**
  (``checkpoints/manifests/<step>.json``), and ``restore()`` verifies
  the restored leaves against it.  A torn or corrupt step — a crash
  mid-save, a bad disk — no longer bricks resume: restore **walks back**
  through the retained steps, newest first, until one verifies
  (``checkpoint/restore_fallbacks_total`` counts each rejected step).
- Non-forced ``maybe_save`` failures (disk full, transient Orbax
  errors) degrade to a logged ``checkpoint/save_failures_total``
  instead of killing a training run that is otherwise healthy; only the
  forced final save re-raises.  The multi-process decision broadcast
  and the state allgather happen BEFORE any fallible IO, so a failing
  primary can never strand its peers inside a collective.
- The learner watchdog heartbeat must be suspended by the caller across
  ``restore()``/rollback (the driver does) — a long Orbax read is not a
  wedge; ``restore()`` additionally suspends the calling thread's own
  heartbeat.
"""

import json
import os
import time
import zlib
from typing import Any, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from scalable_agent_tpu.obs import (
    get_flight_recorder,
    get_registry,
    get_tracer,
    get_watchdog,
)
from scalable_agent_tpu.runtime.faults import get_fault_injector
from scalable_agent_tpu.runtime.fleet import get_fleet
from scalable_agent_tpu.runtime.learner import TrainState
from scalable_agent_tpu.utils import log

_MANIFEST_SCHEMA = 1

# TrainState fields a pre-guard checkpoint (before nonfinite_skips/
# nonfinite_streak) was saved with — the legacy-migration restore target.
_LEGACY_FIELDS = ("params", "opt_state", "env_frames")

# TrainState fields a pre-IMPACT checkpoint (before target_params) was
# saved with.  Orbax records even a None field in the tree structure,
# so restores that cross the IMPACT generation boundary IN EITHER
# DIRECTION need a structure retry (see _restore_step):
# - an --loss=impact run resuming a pre-IMPACT (or vtrace) checkpoint
#   retries with target_params=None, and Learner.place_state then
#   initializes the target net from the restored online params;
# - a --loss=vtrace run resuming an --loss=impact checkpoint retries
#   with the online params as the target's shape donor and carries the
#   restored target through untouched (the vtrace update ignores it),
#   so the checkpoint's integrity manifest still verifies leaf-exact.
_PRE_IMPACT_FIELDS = ("params", "opt_state", "env_frames",
                      "nonfinite_skips", "nonfinite_streak")


class CheckpointIntegrityError(RuntimeError):
    """Retained checkpoint steps exist but NONE restored and verified.

    Deliberately loud: silently returning "no checkpoint" here would
    make the driver retrain from step 0 into the same logdir — and
    rotation would then delete the very steps an operator might still
    recover by hand."""


def _to_host(x):
    """Fetch an array to host memory, multi-host safe: non-addressable
    global arrays are allgathered (a collective — every process must
    reach this together)."""
    if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def _current_topology() -> dict:
    """The process/device layout of THIS run — stamped into manifests
    so a resumed run can tell it resharded."""
    return {"num_processes": int(jax.process_count()),
            "num_devices": int(jax.device_count())}


def _leaf_checksums(host_state) -> List[dict]:
    """Per-leaf (shape, dtype, crc32) in flatten order — the integrity
    manifest's body.  Flatten order is deterministic for a fixed
    TrainState structure, so index-keyed entries suffice."""
    entries = []
    for leaf in jax.tree_util.tree_leaves(host_state):
        arr = np.ascontiguousarray(np.asarray(leaf))
        entries.append({
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "crc32": zlib.crc32(arr.tobytes()),
        })
    return entries


class CheckpointManager:
    """Cadenced save/restore.  Multi-process discipline: ONLY process 0
    owns an Orbax manager and touches the checkpoint directory; the
    state is allgathered to host collectively before a save, and a
    restore is read by process 0 and broadcast to everyone — so the
    on-disk format is identical to single-host runs and no two
    processes ever race on the same paths."""

    def __init__(self, logdir: str, interval_s: float = 600.0,
                 keep: int = 5):
        self._dir = os.path.join(os.path.abspath(logdir), "checkpoints")
        self._manifest_dir = os.path.join(self._dir, "manifests")
        self._is_primary = jax.process_index() == 0
        self._manager = None
        if self._is_primary:
            os.makedirs(self._dir, exist_ok=True)
            options = ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True)
            if jax.process_count() > 1:
                # The manager lives ONLY on process 0; restrict orbax's
                # internal barriers to it, or its construction/save
                # collectives would pair up with unrelated collectives
                # on the other processes.
                from orbax.checkpoint import options as ocp_options

                # create=False: with active_processes set, orbax insists
                # the caller makes the root dir (done above).
                options = ocp.CheckpointManagerOptions(
                    max_to_keep=keep, create=False,
                    multiprocessing_options=(
                        ocp_options.MultiprocessingOptions(
                            primary_host=0, active_processes={0})),
                )
            self._manager = ocp.CheckpointManager(self._dir,
                                                  options=options)
        self._interval_s = interval_s
        self._last_save = 0.0
        registry = get_registry()
        self._save_failures = registry.counter(
            "checkpoint/save_failures_total",
            "non-forced checkpoint saves that failed and were degraded "
            "to a logged retry-next-cadence")
        self._restore_fallbacks = registry.counter(
            "checkpoint/restore_fallbacks_total",
            "retained checkpoint steps rejected during restore (torn/"
            "corrupt/unreadable) before an older step verified")
        self._restored_step_gauge = registry.gauge(
            "checkpoint/restored_step",
            "step of the last successfully verified restore (-1 = none)")
        self._restored_step_gauge.set(-1.0)

    # -- integrity manifest ------------------------------------------------

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self._manifest_dir, f"{step}.json")

    def _write_manifest(self, step: int, host_state) -> None:
        """Atomic (tmp + rename) per-leaf checksum manifest for one
        saved step, and prune manifests of rotated-out steps.

        The manifest is computed over the HOST-GATHERED (fully
        replicated/global) state, so it is topology-agnostic by
        construction: the same bytes describe the checkpoint whether it
        is later restored onto 1 process or N — ``topology`` records
        the writing layout purely so a resumed run can DETECT a
        reshard and re-verify after placement
        (``verify_after_reshard``)."""
        os.makedirs(self._manifest_dir, exist_ok=True)
        payload = {
            "schema_version": _MANIFEST_SCHEMA,
            "step": step,
            "topology": _current_topology(),
            "leaves": _leaf_checksums(host_state),
        }
        path = self._manifest_path(step)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        retained = {str(s) for s in self._manager.all_steps()}
        for name in os.listdir(self._manifest_dir):
            stem, ext = os.path.splitext(name)
            if ext == ".json" and stem not in retained and stem != str(step):
                try:
                    os.remove(os.path.join(self._manifest_dir, name))
                except OSError:
                    pass

    def _verify(self, step: int, restored) -> Tuple[bool, str]:
        """Check restored leaves against the step's manifest.  A missing
        manifest (pre-manifest checkpoint) is accepted — integrity
        verification must not reject every checkpoint written before it
        existed."""
        path = self._manifest_path(step)
        if not os.path.exists(path):
            return True, "no manifest (legacy checkpoint, accepted)"
        try:
            manifest = json.load(open(path))
        except (OSError, json.JSONDecodeError) as exc:
            return False, f"unreadable manifest: {exc}"
        expected = manifest.get("leaves", [])
        got = _leaf_checksums(restored)
        if len(expected) != len(got):
            return False, (f"leaf count {len(got)} != manifest "
                           f"{len(expected)}")
        # Multiset comparison: a typed (NamedTuple) restore and a raw
        # target=None restore flatten the same data in different leaf
        # orders (dict keys sort; NamedTuples keep field order) — bit
        # corruption changes a crc, it cannot reorder leaves.
        def key(entry):
            return (tuple(entry["shape"]), entry["dtype"], entry["crc32"])

        missing = sorted(map(key, expected))
        found = sorted(map(key, got))
        if missing != found:
            bad = next((a, b) for a, b in zip(missing, found) if a != b)
            return False, (f"leaf checksum mismatch: manifest {bad[0]!r}"
                           f" vs restored {bad[1]!r}")
        return True, ""

    def _tear_step(self, step: int) -> None:
        """Chaos (``ckpt_torn``): corrupt the just-written step on disk
        — a deterministic stand-in for a crash mid-save.  Inverts a span
        of bytes in the step's largest file, so either Orbax's restore
        raises or the manifest crc catches the change."""
        step_dir = os.path.join(self._dir, str(step))
        largest, size = None, -1
        for root, _, files in os.walk(step_dir):
            for name in files:
                path = os.path.join(root, name)
                nbytes = os.path.getsize(path)
                if nbytes > size:
                    largest, size = path, nbytes
        if largest is None or size <= 0:
            return
        offset = size // 2
        span = min(256, size - offset)
        with open(largest, "r+b") as f:
            f.seek(offset)
            chunk = f.read(span)
            f.seek(offset)
            f.write(bytes(b ^ 0xFF for b in chunk))
        log.warning("chaos: tore checkpoint step %d (%s, %d bytes "
                    "inverted)", step, os.path.basename(largest), span)

    # -- save --------------------------------------------------------------

    def maybe_save(self, step: int, state: TrainState,
                   force: bool = False) -> bool:
        """Save if the cadence interval elapsed.  ``step`` = update index.

        Multi-process: the wall-clock decision is process 0's, broadcast
        so every process enters the collective allgather (or none does)
        — divergent local clocks must never deadlock it.  The allgather
        runs BEFORE the fallible Orbax IO, so a primary-side save
        failure is local to process 0 and degrades (non-forced) to
        ``checkpoint/save_failures_total`` + a retry next cadence; only
        the forced final save re-raises."""
        now = time.monotonic()
        decision = force or now - self._last_save >= self._interval_s
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            # Fleet-guarded (runtime/fleet.py): a peer lost inside the
            # decision broadcast or the allgather below is attributed
            # and bounded (exit 72) instead of hanging every survivor.
            with get_fleet().collective("ckpt_save_decision"):
                decision = bool(multihost_utils.broadcast_one_to_all(
                    np.asarray(decision)))
        if not decision:
            return False
        registry = get_registry()
        injector = get_fault_injector()
        with get_tracer().span("checkpoint/save", cat="checkpoint"), \
                registry.histogram(
                    "checkpoint/save_s",
                    "state fetch + orbax write seconds").time():
            # Collective state fetch FIRST (every process participates,
            # nothing here may fail on only one of them)...
            with get_fleet().collective("ckpt_save_allgather"):
                host_state = jax.tree_util.tree_map(_to_host, state)
            # ...then the primary-only, fallible IO.
            try:
                if injector.active:
                    injector.maybe_raise("ckpt_save_fail")
                if self._manager is not None:
                    self._manager.save(
                        step, args=ocp.args.StandardSave(host_state))
                    if jax.process_count() > 1:
                        # Complete the write before any peer can race
                        # ahead to process exit — a departing peer tears
                        # down the coordination service and cancels
                        # in-flight async writes on the primary.
                        self._manager.wait_until_finished()
                    self._write_manifest(step, host_state)
            except Exception as exc:
                if force:
                    # The final save is the run's durable result — a
                    # silent degrade here would lose it.
                    raise
                self._save_failures.inc()
                get_flight_recorder().record(
                    "ckpt_save_failure", type(exc).__name__,
                    {"step": step})
                log.error(
                    "checkpoint save at step %d failed (%s: %s) — "
                    "training continues, retry next cadence",
                    step, type(exc).__name__, exc)
                # Back off a full interval: a disk-full loop must not
                # turn every update into a failed save attempt.
                self._last_save = now
                return False
            if (self._manager is not None and injector.active
                    and injector.should_fire("ckpt_torn")):
                self._manager.wait_until_finished()
                self._tear_step(step)
        registry.counter("checkpoint/saves_total",
                         "checkpoints written").inc()
        self._last_save = now
        return True

    # -- restore -----------------------------------------------------------

    def _restore_step(self, step: int, host_target):
        # Always pass explicit StandardRestore args: a FRESH manager
        # over an existing directory has no handler registered for the
        # 'default' item until a save runs, so a bare restore(step)
        # raises — exactly the resume-after-crash situation.
        try:
            return self._manager.restore(
                step, args=ocp.args.StandardRestore(host_target))
        except Exception:
            if host_target is None or not isinstance(host_target,
                                                     TrainState):
                raise
            # IMPACT-generation migration (loss-mode crossing, either
            # direction).  A structure mismatch here fails fast in
            # orbax's key validation, before the array reads — so a
            # genuinely torn step pays at most one wasted retry and
            # the walk-back still proceeds.
            if host_target.target_params is not None:
                # impact run <- pre-IMPACT/vtrace checkpoint: restore
                # the narrower structure; the target net is
                # initialized from the online params AFTER manifest
                # verification (Learner.place_state).
                try:
                    restored = self._manager.restore(
                        step, args=ocp.args.StandardRestore(
                            host_target._replace(target_params=None)))
                    log.warning(
                        "checkpoint step %d predates the IMPACT "
                        "target network; target params will be "
                        "initialized from the restored online params",
                        step)
                    return restored
                except Exception:
                    pass
            else:
                # vtrace run <- impact checkpoint: the online params
                # donate the target subtree's structure; the restored
                # target rides along untouched so the per-leaf CRC
                # manifest still verifies the full checkpoint.
                try:
                    restored = self._manager.restore(
                        step, args=ocp.args.StandardRestore(
                            host_target._replace(
                                target_params=host_target.params)))
                    log.warning(
                        "checkpoint step %d carries an IMPACT target "
                        "network; restored under --loss=vtrace it is "
                        "carried through unused", step)
                    return restored
                except Exception:
                    pass
            # Pre-PR trees: a checkpoint written before target_params
            # existed AT ALL has no entry for it (not even a None
            # placeholder), so both 6-field retries above mismatch —
            # restore the plain 5-field structure and let the default
            # None widen it.
            try:
                restored = self._manager.restore(
                    step, args=ocp.args.StandardRestore(
                        {name: getattr(host_target, name)
                         for name in _PRE_IMPACT_FIELDS}))
                log.warning(
                    "checkpoint step %d restored via the pre-IMPACT "
                    "5-field structure", step)
                return TrainState(**restored)
            except Exception:
                pass
            # Legacy migration: checkpoints written before the guard
            # counters existed carry a 3-field TrainState; a structure
            # mismatch against the widened target must not read as
            # "torn" (that would walk past EVERY old step and silently
            # retrain from scratch).  Retry with the legacy structure
            # and zero-fill the new counters; a genuinely torn step
            # makes this retry raise too, and the walk-back proceeds.
            # Gated on manifest ABSENCE: pre-guard checkpoints predate
            # the manifests, while a torn post-guard step has one — so
            # the walk-back never pays a doubled full read per rejected
            # modern step.
            if os.path.exists(self._manifest_path(step)):
                raise
            legacy_target = {name: getattr(host_target, name)
                             for name in _LEGACY_FIELDS}
            restored = self._manager.restore(
                step, args=ocp.args.StandardRestore(legacy_target))
            log.warning(
                "checkpoint step %d restored via the legacy pre-guard "
                "structure; nonfinite counters start at zero", step)
            return TrainState(
                params=restored["params"],
                opt_state=restored["opt_state"],
                env_frames=restored["env_frames"],
                nonfinite_skips=np.float32(0.0),
                nonfinite_streak=np.float32(0.0),
            )

    def _note_bad_step(self, step: int, why: str) -> None:
        self._restore_fallbacks.inc()
        get_flight_recorder().record(
            "ckpt_fallback", str(step), {"why": why[:200]})
        log.error(
            "checkpoint step %d failed integrity/restore (%s) — "
            "falling back to the next older retained step", step, why)

    def _walk_back(self, host_target) -> Optional[Tuple[int, Any]]:
        """Try retained steps newest-first until one restores AND
        verifies; None when every retained step is bad."""
        rejected: List[int] = []
        for step in sorted(self._manager.all_steps(), reverse=True):
            try:
                restored = self._restore_step(step, host_target)
            except Exception as exc:  # torn files make orbax raise
                self._note_bad_step(
                    step, f"{type(exc).__name__}: {exc}")
                rejected.append(step)
                continue
            ok, why = self._verify(step, restored)
            if not ok:
                self._note_bad_step(step, why)
                rejected.append(step)
                continue
            # Delete the NEWER, proven-bad steps now that a good older
            # one exists: a torn step left as latest_step would make
            # Orbax silently skip (save() returns False) every coming
            # save at a step <= it — including the resumed run's final
            # forced save — while the manifests got rewritten for data
            # never on disk.  Only deleted on a successful walk-back;
            # the nothing-verified path keeps everything for the
            # operator.
            for bad in rejected:
                try:
                    self._manager.delete(bad)
                    log.warning(
                        "deleted corrupt checkpoint step %d (newer "
                        "than the verified step %d it would shadow)",
                        bad, step)
                except Exception:
                    log.exception(
                        "could not delete corrupt checkpoint step %d",
                        bad)
            self._restored_step_gauge.set(float(step))
            return step, restored
        return None

    def restore(self, target: Optional[Any] = None
                ) -> Optional[Tuple[int, Any]]:
        """Newest VERIFIED (step, host-side TrainState pytree), or None.

        ``target``: a structure-matching pytree (e.g. a freshly
        initialized TrainState) — required to restore custom NamedTuple
        nodes like optax optimizer states with their original types.

        Walks back through retained steps when the latest is torn or
        corrupt (crash mid-save), so a bad newest step degrades resume
        by one cadence interval instead of bricking it.  Callers that
        own a named watchdog heartbeat (the driver's ``learner``) must
        suspend it around this call — a long Orbax read is not a wedge;
        the calling thread's own heartbeat is suspended here."""
        get_watchdog().suspend()
        multiprocess = jax.process_count() > 1
        if not multiprocess:
            if not self._manager.all_steps():
                return None
            host_target = (None if target is None else
                           jax.tree_util.tree_map(_to_host, target))
            found = self._walk_back(host_target)
            if found is None:
                raise CheckpointIntegrityError(
                    f"checkpoints exist under {self._dir} but none "
                    f"restored and verified — refusing to silently "
                    f"retrain from scratch (move or delete the "
                    f"directory to start fresh)")
            return found

        from jax.experimental import multihost_utils

        # Every collective below rides the fleet guard: a peer that
        # died between init and restore would otherwise hang the whole
        # fleet at its very first cross-process point.
        fleet = get_fleet()
        has_any = (bool(self._manager.all_steps())
                   if self._is_primary else False)
        with fleet.collective("ckpt_restore_has_any"):
            has_any = bool(multihost_utils.broadcast_one_to_all(
                np.asarray(has_any)))
        if not has_any:
            return None
        if target is None:
            raise ValueError(
                "multi-process restore requires a structure target "
                "(the broadcast needs a pytree shape donor)")
        # Collective (_to_host allgathers) — only pay it once a
        # checkpoint actually exists; every process reaches it together,
        # BEFORE the primary's fallible walk-back.
        with fleet.collective("ckpt_restore_allgather"):
            host_target = jax.tree_util.tree_map(_to_host, target)
        found = self._walk_back(host_target) if self._is_primary else None
        with fleet.collective("ckpt_restore_step_broadcast"):
            step = int(multihost_utils.broadcast_one_to_all(
                np.asarray(-1 if found is None else found[0])))
        if step < 0:
            # has_any was True, so a negative step can only mean the
            # primary's walk-back rejected every retained step — raise
            # on EVERY process (the broadcast keeps them in lock-step).
            raise CheckpointIntegrityError(
                f"checkpoints exist under {self._dir} but none "
                f"restored and verified — refusing to silently retrain "
                f"from scratch (move or delete the directory to start "
                f"fresh)")
        restored = found[1] if self._is_primary else host_target
        with fleet.collective("ckpt_restore_state_broadcast"):
            restored = multihost_utils.broadcast_one_to_all(restored)
        return step, restored

    def saved_topology(self, step: int) -> Optional[dict]:
        """The ``{"num_processes", "num_devices"}`` layout that wrote
        ``step``'s manifest; None for legacy/absent manifests.  A disk
        read — in multi-process runs only the primary's answer is
        authoritative (``verify_after_reshard`` broadcasts the
        decision)."""
        try:
            manifest = json.load(open(self._manifest_path(step)))
        except (OSError, json.JSONDecodeError):
            return None
        return manifest.get("topology")

    def verify_after_reshard(self, step: int, placed_state,
                             force: bool = False) -> bool:
        """Re-verify per-leaf CRCs AFTER a restored state was committed
        onto THIS run's mesh, iff the checkpoint was written by a
        DIFFERENT process/device layout (elastic reshard, ISSUE 6).

        The on-disk format is host-gathered and fully replicated, so a
        reshard is value-preserving by construction — this check proves
        it held end-to-end (restore broadcast + ``place_state``
        resharding included) by gathering the PLACED state back to host
        and comparing it against the step's manifest.  Topology
        unchanged (or unknown/legacy manifest) is a no-op returning
        False; a verified reshard returns True; a mismatch raises
        ``CheckpointIntegrityError`` on every process.

        Collective in multi-process runs (the gather allgathers and the
        decision/verdict are broadcast) — every process must call it
        together, which the driver's restore path guarantees.
        ``force=True`` verifies regardless of the recorded topology
        (same value on every process) — the audit knob, and how the
        single-process reshard tests exercise the machinery on a rig
        whose global device count never changes."""
        current = _current_topology()
        fleet = get_fleet()
        saved = None
        why = ""
        if jax.process_count() <= 1:
            saved = self.saved_topology(step)
            if not (force or (saved and saved != current)):
                return False
            ok, why = self._verify(
                step, jax.tree_util.tree_map(_to_host, placed_state))
        else:
            from jax.experimental import multihost_utils

            if self._is_primary:
                saved = self.saved_topology(step)
            resharded = force or (bool(saved) and saved != current)
            with fleet.collective("ckpt_reshard_decision"):
                resharded = bool(multihost_utils.broadcast_one_to_all(
                    np.asarray(resharded)))
            if not resharded:
                return False
            with fleet.collective("ckpt_reshard_allgather"):
                host_state = jax.tree_util.tree_map(
                    _to_host, placed_state)
            ok = True
            if self._is_primary:
                ok, why = self._verify(step, host_state)
            with fleet.collective("ckpt_reshard_verdict"):
                ok = bool(multihost_utils.broadcast_one_to_all(
                    np.asarray(ok)))
        if not ok:
            raise CheckpointIntegrityError(
                f"checkpoint step {step} failed per-leaf CRC "
                f"verification after resharding onto {current} "
                f"(saved at {saved}): {why or 'see the primary log'}")
        get_registry().counter(
            "checkpoint/reshard_verifications_total",
            "restores that crossed a process/device-layout change and "
            "re-verified their manifest after resharding").inc()
        get_flight_recorder().record(
            "ckpt_reshard", str(step),
            {"saved": saved, "current": current})
        log.info(
            "checkpoint step %d restored across a topology change "
            "(%s -> %s); per-leaf CRCs re-verified after reshard",
            step, saved, current)
        return True

    def latest_verified_step(self) -> Optional[int]:
        """The newest retained step (no verification — cheap metadata
        peek for tests/tools); None when the directory is empty."""
        if self._manager is None:
            return None
        steps = self._manager.all_steps()
        return max(steps) if steps else None

    def wait(self):
        if self._manager is not None:
            self._manager.wait_until_finished()

    def close(self):
        if self._manager is not None:
            self._manager.wait_until_finished()
            self._manager.close()
