"""Checkpoint/resume via Orbax.

Replaces the reference's MonitoredTrainingSession auto-checkpointing
(reference: experiment.py:608-616 — all global variables incl. the
env-frame global step, every 600s) and the SF explicit rotation
(reference: algorithms/utils/agent.py:129-193):

- Saves (params, opt_state, env_frames) on a wall-clock cadence with
  keep-last-N rotation.
- env_frames rides in the checkpoint so the frame-keyed LR schedule
  resumes exactly (SURVEY §5.4).
- The config JSON snapshot is written separately by Config.save.
"""

import os
import time
from typing import Any, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from scalable_agent_tpu.runtime.learner import TrainState


class CheckpointManager:
    def __init__(self, logdir: str, interval_s: float = 600.0,
                 keep: int = 5):
        self._dir = os.path.join(os.path.abspath(logdir), "checkpoints")
        os.makedirs(self._dir, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True),
        )
        self._interval_s = interval_s
        self._last_save = 0.0

    def maybe_save(self, step: int, state: TrainState,
                   force: bool = False) -> bool:
        """Save if the cadence interval elapsed.  ``step`` = update index."""
        now = time.monotonic()
        if not force and now - self._last_save < self._interval_s:
            return False
        host_state = jax.tree_util.tree_map(np.asarray, state)
        self._manager.save(step, args=ocp.args.StandardSave(host_state))
        self._last_save = now
        return True

    def restore(self, target: Optional[Any] = None
                ) -> Optional[Tuple[int, Any]]:
        """Latest (step, host-side TrainState pytree), or None.

        ``target``: a structure-matching pytree (e.g. a freshly initialized
        TrainState) — required to restore custom NamedTuple nodes like
        optax optimizer states with their original types.
        """
        step = self._manager.latest_step()
        if step is None:
            return None
        if target is None:
            restored = self._manager.restore(step)
        else:
            host_target = jax.tree_util.tree_map(np.asarray, target)
            restored = self._manager.restore(
                step, args=ocp.args.StandardRestore(host_target))
        return step, restored

    def wait(self):
        self._manager.wait_until_finished()

    def close(self):
        self._manager.wait_until_finished()
        self._manager.close()
